"""Priority-lane admission: hot queries survive cold floods (ISSUE 10).

Covers: ServiceSettings.validate() naming the bad setting at startup and
``_env_int``/``_env_float`` naming the env variable on parse failure;
malformed ``deadline_s`` as a typed ``bad_request`` (client side); a
snapshot of the health/stats reply key schema including the per-lane
fields; hot/cold classification (index-covered, cold-cache-covered,
malformed); cold-lane sheds carrying ``lane`` while concurrent hot
queries keep answering; brownout halving the cold limit; misclassified
hot queries demoting to the cold lane end to end; the ``svc_flood``
chaos grammar, its injection, and ReplicaSet failover on the resulting
typed ``overloaded``; EVENT_SCHEMA validation of the lane events; and
trace_report's per-lane rows.
"""

import threading

import numpy as np
import pytest

from sieve import metrics, trace
from sieve.chaos import parse_chaos
from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.metrics import MemorySink, validate_record
from sieve.seed import seed_primes
from sieve.service import (
    ReplicaSet,
    ServiceClient,
    ServiceSettings,
    SieveService,
)

N = 50_000
P = seed_primes(400_000)


def o_pi(x):
    return int(np.searchsorted(P, x, side="right"))


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


@pytest.fixture(scope="module")
def ledger_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("lanes_ledger")
    run_local(_cfg(str(path)))
    return path


def _cfg(checkpoint_dir: str, **kw) -> SieveConfig:
    base = dict(
        n=N, backend="cpu-numpy", packing="wheel30", n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw) -> ServiceSettings:
    base = dict(
        workers=2, queue_limit=16, default_deadline_s=10.0,
        cold_chunk=1 << 16, breaker_cooldown_s=0.4, refresh_s=0.0,
    )
    base.update(kw)
    return ServiceSettings(**base)


# --- settings validation (satellite 1) ---------------------------------------


@pytest.mark.parametrize("field,value,needle", [
    ("queue_limit", 0, "queue_limit=0"),
    ("workers", -1, "workers=-1"),
    ("hot_queue_limit", 0, "hot_queue_limit=0"),
    ("cold_queue_limit", -3, "cold_queue_limit=-3"),
    ("hot_workers", -1, "hot_workers=-1"),
    ("cold_age_s", -0.5, "cold_age_s=-0.5"),
    ("cold_age_s", float("nan"), "cold_age_s=nan"),
    ("default_deadline_s", 0, "default_deadline_s=0"),
    ("breaker_fails", "3", "breaker_fails='3'"),
])
def test_validate_names_the_bad_setting(field, value, needle):
    with pytest.raises(ValueError) as ei:
        ServiceSettings(**{field: value}).validate()
    assert needle in str(ei.value)


def test_validate_accepts_defaults_and_lane_inheritance():
    s = ServiceSettings().validate()
    assert s.hot_queue_limit is None  # None inherits queue_limit: valid
    assert ServiceSettings(hot_workers=0).validate().hot_workers == 0


def test_bad_settings_fail_at_service_startup(ledger_dir):
    # the whole point of validate(): a bad knob dies at construction,
    # never as undefined runtime behavior in the admission plane
    with pytest.raises(ValueError, match="workers=0"):
        SieveService(_cfg(str(ledger_dir)), ServiceSettings(workers=0))


def test_env_parse_failure_names_the_variable(monkeypatch):
    monkeypatch.setenv("SIEVE_SVC_QUEUE", "lots")
    with pytest.raises(ValueError, match="SIEVE_SVC_QUEUE='lots'"):
        ServiceSettings.from_env()
    monkeypatch.delenv("SIEVE_SVC_QUEUE")
    monkeypatch.setenv("SIEVE_SVC_COLD_AGE_S", "fast")
    with pytest.raises(ValueError, match="SIEVE_SVC_COLD_AGE_S='fast'"):
        ServiceSettings.from_env()


def test_env_lane_knobs_parse(monkeypatch):
    monkeypatch.setenv("SIEVE_SVC_HOT_QUEUE", "8")
    monkeypatch.setenv("SIEVE_SVC_HOT_WORKERS", "2")
    monkeypatch.setenv("SIEVE_SVC_COLD_AGE_S", "0.25")
    s = ServiceSettings.from_env()
    assert (s.hot_queue_limit, s.hot_workers, s.cold_age_s) == (8, 2, 0.25)
    assert s.cold_queue_limit is None  # unset env keeps the None default


# --- malformed deadline_s (satellite 2) --------------------------------------


@pytest.mark.parametrize("dl", [-1, 0, "nope", float("inf"), True])
def test_bad_deadline_is_typed_bad_request(service, dl):
    svc, cli = service
    r = cli.query("pi", x=1000, deadline_s=dl)
    assert r["ok"] is False
    assert r["error"] == "bad_request"
    assert "deadline_s" in r["detail"]
    # the client connection survives a typed refusal
    assert cli.pi(30_000) == o_pi(30_000)
    assert svc.stats()["bad_requests"] >= 1


# --- health/stats reply schema snapshot (satellite 3) ------------------------


@pytest.fixture
def service(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            yield svc, cli


def test_health_and_stats_key_schema_snapshot(service):
    """Key-set snapshot: a removed or renamed field in either control
    reply is an operator-visible wire break and must show up here."""
    svc, cli = service
    assert cli.pi(30_000) == o_pi(30_000)
    assert sorted(cli.health()) == [
        "brownout", "cold_backend", "covered_hi", "draining", "id",
        "mesh_devices", "mesh_fanout", "ok", "proc",
        "queue_depth", "queue_depth_cold", "queue_depth_hot", "range_lo",
        "refreshes", "snapshot_age_s", "status", "store", "total_primes",
        "type",
    ]
    assert sorted(cli.stats()) == [
        "bad_requests", "batch_members", "batch_requests", "brownout",
        "coalesced", "cold_admitted", "cold_backend",
        "cold_batched_chunks", "cold_cache_hits", "cold_computes",
        "cold_dispatches", "cold_persisted", "cold_store_hits",
        "covered_hi",
        "deadline_exceeded", "degraded", "degraded_replies", "demoted",
        "draining", "draining_replies", "dropped_segments",
        "exemplars_kept", "exemplars_seen",
        "hot_admitted", "hot_workers_dedicated", "index_hits",
        "internal_errors", "lane_shed_cold", "lane_shed_hot",
        "lru_entries", "lru_hits", "materialized", "mesh_devices",
        "mesh_fallbacks", "mesh_fanout", "mesh_launches", "persist_cold",
        "proc_index", "procs", "profile_gaps", "profile_pulls",
        "queue_depth", "queue_depth_cold",
        "queue_depth_hot", "range_lo", "refresh_attempts",
        "refresh_failed", "refreshes", "requests", "segments", "shed",
        "slo", "slow_consumer_closed", "snapshot_age_s", "store",
        "store_errors", "store_hits", "telemetry_replies",
        "total_primes", "trace_drops", "wire_v2_conns",
    ]


# --- classification ----------------------------------------------------------


def test_classification_hot_vs_cold(service):
    svc, cli = service
    idx = svc.index
    hi = idx.covered_hi
    q = lambda **m: svc._classify(m, idx)
    assert q(op="pi", x=hi - 1) == "hot"
    assert q(op="pi", x=2 * hi) == "cold"
    assert q(op="count", lo=10, hi=hi) == "hot"
    assert q(op="count", lo=10, hi=hi + 1000) == "cold"
    assert q(op="count", lo=10, hi=2 * hi, kind="twin") == "cold"
    assert q(op="nth_prime", k=idx.total_primes) == "hot"
    assert q(op="nth_prime", k=idx.total_primes + 1) == "cold"
    assert q(op="primes", lo=10, hi=hi) == "hot"
    assert q(op="primes", lo=10, hi=hi + 1) == "cold"
    # malformed / unknown queries are hot: a typed bad_request is cheap
    # and must never queue behind a cold flood
    assert q(op="pi", x="bad") == "hot"
    assert q(op="count", lo=50, hi=10) == "hot"  # hi < lo: bad_request
    assert q(op="no_such_op") == "hot"
    assert q(op="pi") == "hot"  # missing arg


def test_cold_cache_promotes_to_hot(service):
    svc, cli = service
    x = svc.index.covered_hi + 10_000
    assert svc._classify({"op": "pi", "x": x}, svc.index) == "cold"
    assert cli.pi(x) == o_pi(x)  # fills the cold chunk cache
    assert svc._classify({"op": "pi", "x": x}, svc.index) == "hot"
    assert svc.stats()["cold_admitted"] >= 1
    assert cli.pi(x) == o_pi(x)
    assert svc.stats()["hot_admitted"] >= 1


# --- cold flood: sheds carry lane, hot lane keeps answering ------------------


def test_cold_shed_carries_lane_while_hot_answers(ledger_dir, memsink):
    settings = _settings(
        workers=2, hot_workers=1, queue_limit=16, cold_queue_limit=1,
        cold_delay_s=0.4, cold_age_s=5.0,
    )
    with SieveService(_cfg(str(ledger_dir)), settings) as svc:
        hi = svc.index.covered_hi
        replies = []
        rlock = threading.Lock()

        def cold_query(i):
            x = hi + (i + 1) * (1 << 16) - 1
            with ServiceClient(svc.addr, timeout_s=30) as c:
                r = c.query("pi", x=x)
                with rlock:
                    replies.append((x, r))

        threads = [threading.Thread(target=cold_query, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        # the dedicated hot worker keeps answering under the flood
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            for x in range(5_000, 45_000, 5_000):
                assert cli.pi(x) == o_pi(x)
        for t in threads:
            t.join()
        shed = [(x, r) for x, r in replies if not r["ok"]]
        assert shed, "cold lane at limit 1 must shed under 6 queries"
        for _x, r in shed:
            assert r["error"] == "overloaded"
            assert r["lane"] == "cold"
            assert "cold lane" in r["detail"]
        for x, r in replies:
            if r["ok"]:
                assert r["value"] == o_pi(x)  # admitted cold stays exact
        st = svc.stats()
        assert st["lane_shed_cold"] == len(shed)
        assert st["lane_shed_hot"] == 0
    evs = [x for x in memsink.records
           if x["event"] == "service_lane_shed"]
    assert evs and all(e["lane"] == "cold" for e in evs)
    for x in memsink.records:
        validate_record(x)


def test_brownout_halves_cold_limit(ledger_dir):
    # unstarted service: no workers drain the lanes we stuff by hand
    svc = SieveService(
        _cfg(str(ledger_dir)),
        _settings(hot_queue_limit=8, cold_queue_limit=8),
    )
    assert svc.brownout() is False
    with svc._lane_cond:
        assert svc._lane_limit_locked("cold") == 8
    svc._lanes["hot"].extend(object() for _ in range(4))  # half of 8
    assert svc.brownout() is True
    with svc._lane_cond:
        assert svc._lane_limit_locked("cold") == 4
        assert svc._lane_limit_locked("hot") == 8  # hot never halves
    svc._lanes["hot"].clear()
    assert svc.brownout() is False


# --- demotion: a misclassified hot query hands off to the cold lane ----------


def test_misclassified_hot_query_demotes_and_answers(ledger_dir, memsink):
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(workers=2, hot_workers=1)) as svc:
        svc._classify = lambda msg, idx: "hot"  # force misclassification
        x = svc.index.covered_hi + 5_000
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            assert cli.pi(x) == o_pi(x)  # exact despite the wrong lane
        st = svc.stats()
        assert st["demoted"] >= 1
        assert st["hot_admitted"] >= 1
        # the demoted re-enqueue must not double-count the request
        assert st["requests"] == 1
    evs = [x for x in memsink.records if x["event"] == "service_demoted"]
    assert evs and evs[0]["op"] == "pi" and evs[0]["chunks"] >= 1
    for x in memsink.records:
        validate_record(x)


# --- svc_flood chaos + ReplicaSet failover -----------------------------------


def test_svc_flood_grammar():
    (d,) = parse_chaos("svc_flood:any@s3:hot")
    assert (d.kind, d.seg_id, d.param) == ("svc_flood", 3, "hot")
    (d,) = parse_chaos("svc_flood:any@s1")
    assert d.param == "cold"  # default lane
    with pytest.raises(ValueError, match="must be a lane"):
        parse_chaos("svc_flood:any@s1:luke")
    with pytest.raises(ValueError, match="must be a lane"):
        parse_chaos("svc_flood:any@s1:0.5")


def test_svc_flood_injects_lane_shed(service, memsink):
    svc, cli = service
    svc.inject_chaos(f"svc_flood:any@s{svc._seq + 1}:cold")
    r = cli.query("pi", x=1_000)  # would classify hot; flood wins
    assert r["ok"] is False
    assert r["error"] == "overloaded"
    assert r["lane"] == "cold"
    assert "svc_flood" in r["detail"]
    assert cli.pi(1_000) == o_pi(1_000)  # one-shot: next request admits
    assert svc.stats()["lane_shed_cold"] >= 1
    evs = [x for x in memsink.records if x["event"] == "service_lane_shed"]
    assert evs and evs[-1]["lane"] == "cold"
    for x in memsink.records:
        validate_record(x)


def test_replicaset_fails_over_on_flood_shed(ledger_dir):
    cfg = _cfg(str(ledger_dir))
    with SieveService(cfg, _settings()) as a, \
            SieveService(cfg, _settings()) as b:
        # round-robin starts at replica 0: A sheds typed overloaded
        # (lane cold) via the injected flood, the set retries B —
        # exact answer, no client-visible error, no client change
        a.inject_chaos(f"svc_flood:any@s{a._seq + 1}:cold")
        with ReplicaSet([a.addr, b.addr], timeout_s=10,
                        backoff_base_s=0.01) as rs:
            assert rs.pi(30_000) == o_pi(30_000)
            assert rs.failovers >= 1
        assert a.stats()["lane_shed_cold"] >= 1


# --- trace_report per-lane rows ----------------------------------------------


def test_trace_report_renders_per_lane_rows(service):
    svc, cli = service
    tr = trace.get_tracer()
    tr.enable()
    try:
        assert cli.pi(30_000) == o_pi(30_000)  # hot
        x = svc.index.covered_hi + 70_000
        assert cli.pi(x) == o_pi(x)  # cold
    finally:
        tr.disable()
    from tools.trace_report import service_report

    spans = [e for e in tr.events() if e.get("ph") == "X"]
    lanes = {(e.get("args") or {}).get("lane")
             for e in spans if e["name"] == "rpc.query"}
    assert lanes >= {"hot", "cold"}
    text = "\n".join(service_report(spans))
    assert "lane" in text and "wait p95 ms" in text
    hot_row = next(ln for ln in text.splitlines()
                   if ln.strip().startswith("hot"))
    cold_row = next(ln for ln in text.splitlines()
                    if ln.strip().startswith("cold"))
    assert hot_row and cold_row
    # pre-lane traces (no lane arg) skip the block instead of crashing
    stripped = [dict(e, args={}) for e in spans]
    assert "lane" not in "\n".join(service_report(stripped))
