"""Fleet-wide request tracing + live telemetry plane (ISSUE 12).

Covers: client/router/replica trace-ctx propagation (stamping, child
contexts, fresh per-attempt suffixes on retry); the bounded span ring
(drop accounting, counter-track throttling); the batched telemetry
piggyback, the ``telemetry`` flush op, and ``svc_trace_drop`` chaos
(grammar + wire behavior); the 2-shard SUBPROCESS merged-trace run with
the >=95% route->query correlation acceptance gate; the ``metrics``
wire op on server and router; ``tools/fleet_top.py`` snapshot schema
and rendering; per-op SLO burn (event schema, gauges, empty-window
nulls); and trace_report's malformed-input exit + routed-report guards.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from sieve import metrics, trace
from sieve.chaos import parse_chaos
from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.metrics import MemorySink, registry, validate_record
from sieve.seed import seed_primes
from sieve.service import (
    ReplicaSet,
    RouterSettings,
    ServiceClient,
    ServiceSettings,
    Shard,
    ShardMap,
    SieveRouter,
    SieveService,
)
from sieve.service.client import CallTimeout

REPO = Path(__file__).resolve().parent.parent

N = 50_000
P = seed_primes(200_000)


def o_pi(x):
    return int(np.searchsorted(P, x, side="right"))


def o_count(lo, hi):
    return int(np.searchsorted(P, hi, side="left")
               - np.searchsorted(P, lo, side="left"))


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Each test starts and ends with a disabled, unbounded tracer."""
    yield
    trace.drain_events()
    trace.disable()
    trace.set_event_limit(None)


def _cfg(checkpoint_dir, **kw):
    base = dict(
        n=N, backend="cpu-numpy", packing="wheel30", n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw):
    base = dict(workers=2, queue_limit=16, default_deadline_s=10.0,
                refresh_s=0.0)
    base.update(kw)
    return ServiceSettings(**base)


@pytest.fixture(scope="module")
def src_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("fleet_src")
    run_local(_cfg(str(path)))
    return path


def _split_shards(src_dir, tmp_path):
    segs = sorted(
        Ledger.open_readonly(_cfg(str(src_dir))).completed().values(),
        key=lambda r: r.lo,
    )
    E = segs[2].lo
    dirs = (tmp_path / "shard0", tmp_path / "shard1")
    for d, part in zip(dirs, (segs[:2], segs[2:])):
        led = Ledger.open(_cfg(str(d)))
        for r in part:
            led.record(r)
    return str(dirs[0]), str(dirs[1]), E


def _replace(settings, **kw):
    import dataclasses
    return dataclasses.replace(settings, **kw)


class _Fabric:
    """Two-shard in-process fabric (one replica each) + router."""

    def __init__(self, src_dir, tmp_path, shard_settings=None,
                 shard1_chaos=None):
        d0, d1, self.E = _split_shards(src_dir, tmp_path)
        sset = shard_settings or _settings()
        self.svcs = [
            SieveService(_cfg(d0), sset).start(),
            SieveService(_cfg(d1, chaos=shard1_chaos),
                         _replace(sset, range_lo=self.E)).start(),
        ]
        self.map = ShardMap([
            Shard(2, self.E, (self.svcs[0].addr,)),
            Shard(self.E, N + 1, (self.svcs[1].addr,)),
        ])
        self.router = SieveRouter(
            self.map, RouterSettings(quiet=True)).start()
        self.cli = ServiceClient(self.router.addr, timeout_s=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.cli.close()
        self.router.stop()
        for s in self.svcs:
            s.stop()


# --- ctx propagation ---------------------------------------------------------


def test_client_stamps_ctx_and_router_forwards_children(src_dir, tmp_path):
    trace.enable()
    with _Fabric(src_dir, tmp_path) as f:
        assert f.cli.is_prime(17)
        lo = f.E + 10
        assert f.cli.count(lo, lo + 50) == o_count(lo, lo + 50)
    trace.disable()
    events = trace.get_tracer().events()
    routes = [e for e in events if e.get("name") == "rpc.route"]
    queries = [e for e in events if e.get("name") == "rpc.query"]
    assert len(routes) == 2
    for r in routes:
        rctx = (r.get("args") or {}).get("ctx", "")
        # the ServiceClient stamped run_id/<seq>.0 before the router saw it
        head, tail = rctx.rsplit("/", 1)
        assert head and tail.endswith(".0")
        kids = [q for q in queries
                if (q.get("args") or {}).get("ctx", "")
                .rsplit("/", 1)[0] == rctx]
        assert len(kids) == 1, f"route {rctx} should have one child"
        kctx = kids[0]["args"]["ctx"]
        # child = <route ctx>/s<shard>.<call>.<attempt>
        assert kctx.startswith(rctx + "/s")
        assert kctx.rsplit(".", 1)[1] == "0"  # first wire attempt


def test_replica_retry_gets_fresh_attempt_ctx(src_dir, tmp_path, monkeypatch):
    d0, _d1, _E = _split_shards(src_dir, tmp_path)
    seen = []
    orig_call = ServiceClient._call
    state = {"failed": False}

    def flaky(self, msg):
        if msg.get("type") == "query":
            seen.append(msg["ctx"])
            if not state["failed"]:
                state["failed"] = True
                raise CallTimeout("injected: first attempt dies")
        return orig_call(self, msg)

    monkeypatch.setattr(ServiceClient, "_call", flaky)
    with SieveService(_cfg(d0), _settings()) as svc:
        rs = ReplicaSet([svc.addr], timeout_s=10, rounds=3,
                        backoff_base_s=0.0, backoff_cap_s=0.0,
                        circuit_cooldown_s=0.0)
        reply = rs.query("pi", ctx="root/7", x=1000)
        rs.close()
    assert reply["ok"] and reply["value"] == o_pi(1000)
    # same base, fresh .attempt per wire try — retried spans never alias
    assert seen == ["root/7.0", "root/7.1"]


# --- bounded ring + counter throttle ----------------------------------------


def test_ring_drop_bounds_and_accounting():
    tr = trace.Tracer()
    tr.enable()
    tr.set_event_limit(4)
    for i in range(10):
        tr.add_span("ring.span", float(i), 0.001, i=i)
    kept = [e for e in tr.events() if e.get("ph") != "M"]
    assert len(kept) <= 4
    assert tr.dropped == 10 - len(kept)
    # the survivors are the NEWEST spans (oldest evicted first)
    survivors = [e["args"]["i"] for e in kept]
    assert survivors == list(range(10 - len(survivors), 10))


def test_counter_tracks_are_throttled_not_transition_logged():
    tr = trace.Tracer()
    tr.enable()
    tr.counter("q.depth", 1)
    tr.counter("q.depth", 2)  # same interval: dropped
    tr.counter("q.other", 5)  # first sample of another track: lands
    assert [e["name"] for e in tr.events() if e["ph"] == "C"] \
        == ["q.depth", "q.other"]
    tr._counter_interval_us = 0.0  # interval elapsed
    tr.counter("q.depth", 3)
    vals = [e["args"]["value"] for e in tr.events()
            if e["ph"] == "C" and e["name"] == "q.depth"]
    assert vals == [1, 3]


# --- telemetry piggyback, flush op, chaos drop ------------------------------


def test_piggyback_batches_and_flush_op_drains(src_dir, tmp_path):
    d0, _d1, _E = _split_shards(src_dir, tmp_path)
    trace.enable()
    with SieveService(
        _cfg(d0),
        _settings(telemetry_ship=True, telemetry_batch=10_000),
    ) as svc:
        rs = ReplicaSet([svc.addr], timeout_s=10)
        reply = rs.query("pi", telemetry=True, x=1000)
        # below the batch threshold: the reply must NOT pay a serialize
        assert reply["ok"] and "telemetry" not in reply
        # but the explicit flush op always drains the ring
        flushed = rs.telemetry_flush()
        assert len(flushed) == 1
        tele = flushed[0]["telemetry"]
        assert tele["dropped"] >= 0
        assert any(e.get("name") == "rpc.query" for e in tele["events"])
        assert flushed[0]["probe"]["addr"] == svc.addr
        assert flushed[0]["t_recv"] <= flushed[0]["t_sent"]
        # batch=1: the very next traced reply carries the ring inline
        svc.settings.telemetry_batch = 1
        reply2 = rs.query("pi", telemetry=True, x=2000)
        assert reply2["telemetry"]["events"]
        rs.close()


def test_svc_trace_drop_discards_ring_and_nulls_payload(
        src_dir, tmp_path, memsink):
    d0, _d1, _E = _split_shards(src_dir, tmp_path)
    trace.enable()
    with SieveService(
        _cfg(d0, chaos="svc_trace_drop:any@s1"),
        _settings(telemetry_ship=True, telemetry_batch=1),
    ) as svc:
        rs = ReplicaSet([svc.addr], timeout_s=10)
        r1 = rs.query("pi", telemetry=True, x=1000)
        # request 1: answered exactly, telemetry explicitly lost
        assert r1["ok"] and r1["value"] == o_pi(1000)
        assert r1["telemetry"] is None
        r2 = rs.query("pi", telemetry=True, x=2000)
        # the dropped ring was discarded, not deferred: request 2 ships
        # only spans captured AFTER the drop
        ctxs = [(e.get("args") or {}).get("ctx") for e in r2["telemetry"]
                ["events"] if e.get("name") == "rpc.query"]
        assert len(ctxs) == 1  # only request 2's span, not request 1's
        assert svc.stats()["trace_drops"] == 1
        rs.close()
    drops = [r for r in memsink.records
             if r.get("event") == "service_trace_drop"]
    assert len(drops) == 1 and drops[0]["op"] == "pi"
    validate_record(drops[0])


def test_chaos_grammar_svc_trace_drop():
    d = parse_chaos("svc_trace_drop:any@s3")
    assert len(d) == 1 and d[0].kind == "svc_trace_drop"
    assert d[0].seg_id == 3 and d[0].param is None
    with pytest.raises(ValueError, match="takes no param"):
        parse_chaos("svc_trace_drop:any@s3:2")


# --- the acceptance gate: 2-shard subprocess merged trace --------------------


def test_two_shard_subprocess_merged_trace_correlation(src_dir, tmp_path):
    """Routed workload over two SUBPROCESS shards -> ONE merged trace
    where >=95% of rpc.route spans have exactly one rpc.query child on a
    rebased per-replica track."""
    d0, d1, E = _split_shards(src_dir, tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO),
               SIEVE_SVC_TELEMETRY="1")
    procs, addrs = [], []
    try:
        for d, extra in ((d0, []), (d1, ["--range-lo", str(E)])):
            p = subprocess.Popen(
                [sys.executable, "-m", "sieve", "serve",
                 "--addr", "127.0.0.1:0", "--n", str(N), "--segments", "4",
                 "--packing", "wheel30", "--checkpoint-dir", d,
                 "--refresh-s", "0", "--quiet", *extra],
                env=env, cwd=str(REPO), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
            procs.append(p)
            head = json.loads(p.stdout.readline())
            assert head["event"] == "serving"
            addrs.append(head["addr"])

        trace.enable()
        smap = ShardMap([Shard(2, E, (addrs[0],)),
                         Shard(E, N + 1, (addrs[1],))])
        router = SieveRouter(smap, RouterSettings(quiet=True)).start()
        with ServiceClient(router.addr, timeout_s=30) as cli:
            for i in range(20):  # point routes on both sides of E
                x = (97 * (i + 1)) % N
                assert cli.is_prime(x) == bool(o_count(x, x + 1))
            for i in range(20):  # in-shard windowed counts
                lo = (211 * (i + 1)) % (N - 300)
                if lo < E <= lo + 200:
                    lo = E  # keep the window inside one shard
                assert cli.count(lo, lo + 200) == o_count(lo, lo + 200)
            assert cli.pi(N - 1) == o_pi(N - 1)  # one 2-shard scatter
        router.stop()  # pulls the final telemetry flush from every shard
        stats = router.stats()
        trace.disable()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)

    events = trace.get_tracer().events()
    # ONE trace, one rebased track per shard replica
    tracks = {e["args"]["name"]: e["pid"] for e in events
              if e.get("ph") == "M" and e.get("name") == "process_name"
              and str((e.get("args") or {}).get("name", "")
                      ).startswith("shard")}
    assert len(tracks) == 2
    replica_pids = set(tracks.values())
    routes = [e for e in events if e.get("name") == "rpc.route"]
    assert len(routes) == 41
    kids_by_base = {}
    for q in (e for e in events if e.get("name") == "rpc.query"
              and e.get("pid") in replica_pids):
        base = (q.get("args") or {}).get("ctx", "").rsplit("/", 1)[0]
        kids_by_base.setdefault(base, []).append(q)
    exactly_one = sum(
        1 for r in routes
        if len(kids_by_base.get((r.get("args") or {}).get("ctx"), [])) == 1
    )
    assert exactly_one / len(routes) >= 0.95
    # the merge plane did real work and saw no gaps
    assert stats["telemetry_merged"] >= 2
    assert stats["telemetry_gaps"] == 0
    assert any(e.get("name") == "clock.align" for e in events)


# --- metrics wire op + fleet_top --------------------------------------------


def test_metrics_op_on_server_and_router(src_dir, tmp_path):
    with _Fabric(src_dir, tmp_path) as f:
        assert f.cli.is_prime(101)
        raw = f.cli._call({"type": "metrics"})
        assert raw["ok"] and raw["role"] == "router"
        snap = raw["metrics"]
        assert snap["router.requests"]["type"] == "counter"
        with ServiceClient(f.svcs[0].addr, timeout_s=10) as scli:
            sraw = scli._call({"type": "metrics"})
            assert sraw["role"] == "service"
            assert scli.metrics()["service.requests"]["value"] >= 1
        # histograms with zero observations snapshot None, never 0
        empty = [v for v in snap.values()
                 if v.get("type") == "histogram" and v["count"] == 0]
        assert all(v["mean"] is None for v in empty)


def test_fleet_top_snapshot_schema_and_render(src_dir, tmp_path):
    from tools.fleet_top import fleet_snapshot, render
    with _Fabric(src_dir, tmp_path) as f:
        assert f.cli.is_prime(101)
        assert f.cli.pi(N - 5) == o_pi(N - 5)
        snap = fleet_snapshot(f.router.addr, timeout_s=10)
        assert sorted(snap) == ["router", "shards", "ts"]
        assert snap["router"]["error"] is None
        assert len(snap["shards"]) == 2
        for sh in snap["shards"]:
            assert len(sh["replicas"]) == 1
            rep = sh["replicas"][0]
            assert rep["health"]["status"] in ("ok", "degraded")
            assert "slo" in rep["stats"]
            assert rep["metrics"]["service.requests"]["value"] >= 0
        frame1 = render(snap)
        assert "router" in frame1 and "contiguous" in frame1
        assert frame1.count("s0 ") + frame1.count("s1 ") >= 2
        time.sleep(0.05)
        assert f.cli.is_prime(103)
        snap2 = fleet_snapshot(f.router.addr, timeout_s=10)
        frame2 = render(snap2, prev=snap)
        assert "/s" in frame2  # second frame shows rates, not totals
        # no SLOs configured: burn renders "-", never a fake 0 (the
        # hot-frame column, ISSUE 20, now rides to the right of it)
        hdr = [ln for ln in frame2.splitlines() if "slo burn" in ln][0]
        assert "hot frame" in hdr
        col = hdr.index("slo burn")
        rows = [ln for ln in frame2.splitlines()
                if ln.lstrip().startswith(("s0 ", "s1 "))]
        assert rows and all(
            ln[col:col + len("slo burn")].strip() == "-" for ln in rows)


def test_fleet_top_unreachable_router_renders_error():
    from tools.fleet_top import fleet_snapshot, render
    snap = fleet_snapshot("127.0.0.1:1", timeout_s=0.2)
    assert snap["router"]["health"] is None
    assert "UNREACHABLE" in render(snap)


# --- SLO burn ----------------------------------------------------------------


def test_slo_burn_event_gauges_and_stats(src_dir, tmp_path, memsink):
    d0, _d1, _E = _split_shards(src_dir, tmp_path)
    with SieveService(
        _cfg(d0),
        _settings(slo_ms={"pi": 0.0001, "count": 50.0}, slo_window=8),
    ) as svc, ServiceClient(svc.addr, timeout_s=10) as cli:
        assert cli.pi(1000) == o_pi(1000)
        slo = svc.stats()["slo"]
    # pi burned (no real query finishes in 0.1us); the event is typed
    assert slo["pi"]["burn"] > 1.0 and slo["pi"]["burning"]
    assert slo["pi"]["n"] == 1
    # count never observed: percentile and burn are null, not 0
    assert slo["count"]["p95_ms"] is None and slo["count"]["burn"] is None
    burns = [r for r in memsink.records
             if r.get("event") == "service_slo_burn"]
    assert len(burns) == 1 and burns[0]["op"] == "pi"
    validate_record(burns[0])
    assert burns[0]["slo_ms"] == 0.0001
    assert registry().gauge("service.slo_burn.pi").value > 1.0
    assert registry().gauge("service.slo_burn").value > 1.0


def test_slo_env_parsing(monkeypatch):
    monkeypatch.setenv("SIEVE_SVC_SLO_MS_PI", "5")
    monkeypatch.setenv("SIEVE_SVC_SLO_MS_COUNT", "12.5")
    s = ServiceSettings.from_env()
    assert s.slo_ms == {"pi": 5.0, "count": 12.5}
    monkeypatch.setenv("SIEVE_SVC_SLO_MS_PI", "fast")
    with pytest.raises(ValueError, match="expected a number"):
        ServiceSettings.from_env()


def test_telemetry_batch_env_and_validation(monkeypatch):
    monkeypatch.setenv("SIEVE_SVC_TELEMETRY_BATCH", "64")
    assert ServiceSettings.from_env().telemetry_batch == 64
    with pytest.raises(ValueError, match="telemetry_batch"):
        ServiceSettings(telemetry_batch=0).validate()


# --- trace_report ------------------------------------------------------------


def test_trace_report_malformed_json_named_exit(tmp_path, capsys):
    from tools.trace_report import main
    bad = tmp_path / "trace.json"
    bad.write_text('{"traceEvents": [{"name": "x"')  # truncated
    assert main([str(bad)]) == 1
    err = capsys.readouterr().err
    assert "trace_report: error:" in err
    assert "malformed or truncated" in err


def test_routed_report_guards_and_correlation():
    from tools.trace_report import routed_report
    assert "no rpc.route spans" in routed_report([])
    base = "r1/1.0"
    events = [
        {"name": "process_name", "ph": "M", "pid": 2_000_001,
         "args": {"name": "shard0 127.0.0.1:9"}},
        {"name": "rpc.route", "ph": "X", "ts": 1.0, "dur": 500.0,
         "pid": 1, "args": {"op": "pi", "outcome": "ok", "ctx": base}},
        {"name": "rpc.query", "ph": "X", "ts": 2.0, "dur": 100.0,
         "pid": 2_000_001,
         "args": {"op": "pi", "outcome": "ok", "ctx": f"{base}/s0.1.0"}},
    ]
    out = routed_report(events)
    assert "1/1" in out or "100" in out  # correlated route reported
    assert "shard0" in out
