"""The query service: index tier, admission, degradation, chaos (ISSUE 7).

Covers: Ledger.open_readonly (never flushes, never quarantines, refuses
corruption and foreign configs); seed_primes memoization (bit-exact,
immutable); SieveIndex exactness including hole-dropping; every wire op
against a cpu-numpy oracle over real TCP; typed overloaded /
deadline_exceeded / degraded outcomes (no silent hangs, no wrong
answers); single-flight coalescing; breaker recovery; the service chaos
grammar; EVENT_SCHEMA validation of the service_* events; rpc.query
spans rendered by trace_report; the enumerate flags_fn seam; the
service_smoke tool (including its batched-burst + persisted-restart
phase, ISSUE 9) and the ``serve`` CLI as tier-1 subprocess tests.
The batched cold plane's own unit tests live in tests/test_batch.py.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from sieve import metrics, trace
from sieve.backends.cpu_numpy import sieve_segment_flags
from sieve.chaos import ANY_WORKER, parse_chaos
from sieve.checkpoint import LEDGER_NAME, Ledger, LedgerCorrupt, LedgerMismatch
from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.enumerate import primes_in_range
from sieve.metrics import MemorySink, validate_record
from sieve.seed import _seed_primes_uncached, seed_cache_clear, seed_primes
from sieve.service import (
    QueryCtx,
    ServiceClient,
    ServiceSettings,
    SieveIndex,
    SieveService,
)

REPO = Path(__file__).parent.parent
N = 50_000
ORACLE_HI = 200_000


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


@pytest.fixture(scope="module")
def ledger_dir(tmp_path_factory):
    """A sieved checkpoint dir shared by the service tests (read-only)."""
    path = tmp_path_factory.mktemp("svc_ledger")
    cfg = _cfg(str(path))
    run_local(cfg)
    return path


def _cfg(checkpoint_dir: str, **kw) -> SieveConfig:
    base = dict(
        n=N, backend="cpu-numpy", packing="wheel30", n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw) -> ServiceSettings:
    base = dict(
        workers=2, queue_limit=16, default_deadline_s=10.0,
        cold_chunk=1 << 16, breaker_cooldown_s=0.4,
    )
    base.update(kw)
    return ServiceSettings(**base)


@pytest.fixture
def service(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            yield svc, cli


P = seed_primes(ORACLE_HI)


def o_pi(x):
    return int(np.searchsorted(P, x, side="right"))


def o_count(lo, hi):
    return int(np.searchsorted(P, hi, side="left")
               - np.searchsorted(P, lo, side="left"))


def o_pairs(lo, hi, gap):
    w = P[(P >= lo) & (P < hi)]
    idx = np.searchsorted(w, w + gap)
    ok = idx < w.size
    return int(np.count_nonzero(w[idx[ok]] == w[ok] + gap))


# --- Ledger.open_readonly (satellite a) --------------------------------------


def test_open_readonly_snapshot_never_flushes(ledger_dir):
    path = ledger_dir / LEDGER_NAME
    before = path.read_text()
    led = Ledger.open_readonly(_cfg(str(ledger_dir)))
    assert led.read_only
    assert len(led.completed()) == 4
    with pytest.raises(LedgerMismatch, match="read-only"):
        led.record(next(iter(led.completed().values())))
    assert path.read_text() == before  # byte-identical: nothing rewritten


def test_open_readonly_missing_ledger_is_empty(tmp_path):
    led = Ledger.open_readonly(_cfg(str(tmp_path)))
    assert led.read_only
    assert led.completed() == {}


def test_open_readonly_refuses_corruption_without_quarantine(
    tmp_path, ledger_dir
):
    src = (ledger_dir / LEDGER_NAME).read_text()
    path = tmp_path / LEDGER_NAME
    path.write_text(src[: int(len(src) * 0.6)])  # torn write
    damaged = path.read_text()
    with pytest.raises(LedgerCorrupt, match="read-only|refusing"):
        Ledger.open_readonly(_cfg(str(tmp_path)))
    # unlike Ledger.open: the evidence is untouched, nothing quarantined
    assert path.read_text() == damaged
    assert not os.path.exists(str(path) + ".quarantined")


def test_open_readonly_refuses_foreign_config(ledger_dir):
    with pytest.raises(LedgerMismatch):
        Ledger.open_readonly(_cfg(str(ledger_dir), n=2 * N))


def test_open_readonly_toctou_vanish_reads_empty(
    tmp_path, ledger_dir, monkeypatch
):
    """ISSUE 8 satellite: the file vanishing between ``exists()`` and
    ``read_text()`` (the coordinator's quarantine ``os.replace`` window)
    must read as an empty snapshot, never escape as FileNotFoundError."""
    path = tmp_path / LEDGER_NAME
    path.write_text((ledger_dir / LEDGER_NAME).read_text())
    orig = Path.read_text

    def vanish_then_read(self, *a, **kw):
        if self.name == LEDGER_NAME and self.exists():
            self.unlink()  # quarantined between the stat and the read
        return orig(self, *a, **kw)

    monkeypatch.setattr(Path, "read_text", vanish_then_read)
    led = Ledger.open_readonly(_cfg(str(tmp_path)))
    assert led.read_only
    assert led.completed() == {}  # same as a ledger that never existed


def test_open_readonly_v1_loads_unverified(tmp_path, ledger_dir, memsink):
    """ISSUE 8 satellite: a checksum-less v1 ledger loads, but never
    silently — open_readonly flags it and the service events it."""
    data = json.loads((ledger_dir / LEDGER_NAME).read_text())
    del data["version"], data["checksum"]  # what an old build wrote
    (tmp_path / LEDGER_NAME).write_text(json.dumps(data))
    led = Ledger.open_readonly(_cfg(str(tmp_path)))
    assert led.unverified
    assert led.checksum is not None  # computed, so live-follow still works
    assert len(led.completed()) == 4
    # the fresh v2 ledger is verified — no warning there
    assert not Ledger.open_readonly(_cfg(str(ledger_dir))).unverified
    svc = SieveService(_cfg(str(tmp_path)), _settings())
    try:
        ev = [x for x in memsink.records if x["event"] == "ledger_unverified"]
        assert len(ev) == 1 and ev[0]["path"].endswith(LEDGER_NAME)
        validate_record(ev[0])
        assert svc.index.covered_hi == N + 1  # the v1 entries all served
    finally:
        svc.cold.close()


# --- seed memoization (satellite b) ------------------------------------------


def test_seed_primes_memoized_and_bit_exact():
    seed_cache_clear()
    a = seed_primes(10_000)
    assert seed_primes(10_000) is a  # cache hit returns the same array
    np.testing.assert_array_equal(a, _seed_primes_uncached(10_000))
    np.testing.assert_array_equal(
        seed_primes(9_973), _seed_primes_uncached(9_973)
    )
    assert not a.flags.writeable
    with pytest.raises(ValueError):
        a[0] = 4  # cached arrays are immutable: no cross-caller poisoning


# --- the index tier ----------------------------------------------------------


def test_index_prefix_counts_and_nth_exact(ledger_dir):
    led = Ledger.open_readonly(_cfg(str(ledger_dir)))
    idx = SieveIndex("wheel30", led.completed())
    assert idx.dropped_segments == 0
    assert idx.total_primes == o_pi(idx.covered_hi - 1)
    for v in [2, 3, 100, 12_345] + idx.bounds:
        assert idx.count_upto(v, QueryCtx()) == o_pi(v - 1), v
    for k in (1, 2, 3, 100, idx.total_primes):
        assert idx.nth(k, QueryCtx()) == int(P[k - 1]), k
    # repeat of an interior count is served from the LRU, not re-sieved
    s0 = idx.stats()
    idx.count_upto(12_345, QueryCtx())
    s1 = idx.stats()
    assert s1["lru_hits"] > s0["lru_hits"]
    assert s1["materialized"] == s0["materialized"]


def test_index_drops_segments_after_a_hole(ledger_dir):
    led = Ledger.open_readonly(_cfg(str(ledger_dir)))
    segs = sorted(led.completed().values(), key=lambda r: r.lo)
    holed = [segs[0]] + segs[2:]  # lose segment 1: 2 and 3 are unanchored
    idx = SieveIndex("wheel30", holed)
    assert len(idx.segments) == 1
    assert idx.dropped_segments == 2
    assert idx.covered_hi == segs[0].hi
    with pytest.raises(ValueError, match="beyond covered_hi"):
        idx.count_upto(segs[2].hi, QueryCtx())


# --- wire ops vs oracle ------------------------------------------------------


def test_ops_exact_over_tcp(service):
    svc, cli = service
    covered = svc.index.covered_hi
    assert cli.pi(0) == 0
    assert cli.pi(2) == 1
    assert cli.pi(30_000) == o_pi(30_000)          # hot interior
    assert cli.pi(covered - 1) == o_pi(covered - 1)  # hot boundary
    assert cli.pi(90_000) == o_pi(90_000)          # cold
    assert cli.count(10_000, 40_000) == o_count(10_000, 40_000)
    assert cli.count(40_000, 90_000) == o_count(40_000, 90_000)  # straddle
    assert cli.count(7, 7) == 0
    assert cli.count(2, 40_000, "twins") == o_pairs(2, 40_000, 2)
    assert cli.count(2, 40_000, "cousins") == o_pairs(2, 40_000, 4)
    assert cli.count(45_000, 55_000, "twins") == o_pairs(45_000, 55_000, 2)
    assert cli.nth_prime(1) == 2
    assert cli.nth_prime(1000) == int(P[999])
    beyond = svc.index.total_primes + 50
    assert cli.nth_prime(beyond) == int(P[beyond - 1])
    want = P[(P >= 49_990) & (P < 50_050)]
    assert cli.primes(49_990, 50_050) == [int(v) for v in want]


def test_bad_requests_are_typed(service):
    _, cli = service
    for msg in [
        {"op": "pi", "x": "nope"},
        {"op": "pi", "x": True},
        {"op": "count", "lo": 9, "hi": 4},
        {"op": "count", "lo": 2, "hi": 9, "kind": "sexy"},
        {"op": "nth_prime", "k": 0},
        {"op": "frobnicate"},
    ]:
        r = cli.query(**msg)
        assert not r.get("ok"), msg
        assert r["error"] == "bad_request", (msg, r)


def test_repeated_hot_query_is_an_index_hit(service):
    svc, cli = service
    want = o_pi(30_000)
    assert cli.pi(30_000) == want  # may materialize the chunk once
    s0 = cli.stats()
    for _ in range(3):
        assert cli.pi(30_000) == want
    s1 = cli.stats()
    assert s1["index_hits"] - s0["index_hits"] >= 3
    assert s1["cold_computes"] == s0["cold_computes"]
    assert s1["materialized"] == s0["materialized"]


# --- admission: shed + deadline ----------------------------------------------


def test_queue_saturation_sheds_typed_never_hangs(ledger_dir):
    settings = _settings(workers=1, queue_limit=1, cold_delay_s=0.4)
    with SieveService(_cfg(str(ledger_dir)), settings) as svc:
        replies = []
        lock = threading.Lock()

        def fire():
            with ServiceClient(svc.addr, timeout_s=30) as c:
                r = c.query("pi", x=90_000)
                with lock:
                    replies.append(r)

        threads = [threading.Thread(target=fire) for _ in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not any(t.is_alive() for t in threads), "silent hang"
        assert len(replies) == 5
        shed = [r for r in replies if not r.get("ok")]
        assert shed, "queue_limit=1 with 5 concurrent colds never shed"
        for r in shed:
            assert r["error"] == "overloaded"
            assert "detail" in r
        for r in replies:
            if r.get("ok"):
                assert r["value"] == o_pi(90_000)


def test_injected_shed_and_stall_deadline(service, memsink):
    svc, cli = service
    svc.inject_chaos(f"svc_shed:any@s{svc._seq + 1}")
    r = cli.query("pi", x=100)
    assert r["error"] == "overloaded"
    assert "svc_shed" in r["detail"]
    # a stall past the request deadline: typed deadline_exceeded with the
    # partial prefix answered so far — not a hang, not a wrong answer
    svc.inject_chaos(f"svc_stall:any@s{svc._seq + 1}:0.6")
    r = cli.query("pi", deadline_s=0.2, x=30_000)
    assert r["error"] == "deadline_exceeded"
    assert isinstance(r["partial"], dict)
    assert r["partial"]["answered_hi"] >= 2
    # a stall shorter than the deadline: the answer is still exact
    svc.inject_chaos(f"svc_stall:any@s{svc._seq + 1}:0.05")
    assert cli.pi(30_000, deadline_s=5.0) == o_pi(30_000)
    shed = [x for x in memsink.records if x["event"] == "service_shed"]
    assert shed and shed[0]["op"] == "pi"
    for x in memsink.records:
        validate_record(x)


# --- cold tier: coalescing + degradation -------------------------------------


def test_overlapping_cold_queries_coalesce(ledger_dir):
    settings = _settings(workers=4, cold_delay_s=0.3)
    with SieveService(_cfg(str(ledger_dir)), settings) as svc:
        got, errs = [], []

        def q():
            try:
                with ServiceClient(svc.addr, timeout_s=30) as c:
                    got.append(c.pi(90_000))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        t1, t2 = threading.Thread(target=q), threading.Thread(target=q)
        t1.start()
        time.sleep(0.1)  # inside the leader's simulated 0.3 s compute
        t2.start()
        t1.join(30)
        t2.join(30)
        assert not errs
        assert got == [o_pi(90_000)] * 2
        with ServiceClient(svc.addr) as cli:
            s = cli.stats()
            assert s["coalesced"] >= 1
            # the leader's results are cached: a repeat is answered
            # without another backend call
            c0 = s["cold_computes"]
            assert cli.pi(90_000) == o_pi(90_000)
            s2 = cli.stats()
            assert s2["cold_computes"] == c0
            assert s2["cold_cache_hits"] > s["cold_cache_hits"]


def test_backend_down_keeps_hot_index_up(service, memsink):
    svc, cli = service
    svc.inject_chaos(f"backend_down:any@s{svc._seq + 1}:0.6")
    r = cli.query("pi", x=90_000)  # needs a fresh cold chunk
    assert r["error"] == "degraded"
    assert cli.health()["status"] == "degraded"
    assert cli.pi(30_000) == o_pi(30_000)  # hot tier unaffected, exact
    deadline = time.monotonic() + 10
    while cli.health()["status"] != "ok":
        assert time.monotonic() < deadline, "never recovered"
        time.sleep(0.05)
    assert cli.pi(90_000) == o_pi(90_000)  # cold tier healed, exact
    deg = [x for x in memsink.records if x["event"] == "service_degraded"]
    assert [d["entering"] for d in deg] == [True, False]
    for d in deg:
        validate_record(d)


def test_breaker_opens_after_fail_streak(ledger_dir):
    settings = _settings(breaker_fails=2, breaker_cooldown_s=0.3)
    with SieveService(_cfg(str(ledger_dir)), settings) as svc:
        calls = []

        def boom(lo, hi, seeds, seg_id=0):
            calls.append(lo)
            raise RuntimeError("backend on fire")

        svc.cold._worker = type(
            "W", (), {"process_segment": staticmethod(boom),
                      "close": staticmethod(lambda: None)}
        )()
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            assert cli.query("pi", x=90_000)["error"] == "degraded"
            assert cli.query("pi", x=90_000)["error"] == "degraded"
            n = len(calls)
            # breaker is open: the next cold query fails fast without
            # touching the broken backend; hot queries still exact
            r = cli.query("pi", x=90_000)
            assert r["error"] == "degraded"
            assert "breaker" in r["detail"]
            assert len(calls) == n
            assert cli.pi(30_000) == o_pi(30_000)


# --- chaos grammar for the service kinds -------------------------------------


def test_parse_service_chaos_kinds():
    ds = parse_chaos(
        "svc_stall:any@s3:0.5,svc_shed:any@s4,backend_down:any@s2:1.5"
    )
    assert [(d.kind, d.worker, d.seg_id, d.param) for d in ds] == [
        ("svc_stall", ANY_WORKER, 3, 0.5),
        ("svc_shed", ANY_WORKER, 4, None),
        ("backend_down", ANY_WORKER, 2, 1.5),
    ]
    assert parse_chaos("svc_stall:any@s1")[0].param == 1.0
    assert parse_chaos("backend_down:any@s1")[0].param == 1.0
    with pytest.raises(ValueError, match="svc_shed takes no param"):
        parse_chaos("svc_shed:any@s1:2.0")


def test_cluster_ignores_service_kinds(ledger_dir):
    # a service directive in a cluster run must parse (one schedule, two
    # planes) and simply never fire worker-side
    cfg = _cfg(str(ledger_dir), chaos="svc_stall:any@s1:9")
    assert [d.kind for d in cfg.chaos_directives()] == ["svc_stall"]


# --- observability: events + spans + report ----------------------------------


def test_service_events_validate_and_spans_render(service, memsink):
    svc, cli = service
    tr = trace.get_tracer()
    tr.enable()
    try:
        assert cli.pi(30_000) == o_pi(30_000)
        assert cli.pi(90_000) == o_pi(90_000)  # forces a query.cold span
        cli.query("pi", x="bad")
    finally:
        tr.disable()
    reqs = [x for x in memsink.records if x["event"] == "service_request"]
    assert len(reqs) == 3
    assert {r["outcome"] for r in reqs} == {"ok", "bad_request"}
    assert {r["source"] for r in reqs} >= {"index"}
    for x in memsink.records:
        validate_record(x)

    from tools.trace_report import report, service_report

    spans = [e for e in tr.events() if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    assert {"rpc.query", "query.queue_wait", "query.cold"} <= names
    text = "\n".join(service_report(spans))
    assert "query service" in text
    assert "queue-wait" in text and "cold compute" in text
    assert "index" in text
    assert "query service" in report(spans)  # wired into the full report


def test_flags_fn_seam_matches_local_sieve(ledger_dir):
    # the exact seam the service uses: bounds + flags_fn, with one slice
    # fed from a precomputed bitset and the rest falling back to None
    led = Ledger.open_readonly(_cfg(str(ledger_dir)))
    idx = SieveIndex("wheel30", led.completed())
    seg = idx.segments[1]
    pre = sieve_segment_flags(
        "wheel30", seg.lo, seg.hi, seed_primes(300)
    )
    served = []

    def flags_fn(slo, shi):
        if (slo, shi) == (seg.lo, seg.hi):
            served.append((slo, shi))
            return pre
        return None

    got = np.concatenate(list(primes_in_range(
        "wheel30", 2, idx.covered_hi, bounds=idx.bounds, flags_fn=flags_fn
    )))
    want = np.concatenate(list(primes_in_range("wheel30", 2, idx.covered_hi)))
    np.testing.assert_array_equal(got, want)
    assert served == [(seg.lo, seg.hi)]  # the seam was actually exercised


# --- subprocess gates: smoke tool + serve CLI --------------------------------


def test_service_smoke_tool(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "service_smoke.py"),
         "--keep", str(tmp_path / "work")],
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "SERVICE_SMOKE_OK" in proc.stdout


def test_failover_smoke_tool(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "failover_smoke.py"),
         "--keep", str(tmp_path / "work")],
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "FAILOVER_SMOKE_OK" in proc.stdout


def test_serve_cli_end_to_end(ledger_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.Popen(
        [sys.executable, "-m", "sieve", "serve",
         "--addr", "127.0.0.1:0", "--n", str(N), "--segments", "4",
         "--packing", "wheel30", "--checkpoint-dir", str(ledger_dir),
         "--quiet"],
        env=env, cwd=str(REPO), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    try:
        line = proc.stdout.readline()
        head = json.loads(line)
        assert head["event"] == "serving"
        assert head["segments"] == 4
        with ServiceClient(head["addr"], timeout_s=30) as cli:
            assert cli.pi(30_000) == o_pi(30_000)
            assert cli.health()["status"] == "ok"
            r = cli.query("count", lo=9, hi=4)
            assert r["error"] == "bad_request"
    finally:
        proc.send_signal(signal.SIGINT)
        out, err = proc.communicate(timeout=30)
    assert proc.returncode == 0, (out, err)
