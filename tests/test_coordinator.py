"""Integration: coordinator + cpu-numpy backend against the oracle table,
all packings, with twins and cross-boundary fix-ups (SURVEY.md section 4.2
items 2-3)."""

import numpy as np
import pytest

from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.seed import seed_primes, twin_reference
from tests.oracles import PI, TWINS

PACKINGS = ["plain", "odds", "wheel30"]


@pytest.mark.parametrize("packing", PACKINGS)
@pytest.mark.parametrize("n_segments", [1, 7, 64])
def test_pi_1e5(packing, n_segments):
    cfg = SieveConfig(n=10**5, packing=packing, n_segments=n_segments, twins=True, quiet=True)
    res = run_local(cfg)
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]


@pytest.mark.parametrize("packing", PACKINGS)
def test_pi_1e6(packing):
    cfg = SieveConfig(n=10**6, packing=packing, n_segments=32, twins=True, quiet=True)
    res = run_local(cfg)
    assert res.pi == PI[10**6]
    assert res.twin_pairs == TWINS[10**6]


def test_pi_1e7_config1():
    # driver config 1: single-process sieve to N=1e7
    cfg = SieveConfig(n=10**7, packing="odds", n_segments=16, twins=True, quiet=True)
    res = run_local(cfg)
    assert res.pi == PI[10**7]
    assert res.twin_pairs == TWINS[10**7]


@pytest.mark.parametrize("packing", PACKINGS)
@pytest.mark.parametrize("n", [100, 101, 102, 103, 120, 7, 5, 4, 3, 2, 29, 30, 31])
def test_exact_small_n(packing, n):
    cfg = SieveConfig(n=n, packing=packing, n_segments=3, twins=True, quiet=True)
    res = run_local(cfg)
    assert res.pi == seed_primes(n).size
    assert res.twin_pairs == twin_reference(n)


@pytest.mark.parametrize("packing", PACKINGS)
def test_boundary_twin_straddle(packing):
    """Force segment boundaries that split twin pairs (SURVEY 4.2 fixtures)."""
    # twins around 101,103 and 107,109 and 137,139: use many tiny segments so
    # some boundary almost surely splits a pair; verify exactness regardless.
    for n_segments in [2, 3, 5, 11, 23, 60]:
        cfg = SieveConfig(n=1000, packing=packing, n_segments=n_segments, twins=True, quiet=True)
        res = run_local(cfg)
        assert res.pi == 168
        assert res.twin_pairs == twin_reference(1000), n_segments


def test_segment_results_idempotent():
    cfg = SieveConfig(n=10**4, packing="odds", n_segments=4, quiet=True)
    r1 = run_local(cfg)
    r2 = run_local(cfg)
    for a, b in zip(r1.segments, r2.segments):
        a_d, b_d = a.to_dict(), b.to_dict()
        a_d.pop("elapsed_s"), b_d.pop("elapsed_s")
        assert a_d == b_d


def test_merge_rejects_gaps():
    from sieve.coordinator import merge_results

    cfg = SieveConfig(n=10**4, packing="odds", n_segments=4, quiet=True)
    res = run_local(cfg)
    with pytest.raises(ValueError):
        merge_results(cfg, res.segments[1:])
