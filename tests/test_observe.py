"""Capacity observatory (ISSUE 19): tail-sampled exemplar retention
(100% typed-error keep, rolling-p95 slow tail, deterministic healthy
baseline, ring + rolling-file sinks), the CRC'd on-disk
:class:`SnapshotRing` (torn-tail trim, compaction cap, racing-reader
tolerance), :func:`derive_signals` (counter deltas are 0.0 on the first
sample — never fabricated), the gap-aware EWMA + robust z-score anomaly
engine (warmup arming, edge-triggered fleet_anomaly with bundle pull,
scrape gaps disarm and never alarm), scaling advisories, the
``svc_scrape_gap`` chaos grammar, ObserverSettings validation/env
plumbing, EVENT_SCHEMA coverage of the five new events, and
tools/observe_smoke.py as the tier-1 subprocess acceptance gate
(2-shard fleet, injected regression -> exactly one anomaly, zero false
alarms across the gap window, exemplar files hold the stalled span
trees).
"""

import json
import os
import struct
import subprocess
import sys
import zlib
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from sieve.chaos import (  # noqa: E402
    DEFAULT_PARAM,
    KINDS,
    OBSERVER_KINDS,
    ChaosSchedule,
    parse_chaos,
)
from sieve.metrics import EVENT_SCHEMA, validate_record  # noqa: E402
from sieve.service.exemplar import (  # noqa: E402
    EXEMPLAR_FILE,
    ExemplarSampler,
    load_exemplars,
)
from sieve.service.observe import (  # noqa: E402
    ANOMALY_SIGNALS,
    RING_FILE,
    FleetObserver,
    ObserverSettings,
    SnapshotRing,
    derive_signals,
    read_ring,
)

_REC_HEADER = struct.Struct("<III")
_REC_MAGIC = 0x53524E47


# --- exemplar sampler --------------------------------------------------------


def test_sampler_keeps_every_typed_error():
    s = ExemplarSampler("service", baseline=10**9)
    for outcome in ("deadline_exceeded", "overloaded", "degraded",
                    "draining", "internal", "unavailable"):
        assert s.decide(outcome, 0.1) == "error"


def test_sampler_flagged_keeps_healthy_outcome():
    s = ExemplarSampler("service", baseline=10**9)
    s.decide("ok", 1.0)  # burn the first-request baseline
    assert s.decide("ok", 1.0, flagged=True) == "flagged"
    assert s.decide("ok", 1.0) is None


def test_sampler_baseline_is_deterministic_one_in_n():
    s = ExemplarSampler("service", baseline=5, warmup=10**9)
    reasons = [s.decide("ok", 1.0) for _ in range(20)]
    assert [i for i, r in enumerate(reasons) if r == "baseline"] == \
        [0, 5, 10, 15]


def test_sampler_slow_rule_arms_after_warmup_only():
    # a cold window has no percentile: even an outlier is dropped
    cold = ExemplarSampler("service", slack=2.0, warmup=10,
                           baseline=10**9)
    cold.decide("ok", 1.0)  # request 1 is always the baseline exemplar
    assert cold.decide("ok", 100.0) is None  # 1 obs < warmup: not armed
    # armed after warmup healthy observations; p95 from obs BEFORE the
    # request under decision, so it cannot excuse itself
    s = ExemplarSampler("service", slack=2.0, warmup=10, baseline=10**9)
    assert s.decide("ok", 1.0) == "baseline"
    for _ in range(9):
        assert s.decide("ok", 1.0) is None
    assert s.decide("ok", 100.0) == "slow"  # p95 ~1.0, 100 > 1.0 * 2


def test_sampler_error_storm_does_not_move_the_slow_threshold():
    s = ExemplarSampler("service", slack=2.0, warmup=5, baseline=10**9)
    for _ in range(6):
        s.decide("ok", 1.0)
    for _ in range(50):  # a deadline storm of huge latencies, all errors
        assert s.decide("deadline_exceeded", 5000.0) == "error"
    assert s.decide("ok", 3.0) == "slow"  # p95 still ~1.0 from healthy obs


def test_sampler_keep_ring_file_and_rotation(tmp_path):
    s = ExemplarSampler("router", ring=2, file_bytes=200,
                        debug_dir=str(tmp_path))
    for i in range(5):
        s.keep({"ctx": f"run/{i}.0", "op": "pi", "outcome": "ok",
                "ms": 1.0, "reason": "baseline", "spans": []})
    assert [r["ctx"] for r in s.tail()] == ["run/3.0", "run/4.0"]
    assert s.tail(ctx_prefix="run/4") == [s.tail()[-1]]
    assert s.tail()[0]["role"] == "router"
    # file_bytes=200 < two records: every append rotates, so exactly
    # one generation of history survives next to the live file
    # (appends run on the sampler's writer thread — drain it first)
    s.flush()
    live = load_exemplars(str(tmp_path / EXEMPLAR_FILE))
    rotated = load_exemplars(str(tmp_path / (EXEMPLAR_FILE + ".1")))
    assert [r["ctx"] for r in live] == ["run/4.0"]
    assert [r["ctx"] for r in rotated] == ["run/3.0"]
    st = s.stats()
    assert (st["kept"], st["ring"]) == (5, 2)


def test_load_exemplars_skips_torn_tail(tmp_path):
    p = tmp_path / EXEMPLAR_FILE
    p.write_text(json.dumps({"ctx": "a"}) + "\n" + '{"ctx": "tor')
    assert [r["ctx"] for r in load_exemplars(str(p))] == ["a"]


# --- the on-disk snapshot ring -----------------------------------------------


def _ring_path(tmp_path):
    return str(tmp_path / RING_FILE)


def test_ring_append_read_roundtrip(tmp_path):
    ring = SnapshotRing(_ring_path(tmp_path))
    for i in range(7):
        ring.append({"scrape": i})
    assert [r["scrape"] for r in read_ring(_ring_path(tmp_path))] == \
        list(range(7))
    assert ring.records(2) == [{"scrape": 5}, {"scrape": 6}]


def test_ring_reader_stops_at_torn_tail_and_open_trims_it(tmp_path):
    path = _ring_path(tmp_path)
    ring = SnapshotRing(path)
    ring.append({"scrape": 1})
    ring.append({"scrape": 2})
    with open(path, "ab") as f:
        f.write(_REC_HEADER.pack(_REC_MAGIC, 500, 0) + b"short")
    # a concurrent reader never crashes on the half-written tail
    assert [r["scrape"] for r in read_ring(path)] == [1, 2]
    reopened = SnapshotRing(path)  # crash-restart trims the torn frame
    assert reopened.torn == 1
    assert [r["scrape"] for r in read_ring(path)] == [1, 2]
    reopened.append({"scrape": 3})
    assert [r["scrape"] for r in read_ring(path)] == [1, 2, 3]


def test_ring_reader_stops_at_bad_crc(tmp_path):
    path = _ring_path(tmp_path)
    ring = SnapshotRing(path)
    ring.append({"scrape": 1})
    payload = json.dumps({"scrape": 2}).encode()
    with open(path, "ab") as f:
        f.write(_REC_HEADER.pack(_REC_MAGIC, len(payload),
                                 zlib.crc32(payload) ^ 0xFF) + payload)
    assert [r["scrape"] for r in read_ring(path)] == [1]


def test_ring_compaction_keeps_newest_under_half_cap(tmp_path):
    path = _ring_path(tmp_path)
    ring = SnapshotRing(path, cap_bytes=2048)
    for i in range(100):
        ring.append({"scrape": i, "pad": "x" * 40})
    assert ring.compactions >= 1
    assert os.path.getsize(path) <= 2048
    recs = read_ring(path)
    assert recs  # newest survive, oldest are gone, order preserved
    assert [r["scrape"] for r in recs] == \
        list(range(100 - len(recs), 100))


# --- signal derivation -------------------------------------------------------


def test_derive_signals_first_sample_is_never_fabricated():
    sig = derive_signals(
        "service", {"covered_hi": 1000},
        {"hot_admitted": 500, "queue_depth": 3}, None, None)
    assert sig["hot_qps"] == 0.0  # a trend needs two points
    assert sig["covered_rate"] == 0.0
    assert sig["lane_depth"] == 3.0  # instantaneous reads are fine


def test_derive_signals_service_deltas_over_dt():
    prev = {"hot_admitted": 100, "cold_admitted": 10, "shed": 0,
            "lane_shed_hot": 0, "lane_shed_cold": 2,
            "deadline_exceeded": 1, "internal_errors": 0,
            "degraded_replies": 0, "_covered_hi": 1000}
    cur = {"hot_admitted": 150, "cold_admitted": 20, "shed": 4,
           "lane_shed_hot": 1, "lane_shed_cold": 3,
           "deadline_exceeded": 3, "internal_errors": 1,
           "degraded_replies": 0, "queue_depth": 7,
           "store": {"hits": 30, "misses": 10},
           "slo": {"hot": {"burn": 0.25}, "cold": {"burn": 1.5}}}
    sig = derive_signals("service", {"covered_hi": 3000}, cur, prev, 2.0)
    assert sig["hot_qps"] == 25.0
    assert sig["cold_qps"] == 5.0
    assert sig["shed_rate"] == pytest.approx(3.0)  # (4+1+3)-(0+0+2) over 2s
    assert sig["err_rate"] == pytest.approx(1.5)
    assert sig["lane_depth"] == 7.0
    assert sig["slo_burn"] == 1.5  # worst lane
    assert sig["store_hit"] == 0.75
    assert sig["covered_rate"] == pytest.approx(1000.0)


def test_derive_signals_router_uses_router_counters():
    prev = {"requests": 10, "shed_relayed": 0, "deadline_exceeded": 0,
            "internal_errors": 0, "shard_errors": 0,
            "unavailable_replies": 0}
    cur = {"requests": 30, "shed_relayed": 4, "deadline_exceeded": 1,
           "internal_errors": 0, "shard_errors": 1,
           "unavailable_replies": 2}
    sig = derive_signals("router", {}, cur, prev, 2.0)
    assert sig["hot_qps"] == 10.0
    assert sig["shed_rate"] == 2.0
    assert sig["err_rate"] == 2.0


# --- the anomaly engine (faked fleet, manual clock) --------------------------


class _FakeClient:
    """Programmable health/stats endpoint standing in for a live RPC."""

    def __init__(self):
        self.health_doc = {"covered_hi": 0}
        self.stats_doc = {}
        self.debug_calls = 0

    def health(self):
        return dict(self.health_doc)

    def stats(self):
        return dict(self.stats_doc)

    def debug(self):
        self.debug_calls += 1
        return {"recorder": "state"}


class _FakePool:
    def __init__(self, clients):
        self.clients = clients

    def get(self, addr):
        cli = self.clients[addr]
        if isinstance(cli, Exception):
            raise cli
        return cli

    def invalidate(self, addr):
        pass

    def close(self):
        pass


def _observer(tmp_path, monkeypatch, clients, *, chaos=None, **over):
    """A FleetObserver over faked endpoints with a hand-cranked clock."""
    clock = {"t": 1000.0}
    monkeypatch.setattr("time.time", lambda: clock["t"])
    knobs = dict(warmup=3, min_delta=2.0, z_threshold=6.0, alpha=0.3,
                 cooldown_s=1e9, observe_dir=str(tmp_path), quiet=True)
    knobs.update(over)
    obs = FleetObserver("r:0", ObserverSettings(**knobs), chaos=chaos)
    obs.pool = _FakePool(clients)
    targets = [
        {"role": "router" if a == "r:0" else "shard", "addr": a,
         "shard": None if a == "r:0" else i - 1}
        for i, a in enumerate(clients)
    ]
    monkeypatch.setattr(obs, "_discover", lambda: list(targets))

    def tick(dt=1.0):
        clock["t"] += dt
        return obs.scrape_once()

    return obs, tick


def test_anomaly_requires_warmup_then_edge_triggers_once(
        tmp_path, monkeypatch):
    # an immediate spike on a COLD endpoint must not alarm (not armed)
    cold_svc = _FakeClient()
    cold_svc.stats_doc = {"queue_depth": 80}
    cold, cold_tick = _observer(tmp_path / "cold", monkeypatch,
                                {"r:0": _FakeClient(), "s:0": cold_svc})
    assert cold_tick()["anomalies"] == []
    assert cold.stats()["anomalies"] == 0
    # a calm warmup then the same spike: exactly one fleet_anomaly
    svc = _FakeClient()
    svc.stats_doc = {"queue_depth": 0}
    obs, tick = _observer(tmp_path / "armed", monkeypatch,
                          {"r:0": _FakeClient(), "s:0": svc})
    for _ in range(6):  # settle: warmup consecutive calm samples
        assert tick()["anomalies"] == []
    svc.stats_doc = {"queue_depth": 50}  # lane_depth excursion, dev ~0
    snap = tick()
    assert obs.stats()["anomalies"] == 1
    [evid] = [a for a in snap["anomalies"] if a["signal"] == "lane_depth"]
    assert evid["addr"] == "s:0" and evid["value"] == 50.0
    assert evid["z"] > 6.0
    # edge trigger: the breach persisting does not re-fire in cooldown
    tick()
    assert obs.stats()["anomalies"] == 1
    # the ring row carries the full evidence for fleet_top/postmortems
    rows = read_ring(str(tmp_path / "armed" / RING_FILE))
    assert rows[-2]["anomalies"][0]["signal"] == "lane_depth"


def test_anomaly_fires_fleet_wide_bundle_pull(tmp_path, monkeypatch):
    router, svc = _FakeClient(), _FakeClient()
    obs, tick = _observer(tmp_path, monkeypatch,
                          {"r:0": router, "s:0": svc})
    for _ in range(6):
        tick()
    svc.stats_doc = {"queue_depth": 50}
    tick()
    assert obs.stats()["anomalies"] == 1
    bundles = [f for f in os.listdir(tmp_path)
               if f.startswith("anomaly_")]
    assert len(bundles) == 1
    doc = json.loads((tmp_path / bundles[0]).read_text())
    assert doc["trigger"] == "fleet_anomaly"
    assert {p["addr"] for p in doc["processes"]} == {"r:0", "s:0"}
    assert all(p["bundle"] == {"recorder": "state"}
               for p in doc["processes"])
    assert router.debug_calls == 1 and svc.debug_calls == 1


def test_scrape_gap_counts_disarms_and_never_false_alarms(
        tmp_path, monkeypatch):
    svc = _FakeClient()
    chaos = ChaosSchedule(parse_chaos("svc_scrape_gap:any@s4"))
    obs, tick = _observer(tmp_path, monkeypatch,
                          {"r:0": _FakeClient(), "s:0": svc},
                          chaos=chaos)
    svc.stats_doc = {"hot_admitted": 0, "queue_depth": 0}
    for _ in range(3):
        tick()
    snap = tick()  # scrape 4: the chaos draw eats the router poll
    assert obs.stats()["gaps"] == 1
    gap_rows = [t for t in snap["targets"] if t["gap"]]
    assert [t["addr"] for t in gap_rows] == ["r:0"]
    assert gap_rows[0]["gap"] == "svc_scrape_gap"
    assert "signals" not in gap_rows[0]  # a gap is never a sample
    # the sample right after the gap re-seeds the baseline: even a huge
    # counter jump on the gapped endpoint cannot alarm
    obs.pool.clients["r:0"].stats_doc = {"requests": 10**7}
    for _ in range(3):  # within warmup after the reset
        assert tick()["anomalies"] == []
    assert obs.stats()["anomalies"] == 0


def test_unreachable_endpoint_is_a_named_gap_not_a_sample(
        tmp_path, monkeypatch):
    obs, tick = _observer(
        tmp_path, monkeypatch,
        {"r:0": _FakeClient(), "s:0": ConnectionRefusedError("down")})
    snap = tick()
    [row] = [t for t in snap["targets"] if t["addr"] == "s:0"]
    assert row["gap"] == "ConnectionRefusedError"
    assert obs.stats()["gaps"] == 1
    assert snap["anomalies"] == []


def test_scaling_advice_add_replica_on_sustained_shed(
        tmp_path, monkeypatch):
    svc0, svc1 = _FakeClient(), _FakeClient()
    obs, tick = _observer(tmp_path, monkeypatch,
                          {"r:0": _FakeClient(), "s:0": svc0,
                           "s:1": svc1},
                          z_threshold=1e9)  # isolate the advice path
    shed = {"hot_admitted": 0, "shed": 0}
    for i in range(8):  # sustained shedding on shard 0 only
        shed = {"hot_admitted": shed["hot_admitted"] + 10,
                "shed": shed["shed"] + 5}
        svc0.stats_doc = shed
        svc1.stats_doc = {"hot_admitted": (i + 1) * 10}
        snap = tick()
    advice = [a for a in snap["advice"] if a["advice"] == "add_replica"]
    assert advice == [] or advice[0]["shard"] == 0
    all_advice = [a for row in read_ring(str(tmp_path / RING_FILE))
                  for a in row["advice"]]
    fired = [a for a in all_advice if a["advice"] == "add_replica"]
    assert len(fired) == 1  # edge-triggered: once per cooldown window
    assert fired[0]["shard"] == 0 and fired[0]["shed_rate"] > 0.5


def test_observer_stats_shape(tmp_path, monkeypatch):
    obs, tick = _observer(tmp_path, monkeypatch, {"r:0": _FakeClient()})
    tick()
    st = obs.stats()
    assert st["scrapes"] == 1 and st["endpoints"] == 1
    assert st["ring"]["appended"] == 1


# --- chaos grammar -----------------------------------------------------------


def test_svc_scrape_gap_is_a_first_class_chaos_kind():
    assert "svc_scrape_gap" in KINDS
    assert OBSERVER_KINDS == ("svc_scrape_gap",)
    assert DEFAULT_PARAM["svc_scrape_gap"] is None
    [d] = parse_chaos("svc_scrape_gap:any@s7")
    assert (d.kind, d.seg_id) == ("svc_scrape_gap", 7)
    sched = ChaosSchedule([d])
    assert sched.take_kinds(0, 6, OBSERVER_KINDS) == []
    [hit] = sched.take_kinds(2, 7, OBSERVER_KINDS)  # any worker matches
    assert hit["kind"] == "svc_scrape_gap"
    assert sched.take_kinds(2, 7, OBSERVER_KINDS) == []  # one-shot


# --- settings ----------------------------------------------------------------


def test_observer_settings_validate_rejects_bad_knobs():
    good = ObserverSettings()
    assert good.validate() is good
    import dataclasses as dc
    for bad in (
        {"scrape_s": 0}, {"scrape_s": -1.0}, {"timeout_s": 0},
        {"cooldown_s": -1.0}, {"ring_bytes": 0}, {"ring_bytes": 1.5},
        {"warmup": -1}, {"warmup": 2.5}, {"alpha": 0.0},
        {"alpha": 1.5}, {"z_threshold": -1.0}, {"min_delta": -0.1},
        {"observe_dir": 42},
    ):
        with pytest.raises(ValueError):
            dc.replace(good, **bad).validate()


def test_observer_settings_from_env(monkeypatch):
    monkeypatch.setenv("SIEVE_OBSERVE_SCRAPE_S", "0.25")
    monkeypatch.setenv("SIEVE_OBSERVE_Z", "9.5")
    monkeypatch.setenv("SIEVE_OBSERVE_WARMUP", "4")
    monkeypatch.setenv("SIEVE_OBSERVE_RING_BYTES", "65536")
    s = ObserverSettings.from_env(observe_dir="/tmp/x")
    assert (s.scrape_s, s.z_threshold, s.warmup, s.ring_bytes) == \
        (0.25, 9.5, 4, 65536)
    assert s.observe_dir == "/tmp/x"  # explicit override beats env


# --- event schema ------------------------------------------------------------


def test_new_observatory_events_are_in_the_schema():
    for kind in ("service_exemplar_kept", "observer_scrape_gap",
                 "fleet_anomaly", "scaling_advice", "observer_error"):
        assert kind in EVENT_SCHEMA


def test_observatory_event_records_validate():
    validate_record({
        "event": "fleet_anomaly", "ts": 1.0, "addr": "h:1",
        "signal": "err_rate", "value": 5.0, "mean": 0.0, "dev": 0.0,
        "z": 1e6, "scrape": 9, "bundle": None,
    })
    validate_record({
        "event": "observer_scrape_gap", "ts": 1.0, "addr": "h:1",
        "scrape": 5, "gap": "svc_scrape_gap",
    })
    validate_record({
        "event": "scaling_advice", "ts": 1.0, "advice": "split",
        "shard": 0, "qps": 10.0, "shed_rate": 0.0, "share": 0.7,
        "scrape": 4,
    })
    with pytest.raises(ValueError):
        validate_record({"event": "fleet_anomaly", "ts": 1.0})


# --- the subprocess acceptance gate ------------------------------------------


def test_observe_smoke_tool(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "observe_smoke.py"),
         "--keep", str(tmp_path / "work")],
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "OBSERVE_SMOKE_OK" in proc.stdout
