"""Ground-truth oracle table, from BASELINE.md (computed 2026-07-29 by two
independent implementations that agreed exactly; NOT copied from the
reference — see SURVEY.md section 4.1)."""

PI = {
    10**5: 9_592,
    10**6: 78_498,
    10**7: 664_579,
    10**8: 5_761_455,
    10**9: 50_847_534,
    10**10: 455_052_511,
    10**11: 4_118_054_813,
    10**12: 37_607_912_018,
}

# twin pairs (p, p+2) with p+2 <= N
TWINS = {
    10**5: 1_224,
    10**6: 8_169,
    10**7: 58_980,
    10**8: 440_312,
    10**9: 3_424_506,
    10**10: 27_412_679,
}
