"""Observability tests: span tracer, metrics registry/sinks/schema, and
the --trace / --metrics-file round trip (ROADMAP: every phase visible).

The in-memory sink is the schema oracle: each path (local run, mesh
dry-run, cluster failure injection) must emit records that satisfy
metrics.EVENT_SCHEMA, quiet or not.
"""

import io
import json
import threading
import time

import pytest

from sieve import metrics, trace
from sieve.config import SieveConfig
from sieve.metrics import MemorySink, MetricsLogger, validate_record
from tests.oracles import PI, TWINS
from tools.trace_report import load_events, phase_breakdown, report


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


# --- tracer ------------------------------------------------------------------


def test_span_aggregation_without_capture():
    tr = trace.Tracer()
    with tr.span("phase.a"):
        pass
    with tr.span("phase.a"):
        pass
    tr.add_span("phase.b", trace.now_s(), 0.25)
    agg = tr.snapshot()
    assert agg["phase.a"][1] == 2
    assert agg["phase.b"] == (pytest.approx(0.25), 1)
    assert tr.events() == []  # capture off: aggregation only


def test_span_elapsed_and_nesting_export():
    tr = trace.Tracer()
    tr.enable()
    with tr.span("outer", round=0) as outer:
        with tr.span("inner") as inner:
            time.sleep(0.01)
    tr.disable()
    assert inner.elapsed <= outer.elapsed
    assert outer.elapsed >= 0.01

    buf = io.StringIO()
    tr.save(buf)
    doc = json.loads(buf.getvalue())
    assert isinstance(doc["traceEvents"], list)
    spans = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    # Chrome trace-event contract: microsecond ts/dur, pid/tid present
    for e in spans.values():
        assert {"ts", "dur", "pid", "tid"} <= e.keys()
    # nesting: inner's interval sits inside outer's
    o, i = spans["outer"], spans["inner"]
    assert o["ts"] <= i["ts"]
    assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e-3
    assert o["args"] == {"round": 0}


def test_spans_from_threads_get_own_tracks():
    tr = trace.Tracer()
    tr.enable()

    def work():
        with tr.span("thread.work"):
            pass

    t = threading.Thread(target=work, name="producer-0")
    with tr.span("main.work"):
        t.start()
        t.join()
    tr.disable()
    events = tr.events()
    spans = [e for e in events if e["ph"] == "X"]
    tids = {e["name"]: e["tid"] for e in spans}
    assert tids["thread.work"] != tids["main.work"]
    names = {
        e["tid"]: e["args"]["name"]
        for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert names[tids["thread.work"]] == "producer-0"


def test_snapshot_since_diff():
    tr = trace.Tracer()
    tr.add_span("x", trace.now_s(), 1.0)
    snap = tr.snapshot()
    tr.add_span("x", trace.now_s(), 2.0)
    tr.add_span("y", trace.now_s(), 0.5)
    delta = tr.since(snap)
    assert delta["x"] == (pytest.approx(2.0), 1)
    assert delta["y"] == (pytest.approx(0.5), 1)
    assert tr.total_s("x", snap) == pytest.approx(2.0)


def test_enable_starts_fresh_capture_session():
    tr = trace.Tracer()
    tr.enable()
    with tr.span("old"):
        pass
    tr.disable()
    tr.enable()  # a new --trace session must not replay old events
    with tr.span("new"):
        pass
    tr.disable()
    names = [e["name"] for e in tr.events() if e["ph"] == "X"]
    assert names == ["new"]
    assert tr.snapshot()["old"][1] == 1  # totals survive across sessions


def test_instants_and_counters_gated_by_enable():
    tr = trace.Tracer()
    tr.instant("hb", worker=1)
    tr.counter("inflight", 3)
    assert tr.events() == []
    tr.enable()
    tr.instant("hb", worker=1)
    tr.counter("inflight", 3)
    tr.disable()
    phases = sorted(e["ph"] for e in tr.events())
    assert phases == ["C", "i"]


def test_disabled_tracer_overhead_negligible():
    # satellite: the instrumented hot path must cost <2% when --trace is
    # off. Measure the per-span cost (capture disabled) and compare it,
    # times the spans-per-segment the backends actually emit (~2), to a
    # real cpu-numpy segment's marking time.
    from sieve.backends.cpu_numpy import CpuNumpyWorker
    from sieve.seed import seed_primes

    tr = trace.Tracer()
    assert not tr.enabled

    def batch_cost(k=500):
        t0 = time.perf_counter()
        for _ in range(k):
            with tr.span("bench.noop"):
                pass
        return (time.perf_counter() - t0) / k

    per_span = min(batch_cost() for _ in range(5))

    n = 10**6
    cfg = SieveConfig(n=n, backend="cpu-numpy", quiet=True)
    worker = CpuNumpyWorker(cfg)
    seeds = seed_primes(1000)
    seg_s = min(
        worker.process_segment(2, n + 1, seeds).elapsed_s for _ in range(3)
    )
    # generous: 4 spans per segment, against a 2% budget
    assert 4 * per_span < 0.02 * seg_s, (
        f"span overhead {per_span * 1e6:.2f}us x4 not negligible vs "
        f"{seg_s * 1e3:.2f}ms segment"
    )


# --- registry instruments ----------------------------------------------------


def test_registry_instruments():
    reg = metrics.MetricsRegistry()
    c = reg.counter("done")
    c.inc()
    c.inc(4)
    g = reg.gauge("lag")
    g.set(1.5)
    g.max(0.5)  # running max keeps 1.5
    g.max(2.5)
    h = reg.histogram("ms")
    for v in (1.0, 3.0, 2.0):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["done"] == {"type": "counter", "value": 5}
    assert snap["lag"] == {"type": "gauge", "value": 2.5}
    assert snap["ms"] == {
        "type": "histogram", "count": 3, "sum": 6.0,
        "min": 1.0, "max": 3.0, "mean": 2.0,
        # reservoir percentiles (ISSUE 13): nearest-rank over all 3 obs
        "p50": 2.0, "p95": 3.0, "p99": 3.0,
    }
    assert reg.counter("done") is c  # same name -> same instrument
    with pytest.raises(TypeError):
        reg.gauge("done")  # kind conflict
    json.dumps(snap)  # snapshot is JSON-able by contract


# --- event schema / sinks ----------------------------------------------------


def test_validate_record_rejects_bad_records():
    with pytest.raises(ValueError, match="event"):
        validate_record({"ts": 0.0})
    with pytest.raises(ValueError, match="ts"):
        validate_record({"event": "run"})
    with pytest.raises(ValueError, match="missing keys"):
        validate_record({"event": "segment", "ts": 0.0, "id": 1})


def test_quiet_gates_only_segment_console_lines(memsink):
    from sieve.worker import SegmentResult

    out = io.StringIO()
    cfg = SieveConfig(n=10**5, quiet=True)
    log = MetricsLogger(cfg, stream=out)
    seg = SegmentResult(
        seg_id=0, lo=2, hi=10**5 + 1, count=PI[10**5], twin_count=0,
        first_word=0, last_word=0, nbits=0, elapsed_s=0.001,
    )
    log.segment(seg)
    log.event("worker_failed", worker=0, reason="killed",
              run_id="deadbeef", ctx=None)
    console = [json.loads(line) for line in out.getvalue().splitlines()]
    # quiet console: robustness event yes, per-segment line no
    assert [r["event"] for r in console] == ["worker_failed"]
    # the sink still gets everything
    assert [r["event"] for r in memsink.records] == [
        "segment", "worker_failed",
    ]
    for r in memsink.records:
        validate_record(r)


def test_sink_ts_monotonic_on_trace_epoch(memsink):
    log = MetricsLogger(SieveConfig(n=10**5, quiet=True))
    before = trace.now_s()
    log.event("resume", restored=0)
    log.event("resume", restored=1)
    ts = [r["ts"] for r in memsink.records]
    assert ts == sorted(ts)
    # ts is rounded to 1e-4, so allow that much slack at the edges
    assert before - 1e-3 <= ts[0] <= trace.now_s() + 1e-3


def test_schema_local_run(memsink):
    from sieve.coordinator import run_local

    cfg = SieveConfig(
        n=10**5, backend="cpu-numpy", n_segments=4, twins=True, quiet=True
    )
    res = run_local(cfg)
    assert res.pi == PI[10**5]
    kinds = [r["event"] for r in memsink.records]
    assert kinds.count("segment") == 4
    assert kinds[-1] == "run"
    for r in memsink.records:
        validate_record(r)
    run = memsink.records[-1]
    assert run["pi"] == PI[10**5]
    assert run["twins"] == TWINS[10**5]


# --- mesh --------------------------------------------------------------------


def _n_devices():
    import jax

    try:
        return len(jax.devices("cpu"))
    except RuntimeError:
        return 0


needs_mesh = pytest.mark.skipif(
    _n_devices() < 8, reason="needs the 8-device virtual CPU mesh"
)


@needs_mesh
def test_schema_mesh_dryrun(memsink):
    from sieve.parallel.mesh import run_mesh

    cfg = SieveConfig(
        n=10**6, backend="jax", workers=8, rounds=2, twins=True, quiet=True
    )
    res = run_mesh(cfg)
    assert res.pi == PI[10**6]
    for r in memsink.records:
        validate_record(r)
    kinds = [r["event"] for r in memsink.records]
    assert "host_prepare" in kinds and "run" in kinds
    prep = next(r for r in memsink.records if r["event"] == "host_prepare")
    for key in ("prep_s", "prep_wait_s", "stack_s", "dispatch_s", "drain_s"):
        assert key in prep, f"host_prepare missing {key}"


@needs_mesh
def test_mesh_host_phases_match_trace_spans(tmp_path):
    # acceptance: span sums in the exported trace reproduce host_phases
    from sieve.parallel.mesh import run_mesh

    tr = trace.get_tracer()
    cfg = SieveConfig(
        n=10**6, backend="jax", workers=8, rounds=2, twins=True, quiet=True
    )
    tr.enable()
    try:
        res = run_mesh(cfg)
    finally:
        tr.disable()
    path = tmp_path / "mesh.trace.json"
    tr.save(str(path))
    sums = {
        name: a["total_us"] / 1e6
        for name, a in phase_breakdown(load_events(str(path))).items()
    }
    hp = res.host_phases
    for key, span_name in {
        "prep_s": "prep.round",
        "prep_wait_s": "round.prep_wait",
        "stack_s": "round.stack",
        "dispatch_s": "round.dispatch",
        "drain_s": "round.drain",
        "device_idle_s": "round.device_idle",
    }.items():
        assert sums.get(span_name, 0.0) == pytest.approx(
            hp[key], rel=0.01, abs=1e-4
        ), f"{key} != sum({span_name})"


# --- cluster -----------------------------------------------------------------


def test_schema_cluster_failure_injection(memsink):
    from sieve.cluster import run_cluster

    reg = metrics.registry()
    failures0 = reg.counter("cluster.worker_failures").value
    reassigned0 = reg.counter("cluster.reassigned").value
    cfg = SieveConfig(
        n=10**5, backend="cpu-cluster", workers=2, n_segments=8,
        twins=True, quiet=True, coordinator_addr="127.0.0.1:0",
        chaos_kill="any@2",  # deterministic: whoever draws seg 2 dies
    )
    res = run_cluster(cfg)
    assert res.pi == PI[10**5]
    for r in memsink.records:
        validate_record(r)
    kinds = [r["event"] for r in memsink.records]
    # robustness events must flow even under --quiet
    assert "worker_failed" in kinds
    assert "reassign" in kinds
    assert kinds[-1] == "run"
    assert reg.counter("cluster.worker_failures").value > failures0
    assert reg.counter("cluster.reassigned").value > reassigned0
    snap = reg.snapshot()
    # per-RPC histogram fed by every completed assignment; heartbeats
    # only appear for segments slower than HEARTBEAT_S, so not asserted
    assert snap["cluster.rpc_ms"]["count"] > 0


# --- CLI / trace file round trip --------------------------------------------


def test_cli_trace_and_metrics_file_smoke(tmp_path, capsys):
    from sieve.cli import main

    trace_path = tmp_path / "run.trace.json"
    metrics_path = tmp_path / "run.metrics.jsonl"
    rc = main([
        "--n", "1e5", "--backend", "cpu-numpy", "--segments", "4",
        "--twins", "--quiet", "--json",
        "--trace", str(trace_path), "--metrics-file", str(metrics_path),
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["pi"] == PI[10**5]

    # trace file: valid trace-event JSON that trace_report round-trips
    doc = json.loads(trace_path.read_text())
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    spans = load_events(str(trace_path))
    assert {"segment.mark", "run.merge"} <= {e["name"] for e in spans}
    text = report(spans)
    assert "per-phase breakdown" in text
    assert "segment.mark" in text

    # metrics file: JSONL, schema-valid, includes the quiet-suppressed
    # per-segment records
    records = [
        json.loads(line) for line in metrics_path.read_text().splitlines()
    ]
    for r in records:
        validate_record(r)
    kinds = [r["event"] for r in records]
    assert kinds.count("segment") == 4
    assert kinds[-1] == "run"

    # the global tracer is switched back off after the run
    assert not trace.enabled()


def test_trace_report_cli(tmp_path, capsys):
    from tools.trace_report import main

    tr = trace.Tracer()
    tr.enable()
    with tr.span("round.device_idle", round=0):
        time.sleep(0.002)
    with tr.span("round.dispatch", round=0):
        pass
    tr.disable()
    path = tmp_path / "t.json"
    tr.save(str(path))
    assert main([str(path), "--top", "3"]) == 0
    out = capsys.readouterr().out
    assert "device-idle timeline" in out
    assert "round.dispatch" in out
