"""The always-on continuous profiler (ISSUE 20).

Covers: StackProfiler lifecycle (start/stop idempotence, hz=0 no-op,
hz validation), deterministic sampling via ``sample_once`` (role + span
tagging, idle-leaf filtering, bounded fold table with drop-coldest
eviction), the merge / collapse / self-time / share-diff math shared by
the fleet tools, the ``profile`` wire op on BOTH serving tiers, the
FlightRecorder bundle embed (+ ``profile_captured`` event), the
observer's anomaly-pull profile rows, the ``svc_prof_gap`` chaos kind
(grammar, K-th-reply drop, sampler pause, heal on the next pull),
EVENT_SCHEMA honesty, the check_wire_ops profile pin,
``trace_report --bundle`` rendering of embedded profiles, and
tools/profile_smoke.py as the tier-1 subprocess acceptance gate
(2-shard fleet under load -> one merged collapsed capture >= 90%
role-tagged; injected ``svc_stall`` burn -> ``fleet_profile --diff``
names ``server._handle`` top positive delta).
"""

import json
import os
import subprocess
import sys
import threading
import time
import types
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from sieve import metrics, trace  # noqa: E402
from sieve.chaos import (  # noqa: E402
    DEFAULT_PARAM,
    KINDS,
    PROFILE_KINDS,
    ChaosSchedule,
    parse_chaos,
)
from sieve.config import SieveConfig  # noqa: E402
from sieve.coordinator import run_local  # noqa: E402
from sieve.debug import FlightRecorder  # noqa: E402
from sieve.metrics import EVENT_SCHEMA, MemorySink, validate_record  # noqa: E402
from sieve.profile import (  # noqa: E402
    DEFAULT_HZ,
    PROFILE_VERSION,
    StackProfiler,
    collapse_lines,
    diff_shares,
    merge_stacks,
    role_tagged_fraction,
    self_times,
    thread_label,
    thread_role,
)
from sieve.service import (  # noqa: E402
    RouterSettings,
    ServiceClient,
    ServiceSettings,
    Shard,
    ShardMap,
    SieveRouter,
    SieveService,
)
from sieve.service.client import CallTimeout  # noqa: E402
from sieve.service.observe import FleetObserver, ObserverSettings  # noqa: E402

N = 50_000


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


@pytest.fixture(scope="module")
def ledger_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("prof_ledger")
    run_local(_cfg(str(path)))
    return path


def _cfg(checkpoint_dir, **kw):
    base = dict(
        n=N, backend="cpu-numpy", packing="wheel30", n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw):
    base = dict(
        workers=2, queue_limit=16, default_deadline_s=10.0,
        breaker_cooldown_s=0.4, prof_hz=101.0,
    )
    base.update(kw)
    return ServiceSettings(**base)


def _spin(evt, label=None):
    """Busy-wait target with an optional open span — a deterministic
    non-idle leaf for sample_once to observe."""
    if label is not None:
        with trace.span(label):
            while not evt.is_set():
                pass
    else:
        while not evt.is_set():
            pass


def _spinner(name, label=None):
    evt = threading.Event()
    t = threading.Thread(target=_spin, args=(evt, label),
                         name=name, daemon=True)
    t.start()
    return evt, t


# --- role / label classification ---------------------------------------------


def test_thread_role_covers_the_fleet_thread_classes():
    assert thread_role("svc-wire") == "loop"
    assert thread_role("router-accept") == "loop"
    assert thread_role("router-conn") == "loop"
    assert thread_role("svc-worker-hot-3") == "worker"
    assert thread_role("exemplar-writer") == "writer"
    assert thread_role("svc-batcher") == "writer"
    assert thread_role("prof-sampler-service") == "sampler"
    assert thread_role("sieve-observer") == "sampler"
    assert thread_role("MainThread") == "main"
    assert thread_role("Thread-7") is None


def test_thread_label_strips_instance_suffix_only():
    assert thread_label("svc-worker-hot-0") == "svc-worker-hot"
    assert thread_label("svc-worker-hot-12") == "svc-worker-hot"
    assert thread_label("svc-wire") == "svc-wire"
    assert thread_label("wheel30") == "wheel30"  # no dash: untouched


# --- sampler lifecycle -------------------------------------------------------


def test_start_stop_idempotent_and_table_survives_stop():
    p = StackProfiler("t", hz=200.0)
    assert p.start() is p and p.start() is p
    assert p.running
    evt, t = _spinner("svc-worker-hot-0")
    try:
        deadline = time.time() + 5
        while p.stats()["samples"] == 0 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        evt.set()
        t.join()
    p.stop()
    p.stop()
    assert not p.running
    snap = p.snapshot()  # the fold table stays readable after stop
    assert snap["profile"] == PROFILE_VERSION and snap["samples"] > 0


def test_hz_zero_is_a_no_op_and_negative_rejected():
    p = StackProfiler("t", hz=0)
    assert p.start() is p
    assert not p.running
    assert p.snapshot()["samples"] == 0
    with pytest.raises(ValueError):
        StackProfiler("t", hz=-1)
    with pytest.raises(ValueError):
        StackProfiler("t", hz=True)


def test_pause_skips_beats_and_counts():
    p = StackProfiler("t", hz=0)
    p.pause(2)
    p.pause(1)  # max-merge, never additive
    assert p.stats()["pauses"] == 2
    assert p._paused_beats == 2


# --- deterministic sampling --------------------------------------------------


def test_sample_once_tags_role_and_active_span():
    p = StackProfiler("t", hz=0)
    evt, t = _spinner("svc-worker-hot-0", label="rpc.test")
    try:
        deadline = time.time() + 5
        hits = []
        while not hits and time.time() < deadline:
            p.sample_once()
            hits = [r for r in p.snapshot()["stacks"]
                    if r["stack"].startswith("svc-worker-hot;rpc.test;")]
    finally:
        evt.set()
        t.join()
    assert hits, p.snapshot()["stacks"]
    row = hits[0]
    assert row["role"] == "worker"
    assert "test_profile._spin" in row["stack"]  # the busy frame is on it


def test_sample_once_skips_idle_leaves_by_default():
    p = StackProfiler("t", hz=0)
    pi = StackProfiler("t", hz=0, include_idle=True)
    evt = threading.Event()
    t = threading.Thread(target=evt.wait, name="svc-worker-hot-0",
                         daemon=True)
    t.start()
    try:
        deadline = time.time() + 5
        idle_rows = []
        while not idle_rows and time.time() < deadline:
            p.sample_once()
            pi.sample_once()
            idle_rows = [r for r in pi.snapshot()["stacks"]
                         if r["stack"].startswith("svc-worker-hot;idle;")]
    finally:
        evt.set()
        t.join()
    assert idle_rows  # include_idle keeps the park, tagged idle
    assert not [r for r in p.snapshot()["stacks"]
                if r["stack"].startswith("svc-worker-hot;")]


def test_bounded_table_drops_coldest_on_overflow():
    p = StackProfiler("t", hz=0, max_stacks=2)
    with p._lock:
        p._table["a;hot"] = [9, None]
        p._table["b;warm"] = [3, None]
        p._table["c;cold"] = [1, None]
        while len(p._table) >= p.max_stacks:
            p._evict_coldest_locked()
        p._table["d;new"] = [1, None]
    snap = p.snapshot()
    keys = {r["stack"] for r in snap["stacks"]}
    assert keys == {"a;hot", "d;new"}  # coldest two were evicted
    assert snap["evicted"] == 2


def test_live_eviction_under_many_distinct_stacks():
    p = StackProfiler("t", hz=0, max_stacks=1)
    spinners = [_spinner(f"svc-worker-hot-{i}", label=f"span{i}")
                for i in range(3)]
    try:
        deadline = time.time() + 5
        while p.stats()["evicted"] == 0 and time.time() < deadline:
            p.sample_once()
    finally:
        for evt, t in spinners:
            evt.set()
        for evt, t in spinners:
            t.join()
    st = p.stats()
    assert st["stacks"] <= 1 and st["evicted"] > 0


def test_sampler_never_samples_its_own_thread():
    p = StackProfiler("t", hz=0)
    for _ in range(5):
        p.sample_once()
    me = [r for r in p.snapshot()["stacks"]
          if "sample_once" in r["stack"]]
    assert me == []


# --- merge / report math -----------------------------------------------------


def _doc(stacks):
    return {"profile": PROFILE_VERSION,
            "stacks": [{"stack": s, "count": c, "role": r}
                       for s, c, r in stacks]}


def test_merge_collapse_and_role_fraction():
    merged = merge_stacks([
        ("shard0", _doc([("svc-wire;a.f", 6, "loop"),
                         ("svc-worker;b.g", 3, "worker")])),
        ("shard0.r1", _doc([("svc-wire;a.f", 2, "loop")])),
        ("router", _doc([("Thread-1;c.h", 1, None)])),
    ])
    assert merged["shard0;svc-wire;a.f"] == {"count": 6, "role": "loop"}
    assert merged["shard0.r1;svc-wire;a.f"]["count"] == 2
    lines = collapse_lines(merged)
    assert lines[0] == "shard0;svc-wire;a.f 6"  # hottest first
    assert all(ln.rsplit(" ", 1)[1].isdigit() for ln in lines)
    assert role_tagged_fraction(merged) == pytest.approx(11 / 12)
    assert role_tagged_fraction({}) == 0.0


def test_self_times_counts_leaves_only():
    merged = merge_stacks([("p", _doc([
        ("w;a.f;b.g", 6, "loop"),   # leaf b.g
        ("w;b.g", 4, "loop"),       # leaf b.g again -> folds
        ("w;b.g;a.f", 2, "loop"),   # a.f as leaf only here
    ]))])
    rows = self_times(merged)
    assert rows[0] == {"frame": "b.g", "self": 10,
                       "share": pytest.approx(10 / 12)}
    assert rows[1]["frame"] == "a.f" and rows[1]["self"] == 2
    assert self_times(merged, n=1) == rows[:1]


def test_diff_shares_orders_most_positive_first():
    old = merge_stacks([("p", _doc([("w;a.f", 8, None),
                                    ("w;b.g", 2, None)]))])
    new = merge_stacks([("p", _doc([("w;a.f", 2, None),
                                    ("w;b.g", 8, None)]))])
    rows = diff_shares(old, new)
    assert rows[0]["frame"] == "b.g"
    assert rows[0]["delta"] == pytest.approx(0.6)
    assert rows[-1]["frame"] == "a.f"
    assert rows[-1]["delta"] == pytest.approx(-0.6)
    # a frame only in one capture diffs against zero
    rows = diff_shares(old, merge_stacks([("p", _doc([("w;c.h", 5,
                                                       None)]))]))
    assert rows[0] == {"frame": "c.h", "before": 0.0, "after": 1.0,
                       "delta": pytest.approx(1.0)}


# --- the profile wire op, both tiers -----------------------------------------


def test_profile_wire_op_on_the_server(ledger_dir, memsink):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            cli.query("pi", x=1000)
            doc = cli.profile()
            assert doc["profile"] == PROFILE_VERSION
            assert doc["role"] == "service"
            assert doc["hz"] == 101.0
            assert doc["pid"] == os.getpid()
            st = cli.stats()
            assert st["profile_pulls"] == 1
            assert st["profile_gaps"] == 0
    kinds = [r["event"] for r in memsink.records if "event" in r]
    assert "profile_pulled" in kinds


def test_profile_disabled_service_returns_none_profile(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(prof_hz=0.0)) as svc:
        assert svc.profiler is None
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            assert cli.profile() is None
            assert cli.stats()["profile_pulls"] == 1


def test_profile_wire_op_on_the_router(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        smap = ShardMap([Shard(2, N + 1, (svc.addr,))])
        router = SieveRouter(smap, RouterSettings(
            quiet=True, prof_hz=101.0)).start()
        try:
            with ServiceClient(router.addr, timeout_s=30) as cli:
                for _ in range(8):
                    cli.query("pi", x=1000)
                deadline = time.time() + 5
                doc = cli.profile()
                while doc["samples"] == 0 and time.time() < deadline:
                    time.sleep(0.05)
                    doc = cli.profile()
                assert doc["profile"] == PROFILE_VERSION
                assert doc["role"] == "router"
                assert doc["samples"] > 0
                assert cli.stats()["profile_pulls"] >= 1
        finally:
            router.stop()


def test_settings_validate_profiler_knobs():
    with pytest.raises(ValueError):
        ServiceSettings(prof_hz=-1.0).validate()
    with pytest.raises(ValueError):
        ServiceSettings(prof_stacks=0).validate()
    with pytest.raises(ValueError):
        RouterSettings(prof_hz=-1.0).validate()
    with pytest.raises(ValueError):
        RouterSettings(prof_stacks=0).validate()


# --- FlightRecorder bundle embed ---------------------------------------------


def test_bundle_embeds_profile_snapshot(tmp_path, memsink):
    p = StackProfiler("service", hz=0)
    evt, t = _spinner("svc-worker-hot-0")
    try:
        deadline = time.time() + 5
        while p.stats()["samples"] == 0 and time.time() < deadline:
            p.sample_once()
    finally:
        evt.set()
        t.join()
    logger = metrics.MetricsLogger(
        types.SimpleNamespace(quiet=True))
    rec = FlightRecorder("service", debug_dir=str(tmp_path),
                         cooldown_s=0.0, profiler=p, logger=logger)
    b = rec.trigger("breaker_open", reason="test")
    prof = b["profile"]
    assert prof["profile"] == PROFILE_VERSION and prof["samples"] > 0
    kinds = [r["event"] for r in memsink.records if "event" in r]
    assert "profile_captured" in kinds
    # without a profiler the key is present and null, never missing
    rec2 = FlightRecorder("service", debug_dir=str(tmp_path / "np"),
                          cooldown_s=0.0)
    assert rec2.trigger("breaker_open", reason="t2")["profile"] is None


# --- observer anomaly pull ---------------------------------------------------


class _FakeClient:
    def __init__(self, profile_exc=None):
        self.profile_exc = profile_exc
        self.profile_calls = 0

    def debug(self):
        return {"recorder": "state"}

    def profile(self):
        self.profile_calls += 1
        if self.profile_exc is not None:
            raise self.profile_exc
        return {"profile": PROFILE_VERSION, "samples": 7,
                "stacks": [{"stack": "w;a.f", "count": 7,
                            "role": "worker"}]}


class _FakePool:
    def __init__(self, clients):
        self.clients = clients

    def get(self, addr):
        return self.clients[addr]

    def invalidate(self, addr):
        pass

    def close(self):
        pass


def test_observer_bundle_pull_carries_profiles(tmp_path, memsink):
    obs = FleetObserver("r:0", ObserverSettings(
        observe_dir=str(tmp_path), quiet=True))
    ok, gapped = _FakeClient(), _FakeClient(profile_exc=CallTimeout("gap"))
    obs.pool = _FakePool({"r:0": ok, "s:0": gapped})
    targets = [{"role": "router", "addr": "r:0", "shard": None},
               {"role": "shard", "addr": "s:0", "shard": 0}]
    path = obs._pull_fleet_bundle(targets, 1)
    doc = json.loads(Path(path).read_text())
    rows = {p["addr"]: p for p in doc["processes"]}
    assert rows["r:0"]["profile"]["samples"] == 7
    assert rows["r:0"]["profile_error"] is None
    # a profile gap never takes the debug half down with it
    assert rows["s:0"]["profile"] is None
    assert rows["s:0"]["profile_error"].startswith("CallTimeout")
    assert rows["s:0"]["bundle"] == {"recorder": "state"}
    pulled = [r for r in memsink.records
              if r.get("event") == "profile_pulled"]
    assert [r["gap"] for r in pulled] == [False, True]
    assert all(r["role"] == "observer" for r in pulled)


# --- svc_prof_gap chaos ------------------------------------------------------


def test_svc_prof_gap_is_a_first_class_chaos_kind():
    assert "svc_prof_gap" in KINDS
    assert PROFILE_KINDS == ("svc_prof_gap",)
    assert DEFAULT_PARAM["svc_prof_gap"] is None
    [d] = parse_chaos("svc_prof_gap:any@s2")
    assert d.kind == "svc_prof_gap" and d.seg_id == 2
    sched = ChaosSchedule(parse_chaos("svc_prof_gap:any@s2"))
    assert sched.take_kinds(0, 1, PROFILE_KINDS) == []
    assert [x["kind"] for x in sched.take_kinds(0, 2, PROFILE_KINDS)] \
        == ["svc_prof_gap"]
    assert sched.take_kinds(0, 2, PROFILE_KINDS) == []  # one-shot


def test_svc_prof_gap_drops_kth_reply_pauses_and_heals(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(wire_chaos=True)) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            assert cli.profile() is not None  # pull 1
            cli.inject_chaos("svc_prof_gap:any@s2")
            with ServiceClient(svc.addr, timeout_s=1.5) as short:
                with pytest.raises(CallTimeout):
                    short.profile()  # pull 2: the reply is dropped
            assert cli.profile() is not None  # pull 3 heals
            st = cli.stats()
            assert st["profile_gaps"] == 1
            assert st["profile_pulls"] == 2
        assert svc.profiler.stats()["pauses"] == 1


# --- schema / checker pins ---------------------------------------------------


def test_event_schema_covers_profile_events():
    assert set(EVENT_SCHEMA["profile_captured"]) == \
        {"role", "samples", "stacks"}
    assert set(EVENT_SCHEMA["profile_pulled"]) == \
        {"role", "samples", "stacks", "gap"}
    validate_record({"event": "profile_pulled", "ts": 0.1,
                     "role": "service", "samples": 5, "stacks": 2,
                     "gap": False})


def test_check_wire_ops_pins_the_profile_op():
    from tools.check_wire_ops import check, harvest
    assert check() == []
    for path in ("sieve/service/server.py", "sieve/service/router.py"):
        _, types = harvest(str(REPO / path))
        assert "profile" in types


def test_lock_order_includes_profiler_leaf():
    from sieve.analysis.model import CANONICAL_LOCK_ORDER
    assert "StackProfiler._lock" in CANONICAL_LOCK_ORDER


# --- trace_report renders the embed ------------------------------------------


def test_trace_report_bundle_renders_profile_top_n(tmp_path, capsys):
    from tools.trace_report import main
    p = StackProfiler("service", hz=0)
    evt, t = _spinner("svc-worker-hot-0", label="rpc.test")
    try:
        # the self-time table names LEAF frames only, and the spinner's
        # sampled leaf alternates between the loop test and is_set —
        # sample until _spin itself is a leaf so the render is stable
        deadline = time.time() + 5
        while time.time() < deadline and not any(
                r["stack"].endswith("test_profile._spin")
                for r in p.snapshot()["stacks"]):
            p.sample_once()
    finally:
        evt.set()
        t.join()
    rec = FlightRecorder("service", debug_dir=str(tmp_path),
                         cooldown_s=0.0, profiler=p)
    b = rec.trigger("breaker_open", reason="test")
    assert main([b["path"], "--bundle"]) == 0
    out = capsys.readouterr().out
    assert "top self-time" in out
    assert "test_profile._spin" in out


# --- overhead smoke ----------------------------------------------------------


def test_profiler_overhead_smoke():
    """The daemon at the default rate must not visibly tax a busy
    thread (the bench gates the real ratio at <= 1.05; this is only a
    sanity bound loose enough for shared CI)."""
    def work():
        t0 = time.perf_counter()
        acc = 0
        for i in range(200_000):
            acc += i * i
        return time.perf_counter() - t0, acc

    base = min(work()[0] for _ in range(3))
    p = StackProfiler("t", hz=DEFAULT_HZ).start()
    try:
        timed = min(work()[0] for _ in range(3))
    finally:
        p.stop()
    assert timed < base * 3 + 0.05  # loose: catches pathology only


# --- the subprocess acceptance gate ------------------------------------------


def test_profile_smoke_tool(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "profile_smoke.py"),
         "--keep", str(tmp_path / "work")],
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PROFILE_SMOKE_OK" in proc.stdout
    assert "role-tagged" in proc.stdout
