"""Multi-host (DCN) mesh test: two real processes, one logical 8-device
mesh via jax.distributed.initialize — SURVEY.md section 5.8's "multi-host
runs the identical program over DCN" claim, executed rather than asserted.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_mesh():
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    worker = Path(__file__).parent / "multihost_worker.py"
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        JAX_PLATFORMS="cpu",
        SIEVE_JAX_PLATFORM="cpu",
    )
    # a TPU-attach sitecustomize (if any) would pre-import jax before the
    # worker can call jax.distributed.initialize; the workers are CPU-only
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = str(worker.parent.parent)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), addr, "2", str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=str(worker.parent.parent),
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"process {i} failed:\n{out}\n{err}"
        assert f"MULTIHOST_OK {i} 9592 1224" in out, (out, err)
