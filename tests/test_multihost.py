"""Multi-host (DCN) mesh test: two real processes, one logical 8-device
mesh via jax.distributed.initialize — SURVEY.md section 5.8's "multi-host
runs the identical program over DCN" claim, executed rather than asserted.

Also the distributed trace plane acceptance test: a 2-worker cpu-cluster
run with externally-launched workers (real process clocks) must merge
into one Chrome-trace timeline with a track per worker, every rpc.assign
correlated to its worker.segment by trace context, >=95% of the rebased
worker spans nesting inside their coordinator span, and no telemetry
dropped by the ship ring.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_mesh():
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    worker = Path(__file__).parent / "multihost_worker.py"
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        JAX_PLATFORMS="cpu",
        SIEVE_JAX_PLATFORM="cpu",
    )
    # a TPU-attach sitecustomize (if any) would pre-import jax before the
    # worker can call jax.distributed.initialize; the workers are CPU-only
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = str(worker.parent.parent)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), addr, "2", str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=str(worker.parent.parent),
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"process {i} failed:\n{out}\n{err}"
        assert f"MULTIHOST_OK {i} 9592 1224" in out, (out, err)


# --- distributed trace plane -------------------------------------------------


def _worker_env() -> dict:
    worker = Path(__file__).parent / "multihost_worker.py"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = str(worker.parent.parent)
    return env


def test_cluster_merged_trace(tmp_path, monkeypatch):
    # external workers (SIEVE_CLUSTER_NO_SPAWN): each worker is a real
    # subprocess with its own perf_counter epoch, so the coordinator's
    # clock alignment has genuine offsets to recover — unlike the
    # spawn-local path this coordinator never forks workers itself
    from sieve import trace
    from sieve.cluster import run_cluster
    from sieve.config import SieveConfig
    from tools.trace_report import cluster_report, load_all

    monkeypatch.setenv("SIEVE_CLUSTER_NO_SPAWN", "1")
    addr = f"127.0.0.1:{_free_port()}"
    worker = Path(__file__).parent / "multihost_worker.py"
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), addr, "cluster", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_worker_env(), cwd=str(worker.parent.parent),
        )
        for i in range(2)
    ]
    tr = trace.get_tracer()
    tr.enable()
    try:
        res = run_cluster(SieveConfig(
            n=10**5, backend="cpu-cluster", workers=2, n_segments=8,
            quiet=True, coordinator_addr=addr,
        ))
    finally:
        tr.disable()
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.communicate(timeout=30)
    assert res.pi == 9_592

    path = tmp_path / "cluster.trace.json"
    tr.save(str(path))
    events = load_all(str(path))

    # one Perfetto process track per worker
    tracks = {
        e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and str(e["args"].get("name", "")).startswith("worker ")
    }
    assert tracks == {"worker 0", "worker 1"}

    # every rpc.assign correlates to the worker.segment of the same
    # attempt via the propagated trace context
    spans = [e for e in events if e.get("ph") == "X"]
    rpc = [e for e in spans if e["name"] == "rpc.assign"]
    seg_by_ctx = {
        e["args"]["ctx"]: e
        for e in spans
        if e["name"] == "worker.segment" and e.get("args", {}).get("ctx")
    }
    assert len(rpc) == 8
    nested = 0
    for r in rpc:
        w = seg_by_ctx.get(r["args"]["ctx"])
        assert w is not None, f"rpc.assign {r['args']} has no worker.segment"
        if (w["ts"] >= r["ts"]
                and w["ts"] + w["dur"] <= r["ts"] + r["dur"]):
            nested += 1
    assert nested >= 0.95 * len(rpc), f"only {nested}/{len(rpc)} nested"

    # telemetry shipping and clock alignment health
    hp = res.host_phases
    assert hp["telemetry_workers"] == 2
    assert hp["telemetry_dropped_events"] == 0
    assert 0 <= hp["clock_err_max_s"] < 1.0
    aligns = [e for e in events if e.get("name") == "clock.align"]
    assert len(aligns) == 2
    for a in aligns:
        assert a["args"]["dropped"] == 0
        # error bound is half the min-RTT (fields rounded independently)
        assert a["args"]["err_s"] == pytest.approx(
            a["args"]["rtt_s"] / 2, abs=2e-6
        )

    # the cluster view renders all required reports from this file
    text = cluster_report(events)
    assert "per-worker utilization" in text
    assert "rpc-wait vs compute" in text
    assert "nested after rebase: 8/8" in text or nested < 8
    assert "max clock-alignment error" in text
    assert "straggler ranking" in text


def test_cluster_cli_trace_merges_and_reports(tmp_path, capsys):
    # the CLI path: --trace on cpu-cluster writes the merged timeline
    # (spawn-local workers) and trace_report --cluster renders it
    from sieve.cli import main as sieve_main
    from tools.trace_report import main as report_main

    path = tmp_path / "cluster.trace.json"
    rc = sieve_main([
        "--n", "1e5", "--backend", "cpu-cluster", "--workers", "2",
        "--segments", "8", "--quiet", "--json",
        "--coordinator-addr", "127.0.0.1:0", "--trace", str(path),
    ])
    assert rc == 0
    captured = capsys.readouterr()
    out = json.loads(captured.out)
    assert out["pi"] == 9_592
    assert out["host_phases"]["telemetry_workers"] == 2
    # no truncation -> no CLI warning about the ship ring
    assert "telemetry truncated" not in captured.err

    assert report_main(["--cluster", str(path)]) == 0
    text = capsys.readouterr().out
    assert "cluster timeline: 2 workers" in text
    assert "per-worker utilization" in text
    assert "max clock-alignment error" in text
