"""Distributed trace-plane unit tests: clock alignment math, the
bounded telemetry ring (drain/drop accounting), event ingestion on the
coordinator side, the worker ship payload, and the bench_compare gate.

The end-to-end merged-timeline acceptance test (real worker
subprocesses, rebased nesting, cluster_report) lives in
tests/test_multihost.py; these pin the pieces it composes.
"""

import json

import pytest

from sieve import trace
from sieve.cluster import _ClockAlign
from sieve.metrics import validate_record
from tools.bench_compare import compare, extract_metrics, find_rounds
from tools.bench_compare import main as bench_main

# --- clock alignment ---------------------------------------------------------


def test_clock_align_recovers_offset_exactly():
    # symmetric link: a worker whose clock reads coordinator + 7.25 s,
    # one-way latency 2 ms each direction -> offset recovered exactly,
    # rtt = 4 ms, err bound = 2 ms
    a = _ClockAlign()
    off, lat = 7.25, 0.002
    t_send = 100.0
    a.sample(t_send, t_send + lat + off, t_send + lat + off + 0.01,
             t_send + 2 * lat + 0.01)
    assert a.offset_s == pytest.approx(off)
    assert a.rtt_s == pytest.approx(2 * lat)
    assert a.err_s == pytest.approx(lat)
    assert a.samples == 1


def test_clock_align_keeps_min_rtt_sample():
    a = _ClockAlign()
    # noisy first sample: 100 ms rtt, asymmetric -> biased offset
    a.sample(0.0, 0.09 + 5.0, 0.09 + 5.0, 0.1)
    biased = a.offset_s
    # clean second sample: 1 ms rtt -> replaces the noisy estimate
    a.sample(10.0, 10.0005 + 5.0, 10.0005 + 5.0, 10.001)
    assert a.rtt_s == pytest.approx(0.001)
    assert a.offset_s == pytest.approx(5.0, abs=1e-3)
    assert a.offset_s != biased
    # a worse sample later must NOT displace the kept estimate
    a.sample(20.0, 20.05 + 5.0, 20.05 + 5.0, 20.1)
    assert a.rtt_s == pytest.approx(0.001)
    assert a.samples == 3


def test_clock_align_equal_rtt_refreshes_for_drift():
    # ties refresh to the newest sample so slow drift is tracked
    # (binary-exact values so both RTTs compare equal)
    a = _ClockAlign()
    a.sample(0.0, 0.25 + 5.0, 0.25 + 5.0, 0.5)
    a.sample(64.0, 64.25 + 5.3125, 64.25 + 5.3125, 64.5)
    assert a.rtt_s == 0.5
    assert a.offset_s == 5.3125


def test_clock_align_no_samples_is_infinite_error():
    a = _ClockAlign()
    assert a.err_s == float("inf")
    assert a.samples == 0


# --- the bounded event ring --------------------------------------------------


def test_ring_drops_oldest_and_counts():
    tr = trace.Tracer()
    tr.set_event_limit(3)
    tr.enable()
    for i in range(6):
        tr.instant("e", i=i)
    tr.disable()
    events, dropped = tr.drain_events()
    kept = [e["args"]["i"] for e in events if e["name"] == "e"]
    assert kept == [3, 4, 5]  # oldest evicted first
    assert dropped == 3
    assert tr.dropped == 3
    # drain empties the buffer but keeps the cumulative drop counter
    assert tr.drain_events() == ([], 3)


def test_ring_never_evicts_metadata():
    # "M" records (process/thread names) are required to render every
    # later span; the ring must only evict payload events
    tr = trace.Tracer()
    tr.enable()
    with tr.span("first"):
        pass
    tr.set_event_limit(2)
    for i in range(10):
        tr.instant("e", i=i)
    tr.disable()
    events = tr.events()
    mphases = [e for e in events if e["ph"] == "M"]
    assert mphases, "metadata records were evicted"
    non_m = [e for e in events if e["ph"] != "M"]
    assert len(non_m) <= 2


def test_ring_disabled_by_default():
    tr = trace.Tracer()
    tr.enable()
    for i in range(10_000):
        tr.instant("e", i=i)
    tr.disable()
    assert tr.dropped == 0
    assert len(tr.events()) == 10_000


def test_ingest_folds_durations_and_appends():
    tr = trace.Tracer()
    shipped = [
        {"name": "worker.segment", "ph": "X", "ts": 1000.0, "dur": 2000.0,
         "pid": 1, "tid": 1, "args": {}},
        {"name": "worker.segment", "ph": "X", "ts": 5000.0, "dur": 1000.0,
         "pid": 1, "tid": 1, "args": {}},
        {"name": "hb", "ph": "i", "ts": 1.0, "pid": 1, "tid": 1, "s": "t"},
    ]
    tr.ingest(shipped)  # capture off: totals only
    assert tr.snapshot()["worker.segment"] == (pytest.approx(0.003), 2)
    assert tr.events() == []
    tr.enable()
    tr.ingest(shipped)
    tr.disable()
    assert len(tr.events()) == 3


# --- the worker ship payload -------------------------------------------------


def test_telemetry_payload_shape(monkeypatch):
    from sieve.worker import telemetry_payload, telemetry_ring_size

    monkeypatch.setenv("SIEVE_TELEMETRY_RING", "128")
    assert telemetry_ring_size() == 128
    tr = trace.get_tracer()
    tr.enable()
    try:
        with tr.span("worker.segment", seg=0):
            pass
    finally:
        tr.disable()
    payload = telemetry_payload(worker_id=3)
    assert payload["worker_id"] == 3
    assert payload["dropped"] == 0
    assert isinstance(payload["registry"], dict)
    names = [e["name"] for e in payload["events"] if e.get("ph") == "X"]
    assert "worker.segment" in names
    json.dumps(payload)  # must survive the JSON wire format
    # drained: a second ship carries no stale events
    assert telemetry_payload(worker_id=3)["events"] == []


def test_telemetry_ring_env_zero_disables(monkeypatch):
    from sieve.worker import telemetry_start

    monkeypatch.setenv("SIEVE_TELEMETRY_RING", "0")
    assert telemetry_start() is False


# --- new event kinds ---------------------------------------------------------


def test_schema_new_kinds_validate():
    validate_record({
        "event": "worker_failed", "ts": 0.0, "worker": 1,
        "reason": "killed", "run_id": "ab12cd34", "ctx": "ab12cd34/3.0",
    })
    validate_record({
        "event": "reassign", "ts": 0.0, "seg_id": 3,
        "run_id": "ab12cd34", "ctx": "ab12cd34/3.0",
    })
    validate_record({
        "event": "worker_telemetry", "ts": 0.0, "worker": 0,
        "events": 17, "dropped": 0,
    })
    with pytest.raises(ValueError, match="missing keys"):
        validate_record({
            "event": "worker_failed", "ts": 0.0, "worker": 1,
            "reason": "killed",  # run_id/ctx now part of the contract
        })


# --- bench_compare -----------------------------------------------------------


def _bench_doc(value: float, rc: int = 0) -> str:
    line = json.dumps({
        "metric": "sieve_throughput", "value": value,
        "unit": "values/s/chip", "vs_baseline": 1.0,
    })
    return json.dumps({
        "n": 1, "cmd": "bench", "rc": rc,
        "tail": f"warmup noise\n{line}\n",
        "parsed": json.loads(line),
    })


def test_bench_compare_rounds_sorted_by_suffix_not_mtime(tmp_path):
    # r10 written before r09: numeric suffix wins over mtime
    (tmp_path / "BENCH_r10.json").write_text(_bench_doc(200.0))
    (tmp_path / "BENCH_r09.json").write_text(_bench_doc(100.0))
    rounds = find_rounds(str(tmp_path), "BENCH")
    assert [r for r, _ in rounds] == [9, 10]


def test_bench_compare_ok_and_regression(tmp_path, capsys):
    (tmp_path / "BENCH_r01.json").write_text(_bench_doc(100.0))
    (tmp_path / "BENCH_r02.json").write_text(_bench_doc(95.0))
    assert bench_main(["--dir", str(tmp_path)]) == 0  # -5% within gate
    (tmp_path / "BENCH_r03.json").write_text(_bench_doc(80.0))
    assert bench_main(["--dir", str(tmp_path)]) == 1  # -15.8% fails
    assert "REGRESSION" in capsys.readouterr().out
    # a looser threshold admits the same delta
    assert bench_main(["--dir", str(tmp_path), "--threshold", "0.2"]) == 0


def test_bench_compare_newest_round_rc_failure(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(_bench_doc(100.0))
    (tmp_path / "BENCH_r02.json").write_text(_bench_doc(100.0, rc=2))
    assert bench_main(["--dir", str(tmp_path)]) == 1


def test_bench_compare_single_round_is_not_a_failure(tmp_path, capsys):
    (tmp_path / "BENCH_r01.json").write_text(_bench_doc(100.0))
    assert bench_main(["--dir", str(tmp_path)]) == 0
    assert "need 2 to compare" in capsys.readouterr().out


def test_bench_compare_metric_disappearance_fails():
    old = extract_metrics(json.loads(_bench_doc(100.0)))
    lines, regressions = compare(old, {}, threshold=0.10)
    assert regressions and "disappeared" in regressions[0]
    assert any("GONE" in line for line in lines)
