"""Clean fixture: consistent lock order, guarded state, no blocking
from the loop role."""

import threading


class W:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0  # guard: _a
        self.m = 0  # guard: _b

    def start(self):
        threading.Thread(target=self._run, name="w-1").start()

    def _run(self):
        while True:
            self.step()

    def step(self):
        with self._a:
            self.n += 1
            with self._b:
                self.m += 1

    def peek(self):
        with self._a:
            return self.n
