"""Seeded defect: a ``# guard:``-annotated attribute touched without
its lock from a function two roles reach."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # guard: _lock

    def start(self):
        threading.Thread(target=self._run, name="mut-1").start()

    def _run(self):
        while True:
            self.bump()

    def bump(self):
        self.count += 1
