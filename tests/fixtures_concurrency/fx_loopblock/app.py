"""Seeded defect: a blocking call reachable from an event-loop role."""

import threading
import time


class Loop:
    def start(self):
        threading.Thread(target=self._loop, name="ev-loop").start()

    def _loop(self):
        while True:
            self._tick()

    def _tick(self):
        time.sleep(0.1)
