"""Seeded defect: two locks acquired in opposite orders (deadlock)."""

import threading


class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0  # guard: _a

    def start(self):
        threading.Thread(target=self._run, name="cyc-1").start()

    def _run(self):
        while True:
            self.forward()

    def forward(self):
        with self._a:
            with self._b:
                self.n += 1

    def backward(self):
        with self._b:
            with self._a:
                self.n -= 1
