"""The mesh-backed cold compute plane (ISSUE 18).

Covers: ``MeshWorker.process_segments`` bit-exact against the
``CpuNumpyWorker`` reference across all three packings (sub-word
slivers fall back, pad rows are masked on every launch); a 20-thread
cold burst on ``--cold-backend mesh`` costing one SPMD round per drain
slice with every reply oracle-exact and bit-identical to the loop
backend; the ``svc_mesh_fail`` chaos kind degrading to the typed local
fallback with exact answers; capacity-scaled cluster assignment (the
hello ``capacity`` field, the evidence-gated ``assign_batch_size``
ramp, and an end-to-end capacity-4 run); ``--persist-cold`` tier-1
boundary facts answering a restarted server out of the segment store
(``cold_store_hits``) with zero re-marking; the stats/health/fleet-top
cold-backend surfaces; the trace_report ``cold mesh`` latency row; and
the tools/mesh_cold_smoke.py subprocess gate.
"""

import math
import shutil
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from sieve import metrics
from sieve.backends.cpu_numpy import CpuNumpyWorker
from sieve.backends.mesh_backend import MeshWorker, mesh_device_count
from sieve.chaos import ANY_WORKER, parse_chaos
from sieve.cluster import _Cluster, _worker_capacity, run_cluster
from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.metrics import MemorySink, MetricsLogger, validate_record
from sieve.seed import seed_primes
from sieve.service import ServiceClient, ServiceSettings, SieveService
from sieve.trace import ClockAlign

REPO = Path(__file__).resolve().parent.parent
N = 50_000
PACKINGS = ["plain", "odds", "wheel30"]

# mixed spans and alignments: a sub-word sliver (CPU fallback inside a
# mesh batch), unaligned bounds, and equal-span chunks that land in one
# shape group — 5 rows on an 8-device mesh, so every launch pads and
# must mask the pad rows exactly
SEGMENTS = [
    (2, 40),
    (1_000, 9_000),
    (9_000, 17_192),
    (60_000, 68_192),
    (68_192, 76_384),
]

P = seed_primes(200_000)


def o_pi(x):
    return int(np.searchsorted(P, x, side="right"))


def o_count(lo, hi):
    return int(np.searchsorted(P, hi, side="left")
               - np.searchsorted(P, lo, side="left"))


def _fields(res) -> tuple:
    # everything but elapsed_s (wall time differs between paths)
    return (res.seg_id, res.lo, res.hi, res.count, res.twin_count,
            res.first_word, res.last_word, res.nbits)


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


@pytest.fixture(scope="module")
def ledger_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("mesh_ledger")
    run_local(_cfg(str(path)))
    return path


def _cfg(checkpoint_dir: str, **kw) -> SieveConfig:
    base = dict(
        n=N, backend="cpu-numpy", packing="wheel30", n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw) -> ServiceSettings:
    base = dict(
        workers=4, queue_limit=32, default_deadline_s=10.0,
        cold_chunk=1 << 16, refresh_s=0.0, cold_backend="mesh",
    )
    base.update(kw)
    return ServiceSettings(**base)


# --- MeshWorker parity (satellite c) -----------------------------------------


@pytest.mark.parametrize("twins", [False, True])
@pytest.mark.parametrize("packing", PACKINGS)
def test_mesh_matches_cpu_reference(packing, twins):
    cfg = SieveConfig(n=100_000, backend="cpu-numpy", packing=packing,
                      twins=twins, quiet=True)
    mesh = MeshWorker(cfg)
    ref = CpuNumpyWorker(cfg)
    seeds = seed_primes(math.isqrt(max(hi for _, hi in SEGMENTS) - 1))
    sids = [100 + i for i in range(len(SEGMENTS))]
    got = mesh.process_segments(SEGMENTS, seeds, seg_ids=sids)
    for (lo, hi), sid, res in zip(SEGMENTS, sids, got):
        want = ref.process_segment(lo, hi, seeds, seg_id=sid)
        assert _fields(res) == _fields(want), (packing, twins, lo, hi)
    # the sliver went to the CPU fallback; everything else rode the mesh
    assert mesh.launches >= 1
    assert mesh.devices == mesh_device_count()
    mesh.close()
    ref.close()


def test_mesh_pad_masking_batch_larger_than_mesh():
    # 9 equal-span chunks on an 8-device mesh: b_pad = 16, seven pad
    # rows recomputing row 0 — none of them may leak into the output
    cfg = SieveConfig(n=200_000, backend="cpu-numpy", packing="odds",
                      quiet=True)
    span = 1 << 13  # grid ends at 133_728, inside the P oracle
    segs = [(60_000 + i * span, 60_000 + (i + 1) * span) for i in range(9)]
    mesh = MeshWorker(cfg)
    launches0 = mesh.launches
    got = mesh.process_segments(segs, P)
    # one launch per shape group, never one per chunk (shallow chunks
    # near the seed-tier boundary may split into a second group)
    assert 1 <= mesh.launches - launches0 <= 2
    for (lo, hi), res in zip(segs, got):
        assert res.count == o_count(lo, hi)
    mesh.close()


# --- service burst: one SPMD round per drain slice (tentpole) ----------------


def test_mesh_cold_burst_one_round_per_drain(ledger_dir, memsink):
    # covered prefix ends at 50_001; the two targets need exactly 3
    # distinct chunk keys — a 20-thread burst must drain in <= 3
    # dispatches (<= ceil(K / batch_max_chunks) per slice), each mesh
    # dispatch ONE SPMD round per shape group
    settings = _settings(workers=8, cold_delay_s=0.25)
    targets = [90_000, 120_000] * 10  # 20 overlapping cold queries
    with SieveService(_cfg(str(ledger_dir)), settings) as svc:
        got, errs = [], []

        def q(x):
            try:
                with ServiceClient(svc.addr, timeout_s=30) as c:
                    got.append((x, c.pi(x)))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        threads = [threading.Thread(target=q, args=(x,)) for x in targets]
        threads[0].start()
        time.sleep(0.05)  # inside the first dispatch's delay window
        for t in threads[1:]:
            t.start()
        for t in threads:
            t.join(60)
        assert not errs
        assert sorted(got) == sorted((x, o_pi(x)) for x in targets)
        with ServiceClient(svc.addr) as cli:
            s = cli.stats()
            h = cli.health()
        assert 1 <= s["cold_dispatches"] <= 3
        assert s["mesh_fallbacks"] == 0
        # every dispatched slice was a mesh round: one launch per shape
        # group per drain, never one per chunk
        assert 1 <= s["mesh_launches"] <= 2 * s["cold_dispatches"]
        assert s["mesh_launches"] < 3 * len(set(targets))
        # stats/health expose the cold worker class (satellite f)
        for out in (s, h):
            assert out["cold_backend"] == "mesh"
            assert out["mesh_devices"] == mesh_device_count()
            assert out["mesh_fanout"] >= 1
    ev = [x for x in memsink.records
          if x["event"] == "service_mesh_dispatch"]
    assert ev and all(x["devices"] == mesh_device_count() for x in ev)
    for x in ev:
        validate_record(x)


def test_mesh_replies_bit_exact_vs_loop_backend(ledger_dir):
    # same cold window through both backends: byte-identical counts
    queries = [(50_001, 90_000), (65_000, 120_001), (2, 118_000)]
    answers = {}
    for backend in ("mesh", "loop"):
        with SieveService(
            _cfg(str(ledger_dir)), _settings(cold_backend=backend)
        ) as svc:
            with ServiceClient(svc.addr, timeout_s=30) as c:
                answers[backend] = [c.count(lo, hi) for lo, hi in queries]
            st = svc.stats()
            assert st["cold_backend"] == backend
            assert st["cold_dispatches"] >= 1
    assert answers["mesh"] == answers["loop"]
    assert answers["mesh"] == [o_count(lo, hi) for lo, hi in queries]


# --- svc_mesh_fail: typed local fallback (satellite a) -----------------------


def test_parse_svc_mesh_fail():
    d = parse_chaos("svc_mesh_fail:any@s2")[0]
    assert (d.kind, d.worker, d.seg_id) == ("svc_mesh_fail", ANY_WORKER, 2)


def test_svc_mesh_fail_degrades_to_exact_loop(ledger_dir, memsink):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        # K-th mesh dispatch raises inside the launch span
        svc.inject_chaos("svc_mesh_fail:any@s1")
        with ServiceClient(svc.addr, timeout_s=30) as c:
            assert c.pi(90_000) == o_pi(90_000)   # through the fallback
            assert c.pi(120_000) == o_pi(120_000)  # mesh again
        s = svc.stats()
        assert s["mesh_fallbacks"] == 1
        assert s["mesh_launches"] >= 1  # the later drain recovered
        assert s["cold_backend"] == "mesh"  # launch failure isn't fatal
    ev = [x for x in memsink.records
          if x["event"] == "service_mesh_fallback"]
    assert len(ev) == 1
    assert "svc_mesh_fail" in ev[0]["reason"]
    for x in ev:
        validate_record(x)


def test_mesh_init_failure_degrades_once(ledger_dir, memsink, monkeypatch):
    # impossible device ask: init fails, the loop path answers, and the
    # failure is permanent (one event, no retry storm)
    monkeypatch.setenv("SIEVE_MESH_COLD_DEVICES", "4096")
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as c:
            assert c.pi(90_000) == o_pi(90_000)
            assert c.pi(120_000) == o_pi(120_000)
        s = svc.stats()
        assert s["mesh_launches"] == 0
        assert s["mesh_fallbacks"] == 1
        assert s["cold_backend"] == "loop (mesh failed)"
        assert s["mesh_devices"] == 0
    ev = [x for x in memsink.records
          if x["event"] == "service_mesh_fallback"]
    assert len(ev) == 1 and "init" in ev[0]["reason"]


def test_cold_backend_setting_validated():
    with pytest.raises(ValueError, match="cold_backend"):
        ServiceSettings(cold_backend="gpu").validate()
    assert ServiceSettings.from_env().cold_backend == "loop"


# --- capacity-scaled cluster assignment (tentpole, cluster half) -------------


def test_worker_capacity_env_override(monkeypatch):
    monkeypatch.setenv("SIEVE_WORKER_CAPACITY", "5")
    assert _worker_capacity() == 5
    monkeypatch.delenv("SIEVE_WORKER_CAPACITY")
    monkeypatch.setenv("SIEVE_CLUSTER_WORKER_BACKEND", "cpu-numpy")
    assert _worker_capacity() == 1  # scalar class: classic protocol


def test_assign_batch_size_evidence_ramp():
    cfg = SieveConfig(n=10**5, quiet=True)
    cl = _Cluster(cfg, None, [], MetricsLogger(cfg), None)
    # unknown worker / scalar class: always 1
    assert cl.assign_batch_size(7) == 1
    cl.set_capacity(7, 8)
    # no attempt samples, no clock alignment: half the ceiling
    assert cl.assign_batch_size(7) == 4
    align = cl.clock[7] = ClockAlign()
    align.sample(0.0, 0.001, 0.001, 0.002)  # rtt ~2 ms
    for _ in range(8):
        cl.observe_attempt(0.05)  # fast segments
    # evidence in, p95*slack*8 well under the deadline floor: full fanout
    assert cl.assign_batch_size(7) == 8
    # a straggling worker class halves until the projected silent
    # window fits the deadline budget again
    for _ in range(256):
        cl.observe_attempt(30.0)  # p95*slack = 120 s > 60 s floor
    assert cl.assign_batch_size(7) < 8
    # malformed hello never breaks sizing
    cl.set_capacity(9, "bogus")
    assert cl.assign_batch_size(9) == 1


def test_cluster_capacity_run_exact(monkeypatch):
    from sieve.metrics import registry
    from tests.oracles import PI

    monkeypatch.setenv("SIEVE_WORKER_CAPACITY", "4")
    cfg = SieveConfig(
        n=10**5, backend="cpu-cluster", workers=2, n_segments=12,
        twins=True, quiet=True, coordinator_addr="127.0.0.1:0",
    )
    res = run_cluster(cfg)
    assert res.pi == PI[10**5]
    # the hello handshake carried the class to the coordinator
    assert registry().gauge("cluster.worker0.capacity").value == 4


# --- persist-cold tier-1: restart answers from the store (tentpole) ----------


def test_persist_cold_store_restart_hot(tmp_path):
    dir_a = tmp_path / "a"
    run_local(_cfg(str(dir_a)))
    # pre-cold snapshot: B's ledger never sees the cold results, so a
    # server over B can only answer out of the segment store
    dir_b = tmp_path / "b"
    shutil.copytree(dir_a, dir_b)
    queries = [(50_001, 90_000), (2, 120_000)]
    settings = _settings(cold_backend="loop", persist_cold=True)
    with SieveService(_cfg(str(dir_a)), settings) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as c:
            first = [c.count(lo, hi) for lo, hi in queries]
        assert svc.stats()["cold_persisted"] >= 1
    assert first == [o_count(lo, hi) for lo, hi in queries]
    # the store (boundary words, not just counts) survives; the cold
    # ledger appends do not — the pre-PR failure mode this tier fixes
    shutil.copytree(dir_a / "store", dir_b / "store")
    with SieveService(_cfg(str(dir_b)), settings) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as c:
            again = [c.count(lo, hi) for lo, hi in queries]
        s = svc.stats()
    assert again == first
    # restart-hot: every cold chunk came out of tier-1, nothing re-marked
    assert s["cold_store_hits"] >= 1
    assert s["cold_computes"] == 0


# --- observability surfaces (satellites e/f) ---------------------------------


def test_fleet_top_cold_cell():
    from tools.fleet_top import _cold_cell

    assert _cold_cell(None) == "-"
    assert _cold_cell({}) == "-"
    assert _cold_cell({"cold_backend": "loop"}) == "loop"
    assert _cold_cell(
        {"cold_backend": "mesh", "mesh_devices": 8, "mesh_fanout": 3}
    ) == "mesh/8x3"
    assert _cold_cell(
        {"cold_backend": "loop (mesh failed)"}
    ) == "loop (mesh failed)"


def test_trace_report_cold_mesh_row():
    from tools.trace_report import service_report

    spans = [
        {"name": "rpc.query", "ts": 0.0, "dur": 9_000.0,
         "args": {"op": "pi", "outcome": "ok", "source": "cold"}},
        {"name": "query.cold", "ts": 100.0, "dur": 8_000.0, "args": {}},
        {"name": "query.cold_mesh", "ts": 200.0, "dur": 6_000.0,
         "args": {"chunks": 5, "devices": 8, "launch": 1}},
    ]
    out = "\n".join(service_report(spans))
    assert "cold mesh" in out
    assert "1 SPMD launches, 5 chunks, 8 devices" in out
    # nested inside cold compute: the row must not inflate the split
    assert "nested in cold compute" in out


# --- the smoke gate (satellite c) --------------------------------------------


def test_mesh_cold_smoke_subprocess():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "mesh_cold_smoke.py"),
         "--chunks", "8", "--span", "14"],
        capture_output=True, text=True, timeout=280,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "MESH_COLD_SMOKE_OK" in proc.stdout
    assert '"unit": "cold_throughput"' in proc.stdout


def test_bench_compare_cold_throughput_gate():
    from tools.bench_compare import compare

    def _rec(value):
        return {"service_cold_drain_throughput": {
            "metric": "service_cold_drain_throughput",
            "value": value, "unit": "cold_throughput",
            "vs_baseline": 1.4,
        }}

    # 50% cold-drain drop: gated
    lines, regressions = compare(_rec(2_000_000.0), _rec(1_000_000.0), 0.10)
    assert regressions and "service_cold_drain_throughput" in regressions[0]
    assert any("cold-drain drop" in line for line in lines)
    # improvement: clean
    _, regressions = compare(_rec(2_000_000.0), _rec(2_100_000.0), 0.10)
    assert not regressions
