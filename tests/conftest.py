"""Test environment: force an 8-device virtual CPU mesh BEFORE jax imports.

SURVEY.md section 4.2 item 4: `--xla_force_host_platform_device_count=8`
gives an 8-device CPU mesh so shard_map/psum/ppermute logic runs in CI with
no TPU. Must happen before the first `import jax` anywhere in the test run.
"""

import os
import sys

# NOTE: in the axon environment a sitecustomize imports jax at interpreter
# startup with JAX_PLATFORMS=axon, so flipping env vars here cannot change
# the default platform. The CPU client initializes lazily, though, so the
# device-count flag still takes effect, and sieve's jax paths honor
# SIEVE_JAX_PLATFORM for explicit placement (tests run hermetically on the
# virtual 8-device CPU mesh either way).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SIEVE_JAX_PLATFORM", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
