"""Unit tests: seed sieve, layouts (index maps + marking), segment planning.

SURVEY.md section 4.2 item 1: pure math, no devices.
"""

import numpy as np
import pytest

from sieve.bitset import (
    LAYOUTS,
    WHEEL30_RESIDUES,
    boundary_words,
    get_layout,
    pack_words,
    popcount_words,
    unpack_words,
)
from sieve.seed import pi_reference, seed_primes, twin_reference
from sieve.segments import plan_segments, validate_plan
from tests.oracles import PI, TWINS


class TestSeed:
    def test_small(self):
        assert seed_primes(1).size == 0
        assert seed_primes(2).tolist() == [2]
        assert seed_primes(20).tolist() == [2, 3, 5, 7, 11, 13, 17, 19]

    def test_oracles(self):
        assert pi_reference(10**5) == PI[10**5]
        assert pi_reference(10**6) == PI[10**6]

    def test_twin_oracles(self):
        assert twin_reference(10**5) == TWINS[10**5]


class TestLayoutIndexMaps:
    @pytest.mark.parametrize("name", list(LAYOUTS))
    def test_gidx_monotone_and_roundtrip(self, name):
        layout = get_layout(name)
        lo, hi = 2, 500
        vals = layout.candidates(lo, hi)
        g = np.array([layout.gidx(int(v)) for v in vals])
        # strictly increasing and CONSECUTIVE (no holes in flag space)
        assert (np.diff(g) == 1).all()
        assert layout.nbits(lo, hi) == vals.size
        first = layout.first_candidate(lo)
        assert first == vals[0]
        for v in vals[:50]:
            assert layout.bit_of(int(v), lo) == layout.gidx(int(v)) - layout.gidx(first)

    def test_odds_identity(self):
        # SURVEY 7.3: bit b of segment at odd lo == value lo + 2b
        layout = get_layout("odds")
        lo = 101
        for b in range(20):
            assert layout.bit_of(lo + 2 * b, lo) == b

    def test_wheel30_identity(self):
        # SURVEY 7.3: flag index of v = 8*(v//30) + idx[v%30]
        layout = get_layout("wheel30")
        assert layout.gidx(31) == 8 * 1 + 0
        assert layout.gidx(7) == 1
        assert layout.gidx(29) == 7
        assert [layout.gidx(30 + r) for r in WHEEL30_RESIDUES] == list(range(8, 16))

    @pytest.mark.parametrize("name", list(LAYOUTS))
    @pytest.mark.parametrize("lo", [2, 3, 7, 30, 31, 97, 120])
    def test_nbits_matches_enumeration(self, name, lo):
        layout = get_layout(name)
        for hi in [lo + 1, lo + 2, lo + 7, lo + 30, lo + 101]:
            assert layout.nbits(lo, hi) == layout.candidates(lo, hi).size


def _segment_primes(name, lo, hi, n):
    """Prime values in [lo, hi) according to a marked segment."""
    from sieve.backends.cpu_numpy import sieve_segment_flags

    layout = get_layout(name)
    seeds = seed_primes(int(np.sqrt(n)) + 1)
    flags = sieve_segment_flags(name, lo, hi, seeds)
    vals = layout.candidates(lo, hi)
    found = set(vals[flags[: vals.size]].tolist())
    found |= {p for p in layout.extra_primes if lo <= p < hi}
    return found


class TestMarking:
    @pytest.mark.parametrize("name", list(LAYOUTS))
    def test_whole_range_small(self, name):
        n = 1000
        found = _segment_primes(name, 2, n + 1, n)
        truth = set(seed_primes(n).tolist())
        assert found == truth

    @pytest.mark.parametrize("name", list(LAYOUTS))
    @pytest.mark.parametrize(
        "lo,hi",
        [
            (2, 10),        # contains the extra primes
            (49, 121),      # boundary exactly at p^2 (7^2, 11^2)
            (97, 98),       # single value, prime
            (100, 102),     # single candidate, composite region
            (121, 122),     # p^2 exactly
            (991, 1009),    # prime at both edges
            (2, 3),         # just {2}
            (9973, 10000),  # segment entirely above sqrt(n) for small n
        ],
    )
    def test_adversarial_segments(self, name, lo, hi):
        n = 10**4
        truth = {int(p) for p in seed_primes(n) if lo <= p < hi}
        assert _segment_primes(name, lo, hi, n) == truth

    @pytest.mark.parametrize("name", list(LAYOUTS))
    def test_randomized_segments(self, name):
        rng = np.random.default_rng(42)
        n = 10**5
        all_primes = seed_primes(n)
        for _ in range(25):
            lo = int(rng.integers(2, n - 2))
            hi = int(rng.integers(lo + 1, min(lo + 5000, n + 1) + 1))
            truth = {int(p) for p in all_primes if lo <= p < hi}
            assert _segment_primes(name, lo, hi, n) == truth, (lo, hi)


class TestPacking:
    def test_pack_roundtrip(self):
        rng = np.random.default_rng(0)
        for nbits in [1, 31, 32, 33, 64, 100, 1000]:
            flags = rng.random(nbits) < 0.5
            words = pack_words(flags)
            assert words.dtype == np.uint32
            assert unpack_words(words, nbits).tolist() == flags.tolist()
            assert popcount_words(words) == int(flags.sum())

    def test_boundary_words(self):
        rng = np.random.default_rng(1)
        for nbits in [1, 5, 32, 33, 40, 64, 65, 96, 130]:
            flags = rng.random(nbits) < 0.5
            fw, lw = boundary_words(flags)
            for k in range(min(32, nbits)):
                assert (fw >> k) & 1 == int(flags[k])
            if nbits >= 32:
                for k in range(32):
                    assert (lw >> k) & 1 == int(flags[nbits - 32 + k])
            else:
                assert lw == fw


class TestPlanSegments:
    @pytest.mark.parametrize("n", [10, 100, 10**6, 10**6 + 7])
    @pytest.mark.parametrize("k", [1, 3, 17, 256])
    def test_tiling(self, n, k):
        segs = plan_segments(n, k)
        validate_plan(segs, n)
        assert len(segs) <= k
        assert sum(s.span for s in segs) == n - 1

    def test_owners_round_robin(self):
        segs = plan_segments(10**5, 16, n_workers=4)
        assert {s.owner for s in segs} == {0, 1, 2, 3}
        for s in segs:
            assert s.owner == s.seg_id % 4

    def test_tiny_range(self):
        segs = plan_segments(2, 8)
        validate_plan(segs, 2)
        assert segs[0].lo == 2 and segs[-1].hi == 3
