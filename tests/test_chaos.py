"""Elastic membership, adaptive deadlines, ledger integrity, and the
composable chaos plane (ISSUE 6).

Covers: the --chaos grammar and one-shot schedule; the adaptive silence
deadline's floors and audit events; ledger v2 checksums, quarantine and
per-entry salvage; double-completion idempotency; multi-worker failures
in one run; mid-segment disconnect + reconnect; a stalled-but-alive
worker surviving a tight static deadline; resume after SIGKILLing the
coordinator; a worker joining mid-run under a four-fault composed
schedule (the acceptance scenario); and the chaos_smoke tool as a
tier-1 subprocess test.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from sieve import metrics, trace
from sieve.chaos import ANY_WORKER, ChaosSchedule, parse_chaos
from sieve.checkpoint import LEDGER_NAME, Ledger, LedgerCorrupt, LedgerMismatch
from sieve.cluster import _Cluster, run_cluster, serve_worker
from sieve.config import SieveConfig
from sieve.metrics import MemorySink, MetricsLogger, validate_record
from sieve.worker import SegmentResult
from tests.oracles import PI, TWINS

REPO = Path(__file__).parent.parent
ADDR = "127.0.0.1:0"


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cfg(**kw):
    base = dict(
        n=10**5,
        backend="cpu-cluster",
        workers=2,
        n_segments=8,
        twins=True,
        quiet=True,
        coordinator_addr=ADDR,
    )
    base.update(kw)
    return SieveConfig(**base)


def _result(seg_id=0, count=15):
    return SegmentResult(
        seg_id=seg_id, lo=2, hi=50, count=count, twin_count=6,
        first_word=1, last_word=3, nbits=48, elapsed_s=0.01,
    )


# --- grammar + schedule ------------------------------------------------------


def test_parse_chaos_grammar():
    ds = parse_chaos("kill:1@s4,stall:2@s7:3.0,drop_hb:any@s9,disconnect:0@s2")
    assert [(d.kind, d.worker, d.seg_id, d.param) for d in ds] == [
        ("kill", 1, 4, None),
        ("stall", 2, 7, 3.0),
        ("drop_hb", ANY_WORKER, 9, None),
        ("disconnect", 0, 2, 0.05),
    ]
    # defaults when the param is omitted
    assert parse_chaos("stall:0@s1")[0].param == 1.0


@pytest.mark.parametrize("bad,match", [
    ("explode:0@s1", "unknown kind"),
    ("kill:0", "worker@s<seg>"),
    ("kill:x@s1", "worker must be an integer"),
    ("kill:0@3", "segment must be written s<id>"),
    ("kill:0@s1:2.0", "kill takes no param"),
    ("stall:0@s1:abc", "param must be a number"),
    ("stall:0@s1:-1", "param must be >= 0"),
])
def test_parse_chaos_rejects_bad(bad, match):
    with pytest.raises(ValueError, match=match):
        parse_chaos(bad)


def test_schedule_take_is_one_shot():
    sched = ChaosSchedule(parse_chaos("kill:any@s2,stall:1@s2:0.5"))
    assert sched.take(0, 1) == []
    got = sched.take(1, 2)  # matches both (any + worker 1)
    assert sorted(d["kind"] for d in got) == ["kill", "stall"]
    assert sched.take(1, 2) == []  # consumed: a reassignment runs fault-free
    assert len(sched) == 0


def test_config_merges_legacy_chaos_kill():
    cfg = _cfg(chaos="stall:any@s3", chaos_kill="0@2")
    kinds = {(d.kind, d.worker, d.seg_id) for d in cfg.chaos_directives()}
    assert kinds == {("stall", ANY_WORKER, 3), ("kill", 0, 2)}


def test_config_rejects_bad_chaos_eagerly():
    with pytest.raises(ValueError, match="unknown kind"):
        _cfg(chaos="frob:0@s1")


# --- adaptive deadline -------------------------------------------------------


def test_adaptive_deadline_floors_and_p95(monkeypatch, memsink):
    monkeypatch.setenv("SIEVE_CLUSTER_DEADLINE_S", "1")
    cfg = _cfg()
    cl = _Cluster(cfg, None, [], MetricsLogger(cfg), None)
    # no samples yet: the heartbeat-miss floor (4 x HEARTBEAT_S) wins over
    # the tightened static floor
    assert cl.assign_deadline_s(0) == pytest.approx(4.0)
    for _ in range(8):
        cl.observe_attempt(2.0)
    # p95(2.0) x slack(4) = 8 now dominates
    assert cl.assign_deadline_s(0) == pytest.approx(8.0)
    events = [r for r in memsink.records if r["event"] == "deadline_adjusted"]
    assert len(events) == 2  # first computation, then the >20% change
    assert events[0]["prev_s"] is None
    assert events[1]["prev_s"] == pytest.approx(4.0)
    assert events[1]["deadline_s"] == pytest.approx(8.0)
    for r in events:
        validate_record(r)
    # small jitter around the current deadline does not spam events
    cl.assign_deadline_s(0)
    assert len([r for r in memsink.records
                if r["event"] == "deadline_adjusted"]) == 2


def test_static_floor_still_respected(monkeypatch):
    monkeypatch.setenv("SIEVE_CLUSTER_DEADLINE_S", "120")
    cfg = _cfg()
    cl = _Cluster(cfg, None, [], MetricsLogger(cfg), None)
    assert cl.assign_deadline_s(0) == pytest.approx(120.0)


# --- ledger integrity --------------------------------------------------------


def test_ledger_v2_roundtrip_with_checksum(tmp_path):
    cfg = _cfg(checkpoint_dir=str(tmp_path))
    led = Ledger.open(cfg)
    led.record(_result(0))
    led.record(_result(1, count=20))
    data = json.loads((tmp_path / LEDGER_NAME).read_text())
    assert data["version"] == 2
    assert "checksum" in data
    led2 = Ledger.open(cfg)
    assert led2.salvaged == 0
    assert {r.seg_id for r in led2.completed().values()} == {0, 1}


def test_ledger_v1_files_still_load(tmp_path):
    cfg = _cfg(checkpoint_dir=str(tmp_path))
    (tmp_path / LEDGER_NAME).write_text(json.dumps({
        "config_hash": cfg.config_hash(),
        "completed": {"0": _result(0).to_dict()},
    }))
    led = Ledger.open(cfg)
    assert led.salvaged == 0
    assert list(led.completed()) == [0]


def test_ledger_truncated_quarantines_and_salvages(tmp_path, memsink):
    cfg = _cfg(checkpoint_dir=str(tmp_path))
    led = Ledger.open(cfg)
    for i in range(3):
        led.record(_result(i))
    path = tmp_path / LEDGER_NAME
    text = path.read_text()
    path.write_text(text[: int(len(text) * 0.7)])  # torn write
    led2 = Ledger.open(cfg)
    assert led2.salvaged >= 1
    assert led2.quarantined == str(path) + ".quarantined"
    assert os.path.exists(led2.quarantined)
    # the rewritten ledger is clean v2 again
    led3 = Ledger.open(cfg)
    assert led3.salvaged == 0
    assert set(led3.completed()) == set(led2.completed())


def test_ledger_unsalvageable_raises_clear_error(tmp_path):
    cfg = _cfg(checkpoint_dir=str(tmp_path))
    path = tmp_path / LEDGER_NAME
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("{{{garbage")
    with pytest.raises(LedgerCorrupt, match="quarantined.*--resume") as ei:
        Ledger.open(cfg)
    assert isinstance(ei.value, LedgerMismatch)  # old handlers still catch
    assert not path.exists()
    assert os.path.exists(str(path) + ".quarantined")


def test_ledger_checksum_mismatch_never_salvaged(tmp_path):
    cfg = _cfg(checkpoint_dir=str(tmp_path))
    led = Ledger.open(cfg)
    led.record(_result(0, count=15))
    path = tmp_path / LEDGER_NAME
    data = json.loads(path.read_text())
    data["completed"]["0"]["count"] = 16  # silent bit flip, stale checksum
    path.write_text(json.dumps(data))
    with pytest.raises(LedgerCorrupt, match="checksum"):
        Ledger.open(cfg)


def test_ledger_salvage_refuses_foreign_config(tmp_path):
    cfg = _cfg(checkpoint_dir=str(tmp_path))
    path = tmp_path / LEDGER_NAME
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(
        '{"config_hash": "beef00000000dead", '
        '"completed": {"0": ' + json.dumps(_result(0).to_dict()) + "}"
    )  # truncated AND written for another run
    with pytest.raises(LedgerCorrupt, match="does not match"):
        Ledger.open(cfg)


def test_double_completion_is_idempotent(tmp_path, memsink):
    # a reassigned segment completing twice must land once in done, once
    # in the ledger, and once in the metrics stream
    cfg = _cfg(checkpoint_dir=str(tmp_path))
    led = Ledger.open(cfg)
    cl = _Cluster(cfg, None, [], MetricsLogger(cfg), led)
    cl.n_expected = 2
    cl.complete(_result(0))
    cl.complete(_result(0))
    assert len(cl.done) == 1
    assert list(Ledger.open(cfg).completed()) == [0]
    assert len([r for r in memsink.records if r["event"] == "segment"]) == 1


# --- cluster fault handling --------------------------------------------------


def test_two_workers_fail_on_different_segments(memsink):
    # two kills on different segments in ONE run: with three workers the
    # survivors absorb both reassignments and the counts stay exact
    res = run_cluster(_cfg(workers=3, chaos="kill:any@s1,kill:any@s4"))
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]
    failed = [r for r in memsink.records if r["event"] == "worker_failed"]
    assert len(failed) >= 2
    joins = [r for r in memsink.records if r["event"] == "worker_joined"]
    assert len(joins) >= 3
    for r in memsink.records:
        validate_record(r)


def test_disconnect_requeues_and_worker_rejoins(monkeypatch, memsink):
    monkeypatch.setenv("SIEVE_WORKER_BACKOFF_S", "0.05")
    # the stall on the last segment holds the run open long enough for the
    # disconnected worker's reconnect to land before all_done
    res = run_cluster(_cfg(chaos="disconnect:any@s3,stall:any@s7:1.0"))
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]
    # the dropped worker reconnects: strictly more joins than the two
    # initial ones, and the segment was reassigned
    assert res.host_phases["workers_joined"] >= 3
    assert any(r["event"] == "reassign" and r["seg_id"] == 3
               for r in memsink.records)


def test_stalled_but_alive_worker_not_declared_failed(monkeypatch, memsink):
    # 1.5 s silent stall with the static floor tightened to 1 s: the
    # heartbeat-miss floor must keep the worker alive (no worker_failed,
    # no reassignment) and the run exact
    monkeypatch.setenv("SIEVE_CLUSTER_DEADLINE_S", "1")
    res = run_cluster(_cfg(chaos="stall:any@s5:1.5"))
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]
    assert [r for r in memsink.records if r["event"] == "worker_failed"] == []
    assert [r for r in memsink.records if r["event"] == "reassign"] == []


# --- worker-side robustness (satellite a) ------------------------------------


def test_worker_gives_up_when_coordinator_never_comes_back(
    monkeypatch, capfd
):
    monkeypatch.setenv("SIEVE_WORKER_RECONNECT_MAX", "2")
    monkeypatch.setenv("SIEVE_WORKER_BACKOFF_S", "0.01")
    monkeypatch.setenv("SIEVE_TELEMETRY_RING", "0")
    port = _free_port()  # nothing listening
    t0 = time.monotonic()
    serve_worker(_cfg(coordinator_addr=f"127.0.0.1:{port}"), worker_id=7)
    assert time.monotonic() - t0 < 10
    assert "worker 7: giving up after 2 reconnect attempts" in (
        capfd.readouterr().err
    )


def test_worker_recv_timeout_unsticks_dead_coordinator(monkeypatch, capfd):
    # a coordinator that accepts but never speaks: the bounded recv must
    # turn the silence into reconnect attempts instead of blocking forever
    monkeypatch.setenv("SIEVE_WORKER_RECV_TIMEOUT_S", "0.2")
    monkeypatch.setenv("SIEVE_WORKER_RECONNECT_MAX", "1")
    monkeypatch.setenv("SIEVE_WORKER_BACKOFF_S", "0.01")
    monkeypatch.setenv("SIEVE_TELEMETRY_RING", "0")
    server = socket.socket()
    server.bind(("127.0.0.1", 0))
    server.listen(4)
    addr = f"127.0.0.1:{server.getsockname()[1]}"
    held = []
    stop = threading.Event()

    def _accept():
        server.settimeout(0.1)
        while not stop.is_set():
            try:
                held.append(server.accept()[0])  # accept, then stay silent
            except socket.timeout:
                continue

    acceptor = threading.Thread(target=_accept, daemon=True)
    acceptor.start()
    try:
        t0 = time.monotonic()
        serve_worker(_cfg(coordinator_addr=addr), worker_id=3)
        assert time.monotonic() - t0 < 10
        assert "giving up" in capfd.readouterr().err
    finally:
        stop.set()
        acceptor.join(timeout=2)
        for s in held:
            s.close()
        server.close()


# --- resume after coordinator SIGKILL (satellite c) --------------------------


def test_resume_after_coordinator_sigkill(tmp_path):
    port = _free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SIEVE_WORKER_RECONNECT_MAX="3",
        SIEVE_WORKER_BACKOFF_S="0.05",
        PYTHONPATH=str(REPO),
    )
    # the stall holds segment 6 open for 30 s, guaranteeing a mid-run kill
    # window while the other segments land in the ledger
    proc = subprocess.Popen(
        [sys.executable, "-m", "sieve",
         "--n", "1e5", "--backend", "cpu-cluster", "--workers", "2",
         "--segments", "10", "--quiet",
         "--coordinator-addr", f"127.0.0.1:{port}",
         "--checkpoint-dir", str(tmp_path),
         "--chaos", "stall:any@s6:30"],
        env=env, cwd=str(REPO),
        # DEVNULL, not PIPE: the orphaned (still-stalling) worker inherits
        # the pipe and would block a communicate() after the SIGKILL
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    ledger_path = tmp_path / LEDGER_NAME
    try:
        deadline = time.monotonic() + 60
        completed = 0
        while time.monotonic() < deadline:
            if ledger_path.exists():
                try:
                    completed = len(
                        json.loads(ledger_path.read_text())["completed"]
                    )
                except (ValueError, KeyError):
                    completed = 0
                if completed >= 2:
                    break
            time.sleep(0.05)
        assert completed >= 2, "coordinator made no checkpoint progress"
    finally:
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)

    # same math-relevant config (n, segments, packing, twins) resumes on a
    # fresh coordinator; counts must be exact, not doubled
    res = run_cluster(SieveConfig(
        n=10**5, backend="cpu-cluster", workers=2, n_segments=10,
        quiet=True, coordinator_addr=ADDR,
        checkpoint_dir=str(tmp_path), resume=True,
    ))
    assert res.pi == PI[10**5]
    final = json.loads(ledger_path.read_text())
    assert len(final["completed"]) == 10
    assert sorted(int(k) for k in final["completed"]) == list(range(10))


# --- acceptance: composed faults + mid-run join ------------------------------


def test_chaos_acceptance_midrun_join(tmp_path, monkeypatch, memsink):
    from tools.trace_report import cluster_report, load_all

    monkeypatch.setenv("SIEVE_CLUSTER_NO_SPAWN", "1")
    monkeypatch.setenv("SIEVE_WORKER_BACKOFF_S", "0.05")
    addr = f"127.0.0.1:{_free_port()}"
    worker = Path(__file__).parent / "multihost_worker.py"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def _launch(i):
        return subprocess.Popen(
            [sys.executable, str(worker), addr, "cluster", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=str(REPO),
        )

    procs = [_launch(0)]
    stop = threading.Event()

    def _joiner():
        # worker 1 joins only after the run has made progress (first
        # completed segment) — a genuine mid-run elastic join
        while not stop.is_set():
            if any(r.get("event") == "segment" for r in list(memsink.records)):
                procs.append(_launch(1))
                return
            time.sleep(0.02)

    joiner = threading.Thread(target=_joiner, daemon=True)
    joiner.start()
    tr = trace.get_tracer()
    tr.enable()
    try:
        res = run_cluster(_cfg(
            coordinator_addr=addr,
            checkpoint_dir=str(tmp_path),
            chaos="kill:any@s2,disconnect:any@s3,drop_hb:any@s4,"
                  "stall:any@s5:1.5",
        ))
    finally:
        tr.disable()
        stop.set()
        joiner.join(timeout=5)
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.communicate(timeout=30)

    # exact oracle parity under 4 composed faults + elastic membership
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]

    # zero double-counted ledger segments
    data = json.loads((tmp_path / LEDGER_NAME).read_text())
    assert sorted(int(k) for k in data["completed"]) == list(range(8))

    # membership: initial join, kill leave, mid-run join, disconnect
    # leave+rejoin — at least 3 joins and 2 leaves total
    hp = res.host_phases
    assert hp["workers_joined"] >= 3
    assert hp["workers_left"] >= 2
    kinds = {r["event"] for r in memsink.records}
    assert {"worker_joined", "worker_left", "deadline_adjusted",
            "worker_failed", "reassign"} <= kinds
    # the stalled worker was NOT declared failed: every worker_failed is
    # the kill or the disconnect, never the adaptive silence deadline
    for r in memsink.records:
        if r["event"] == "worker_failed":
            assert "adaptive deadline" not in r["reason"]
        validate_record(r)

    # the merged trace timeline carries join/leave/deadline-adjust events
    path = tmp_path / "chaos.trace.json"
    tr.save(str(path))
    events = load_all(str(path))
    names = {e.get("name") for e in events}
    assert {"cluster.worker_joined", "cluster.worker_left",
            "cluster.deadline_adjusted"} <= names
    text = cluster_report(events)
    assert "membership timeline" in text
    assert "joined" in text and "left" in text


# --- chaos_smoke tool as tier-1 (satellite e) --------------------------------


def test_chaos_smoke_tool(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "chaos_smoke.py"),
         "--keep", str(tmp_path / "work")],
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "CHAOS_SMOKE_OK" in proc.stdout
