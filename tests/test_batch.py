"""The batched cold-compute plane (ISSUE 9).

Covers: the ``process_segments`` seam — the default loop and the jax
vmapped batch path are bit-exact against per-segment ``process_segment``
across all three packings (including the sub-word CPU fallback inside a
batch); a concurrent cold burst costs at most one dispatch per distinct
grid chunk (counter-gated); ``svc_batch_partial`` degrades exactly one
chunk of a batch while the rest answer exact; ``--persist-cold`` ledger
write-back with the never-shrink guard and an all-hot restart; the
OrderedDict LRU cold cache; ``service_batched`` EVENT_SCHEMA validation;
and the bench_compare ``ms_p95`` regression gate.
"""

import math
import threading
import time

import numpy as np
import pytest

from sieve import metrics
from sieve.backends.cpu_numpy import CpuNumpyWorker
from sieve.chaos import ANY_WORKER, parse_chaos
from sieve.checkpoint import COLD_SEG_BASE, Ledger
from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.metrics import MemorySink, validate_record
from sieve.seed import seed_primes
from sieve.service import ServiceClient, ServiceSettings, SieveService
from sieve.service.server import Degraded, _Flight
from tools.bench_compare import compare

N = 50_000
PACKINGS = ["plain", "odds", "wheel30"]

# mixed spans and alignments: a sub-word segment (CPU fallback inside a
# device batch), unaligned bounds, and two equal-span chunks that land in
# one vmap group on the jax path
SEGMENTS = [
    (2, 40),
    (1_000, 9_000),
    (9_000, 17_192),
    (60_000, 68_192),
    (68_192, 76_384),
]

P = seed_primes(200_000)


def o_pi(x):
    return int(np.searchsorted(P, x, side="right"))


def o_count(lo, hi):
    return int(np.searchsorted(P, hi, side="left")
               - np.searchsorted(P, lo, side="left"))


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


@pytest.fixture(scope="module")
def ledger_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("batch_ledger")
    run_local(_cfg(str(path)))
    return path


def _cfg(checkpoint_dir: str, **kw) -> SieveConfig:
    base = dict(
        n=N, backend="cpu-numpy", packing="wheel30", n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw) -> ServiceSettings:
    base = dict(
        workers=2, queue_limit=16, default_deadline_s=10.0,
        cold_chunk=1 << 16, refresh_s=0.0,
    )
    base.update(kw)
    return ServiceSettings(**base)


def _fields(res) -> tuple:
    # everything but elapsed_s (wall time differs between paths)
    return (res.seg_id, res.lo, res.hi, res.count, res.twin_count,
            res.first_word, res.last_word, res.nbits)


# --- process_segments parity (satellite c) -----------------------------------


@pytest.mark.parametrize("packing", PACKINGS)
def test_default_loop_matches_sequential(packing):
    cfg = SieveConfig(n=100_000, backend="cpu-numpy", packing=packing,
                      quiet=True)
    w = CpuNumpyWorker(cfg)
    seeds = seed_primes(math.isqrt(max(hi for _, hi in SEGMENTS) - 1))
    sids = [100 + i for i in range(len(SEGMENTS))]
    batched = w.process_segments(SEGMENTS, seeds, seg_ids=sids)
    assert len(batched) == len(SEGMENTS)
    for (lo, hi), sid, res in zip(SEGMENTS, sids, batched):
        ref = w.process_segment(lo, hi, seeds, seg_id=sid)
        assert _fields(res) == _fields(ref)
    # default seg_ids are positional; a length mismatch is a caller bug
    assert [r.seg_id for r in w.process_segments(SEGMENTS[:2], seeds)] == [0, 1]
    with pytest.raises(ValueError, match="seg_ids"):
        w.process_segments(SEGMENTS, seeds, seg_ids=[0])


@pytest.mark.parametrize("packing", PACKINGS)
def test_jax_batch_matches_sequential(packing):
    pytest.importorskip("jax")
    from sieve.backends.jax_backend import JaxWorker

    cfg = SieveConfig(n=100_000, backend="jax", packing=packing,
                      twins=True, quiet=True)
    w = JaxWorker(cfg)
    seeds = seed_primes(math.isqrt(max(hi for _, hi in SEGMENTS) - 1))
    batched = w.process_segments(SEGMENTS, seeds)
    sequential = [
        w.process_segment(lo, hi, seeds, seg_id=i)
        for i, (lo, hi) in enumerate(SEGMENTS)
    ]
    for res, ref in zip(batched, sequential):
        assert _fields(res) == _fields(ref)
    # and both agree with the numpy reference backend
    ref_w = CpuNumpyWorker(SieveConfig(
        n=100_000, backend="cpu-numpy", packing=packing, twins=True,
        quiet=True,
    ))
    for i, (lo, hi) in enumerate(SEGMENTS):
        assert _fields(batched[i]) == _fields(
            ref_w.process_segment(lo, hi, seeds, seg_id=i)
        )


# --- burst batching: one dispatch per distinct chunk (satellite c) -----------


def test_cold_burst_batches_to_distinct_chunks(ledger_dir):
    # covered prefix ends at 50_001; cold_chunk 1<<16 puts the grid cut
    # at 65_536, so the two targets need exactly 3 distinct chunk keys:
    # (50001, 65536) shared, (65536, 90001), (65536, 120001)
    settings = _settings(workers=8, queue_limit=32, cold_delay_s=0.25)
    targets = [90_000, 120_000] * 6  # 12 overlapping cold queries
    with SieveService(_cfg(str(ledger_dir)), settings) as svc:
        got, errs = [], []

        def q(x):
            try:
                with ServiceClient(svc.addr, timeout_s=30) as c:
                    got.append((x, c.pi(x)))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        threads = [threading.Thread(target=q, args=(x,)) for x in targets]
        threads[0].start()
        time.sleep(0.05)  # inside the first dispatch's simulated compute
        for t in threads[1:]:
            t.start()
        for t in threads:
            t.join(30)
        assert not errs
        assert sorted(got) == sorted((x, o_pi(x)) for x in targets)
        with ServiceClient(svc.addr) as cli:
            s = cli.stats()
        # single-flight + queue-drain batching: 12 queries, ≤ 3 dispatches
        assert 1 <= s["cold_dispatches"] <= 3
        assert s["cold_batched_chunks"] <= 3
        assert s["cold_computes"] <= 3


# --- svc_batch_partial: per-chunk degradation (satellite b) ------------------


def test_parse_svc_batch_partial():
    d = parse_chaos("svc_batch_partial:any@s2:1")[0]
    assert (d.kind, d.worker, d.seg_id, d.param) == (
        "svc_batch_partial", ANY_WORKER, 2, 1.0
    )
    # default param: fail the first chunk of the batch
    assert parse_chaos("svc_batch_partial:any@s1")[0].param == 0.0


def test_svc_batch_partial_degrades_one_chunk(ledger_dir, memsink):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        k0, k1 = (50_001, 65_536), (65_536, 90_001)
        with svc._cold_lock:
            f0 = svc._inflight[k0] = _Flight()
            f1 = svc._inflight[k1] = _Flight()
        # key on the NEXT dispatch number; param 0 = first chunk in
        # sorted batch order
        svc.inject_chaos(
            f"svc_batch_partial:any@s{svc.batcher.batches + 1}:0"
        )
        svc.batcher._dispatch([k0, k1])
        assert f0.event.is_set() and isinstance(f0.error, Degraded)
        assert "svc_batch_partial" in str(f0.error)
        assert f1.event.is_set() and f1.error is None
        assert int(f1.result.count) == o_count(65_536, 90_001)
        assert f1.result.seg_id == COLD_SEG_BASE + 65_536
        ev = [x for x in memsink.records if x["event"] == "service_batched"]
        assert len(ev) == 1
        assert ev[0]["failed"] == 1 and ev[0]["chunks"] == 1
        for x in ev:
            validate_record(x)


# --- ledger write-back + restart (tentpole acceptance) -----------------------


def test_persist_cold_write_back_and_restart(tmp_path):
    ck = str(tmp_path / "ck")
    run_local(_cfg(ck))
    with SieveService(_cfg(ck), _settings(persist_cold=True)) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            assert cli.stats()["persist_cold"] is True
            assert cli.pi(90_000) == o_pi(90_000)
            s = cli.stats()
            assert s["cold_persisted"] == 2  # (50001,65536) + (65536,90001)
            # never-shrink guard: a clipped recompute of the head chunk
            # must NOT overwrite the persisted full chunk
            assert cli.pi(60_000) == o_pi(60_000)
            assert cli.stats()["cold_persisted"] == 2
    led = Ledger.open_readonly(_cfg(ck))
    assert led.recorded_hi(COLD_SEG_BASE + 50_001) == 65_536
    assert led.recorded_hi(COLD_SEG_BASE + 65_536) == 90_001
    # restart (no writer): the persisted chunks are hot from the index
    with SieveService(_cfg(ck), _settings()) as svc2:
        with ServiceClient(svc2.addr, timeout_s=30) as cli:
            assert cli.pi(90_000) == o_pi(90_000)
            s = cli.stats()
            assert s["covered_hi"] >= 90_001
            assert s["cold_computes"] == 0 and s["cold_dispatches"] == 0
            assert s["persist_cold"] is False


# --- cold cache is a real LRU now (satellite a) ------------------------------


def test_cold_cache_lru_eviction(ledger_dir):
    # chunk grid 1<<14 from 50_001: (50001,65536) (65536,81920)
    # (81920,90001) — three chunks through a two-entry cache
    settings = _settings(cold_chunk=1 << 14, cold_cache_entries=2)
    with SieveService(_cfg(str(ledger_dir)), settings) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            assert cli.pi(90_000) == o_pi(90_000)
            s1 = cli.stats()
            assert list(svc._cold_cache) == [
                (65_536, 81_920), (81_920, 90_001)
            ]  # oldest (50001, 65536) evicted
            # the repeat recomputes ONLY the evicted head chunk; the two
            # cached tails are hits and get refreshed to most-recent
            assert cli.pi(90_000) == o_pi(90_000)
            s2 = cli.stats()
            assert s2["cold_cache_hits"] - s1["cold_cache_hits"] == 2
            assert s2["cold_computes"] - s1["cold_computes"] == 1
            assert list(svc._cold_cache) == [
                (81_920, 90_001), (50_001, 65_536)
            ]


# --- bench_compare p95 gate (tentpole observability) -------------------------


def test_bench_compare_gates_p95_regressions():
    def rec(v, unit):
        return {"m": {"metric": "m", "value": v, "unit": unit}}

    # >10% p95 increase fails; a decrease never does
    _, regs = compare(rec(10.0, "ms_p95"), rec(12.0, "ms_p95"), 0.10)
    assert regs and "p95" in regs[0]
    _, regs = compare(rec(10.0, "ms_p95"), rec(10.5, "ms_p95"), 0.10)
    assert regs == []
    _, regs = compare(rec(10.0, "ms_p95"), rec(7.0, "ms_p95"), 0.10)
    assert regs == []
    # throughput keeps its downward gate: an increase is fine
    _, regs = compare(
        rec(100.0, "values/s/chip"), rec(120.0, "values/s/chip"), 0.10
    )
    assert regs == []
    _, regs = compare(
        rec(100.0, "values/s/chip"), rec(80.0, "values/s/chip"), 0.10
    )
    assert regs
