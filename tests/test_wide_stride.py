"""Coverage for crossing-proportional wide-stride marking: the group-D
zero-crossing pruner, the flat crossing-list path, the cutoff boundary
between the two mechanisms, the 8-way mesh with live group D, and the
ASan build of the native kernel (subprocess, so the env switch takes
effect before the library loads).
"""

import dataclasses
import os
import subprocess
import sys

import numpy as np
import pytest

from sieve.config import SieveConfig
from sieve.seed import seed_primes

# test_pallas_group_d_parity's segment: seed primes up to 5477, so strides
# in (4096, 5477] populate group D / the flat path
N_D = 30_000_000
LO_D, HI_D = 2_000_003, 24_000_001


def _segment(backend, lo, hi, n, seeds=None):
    from sieve.backends import make_worker

    cfg = SieveConfig(n=n, backend=backend, packing="odds", twins=True,
                      quiet=True)
    w = make_worker(cfg)
    if seeds is None:
        seeds = seed_primes(cfg.seed_limit)
    try:
        d = dataclasses.asdict(w.process_segment(lo, hi, seeds))
    finally:
        w.close()
    d.pop("elapsed_s")
    return d


@pytest.fixture(scope="module")
def ref_d():
    return _segment("cpu-numpy", LO_D, HI_D, N_D)


# Cutoff boundary values against the stride population {4099, ..., 5477}:
# 4097 routes EVERY group-D stride through the flat crossing list (ND=0);
# 5477 routes exactly the widest stride flat (>= comparison, lower edge);
# 5478 leaves flat empty again (upper edge — pure pruned-D behavior).
@pytest.mark.parametrize("flat_min", [4097, 5477, 5478])
def test_flat_cutoff_parity(monkeypatch, ref_d, flat_min):
    from sieve.kernels.pallas_mark import _flat_cutoff, prepare_pallas, spec_counts

    monkeypatch.setenv("SIEVE_PALLAS_FLAT_MIN", str(flat_min))
    ps = prepare_pallas("odds", LO_D, HI_D, seed_primes(5477))
    counts = spec_counts(ps)
    n_wide = int(np.sum(seed_primes(5477) >= max(flat_min, 4099)))
    if flat_min <= 5477:
        assert counts["flat_words"] > 0 and n_wide > 0
    else:
        assert counts["flat_words"] == 0
    assert _flat_cutoff(ps.Wpad) == flat_min
    got = _segment("tpu-pallas", LO_D, HI_D, N_D)
    assert got == ref_d, f"flat_min={flat_min}"


def test_prune_zero_crossing_specs():
    """A window far narrower than the widest strides: specs whose first
    hit lies beyond nbits must be dropped and the D table compacted to
    exactly the live rows — with parity intact."""
    from sieve.kernels.pallas_mark import _flat_cutoff, prepare_pallas, spec_counts
    from sieve.kernels.specs import tier1_specs

    n = 10**9  # seeds up to 31623 -> strides up to 31607 bits
    lo, hi = 500_000_001, 500_040_001  # 40k values = 20k bits << max stride
    seeds = seed_primes(31623)
    ps = prepare_pallas("odds", lo, hi, seeds)
    m, r = tier1_specs("odds", lo, seeds, tier1_max=1 << 62)
    f_min = _flat_cutoff(ps.Wpad)
    in_d = (m > 4096) & (m < f_min)
    live = int(np.sum(in_d & (r < ps.nbits)))
    assert live < int(np.sum(in_d)), "window admits no pruning — bad fixture"
    assert spec_counts(ps)["D"] == live
    # compacted: every surviving row has at least one active lane
    assert all(ps.D[3][i].any() for i in range(ps.D[0].shape[0]))
    got = _segment("tpu-pallas", lo, hi, n, seeds)
    assert got == _segment("cpu-numpy", lo, hi, n, seeds)


def test_flat_crossings_merges_duplicates():
    from sieve.kernels.specs import flat_crossings

    # two specs crossing the same words: masks must OR-merge per word
    m = np.array([70_000, 70_003], np.int64)
    r = np.array([5, 9], np.int64)
    idx, msk = flat_crossings(m, r, nbits=100_000)
    real = msk != 0
    # crossings: bits {5, 70005} and {9, 70012} -> words {0, 2187} each
    assert idx[real].tolist() == [0, 2187]
    assert msk[real][0] == (1 << 5) | (1 << 9)
    assert msk[real][1] == (1 << (70_005 % 32)) | (1 << (70_012 % 32))
    assert idx.size % 128 == 0


def test_mesh_group_d_8way():
    """8-way CPU mesh, 2 rounds, n large enough that group D is live in
    every shard — the sharded counterpart of test_pallas_group_d_parity
    (and the regression net for per-round ND/FC shape padding)."""
    from sieve.parallel.mesh import run_mesh

    cfg = SieveConfig(n=N_D, backend="tpu-pallas", packing="odds",
                      workers=8, rounds=2, twins=True, quiet=True)
    res = run_mesh(cfg)
    # oracle computed 2026-08-05 by an independent numpy sieve (consistent
    # with BASELINE.md's table at the bracketing powers of ten)
    assert res.pi == 1_857_859
    assert res.twin_pairs == 152_891


def test_asan_native_parity():
    """The wired-but-never-run ASan build: run one native-vs-numpy parity
    check in a subprocess with SIEVE_NATIVE_ASAN=1 (the env must be set
    before the library loads, and the asan runtime must be preloaded into
    the non-instrumented python)."""
    pytest.importorskip("sieve.backends.cpu_native")
    libasan = subprocess.run(
        ["g++", "-print-file-name=libasan.so"],
        capture_output=True, text=True,
    ).stdout.strip()
    if not libasan or not os.path.exists(libasan):
        pytest.skip("libasan.so not found")
    code = (
        "import dataclasses\n"
        "from sieve.config import SieveConfig\n"
        "from sieve.backends.cpu_native import CpuNativeWorker\n"
        "from sieve.backends.cpu_numpy import CpuNumpyWorker\n"
        "from sieve.seed import seed_primes\n"
        "cfg = SieveConfig(n=10**6, backend='cpu-native', packing='odds',\n"
        "                  twins=True, quiet=True)\n"
        "seeds = seed_primes(cfg.seed_limit)\n"
        "strip = lambda r: {k: v for k, v in dataclasses.asdict(r).items()\n"
        "                   if k != 'elapsed_s'}\n"
        "a = CpuNativeWorker(cfg).process_segment(101, 400001, seeds)\n"
        "b = CpuNumpyWorker(cfg).process_segment(101, 400001, seeds)\n"
        "assert strip(a) == strip(b), (a, b)\n"
        "print('ASAN_PARITY_OK')\n"
    )
    env = {
        **os.environ,
        "SIEVE_NATIVE_ASAN": "1",
        "LD_PRELOAD": libasan,
        # python itself is not asan-instrumented; its allocations look like
        # leaks and would fail the exit hook
        "ASAN_OPTIONS": "detect_leaks=0",
    }
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )
    if proc.returncode != 0 and "cannot" in proc.stderr.lower():
        pytest.skip(f"asan runtime unusable here: {proc.stderr[-200:]}")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ASAN_PARITY_OK" in proc.stdout
