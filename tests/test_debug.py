"""Black-box flight recorder: metrics history, triggered debug
bundles, and fleet-wide postmortem collection (ISSUE 13).

Covers: the bounded Histogram reservoir (memory bound + p50/p95/p99
accuracy); MetricsHistory lifecycle (idempotent start/stop, registry
churn, disabled sampler, drain-on-stop) and two-tier downsampling;
FlightRecorder snapshot/trigger/cooldown/crash-hook/redaction; the
``svc_crash`` chaos kind (grammar + a worker genuinely dying + the
crash bundle); the ``debug`` wire op on server and router; the
shard_down and slo_burn bundle triggers; ``tools/check_event_schema``
(tier-1 schema honesty); ``tools/fleet_top --json`` exit codes;
``tools/trace_report --bundle`` guards; and the acceptance E2E — a
2-shard subprocess fleet under SLO burn plus one svc_crash produces
bundles on the affected replica and the router, tools/fleet_debug.py
merges >= 3 processes into ONE fleet bundle, and trace_report
--bundle renders it, with query results exact throughout.
"""

import json
import os
import random
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from sieve import metrics
from sieve.chaos import ChaosCrash, parse_chaos
from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.debug import BUNDLE_VERSION, FLEET_BUNDLE_VERSION, FlightRecorder, redact
from sieve.metrics import (
    HISTOGRAM_RESERVOIR,
    Histogram,
    MetricsHistory,
    MetricsRegistry,
    sample_interval_s,
)
from sieve.seed import seed_primes
from sieve.service import (
    RouterSettings,
    ServiceClient,
    ServiceSettings,
    Shard,
    ShardMap,
    SieveRouter,
    SieveService,
)
from sieve.service.client import CallTimeout

REPO = Path(__file__).resolve().parent.parent

N = 50_000
P = seed_primes(200_000)


def o_pi(x):
    return int(np.searchsorted(P, x, side="right"))


def o_count(lo, hi):
    return int(np.searchsorted(P, hi, side="left")
               - np.searchsorted(P, lo, side="left"))


def _cfg(checkpoint_dir, **kw):
    base = dict(
        n=N, backend="cpu-numpy", packing="wheel30", n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw):
    base = dict(workers=2, queue_limit=16, default_deadline_s=10.0,
                refresh_s=0.0, metrics_sample_s=0.0)
    base.update(kw)
    return ServiceSettings(**base)


@pytest.fixture(scope="module")
def src_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("debug_src")
    run_local(_cfg(str(path)))
    return path


def _split_shards(src_dir, tmp_path):
    segs = sorted(
        Ledger.open_readonly(_cfg(str(src_dir))).completed().values(),
        key=lambda r: r.lo,
    )
    E = segs[2].lo
    dirs = (tmp_path / "shard0", tmp_path / "shard1")
    for d, part in zip(dirs, (segs[:2], segs[2:])):
        led = Ledger.open(_cfg(str(d)))
        for r in part:
            led.record(r)
    return str(dirs[0]), str(dirs[1]), E


def _wait(cond, timeout_s=5.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# --- histogram reservoir (satellite) ----------------------------------------


def test_histogram_reservoir_bound_and_percentile_accuracy():
    h = Histogram("acc.test")
    rng = random.Random(42)
    n = 50_000
    for _ in range(n):
        h.observe(rng.uniform(0.0, 100.0))
    # memory bound: the reservoir never exceeds its cap no matter how
    # many observations stream through
    assert len(h._reservoir) == HISTOGRAM_RESERVOIR < n
    snap = h.snapshot()
    assert snap["count"] == n
    assert snap["min"] >= 0.0 and snap["max"] <= 100.0
    # uniform [0, 100]: true quantile q is 100q; 2% of full scale
    for key, true in (("p50", 50.0), ("p95", 95.0), ("p99", 99.0)):
        assert abs(snap[key] - true) <= 2.0, f"{key}={snap[key]}"
    assert snap["p50"] <= snap["p95"] <= snap["p99"] <= snap["max"]


def test_histogram_deterministic_and_empty_snapshot_nulls():
    a, b = Histogram("det.x"), Histogram("det.x")
    for i in range(20_000):
        v = float((i * 2654435761) % 1000)
        a.observe(v)
        b.observe(v)
    # per-name seeded reservoir: identical streams -> identical stats
    assert a.snapshot() == b.snapshot()
    empty = Histogram("det.empty").snapshot()
    for key in ("mean", "min", "max", "p50", "p95", "p99"):
        assert empty[key] is None  # never a fake 0


# --- MetricsHistory lifecycle (satellite) -----------------------------------


def test_history_start_stop_idempotent_and_drain_on_stop():
    reg = MetricsRegistry()
    reg.counter("t.c").inc()
    h = MetricsHistory(reg=reg, sample_s=0.01)
    h.start()
    first_thread = h._thread
    h.start()  # idempotent: same sampler thread, not a second one
    assert h._thread is first_thread
    _wait(lambda: h.samples >= 3, what="3 samples")
    # registry churn: an instrument born mid-flight appears in later rows
    reg.counter("t.born_late").inc(5)
    seen = h.samples
    _wait(lambda: h.samples >= seen + 2, what="churn samples")
    reg.counter("t.final_tick").inc()
    h.stop()
    taken = h.samples
    assert taken >= 5
    # drain-on-stop: the synchronous final sample caught the last bump
    assert h.history("t.final_tick", 60.0)[-1][1] == 1
    assert [v for _, v in h.history("t.born_late", 60.0)] \
        and all(v == 5 for _, v in h.history("t.born_late", 60.0))
    # pre-churn rows simply lack the instrument (absent, not None)
    assert len(h.history("t.born_late", 60.0)) < len(h.rows())
    h.stop()  # second stop: no thread, no extra sample
    assert h.samples == taken
    assert h._thread is None


def test_history_disabled_takes_zero_samples():
    reg = MetricsRegistry()
    h = MetricsHistory(reg=reg, sample_s=0.0)
    h.start()
    assert h._thread is None
    time.sleep(0.03)
    assert h.samples == 0
    h.stop()  # safe when disabled
    assert h.samples == 0 and h.rows() == []


def test_history_two_tier_downsampling_bounds_memory():
    reg = MetricsRegistry()
    g = reg.gauge("t.g")
    h = MetricsHistory(reg=reg, sample_s=0.0, recent=4, coarse=8,
                       decimate=2)
    for i in range(20):
        g.set(float(i))
        h.sample_now()
    assert h.samples == 20
    rows = h.rows()
    # dense tier: the newest 4; coarse tier: every 2nd evicted ordinal
    assert len(rows) == 4 + 8
    vals = [snap["t.g"]["value"] for _, snap in rows]
    assert vals[-4:] == [16.0, 17.0, 18.0, 19.0]  # dense, newest last
    assert vals[:8] == [1.0, 3.0, 5.0, 7.0, 9.0, 11.0, 13.0, 15.0]
    assert [ts for ts, _ in rows] == sorted(ts for ts, _ in rows)


def test_sample_interval_env(monkeypatch):
    monkeypatch.delenv("SIEVE_METRICS_SAMPLE_S", raising=False)
    assert sample_interval_s() == 1.0
    monkeypatch.setenv("SIEVE_METRICS_SAMPLE_S", "0")
    assert sample_interval_s() == 0.0
    monkeypatch.setenv("SIEVE_METRICS_SAMPLE_S", "fast")
    with pytest.raises(ValueError, match="SIEVE_METRICS_SAMPLE_S"):
        sample_interval_s()
    monkeypatch.setenv("SIEVE_METRICS_SAMPLE_S", "-1")
    with pytest.raises(ValueError, match="non-negative"):
        sample_interval_s()


# --- FlightRecorder unit -----------------------------------------------------


def test_redact_masks_secretish_keys_and_survives_non_json():
    masked = redact({
        "api_key": "hunter2",
        "nested": {"auth_token": "x", "ok": 2},
        "fine": [1, "two", None],
        "obj": object(),
    })
    assert masked["api_key"] == "<redacted>"
    assert masked["nested"]["auth_token"] == "<redacted>"
    assert masked["nested"]["ok"] == 2
    assert masked["fine"] == [1, "two", None]
    assert isinstance(masked["obj"], str)  # repr, still JSON-able
    json.dumps(masked)
    # dataclasses flatten: settings configs ride along readably
    flat = redact(RouterSettings())
    assert isinstance(flat, dict) and "timeout_s" in flat


def test_recorder_snapshot_trigger_cooldown_and_bundle_dir(tmp_path):
    rec = FlightRecorder("service", debug_dir=str(tmp_path / "dbg"),
                         cooldown_s=60.0, config={"n": 7, "token": "s3"})
    rec.install()
    try:
        rec.emit({"event": "service_shed", "op": "pi"})
        rec.emit({"event": "run", "n": 7})
        snap = rec.snapshot()
        assert snap["bundle"] == BUNDLE_VERSION
        assert snap["role"] == "service" and snap["trigger"] == "manual"
        assert snap["config"]["token"] == "<redacted>"
        assert {"event": "service_shed", "op": "pi"} in snap["events"]
        # "shed" is errorish, "run" is not
        assert [e["event"] for e in snap["errors"]] == ["service_shed"]
        for key in ("spans", "metrics", "history", "recorder", "pid"):
            assert key in snap

        b1 = rec.trigger("slo_burn", op="pi", p95_ms=9.0)
        assert b1 is not None and b1["path"]
        assert os.path.isfile(os.path.join(b1["path"], "bundle.json"))
        with open(os.path.join(b1["path"], "bundle.json")) as f:
            on_disk = json.load(f)
        assert on_disk["trigger"] == "slo_burn"
        assert on_disk["detail"] == {"op": "pi", "p95_ms": 9.0}
        # same kind inside the cooldown: suppressed, counted, no dir
        assert rec.trigger("slo_burn", op="pi") is None
        assert rec.snapshot()["recorder"]["suppressed"] == 1
        # a different kind is its own edge: fires immediately
        b2 = rec.trigger("breaker_open", reason="cold errors")
        assert b2 is not None and b2["path"] != b1["path"]
        assert rec.snapshot()["recorder"]["bundles"] == 2
    finally:
        rec.uninstall()


def test_recorder_crash_hook_fires_and_uninstall_restores(monkeypatch):
    quiet_hook = lambda args: None  # noqa: E731 — silence the traceback
    monkeypatch.setattr(threading, "excepthook", quiet_hook)
    prev_sys = sys.excepthook
    rec = FlightRecorder("service", cooldown_s=0.0)
    rec.install()
    try:
        t = threading.Thread(target=lambda: 1 / 0, name="doomed")
        t.start()
        t.join()
        _wait(lambda: rec.last_bundle is not None, what="crash bundle")
        b = rec.last_bundle
        assert b["trigger"] == "crash"
        assert "ZeroDivisionError" in b["detail"]["error"]
        assert b["detail"]["thread"] == "doomed"
        assert b["path"] is None  # no debug_dir: in-memory only
    finally:
        rec.uninstall()
    assert threading.excepthook is quiet_hook
    assert sys.excepthook is prev_sys


# --- svc_crash chaos ---------------------------------------------------------


def test_chaos_grammar_svc_crash():
    d = parse_chaos("svc_crash:any@s3")
    assert len(d) == 1 and d[0].kind == "svc_crash"
    assert d[0].seg_id == 3 and d[0].param is None
    with pytest.raises(ValueError, match="takes no param"):
        parse_chaos("svc_crash:any@s3:2")
    assert issubclass(ChaosCrash, RuntimeError)


def test_svc_crash_kills_worker_and_fires_crash_bundle(
        src_dir, tmp_path, monkeypatch):
    monkeypatch.setattr(threading, "excepthook", lambda args: None)
    d0, _d1, _E = _split_shards(src_dir, tmp_path)
    dbg = tmp_path / "dbg"
    with SieveService(
        _cfg(d0, chaos="svc_crash:any@s1"),
        _settings(debug_dir=str(dbg)),
    ) as svc, ServiceClient(svc.addr, timeout_s=2) as cli:
        # the crashed request never gets a reply: the client times out
        with pytest.raises((CallTimeout, ConnectionError)):
            cli.pi(1000)
        _wait(lambda: svc.recorder.last_bundle is not None,
              what="crash bundle")
        b = svc.recorder.last_bundle
        assert b["trigger"] == "crash"
        assert "ChaosCrash" in b["detail"]["error"]
        dirs = list(dbg.glob("bundle-crash-*"))
        assert len(dirs) == 1
        with open(dirs[0] / "bundle.json") as f:
            doc = json.load(f)
        assert doc["bundle"] == BUNDLE_VERSION and doc["role"] == "service"
        # one worker died; the survivors still answer exactly (the
        # timed-out client is desynced by design — use a fresh one)
        with ServiceClient(svc.addr, timeout_s=10) as cli2:
            assert cli2.pi(1000) == o_pi(1000)
            assert cli2.count(100, 5000) == o_count(100, 5000)


# --- debug wire op + triggers on server and router --------------------------


def test_debug_op_on_server_inline_and_slo_burn_bundle(src_dir, tmp_path):
    d0, _d1, _E = _split_shards(src_dir, tmp_path)
    dbg = tmp_path / "dbg"
    with SieveService(
        _cfg(d0),
        _settings(slo_ms={"pi": 0.0001}, slo_window=8,
                  debug_dir=str(dbg), metrics_sample_s=0.02),
    ) as svc, ServiceClient(svc.addr, timeout_s=10) as cli:
        assert cli.pi(1000) == o_pi(1000)  # burns the 0.1us pi SLO
        _wait(lambda: list(dbg.glob("bundle-slo_burn-*")),
              what="slo_burn bundle dir")
        _wait(lambda: svc.history.samples >= 2, what="history samples")
        b = cli.debug()
        assert b["bundle"] == BUNDLE_VERSION and b["role"] == "service"
        assert b["trigger"] == "manual"
        assert b["recorder"]["bundles"] >= 1
        assert b["history"], "sampler on: inline bundle carries trend rows"
        assert any(e.get("event") == "service_slo_burn"
                   for e in b["events"])
        assert any(e.get("event") == "service_slo_burn"
                   for e in b["errors"])  # burn is errorish
    # recorder off: the op still answers, with a null bundle
    with SieveService(
        _cfg(d0), _settings(recorder=False),
    ) as svc2, ServiceClient(svc2.addr, timeout_s=10) as cli2:
        assert svc2.recorder is None
        assert cli2.debug() is None
        assert cli2.pi(1000) == o_pi(1000)


def test_debug_op_on_router_and_shard_down_bundle(src_dir, tmp_path):
    d0, d1, E = _split_shards(src_dir, tmp_path)
    dbgr = tmp_path / "dbgr"
    svcs = [
        SieveService(_cfg(d0), _settings()).start(),
        SieveService(_cfg(d1), _settings(range_lo=E)).start(),
    ]
    smap = ShardMap([
        Shard(2, E, (svcs[0].addr,)),
        Shard(E, N + 1, (svcs[1].addr,)),
    ])
    router = SieveRouter(
        smap, RouterSettings(quiet=True, debug_dir=str(dbgr),
                             metrics_sample_s=0.0)).start()
    try:
        with ServiceClient(router.addr, timeout_s=30) as cli:
            assert cli.is_prime(101)
            b = cli.debug()
            assert b["bundle"] == BUNDLE_VERSION and b["role"] == "router"
            # shard 0 dark for 0.2s on the next request; the request
            # itself targets shard 1, so it stays exact
            router.inject_chaos(f"svc_shard_down:0@s{router._seq + 1}:0.2")
            lo = E + 10
            assert cli.count(lo, lo + 100) == o_count(lo, lo + 100)
            _wait(lambda: list(dbgr.glob("bundle-shard_down-*")),
                  what="shard_down bundle dir")
            with open(next(iter(dbgr.glob("bundle-shard_down-*")))
                      / "bundle.json") as f:
                doc = json.load(f)
            assert doc["role"] == "router"
            assert doc["detail"]["shard"] == 0
            time.sleep(0.25)  # window over: shard 0 exact again
            assert cli.pi(1000) == o_pi(1000)
    finally:
        router.stop()
        for s in svcs:
            s.stop()


# --- check_event_schema (satellite, tier-1) ---------------------------------


def test_event_schema_check_is_clean_on_this_repo():
    from tools.check_event_schema import main, missing_kinds
    assert missing_kinds(str(REPO)) == []
    assert main([str(REPO)]) == 0


def test_event_schema_check_catches_undocumented_kind(tmp_path):
    from tools.check_event_schema import main, missing_kinds
    pkg = tmp_path / "sieve"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(
        'class X:\n'
        '    def f(self):\n'
        '        self.metrics.event(\n'
        '            "bogus_kind_xyz", a=1)\n'
        '        validate_record({"event": "other_bogus_kind"})\n'
    )
    bad = missing_kinds(str(tmp_path))
    kinds = {k for _, _, k in bad}
    assert kinds == {"bogus_kind_xyz", "other_bogus_kind"}
    path, line, _ = bad[0]
    assert path == os.path.join("sieve", "rogue.py") and line == 3
    assert main([str(tmp_path)]) == 1


# --- fleet_top --json (satellite) -------------------------------------------


def _fake_snap(replica_health, shard_status="ok", router_health={"ok": 1}):
    rep = {"addr": "127.0.0.1:2", "health": replica_health,
           "stats": {}, "metrics": {}, "error": None}
    return {
        "ts": 1.0,
        "router": {"addr": "127.0.0.1:1", "health": router_health,
                   "stats": {}, "metrics": {}, "error": None},
        "shards": [{"shard": 0, "lo": 2, "hi": 100,
                    "status": shard_status, "replicas": [rep]}],
    }


def test_fleet_top_json_exit_codes(monkeypatch, capsys):
    import tools.fleet_top as ft
    snap = _fake_snap({"status": "ok"})
    monkeypatch.setattr(ft, "fleet_snapshot", lambda a, timeout_s: snap)
    assert ft.main(["127.0.0.1:1", "--json"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out)["shards"][0]["status"] == "ok"  # machine-readable
    # a DOWN replica row flips the exit code
    snap = _fake_snap(None)
    assert ft.main(["127.0.0.1:1", "--json"]) == 1
    # so does a router-side down shard, and an unreachable router
    snap = _fake_snap({"status": "ok"}, shard_status="down")
    assert ft.main(["127.0.0.1:1", "--json"]) == 1
    snap = _fake_snap({"status": "ok"}, router_health=None)
    assert ft.main(["127.0.0.1:1", "--json"]) == 1


# --- trace_report --bundle guards -------------------------------------------


def test_trace_report_bundle_named_errors(tmp_path, capsys):
    from tools.trace_report import main
    # not a bundle: a plain JSON object without the version key
    plain = tmp_path / "not_bundle.json"
    plain.write_text('{"hello": 1}')
    assert main([str(plain), "--bundle"]) == 1
    assert "no recognised 'bundle' version key" in capsys.readouterr().err
    # an empty directory names what it looked for
    empty = tmp_path / "emptydir"
    empty.mkdir()
    assert main([str(empty), "--bundle"]) == 1
    assert "fleet_bundle.json" in capsys.readouterr().err
    # truncated JSON exits named, never a traceback
    trunc = tmp_path / "trunc.json"
    trunc.write_text('{"bundle": "sieve-debug/1", ')
    assert main([str(trunc), "--bundle"]) == 1
    assert "malformed or truncated" in capsys.readouterr().err


def test_trace_report_renders_single_bundle(tmp_path, capsys):
    from tools.trace_report import main
    rec = FlightRecorder("service", debug_dir=str(tmp_path / "dbg"),
                         cooldown_s=0.0)
    rec.emit({"event": "service_shed", "op": "pi"})
    b = rec.trigger("breaker_open", reason="cold plane errors")
    assert main([b["path"], "--bundle"]) == 0  # a bundle DIR is accepted
    out = capsys.readouterr().out
    assert "debug bundle" in out and "breaker_open" in out
    assert "service_shed" in out


# --- acceptance E2E: subprocess fleet, burn + crash, merged bundle ----------


def test_fleet_debug_e2e_burn_crash_merge_and_render(
        src_dir, tmp_path, capsys):
    from tools.fleet_debug import collect, main as fleet_debug_main
    from tools.trace_report import main as trace_report_main

    d0, d1, E = _split_shards(src_dir, tmp_path)
    dbg = [tmp_path / "dbg0", tmp_path / "dbg1", tmp_path / "dbgr"]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO),
               SIEVE_SVC_SLO_MS_PI="0.0001", SIEVE_SVC_SLO_MS_COUNT="0.0001",
               SIEVE_METRICS_SAMPLE_S="0.05")
    procs, addrs = [], []
    try:
        for i, (d, extra) in enumerate((
            (d0, ["--chaos", "svc_crash:any@s1"]),
            (d1, ["--range-lo", str(E)]),
        )):
            p = subprocess.Popen(
                [sys.executable, "-m", "sieve", "serve",
                 "--addr", "127.0.0.1:0", "--n", str(N), "--segments", "4",
                 "--packing", "wheel30", "--checkpoint-dir", d,
                 "--refresh-s", "0", "--quiet", "--allow-chaos",
                 "--debug-dir", str(dbg[i]), *extra],
                env=env, cwd=str(REPO), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
            procs.append(p)
            head = json.loads(p.stdout.readline())
            assert head["event"] == "serving"
            addrs.append(head["addr"])

        # shard 0's first query trips svc_crash: the worker dies, the
        # request gets no reply, and the crash bundle freezes
        with ServiceClient(addrs[0], timeout_s=3) as direct:
            with pytest.raises((CallTimeout, ConnectionError)):
                direct.pi(1000)

        rp = subprocess.Popen(
            [sys.executable, "-m", "sieve", "route",
             "--addr", "127.0.0.1:0",
             "--shard", f"2:{E}={addrs[0]}",
             "--shard", f"{E}:{N + 1}={addrs[1]}",
             "--quiet", "--allow-chaos", "--debug-dir", str(dbg[2])],
            env=env, cwd=str(REPO), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        procs.append(rp)
        rhead = json.loads(rp.stdout.readline())
        assert rhead["event"] == "routing"
        raddr = rhead["addr"]

        with ServiceClient(raddr, timeout_s=30) as cli:
            q = 0
            # exact answers on both shards; every completed op burns the
            # absurd 0.1us SLO, freezing slo_burn bundles per replica
            for i in range(6):
                x = (97 * (i + 1)) % N
                assert cli.is_prime(x) == bool(o_count(x, x + 1))
                q += 1
            assert cli.pi(N - 1) == o_pi(N - 1)  # 2-shard scatter
            q += 1
            # shard 0 dark for 0.2s at request q+1, which targets shard
            # 1 — exact result, shard_down bundle on the router
            cli.inject_chaos(f"svc_shard_down:0@s{q + 1}:0.2")
            lo = E + 10
            assert cli.count(lo, lo + 100) == o_count(lo, lo + 100)
            q += 1
            time.sleep(0.25)
            assert cli.pi(1000) == o_pi(1000)  # shard 0 back, still exact

        _wait(lambda: list(dbg[0].glob("bundle-crash-*")),
              what="replica crash bundle")
        _wait(lambda: list(dbg[0].glob("bundle-slo_burn-*"))
              and list(dbg[1].glob("bundle-slo_burn-*")),
              what="slo_burn bundles on both replicas")
        _wait(lambda: list(dbg[2].glob("bundle-shard_down-*")),
              what="router shard_down bundle")

        # fleet-wide collection: router + both replicas, ONE document
        fleet = collect(raddr, timeout_s=10)
        assert fleet["bundle"] == FLEET_BUNDLE_VERSION
        assert fleet["processes"] == 3
        assert fleet["router"]["bundle"]["role"] == "router"
        assert sorted(r["shard"] for r in fleet["replicas"]) == [0, 1]
        pids = {fleet["router"]["bundle"]["pid"]} | {
            r["bundle"]["pid"] for r in fleet["replicas"]
        }
        assert len(pids) == 3  # three distinct OS processes merged
        for rep in fleet["replicas"]:
            assert rep["bundle"]["role"] == "service"
            assert rep["bundle"]["recorder"]["bundles"] >= 1
            assert rep["bundle"]["history"], "sampler env reached subprocs"

        out_dir = tmp_path / "fleet"
        assert fleet_debug_main(
            [raddr, "--out", str(out_dir), "--timeout", "10"]) == 0
        line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert line["event"] == "fleet_bundle"
        assert line["processes"] == 3 and line["unreachable"] == []
        bundle_path = out_dir / "fleet_bundle.json"
        assert bundle_path.is_file() and Path(line["path"]) == bundle_path

        # the postmortem renders without error and names the trauma
        assert trace_report_main([str(bundle_path), "--bundle"]) == 0
        rendered = capsys.readouterr().out
        assert "fleet debug bundle" in rendered
        assert "3 processes captured" in rendered
        assert "router" in rendered and "replica" in rendered
        assert "metrics history" in rendered
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
