"""Distributed-without-a-cluster tests (SURVEY.md section 4.2 item 4):
shard_map/psum/ppermute logic on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

from sieve.config import SieveConfig
from sieve.parallel.mesh import build_mesh, run_mesh
from sieve.seed import seed_primes, twin_reference
from tests.oracles import PI, TWINS


def _n_devices():
    import jax

    try:
        return len(jax.devices("cpu"))
    except RuntimeError:
        return 0


pytestmark = pytest.mark.skipif(
    _n_devices() < 8, reason="needs the 8-device virtual CPU mesh"
)


@pytest.mark.parametrize("backend", ["jax", "tpu-pallas"])
@pytest.mark.parametrize("packing", ["plain", "odds", "wheel30"])
def test_mesh_1e5_8way(packing, backend):
    cfg = SieveConfig(
        n=10**5, backend=backend, packing=packing, workers=8, twins=True, quiet=True
    )
    res = run_mesh(cfg)
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]
    assert res.n_segments == 8


@pytest.mark.parametrize("ndev", [2, 4, 8])
def test_mesh_device_counts(ndev):
    cfg = SieveConfig(n=10**5, workers=ndev, backend="jax", twins=True, quiet=True)
    res = run_mesh(cfg)
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]


@pytest.mark.parametrize("backend", ["jax", "tpu-pallas"])
def test_mesh_rounds_streaming(backend):
    # rounds > 1: sequential dispatches, one segment per device per round
    cfg = SieveConfig(
        n=10**6, workers=4, rounds=4, backend=backend, twins=True, quiet=True
    )
    res = run_mesh(cfg)
    assert res.pi == PI[10**6]
    assert res.twin_pairs == TWINS[10**6]
    assert res.n_segments == 16


@pytest.mark.parametrize("n", [10**4, 10**4 + 7, 123_456])
def test_mesh_odd_sizes(n):
    cfg = SieveConfig(n=n, workers=8, backend="jax", twins=True, quiet=True)
    res = run_mesh(cfg)
    assert res.pi == seed_primes(n).size
    assert res.twin_pairs == twin_reference(n)


def test_mesh_tiny_n_falls_back():
    cfg = SieveConfig(n=200, workers=8, backend="jax", twins=True, quiet=True)
    res = run_mesh(cfg)
    assert res.pi == 46
    assert res.twin_pairs == twin_reference(200)


def test_mesh_checkpoint_resume(tmp_path):
    cfg = SieveConfig(
        n=10**5, workers=4, rounds=2, backend="jax", twins=True, quiet=True,
        checkpoint_dir=str(tmp_path),
    )
    res1 = run_mesh(cfg)
    assert res1.pi == PI[10**5]
    # resume: everything restored from the ledger, no recompute needed
    cfg2 = SieveConfig(**{**cfg.to_dict(), "resume": True})
    res2 = run_mesh(cfg2)
    assert res2.pi == PI[10**5]
    assert res2.twin_pairs == TWINS[10**5]
