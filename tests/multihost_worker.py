"""Subprocess body for the multi-host mesh test (tests/test_multihost.py).

Each process owns 4 virtual CPU devices; jax.distributed.initialize joins
them into one 8-device mesh and run_mesh executes the identical SPMD
program on both — the DCN scaling story of SURVEY.md section 5.8, minus
the actual second host.

Also the external-worker body for the cpu-cluster trace-plane test:
``multihost_worker.py <coordinator_addr> cluster <worker_id>`` connects
a real subprocess worker to an in-test coordinator (retrying while the
coordinator is still binding), exercising telemetry shipping and clock
alignment across genuine process clocks.

Usage: multihost_worker.py <coordinator_addr> <num_processes> <process_id>
       multihost_worker.py <coordinator_addr> cluster <worker_id>
"""

import sys
import time


def cluster_main() -> int:
    addr, worker_id = sys.argv[1], int(sys.argv[3])
    from sieve.cluster import serve_worker
    from sieve.config import SieveConfig

    cfg = SieveConfig(n=10**5, backend="cpu-cluster", coordinator_addr=addr)
    deadline = time.monotonic() + 30
    while True:
        try:
            serve_worker(cfg, worker_id)
            return 0
        except ConnectionRefusedError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


def main() -> int:
    if len(sys.argv) > 2 and sys.argv[2] == "cluster":
        return cluster_main()
    addr, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    import jax

    jax.distributed.initialize(
        coordinator_address=addr, num_processes=nproc, process_id=pid
    )
    assert jax.process_count() == nproc

    from sieve.config import SieveConfig
    from sieve.parallel.mesh import run_mesh

    cfg = SieveConfig(
        n=10**5, backend="jax", workers=8, rounds=2, twins=True, quiet=True
    )
    res = run_mesh(cfg)
    assert res.pi == 9_592, res.pi
    assert res.twin_pairs == 1_224, res.twin_pairs

    # pallas kernel (interpret mode) through the same multi-host mesh
    cfg2 = SieveConfig(
        n=10**5, backend="tpu-pallas", workers=8, twins=True, quiet=True
    )
    res2 = run_mesh(cfg2)
    assert res2.pi == 9_592, res2.pi
    assert res2.twin_pairs == 1_224, res2.twin_pairs
    print(f"MULTIHOST_OK {pid} {res.pi} {res2.twin_pairs}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
