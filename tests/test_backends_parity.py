"""Differential backend-parity tests (SURVEY.md section 4.2 item 2).

Every SieveWorker backend x every packing: same (lo, hi, seeds) must give an
identical SegmentResult. Randomized segments plus the adversarial fixtures.
Runs on the CPU jax platform (tests/conftest.py).
"""

import dataclasses

import numpy as np
import pytest

from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.seed import seed_primes
from tests.oracles import PI, TWINS

PACKINGS = ["plain", "odds", "wheel30"]


def _available_backends():
    backends = ["cpu-numpy", "jax", "tpu-pallas"]  # pallas: interpret mode in CI
    try:
        from sieve.backends.cpu_native import CpuNativeWorker  # noqa: F401

        backends.append("cpu-native")
    except Exception:
        pass
    return backends


BACKENDS = _available_backends()


def _result(backend, packing, lo, hi, n):
    from sieve.backends import make_worker

    cfg = SieveConfig(n=n, backend=backend, packing=packing, twins=True, quiet=True)
    w = make_worker(cfg)
    seeds = seed_primes(cfg.seed_limit)
    try:
        return w.process_segment(lo, hi, seeds)
    finally:
        w.close()


def _strip(res):
    d = dataclasses.asdict(res)
    d.pop("elapsed_s")
    return d


FIXTURES = [
    # (lo, hi, n) — adversarial per SURVEY 4.2: p^2 at boundary, prime at lo,
    # twin straddling, segment above sqrt(n), tiny segments
    (2, 1000, 10**4),
    (49, 121, 10**4),
    (121, 290, 10**4),
    (991, 1009, 10**4),
    (9000, 10001, 10**4),
    (2, 130, 10**4),
    (101, 4000, 10**5),
    (65536, 70000, 10**5),
    # multi-tile for the pallas kernel (one tile = R_ROWS*128*32 bits =
    # 1,048,576 at the default R_ROWS=256): wheel30 has the fewest bits
    # (8/30 per value), so n=4e6 guarantees >= 2 tiles for EVERY packing,
    # exercising the cross-tile twin carry and per-tile accumulators
    (2, 4 * 10**6 + 1, 4 * 10**6),
]


@pytest.mark.parametrize("packing", PACKINGS)
@pytest.mark.parametrize("backend", [b for b in BACKENDS if b != "cpu-numpy"])
def test_fixture_parity(backend, packing):
    for lo, hi, n in FIXTURES:
        ref = _result("cpu-numpy", packing, lo, hi, n)
        got = _result(backend, packing, lo, hi, n)
        assert _strip(got) == _strip(ref), (packing, lo, hi)


@pytest.mark.parametrize("packing", PACKINGS)
@pytest.mark.parametrize("backend", [b for b in BACKENDS if b != "cpu-numpy"])
def test_randomized_parity(backend, packing):
    rng = np.random.default_rng(7)
    n = 10**6
    for _ in range(10):
        lo = int(rng.integers(2, n - 10))
        hi = int(rng.integers(lo + 2, min(lo + 200_000, n + 1) + 1))
        ref = _result("cpu-numpy", packing, lo, hi, n)
        got = _result(backend, packing, lo, hi, n)
        assert _strip(got) == _strip(ref), (packing, lo, hi)


def test_pallas_group_d_parity():
    """Group D of the pallas kernel (strides > 4096 bits = one tile row)
    needs seed primes > 4096, i.e. n > 4096^2 — beyond the other fixtures.
    One segment at n=3e7 in interpret mode vs the numpy reference (odds
    only: plain duplicates the same m=p strides and wheel30's m=8p strides
    already populate D in the n=4e6 fixture)."""
    n = 30_000_000
    lo, hi = 2_000_003, 24_000_001  # interior segment: nonzero phase per spec
    ref = _result("cpu-numpy", "odds", lo, hi, n)
    got = _result("tpu-pallas", "odds", lo, hi, n)
    assert _strip(got) == _strip(ref)


@pytest.mark.parametrize("packing", PACKINGS)
@pytest.mark.parametrize("backend", [b for b in BACKENDS if b != "cpu-numpy"])
def test_full_run_oracle(backend, packing):
    cfg = SieveConfig(
        n=10**6, backend=backend, packing=packing, n_segments=8, twins=True, quiet=True
    )
    res = run_local(cfg)
    assert res.pi == PI[10**6]
    assert res.twin_pairs == TWINS[10**6]
