"""The range-sharded router fabric (ISSUE 11).

Covers: ShardMap validation by name (gap / overlap / unsorted /
empty / narrow) and the flags/JSON wire-ins; routing geometry
(shard_for, shards_in cold extension, edges); routing math vs the
bitset oracle across all three packings including the shard-edge pair
splice; cold-only splice edges where a twin / cousin pair actually
straddles the boundary; the scatter-gather partial-deadline
contiguous-prefix contract; typed ``unavailable`` naming the shard;
lane-aware shed propagation; router draining; ``svc_shard_down``
grammar, injection, scoping, and ``any`` = every shard; per-shard
replica failover; health/stats key schema snapshots; the probe-TTL
cache counters; shard-server ``--range-lo`` contracts (below-range and
``pi`` rejections); ``is_prime`` on a plain server; router event
schema validation; the trace-report router block; and the shard_smoke
subprocess gate.
"""

import dataclasses
import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from sieve import metrics
from sieve.chaos import (
    ANY_WORKER,
    KINDS,
    ROUTER_REQUEST_KINDS,
    parse_chaos,
)
from sieve.checkpoint import Ledger
from sieve.config import PACKINGS, SieveConfig
from sieve.coordinator import run_local
from sieve.metrics import MemorySink, registry, validate_record
from sieve.seed import seed_primes
from sieve.service import (
    ReplicaSet,
    RouterSettings,
    ServiceClient,
    ServiceSettings,
    Shard,
    ShardMap,
    SieveRouter,
    SieveService,
)

REPO = Path(__file__).resolve().parent.parent

N = 50_000
P = seed_primes(200_000)


def o_pi(x):
    return int(np.searchsorted(P, x, side="right"))


def o_count(lo, hi):
    return int(np.searchsorted(P, hi, side="left")
               - np.searchsorted(P, lo, side="left"))


def o_primes(lo, hi):
    return [int(v) for v in P[(P >= lo) & (P < hi)]]


def o_pairs(lo, hi, gap):
    w = P[(P >= lo) & (P < hi)]
    if w.size < 2:
        return 0
    idx = np.searchsorted(w, w + gap)
    ok = idx < w.size
    return int(np.count_nonzero(w[idx[ok]] == w[ok] + gap))


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


@pytest.fixture(scope="module")
def src_dirs(tmp_path_factory):
    """One fully-sieved source dir per packing; tests split its segments
    into per-shard serving dirs."""
    out = {}
    for packing in PACKINGS:
        path = tmp_path_factory.mktemp(f"router_src_{packing}")
        run_local(_cfg(str(path), packing=packing))
        out[packing] = path
    return out


def _cfg(checkpoint_dir, packing="wheel30", **kw):
    base = dict(
        n=N, backend="cpu-numpy", packing=packing, n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw):
    base = dict(
        workers=2, queue_limit=16, default_deadline_s=10.0,
        refresh_s=0.0,
    )
    base.update(kw)
    return ServiceSettings(**base)


def _split_shards(src_dir, tmp_path, packing="wheel30"):
    """Split the source ledger's 4 segments 2+2 into two shard dirs.
    Returns (shard0_dir, shard1_dir, E) with E on a segment boundary."""
    segs = sorted(
        Ledger.open_readonly(_cfg(str(src_dir), packing=packing))
        .completed().values(),
        key=lambda r: r.lo,
    )
    E = segs[2].lo
    dirs = (tmp_path / "shard0", tmp_path / "shard1")
    for d, part in zip(dirs, (segs[:2], segs[2:])):
        led = Ledger.open(_cfg(str(d), packing=packing))
        for r in part:
            led.record(r)
    return str(dirs[0]), str(dirs[1]), E


class _Fabric:
    """Two-shard in-process fabric: shard services + a SieveRouter."""

    def __init__(self, src_dir, tmp_path, packing="wheel30",
                 router_settings=None, shard1_chaos=None, shard1_extra=None):
        d0, d1, self.E = _split_shards(src_dir, tmp_path, packing)
        self.svcs = [
            SieveService(_cfg(d0, packing=packing), _settings()).start(),
            SieveService(_cfg(d1, packing=packing, chaos=shard1_chaos),
                         _settings(range_lo=self.E)).start(),
        ]
        if shard1_extra:
            self.svcs.append(
                SieveService(_cfg(d1, packing=packing),
                             _settings(range_lo=self.E)).start()
            )
        s1_addrs = tuple(s.addr for s in self.svcs[1:])
        self.map = ShardMap([
            Shard(2, self.E, (self.svcs[0].addr,)),
            Shard(self.E, N + 1, s1_addrs),
        ])
        self.router = SieveRouter(
            self.map,
            router_settings or RouterSettings(quiet=True),
        ).start()
        self.cli = ServiceClient(self.router.addr, timeout_s=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.cli.close()
        self.router.stop()
        for s in self.svcs:
            s.stop()


def _dead_addr():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here anymore
    return f"127.0.0.1:{port}"


# --- ShardMap validation & geometry ------------------------------------------


def test_shardmap_rejects_misconfigurations_by_name():
    a, b = ("127.0.0.1:1",), ("127.0.0.1:2",)
    with pytest.raises(ValueError, match="gap in shard map"):
        ShardMap([Shard(2, 100, a), Shard(120, 200, b)])
    with pytest.raises(ValueError, match="overlap in shard map"):
        ShardMap([Shard(2, 100, a), Shard(90, 200, b)])
    with pytest.raises(ValueError, match="unsorted shard map"):
        ShardMap([Shard(100, 200, a), Shard(2, 100, b)])
    with pytest.raises(ValueError, match="empty"):
        ShardMap([])
    with pytest.raises(ValueError, match="MIN_SPAN"):
        Shard(2, 10, a)
    with pytest.raises(ValueError, match="lo must be >= 2"):
        Shard(0, 100, a)
    with pytest.raises(ValueError, match="range empty"):
        Shard(100, 100, a)
    with pytest.raises(ValueError, match="no addrs"):
        Shard(2, 100, ())


def test_shardmap_flags_and_json_roundtrip(tmp_path):
    m = ShardMap.from_flags([
        "2:1e3=127.0.0.1:7701,127.0.0.1:7702",
        "1e3:10**4=127.0.0.1:7711",
    ])
    assert (m.lo, m.hi, len(m)) == (2, 10_000, 2)
    assert m.shards[0].addrs == ("127.0.0.1:7701", "127.0.0.1:7702")
    path = tmp_path / "map.json"
    path.write_text(json.dumps(m.to_dict()))
    m2 = ShardMap.from_json(str(path))
    assert m2.to_dict() == m.to_dict()
    with pytest.raises(ValueError, match="bad --shard"):
        ShardMap.from_flags(["2:1000"])
    with pytest.raises(ValueError, match="bad shard bound"):
        ShardMap.from_flags(["2:x=127.0.0.1:1"])
    with pytest.raises(ValueError, match='"shards"'):
        ShardMap.from_dict({"nope": []})


def test_shardmap_geometry():
    m = ShardMap([
        Shard(2, 100, ("a",)), Shard(100, 200, ("b",)),
        Shard(200, 300, ("c",)),
    ])
    assert m.edges() == [100, 200]
    assert [m.shard_for(x) for x in (2, 99, 100, 199, 200, 299)] == \
        [0, 0, 1, 1, 2, 2]
    assert m.shard_for(10**9) == 2  # beyond hi: last shard's cold tier
    with pytest.raises(ValueError, match="below shard map range"):
        m.shard_for(1)
    assert m.shards_in(50, 250) == [(0, 50, 100), (1, 100, 200),
                                    (2, 200, 250)]
    assert m.shards_in(150, 180) == [(1, 150, 180)]
    # cold-tier extension: the last part runs past the declared hi
    assert m.shards_in(250, 400) == [(2, 250, 400)]
    assert m.shards_in(100, 100) == []
    with pytest.raises(ValueError, match="below shard map range"):
        m.shards_in(0, 50)


# --- svc_shard_down grammar --------------------------------------------------


def test_svc_shard_down_grammar():
    assert "svc_shard_down" in KINDS
    assert ROUTER_REQUEST_KINDS == ("svc_shard_down",)
    (d,) = parse_chaos("svc_shard_down:1@s3:2.0")
    assert (d.kind, d.worker, d.seg_id, d.param) == \
        ("svc_shard_down", 1, 3, 2.0)
    (d,) = parse_chaos("svc_shard_down:any@s5")
    assert (d.worker, d.param) == (ANY_WORKER, 1.0)  # default window
    # the wire dict carries the worker field: it is an ADDRESS (shard
    # index) on the router plane, not just a match key
    assert d.to_wire() == {"kind": "svc_shard_down", "param": 1.0,
                           "worker": ANY_WORKER}
    with pytest.raises(ValueError, match="worker must be an integer"):
        parse_chaos("svc_shard_down:x@s3")
    with pytest.raises(ValueError, match="param must be a number"):
        parse_chaos("svc_shard_down:0@s3:soon")


# --- routing math vs the oracle ----------------------------------------------


@pytest.mark.parametrize("packing", PACKINGS)
def test_router_math_vs_oracle(src_dirs, tmp_path, packing):
    with _Fabric(src_dirs[packing], tmp_path, packing=packing) as f:
        E, cli = f.E, f.cli
        checks = [
            ("pi", {"x": N}, o_pi(N)),
            ("pi", {"x": 0}, 0),
            ("pi", {"x": 2}, 1),
            ("pi", {"x": E - 1}, o_pi(E - 1)),
            ("pi", {"x": E}, o_pi(E)),
            ("pi", {"x": E + 1}, o_pi(E + 1)),
            ("pi", {"x": N + 3000}, o_pi(N + 3000)),  # cold extension
            ("count", {"lo": E - 400, "hi": E + 400},
             o_count(E - 400, E + 400)),
            ("count", {"lo": E - 400, "hi": E + 400, "kind": "twins"},
             o_pairs(E - 400, E + 400, 2)),
            ("count", {"lo": E - 400, "hi": E + 400, "kind": "cousins"},
             o_pairs(E - 400, E + 400, 4)),
            ("count", {"lo": 2, "hi": N + 1, "kind": "twins"},
             o_pairs(2, N + 1, 2)),
            ("count", {"lo": E - 1, "hi": E + 1}, o_count(E - 1, E + 1)),
            ("nth_prime", {"k": o_pi(E - 1) + 7}, int(P[o_pi(E - 1) + 6])),
            ("nth_prime", {"k": 10}, int(P[9])),
            ("primes", {"lo": E - 60, "hi": E + 60}, o_primes(E - 60, E + 60)),
            ("is_prime", {"x": int(P[o_pi(E)])}, True),
            ("is_prime", {"x": int(P[o_pi(E)]) + 1}, False),
            ("is_prime", {"x": 1}, False),
        ]
        for op, params, want in checks:
            rep = cli.query(op, **params)
            assert rep.get("ok"), (op, params, rep)
            assert rep["value"] == want, (op, params, rep["value"], want)
            assert rep["source"] == "router"
        st = cli.stats()
        assert st["totals_cached"] == 2  # both full-shard totals learned
        assert st["spliced"] >= 2  # edge pair windows were stitched
        assert st["requests"] == len(checks)


def test_router_bad_requests_are_typed(src_dirs, tmp_path):
    with _Fabric(src_dirs["wheel30"], tmp_path) as f:
        # lo below 2 clamps like the single server (the below-fabric
        # rejection only exists for maps starting above 2)
        rep = f.cli.query("count", lo=0, hi=100)
        assert rep["ok"] and rep["value"] == o_count(2, 100)
        rep = f.cli.query("count", lo=9, hi=4)
        assert rep["error"] == "bad_request"
        rep = f.cli.query("count", lo=2, hi=100, kind="sexy")
        assert rep["error"] == "bad_request" and "sexy" in rep["detail"]
        rep = f.cli.query("nth_prime", k=0)
        assert rep["error"] == "bad_request"
        rep = f.cli.query("frobnicate")
        assert rep["error"] == "bad_request"
        assert f.cli.stats()["bad_requests"] == 4


# --- cold-only splice edges with straddling pairs ----------------------------


@pytest.mark.parametrize("edge,kind,pair", [
    (1032, "twins", (1031, 1033)),
    (1050, "twins", (1049, 1051)),
    (1491, "cousins", (1489, 1493)),
])
def test_pair_splice_straddles_edge(memsink, edge, kind, pair):
    gap = {"twins": 2, "cousins": 4}[kind]
    # the scenario is only honest if the pair really straddles the edge
    assert pair[0] < edge <= pair[1] and pair[1] - pair[0] == gap
    assert o_count(pair[0], pair[0] + 1) and o_count(pair[1], pair[1] + 1)
    n = 4000
    svcs = [
        SieveService(SieveConfig(n=n, backend="cpu-numpy", packing="odds",
                                 n_segments=2, quiet=True),
                     _settings()).start(),
        SieveService(SieveConfig(n=n, backend="cpu-numpy", packing="odds",
                                 n_segments=2, quiet=True),
                     _settings(range_lo=edge)).start(),
    ]
    m = ShardMap([Shard(2, edge, (svcs[0].addr,)),
                  Shard(edge, n + 1, (svcs[1].addr,))])
    try:
        with SieveRouter(m, RouterSettings(quiet=True)) as r, \
                ServiceClient(r.addr, timeout_s=30) as cli:
            lo, hi = edge - 200, edge + 200
            rep = cli.query("count", lo=lo, hi=hi, kind=kind)
            assert rep["ok"] and rep["value"] == o_pairs(lo, hi, gap)
            spliced = [rec for rec in memsink.records
                       if rec.get("event") == "router_spliced"]
            assert spliced and spliced[-1]["edge"] == edge
            assert spliced[-1]["pair_kind"] == kind
            assert spliced[-1]["pairs"] >= 1  # the straddler was counted
    finally:
        for s in svcs:
            s.stop()


# --- deadline budgeting: contiguous-prefix partials --------------------------


def test_scatter_partial_is_contiguous_prefix(src_dirs, tmp_path):
    # shard 1's first request stalls past the whole budget: the fabric
    # reply must be a typed deadline_exceeded whose partial covers
    # exactly the contiguous prefix [2, E) answered by shard 0
    with _Fabric(src_dirs["wheel30"], tmp_path,
                 shard1_chaos="svc_stall:any@s1:1.2") as f:
        rep = f.cli.query("pi", x=N, deadline_s=0.6)
        assert rep["error"] == "deadline_exceeded"
        assert rep["shard"] == 1
        part = rep["partial"]
        assert part["answered_hi"] >= f.E
        assert part["pi_so_far"] == o_count(2, part["answered_hi"])
        st = f.cli.stats()
        assert st["deadline_exceeded"] == 1


# --- typed unavailable names the shard ---------------------------------------


def test_whole_shard_down_is_typed_unavailable(src_dirs, tmp_path):
    d0, _d1, E = _split_shards(src_dirs["wheel30"], tmp_path)
    svc = SieveService(_cfg(d0), _settings()).start()
    m = ShardMap([Shard(2, E, (svc.addr,)),
                  Shard(E, N + 1, (_dead_addr(),))])
    try:
        with SieveRouter(m, RouterSettings(quiet=True, rounds=1,
                                           probe_timeout_s=1.0)) as r, \
                ServiceClient(r.addr, timeout_s=30) as cli:
            rep = cli.query("count", lo=E + 10, hi=E + 2000)
            assert rep["error"] == "unavailable"
            assert rep["shard"] == 1
            assert rep["shard_range"] == [E, N + 1]
            assert "shard 1" in rep["detail"]
            # the healthy shard keeps answering exact through the outage
            # (the window must stay below E to be shard-0-only)
            good = cli.query("count", lo=10_000, hi=20_000)
            assert good["ok"] and good["value"] == o_count(10_000, 20_000)
            st = cli.stats()
            assert st["unavailable_replies"] >= 1
    finally:
        svc.stop()


# --- shed propagation carries lane + shard -----------------------------------


def test_shed_propagation_carries_lane_and_shard(src_dirs, tmp_path):
    with _Fabric(src_dirs["wheel30"], tmp_path,
                 shard1_chaos="svc_flood:any@s1:cold",
                 router_settings=RouterSettings(quiet=True, rounds=1)) as f:
        rep = f.cli.query("count", lo=f.E + 10, hi=f.E + 2000)
        assert rep["error"] == "overloaded"
        assert rep["lane"] == "cold"  # lane rides through the router
        assert rep["shard"] == 1
        assert f.cli.stats()["shed_relayed"] == 1


# --- router draining ---------------------------------------------------------


def test_router_drains_typed(src_dirs, tmp_path):
    with _Fabric(src_dirs["wheel30"], tmp_path) as f:
        assert f.cli.query("pi", x=100)["ok"]
        f.router.drain()
        rep = f.cli.query("pi", x=100)
        assert rep["error"] == "draining"
        assert f.cli.stats()["draining_replies"] == 1
        assert f.router.wait_drained(5.0)


# --- svc_shard_down injection ------------------------------------------------


def test_svc_shard_down_window_scoped_to_shard(src_dirs, tmp_path):
    with _Fabric(src_dirs["wheel30"], tmp_path) as f:
        E, cli = f.E, f.cli
        assert cli.query("pi", x=N)["ok"]  # caches both shard totals
        f.router.inject_chaos(
            f"svc_shard_down:0@s{f.router._seq + 1}:0.5")
        # the drawing request itself targets shard 1 and stays exact
        assert cli.query("is_prime", x=int(P[o_pi(E)]))["value"] is True
        rep = cli.query("count", lo=10_000, hi=20_000)  # needs shard 0
        assert rep["error"] == "unavailable" and rep["shard"] == 0
        # cached immutable totals still compose during the window
        assert cli.query("pi", x=N)["value"] == o_pi(N)
        time.sleep(0.55)
        deadline = time.monotonic() + 5
        while True:
            rep = cli.query("count", lo=10_000, hi=20_000)
            if rep.get("ok"):
                assert rep["value"] == o_count(10_000, 20_000)
                break
            assert time.monotonic() < deadline
            time.sleep(0.1)
        assert cli.stats()["shard_down_windows"] == 1


def test_svc_shard_down_any_hits_every_shard(src_dirs, tmp_path):
    with _Fabric(src_dirs["wheel30"], tmp_path) as f:
        f.router.inject_chaos(
            f"svc_shard_down:any@s{f.router._seq + 1}:0.4")
        rep = f.cli.query("is_prime", x=7919)  # draws the directive
        assert rep["error"] == "unavailable"
        rep = f.cli.query("is_prime", x=f.E + 3)
        assert rep["error"] == "unavailable"
        assert f.cli.stats()["shard_down_windows"] == 2  # one per shard


# --- per-shard replica failover ----------------------------------------------


def test_router_fails_over_within_shard(src_dirs, tmp_path):
    with _Fabric(src_dirs["wheel30"], tmp_path, shard1_extra=True) as f:
        E = f.E
        assert f.cli.query("count", lo=E + 10, hi=E + 2000)["ok"]
        f.svcs[1].stop()  # kill the first shard-1 replica
        # the set round-robins, so drive a few queries: whichever lands
        # on the dead replica first must fail over, all replies exact
        for _ in range(4):
            rep = f.cli.query("count", lo=E + 10, hi=E + 2000)
            assert rep["ok"] and rep["value"] == o_count(E + 10, E + 2000)
        assert f.cli.stats()["failovers"] >= 1


# --- health / stats schema ---------------------------------------------------


def test_router_health_and_stats_key_schema_snapshot(src_dirs, tmp_path):
    with _Fabric(src_dirs["wheel30"], tmp_path) as f:
        h = f.cli.health()
        assert sorted(h) == [
            "covered_hi", "draining", "id", "ok", "range_hi", "range_lo",
            "role", "shard_count", "shards", "status", "type",
        ]
        assert (h["role"], h["status"], h["draining"]) == \
            ("router", "ok", False)
        assert (h["range_lo"], h["range_hi"]) == (2, N + 1)
        assert h["covered_hi"] >= N + 1  # both ledgers fully cover
        assert len(h["shards"]) == 2
        for i, sh in enumerate(h["shards"]):
            assert sorted(sh) == [
                "addrs", "brownout", "covered_hi", "draining", "hi",
                "lo", "queue_depth", "shard", "status",
            ]
            assert sh["shard"] == i and sh["status"] == "ok"
        st = f.cli.stats()
        assert sorted(st) == [
            "bad_requests", "batch_members", "batch_requests",
            "batch_rpcs", "deadline_exceeded", "draining",
            "draining_replies", "exemplar_pulls", "exemplars_kept",
            "exemplars_seen", "failovers", "internal_errors", "probes",
            "profile_gaps", "profile_pulls",
            "range_hi", "range_lo", "requests", "routed_point",
            "scattered", "shard_count", "shard_down_windows",
            "shard_errors", "shed_relayed", "spliced",
            "telemetry_events", "telemetry_gaps", "telemetry_merged",
            "totals_cached", "unavailable_replies", "wire_downgrades",
        ]
        # a downed shard degrades fabric health and breaks contiguity
        f.svcs[1].stop()
        h = f.cli.health()
        assert h["status"] == "degraded"
        assert h["shards"][1]["status"] == "unavailable"
        assert h["covered_hi"] < N + 1


# --- probe TTL cache ---------------------------------------------------------


def test_probe_ttl_caches_health_probes(src_dirs, tmp_path):
    d0, _d1, _E = _split_shards(src_dirs["wheel30"], tmp_path)
    svc = SieveService(_cfg(d0), _settings()).start()

    def counters():
        return (registry().counter("router.probe_sent").value,
                registry().counter("router.probe_cached").value)

    try:
        with ReplicaSet([svc.addr], probe_ttl_s=60.0) as rs:
            sent0, cached0 = counters()
            for _ in range(3):
                assert rs.pi(1000) == o_pi(1000)
            sent, cached = counters()
            assert sent - sent0 == 1  # one real probe...
            assert cached - cached0 == 2  # ...then the TTL cache serves
        with ReplicaSet([svc.addr], probe_ttl_s=0.0) as rs:
            sent0, _ = counters()
            for _ in range(2):
                assert rs.pi(1000) == o_pi(1000)
            assert counters()[0] - sent0 == 2  # ttl 0: every call probes
    finally:
        svc.stop()


# --- shard-server --range-lo contracts ---------------------------------------


def test_range_lo_server_rejects_global_and_below_range(src_dirs, tmp_path):
    d0, d1, E = _split_shards(src_dirs["wheel30"], tmp_path)
    with SieveService(_cfg(d1), _settings(range_lo=E)) as svc, \
            ServiceClient(svc.addr, timeout_s=30) as cli:
        assert cli.health()["range_lo"] == E
        rep = cli.query("pi", x=N)  # global-prefix op: composition is
        assert rep["error"] == "bad_request"  # the router's job
        assert "router" in rep["detail"]
        rep = cli.query("count", lo=2, hi=E + 100)
        assert rep["error"] == "bad_request"
        assert f"range_lo={E}" in rep["detail"]
        # in-range ops anchor at the base and answer exact
        assert cli.count(E, N + 1) == o_count(E, N + 1)
        assert cli.count(E + 10, E + 5000) == o_count(E + 10, E + 5000)
        assert cli.nth_prime(5) == o_primes(E, N)[4]  # 5th prime >= E


def test_is_prime_on_plain_server(src_dirs, tmp_path):
    d0, _d1, _E = _split_shards(src_dirs["wheel30"], tmp_path)
    with SieveService(_cfg(d0), _settings()) as svc, \
            ServiceClient(svc.addr, timeout_s=30) as cli:
        assert cli.is_prime(7919) is True
        assert cli.is_prime(7917) is False
        assert cli.is_prime(2) is True
        assert cli.is_prime(1) is False
        assert cli.is_prime(0) is False


# --- events & trace report ---------------------------------------------------


def test_router_events_validate_against_schema(src_dirs, tmp_path, memsink):
    with _Fabric(src_dirs["wheel30"], tmp_path) as f:
        f.cli.query("count", lo=f.E - 50, hi=f.E + 50, kind="twins")
        f.router.inject_chaos(
            f"svc_shard_down:1@s{f.router._seq + 1}:0.2")
        f.cli.query("is_prime", x=f.E + 3)  # draws + hits the window
        # the wire chaos gate defaults closed on the router too
        rep = f.cli.inject_chaos("svc_shard_down:0@s99")
        assert rep.get("error") == "bad_request"
        f.router.drain()
    kinds = {r["event"] for r in memsink.records
             if r["event"].startswith("router_")}
    assert {"router_request", "router_spliced", "router_shard_down",
            "router_chaos_refused", "router_drain"} <= kinds
    for rec in memsink.records:
        if rec["event"].startswith("router_"):
            validate_record(rec)  # raises on any missing schema key


def test_trace_report_router_block():
    from tools.trace_report import report, router_report

    spans = [
        {"name": "rpc.route", "ph": "X", "ts": 0.0, "dur": 900.0,
         "args": {"op": "pi", "outcome": "ok", "shards": 2}},
        {"name": "route.scatter", "ph": "X", "ts": 10.0, "dur": 400.0,
         "args": {"shard": 0, "op": "count", "outcome": "ok"}},
        {"name": "route.scatter", "ph": "X", "ts": 450.0, "dur": 420.0,
         "args": {"shard": 1, "op": "count", "outcome": "unavailable"}},
    ]
    text = report(spans)
    assert "shard router (rpc.route requests):" in text
    assert "unavailable=1" in text
    # pre-router traces (no rpc.route spans) skip the block entirely
    assert router_report([{"name": "rpc.query", "ph": "X", "ts": 0.0,
                           "dur": 1.0, "args": {}}]) == []


# --- subprocess gate: the shard smoke ----------------------------------------


def test_shard_smoke_tool(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "shard_smoke.py"),
         "--keep", str(tmp_path / "work")],
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "SHARD_SMOKE_OK" in proc.stdout
