"""Coverage for the fused in-kernel reduction (ISSUE 3): bit-exact parity
against the split kernel + XLA-postlude oracle across packings, pair
kinds, sliver/boundary segments and need_bits on/off; flat-cutoff
invariance; the --count-kind plug point (config, CLI, backends, merge);
the tuned.json knob loader; and the fused mesh step vs the split one.
"""

import dataclasses
import json
import math

import numpy as np
import pytest

from sieve.config import SieveConfig
from sieve.seed import seed_primes

# (twin kind, cousin kind) per packing — the device splice ids
_KINDS = {
    "odds": ("twins", "cousins"),
    "wheel30": ("twins", "cousins"),
    "plain": ("twins", "cousins"),
}
# one multi-tile segment and one in-tile sliver (odd, non-word-aligned
# bounds) per packing; spans sized to keep interpret mode quick
_SEGMENTS = {
    "odds": [(2_000_003, 6_000_001), (1_001, 33_001)],
    "wheel30": [(2, 3_000_001), (1_013, 37_017)],
    "plain": [(2, 500_002), (977, 40_001)],
}


def _kind(packing: str, gapname: str) -> int:
    from sieve.kernels.jax_mark import (
        COUSIN_ADJ,
        COUSIN_PLAIN,
        COUSIN_W30,
        TWIN_ADJ,
        TWIN_NONE,
        TWIN_PLAIN,
        TWIN_W30,
    )

    if gapname == "none":
        return TWIN_NONE
    table = {
        ("plain", "twins"): TWIN_PLAIN,
        ("odds", "twins"): TWIN_ADJ,
        ("wheel30", "twins"): TWIN_W30,
        ("plain", "cousins"): COUSIN_PLAIN,
        ("odds", "cousins"): COUSIN_ADJ,
        ("wheel30", "cousins"): COUSIN_W30,
    }
    return table[(packing, gapname)]


@pytest.mark.parametrize("packing", list(_SEGMENTS))
@pytest.mark.parametrize("gapname", ["none", "twins", "cousins"])
def test_fused_vs_split_parity(packing, gapname):
    """The acceptance bar: fused returns bit-exact (count, pairs, first,
    last) vs the split kernel + reduce_packed across pair kinds and both
    a multi-tile segment and a sliver with unaligned boundary words."""
    from sieve.kernels.pallas_mark import (
        mark_pallas_fused,
        mark_pallas_split,
        prepare_pallas,
    )

    gap = 4 if gapname == "cousins" else 2
    kind = _kind(packing, gapname)
    for lo, hi in _SEGMENTS[packing]:
        seeds = seed_primes(math.isqrt(hi - 1))
        ps = prepare_pallas(packing, lo, hi, seeds, pair_gap=gap)
        fused = mark_pallas_fused(ps, kind, interpret=True)
        split = mark_pallas_split(ps, kind, interpret=True)
        assert fused == split, (packing, gapname, lo, hi)


def test_fused_need_bits_words_are_final():
    """need_bits=True must return the SAME scalars plus the final word
    array: flat clears, corrections and the beyond-nbits validity mask
    already applied — checked bit-for-bit against the numpy reference."""
    from sieve.backends.cpu_numpy import sieve_segment_flags
    from sieve.kernels.jax_mark import TWIN_ADJ
    from sieve.kernels.pallas_mark import mark_pallas_fused, prepare_pallas

    lo, hi = 2_000_003, 6_000_001
    seeds = seed_primes(math.isqrt(hi - 1))
    ps = prepare_pallas("odds", lo, hi, seeds)
    scalars = mark_pallas_fused(ps, TWIN_ADJ, interpret=True)
    scalars_nb, words = mark_pallas_fused(
        ps, TWIN_ADJ, interpret=True, need_bits=True
    )
    assert scalars_nb == scalars
    flags = sieve_segment_flags("odds", lo, hi, seeds)
    padded = np.zeros(ps.Wpad * 32, bool)
    padded[: flags.size] = flags
    want = (
        (padded.reshape(-1, 32).astype(np.uint32)
         << np.arange(32, dtype=np.uint32)).sum(axis=1, dtype=np.uint32)
    ).reshape(-1, 128)
    assert np.array_equal(np.asarray(words), want)


def test_fused_flat_min_invariance(monkeypatch):
    """Property: the fused result must be invariant under the
    SIEVE_PALLAS_FLAT_MIN cutoff — moving strides between group D and the
    in-kernel flat crossing loop reshapes the work, never the answer."""
    from sieve.kernels.jax_mark import TWIN_ADJ
    from sieve.kernels.pallas_mark import (
        mark_pallas_fused,
        prepare_pallas,
        spec_counts,
    )

    lo, hi = 2_000_003, 12_000_001  # seeds to 5477: strides > 4096 live
    seeds = seed_primes(5477)
    baseline = mark_pallas_fused(
        prepare_pallas("odds", lo, hi, seeds), TWIN_ADJ, interpret=True
    )
    flat_word_counts = set()
    for flat_min in (4097, 5477, 5478):
        monkeypatch.setenv("SIEVE_PALLAS_FLAT_MIN", str(flat_min))
        ps = prepare_pallas("odds", lo, hi, seeds)
        flat_word_counts.add(spec_counts(ps)["flat_words"])
        got = mark_pallas_fused(ps, TWIN_ADJ, interpret=True)
        assert got == baseline, f"flat_min={flat_min}"
    assert len(flat_word_counts) > 1, "cutoffs never moved any stride"


def test_tile_offsets_cursors():
    from sieve.kernels.pallas_mark import TILE_WORDS, tile_offsets

    Wpad = 3 * TILE_WORDS
    idx = np.array(
        [[5, TILE_WORDS - 1, TILE_WORDS, 2 * TILE_WORDS + 7, 0, 0]], np.int32
    )
    mask = np.array([[1, 1, 1, 1, 0, 0]], np.uint32)  # 2 pad entries
    off = tile_offsets(idx, mask, Wpad)
    assert off.tolist() == [[0, 2, 3, 4]]
    # empty list: all cursors collapse to zero
    assert tile_offsets(
        np.zeros((1, 4), np.int32), np.zeros((1, 4), np.uint32), Wpad
    ).tolist() == [[0, 0, 0, 0]]


def test_tuned_json_loader(monkeypatch, tmp_path):
    import sieve.kernels.pallas_mark as pm

    path = tmp_path / "tuned.json"
    path.write_text(json.dumps(
        {"SIEVE_PALLAS_DMIN": 8192, "_meta": {"platform": "test"}}
    ))
    monkeypatch.setenv("SIEVE_TUNED_JSON", str(path))
    assert pm._load_tuned() == {"SIEVE_PALLAS_DMIN": 8192}  # _meta filtered
    monkeypatch.setenv("SIEVE_TUNED_JSON", str(tmp_path / "absent.json"))
    assert pm._load_tuned() == {}

    # resolution order: env var > tuned.json > default
    monkeypatch.setattr(pm, "_TUNED", {"SIEVE_PALLAS_DMIN": 8192})
    monkeypatch.delenv("SIEVE_PALLAS_DMIN", raising=False)
    assert pm._knob("SIEVE_PALLAS_DMIN", 4096) == 8192
    monkeypatch.setenv("SIEVE_PALLAS_DMIN", "16384")
    assert pm._knob("SIEVE_PALLAS_DMIN", 4096) == 16384
    monkeypatch.setattr(pm, "_TUNED", {})
    monkeypatch.delenv("SIEVE_PALLAS_DMIN", raising=False)
    assert pm._knob("SIEVE_PALLAS_DMIN", 4096) == 4096

    # the fused toggle honors tuned.json too, with env winning
    monkeypatch.setattr(pm, "_TUNED", {"SIEVE_PALLAS_FUSED": "0"})
    monkeypatch.delenv("SIEVE_PALLAS_FUSED", raising=False)
    assert pm.pallas_fused_enabled() is False
    monkeypatch.setenv("SIEVE_PALLAS_FUSED", "1")
    assert pm.pallas_fused_enabled() is True


def _pairs_oracle(n: int, gap: int) -> int:
    sieve = np.ones(n + 1, bool)
    sieve[:2] = False
    for p in range(2, math.isqrt(n) + 1):
        if sieve[p]:
            sieve[p * p :: p] = False
    pr = np.flatnonzero(sieve)
    pr = pr[pr + gap <= n]
    return int(np.count_nonzero(sieve[pr + gap]))


@pytest.mark.parametrize("backend", ["cpu-numpy", "cpu-native", "jax",
                                     "tpu-pallas"])
@pytest.mark.parametrize("packing", ["plain", "odds", "wheel30"])
def test_count_kind_cousins_all_backends(backend, packing):
    """--count-kind cousins through every backend and packing, multi-
    segment so the gap-4 straddle merge is exercised, against a brute
    numpy oracle."""
    from sieve.coordinator import run_local

    n = 300_000
    cfg = SieveConfig(n=n, backend=backend, packing=packing,
                      count_kind="cousins", n_segments=3, quiet=True)
    res = run_local(cfg)
    assert res.pi == 25_997
    assert res.twin_pairs == _pairs_oracle(n, 4)


def test_count_kind_config_normalization():
    cfg = SieveConfig(n=100, count_kind="cousins")
    assert cfg.twins and cfg.pair_gap == 4
    cfg = SieveConfig(n=100, twins=True)
    assert cfg.count_kind == "twins" and cfg.pair_gap == 2
    cfg = SieveConfig(n=100)
    assert cfg.count_kind == "primes" and cfg.pair_gap == 0
    with pytest.raises(ValueError):
        SieveConfig(n=100, count_kind="sexy")


def test_count_kind_cli():
    from sieve.cli import build_parser, config_from_args

    args = build_parser().parse_args(["--n", "1000", "--count-kind",
                                      "cousins"])
    cfg = config_from_args(args)
    assert cfg.count_kind == "cousins" and cfg.twins
    args = build_parser().parse_args(["--n", "1000", "--twins"])
    assert config_from_args(args).count_kind == "twins"
    args = build_parser().parse_args(["--n", "1000", "--twins",
                                      "--count-kind", "cousins"])
    with pytest.raises(ValueError, match="conflicts"):
        config_from_args(args)


def test_mesh_fused_vs_split(monkeypatch):
    """8-way mesh: the fused shard step must match the split one on every
    per-segment field, and both must report their reduction_mode."""
    from sieve.parallel.mesh import run_mesh

    cfg = SieveConfig(n=3_000_000, backend="tpu-pallas", packing="odds",
                      workers=8, rounds=1, twins=True, quiet=True)
    monkeypatch.delenv("SIEVE_PALLAS_FUSED", raising=False)
    fused = run_mesh(cfg)
    monkeypatch.setenv("SIEVE_PALLAS_FUSED", "0")
    split = run_mesh(cfg)
    assert (fused.host_phases or {}).get("reduction_mode") == "fused"
    assert (split.host_phases or {}).get("reduction_mode") == "split"
    assert fused.pi == split.pi == 216_816
    assert fused.twin_pairs == split.twin_pairs
    strip = lambda s: {k: v for k, v in dataclasses.asdict(s).items()
                       if k != "elapsed_s"}
    for a, b in zip(fused.segments, split.segments):
        assert strip(a) == strip(b)


def test_local_pallas_reports_fused_phase():
    """run_local on tpu-pallas surfaces reduction_mode and the
    postlude_fused phase through SieveResult.host_phases."""
    from sieve.coordinator import run_local

    cfg = SieveConfig(n=1_000_000, backend="tpu-pallas", packing="odds",
                      n_segments=1, twins=True, quiet=True)
    res = run_local(cfg)
    assert res.pi == 78_498
    ph = res.host_phases or {}
    assert ph.get("reduction_mode") == "fused"
    assert ph.get("postlude_fused_s", 0) > 0
