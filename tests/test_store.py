"""The tiered segment store (ISSUE 17 tentpole).

Covers: the wheel-210 at-rest codec against the seed-prime oracle
(including the 2/3/5/7 side mask and unaligned ranges); tier-0/tier-2
puts, reads, and restart persistence; the ``store_torn_write`` chaos
kind (CRC readers skip, count ``store_torn_entry``, re-materialize —
never a crash, never a wrong answer); cross-handle follow of appends
and compaction generation swaps; the BitsetLRU demotion hook through
SieveIndex (evicted chunks come back as store hits, zero
re-materializations); EVENT_SCHEMA validation of the three new store
events; the bench_compare ``scaling_ratio`` floor (cpus-gated); and
tools/store_smoke.py as a tier-1 subprocess gate (multi-process
SO_REUSEPORT serving, byte-identical replies, warm restart).
"""

import math
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from sieve.backends.cpu_numpy import sieve_segment_flags
from sieve.bitset import get_layout, pack_wheel210, unpack_wheel210
from sieve.chaos import ChaosSchedule, parse_chaos
from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.metrics import validate_record
from sieve.seed import seed_primes
from sieve.service import QueryCtx, SieveIndex, StoreSettings, TieredSegmentStore

REPO = Path(__file__).parent.parent
ORACLE_HI = 100_000
P = seed_primes(ORACLE_HI)


def o_primes(lo, hi):
    return P[(P >= lo) & (P < hi)].astype(np.int64)


def _flags(packing, lo, hi):
    """Real post-sieve flags for [lo, hi) — exactly what the LRU holds."""
    return sieve_segment_flags(packing, lo, hi,
                               seed_primes(math.isqrt(hi - 1)))


# --- wheel-210 codec ----------------------------------------------------------


@pytest.mark.parametrize("lo,hi", [
    (0, 500),          # includes all four wheel primes via small_mask
    (2, 211),          # lo below 10, one block boundary crossed
    (1000, 1999),      # unaligned on both ends
    (209, 421),        # straddles block edges by one value
    (4200, 4200),      # empty range
])
def test_wheel210_roundtrip_oracle(lo, hi):
    vals = o_primes(lo, hi)
    payload, small_mask = pack_wheel210(lo, hi, vals)
    back = unpack_wheel210(lo, hi, payload, small_mask)
    assert np.array_equal(back, vals)
    # 6 bytes per touched 210-block, never more
    if hi > lo:
        blocks = (hi - 1) // 210 - lo // 210 + 1
        assert len(payload) == 6 * blocks


def test_wheel210_rejects_noncoprime():
    # 25 shares a factor with 210: a composite "survivor" must raise,
    # not vanish silently from the at-rest encoding
    with pytest.raises(ValueError, match="wheel"):
        pack_wheel210(0, 100, np.array([2, 3, 25], dtype=np.int64))


# --- store: tiers, persistence, torn writes ----------------------------------


def _store(root, **kw):
    kw.setdefault("settings", StoreSettings(compact_s=0.0))
    return TieredSegmentStore(root, **kw)


def test_store_tiers_and_restart_persistence(tmp_path):
    layout = get_layout("odds")
    lo, hi = 1050, 2940
    flags = _flags("odds", lo, hi)
    with _store(tmp_path, writer=True) as st:
        st.put_count(5000, 6000, int(o_primes(5000, 6000).size))
        assert st.put_flags(lo, hi, flags, layout)
        assert not st.put_flags(lo, hi, flags, layout)  # duplicate: no churn
        assert st.get_entry(5000, 6000)[0] == 0
        assert st.get_entry(lo, hi)[0] == 2
        assert np.array_equal(st.load_values(lo, hi), o_primes(lo, hi))
        s = st.stats()
        assert s["entries"] == {0: 1, 1: 0, 2: 1}
        assert s["demotions"] == 1 and s["writer"]
    # a fresh handle (restart) sees everything without any recompute
    with _store(tmp_path, writer=True) as st2:
        assert st2.stats()["entries"] == {0: 1, 1: 0, 2: 1}
        got = st2.load_flags(lo, hi, layout)
        assert np.array_equal(got, flags)
        assert st2.stats()["hits"] == 1


def test_store_low_range_small_mask_roundtrip(tmp_path):
    # lo=2 exercises the 2/3/5/7 side mask end to end through the store
    layout = get_layout("odds")
    flags = _flags("odds", 2, 5000)
    with _store(tmp_path, writer=True) as st:
        assert st.put_flags(2, 5000, flags, layout)
        assert np.array_equal(st.load_flags(2, 5000, layout), flags)


def test_store_import_ledger_idempotent(tmp_path):
    entries = [(2, 1000, 168), (1000, 2000, 135)]
    with _store(tmp_path, writer=True) as st:
        assert st.import_ledger(entries) == 2
        assert st.import_ledger(entries) == 0
        assert st.get_entry(2, 1000) == (0, 168, 0, 0)


def test_store_torn_write_skipped_counted_retried(tmp_path):
    layout = get_layout("odds")
    events = []
    chaos = ChaosSchedule(parse_chaos("store_torn_write:any@s2"))
    flags = _flags("odds", 1050, 2940)
    with _store(tmp_path, writer=True, chaos=chaos,
                events=lambda kind, quietable=False, **f:
                events.append({"event": kind, "ts": 0.0, **f})) as st:
        st.put_count(5000, 6000, 101)           # append 1: clean
        assert not st.put_flags(1050, 2940, flags, layout)  # append 2: torn
        assert st.get_entry(1050, 2940) is None
        assert st.load_values(1050, 2940) is None
        s = st.stats()
        assert s["torn_writes"] == 1 and s["torn"] == 1
        assert s["demotions"] == 0              # a torn demotion never counts
        # chaos draw consumed: the re-materialized demotion lands clean
        assert st.put_flags(1050, 2940, flags, layout)
        assert np.array_equal(st.load_values(1050, 2940),
                              o_primes(1050, 2940))
    torn = [e for e in events if e["event"] == "store_torn_entry"]
    assert len(torn) == 1
    for e in events:
        validate_record(e)
    # a restarted reader skips the interior torn record the same way
    with _store(tmp_path, writer=False) as rd:
        assert rd.stats()["torn"] == 1
        assert np.array_equal(rd.load_values(1050, 2940),
                              o_primes(1050, 2940))


def test_store_cross_handle_append_follow(tmp_path):
    layout = get_layout("odds")
    flags = _flags("odds", 1050, 2940)
    with _store(tmp_path, writer=True) as wr, \
            _store(tmp_path, writer=False) as rd:
        assert wr.put_flags(1050, 2940, flags, layout)
        rd.maybe_refresh(force=True)
        assert rd.get_entry(1050, 2940)[0] == 2
        assert np.array_equal(rd.load_values(1050, 2940),
                              o_primes(1050, 2940))
        assert not rd.writer


def test_store_compaction_reclaims_and_peers_follow(tmp_path):
    layout = get_layout("odds")
    flags = _flags("odds", 1050, 2940)
    events = []
    with _store(tmp_path, writer=True,
                events=lambda kind, quietable=False, **f:
                events.append({"event": kind, "ts": 0.0, **f})) as wr, \
            _store(tmp_path, writer=False) as rd:
        wr.put_count(1050, 2940, int(o_primes(1050, 2940).size))
        assert wr.put_flags(1050, 2940, flags, layout)  # supersedes tier 0
        assert wr.stats()["dead_bytes"] > 0
        g0 = wr.stats()["gen"]
        assert wr.compact_once(force=True)
        s = wr.stats()
        assert s["gen"] == g0 + 1 and s["compactions"] == 1
        assert s["dead_bytes"] == 0 and s["entries"] == {0: 0, 1: 0, 2: 1}
        # the pre-compaction handle follows the pointer swap
        rd.maybe_refresh(force=True)
        assert rd.stats()["gen"] == g0 + 1
        assert np.array_equal(rd.load_values(1050, 2940),
                              o_primes(1050, 2940))
        # readers never compact
        assert not rd.compact_once(force=True)
    comp = [e for e in events if e["event"] == "store_compacted"]
    assert len(comp) == 1 and comp[0]["live"] == 1
    validate_record(comp[0])


def test_store_t2_cap_downgrades_oldest(tmp_path):
    layout = get_layout("odds")
    with _store(tmp_path, writer=True,
                settings=StoreSettings(compact_s=0.0, t2_bytes=1)) as st:
        for lo in (1050, 3150):
            assert st.put_flags(lo, lo + 1890, _flags("odds", lo, lo + 1890),
                                layout)
        assert st.compact_once(force=True)
        s = st.stats()
        assert s["downgraded"] >= 1
        assert s["entries"][1] >= 1
        # a downgraded entry still answers counts (tier 1), not values
        tier, count, _, _ = st.get_entry(1050, 2940)
        assert tier == 1 and count == int(o_primes(1050, 2940).size)
        assert st.load_values(1050, 2940) is None


# --- SieveIndex demotion/readback --------------------------------------------


@pytest.fixture(scope="module")
def sieved_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("store_ledger")
    run_local(SieveConfig(n=50_000, backend="cpu-numpy", packing="odds",
                          n_segments=4, quiet=True,
                          checkpoint_dir=str(path)))
    return path


def test_index_evictions_demote_and_hit_store(tmp_path, sieved_dir):
    cfg = SieveConfig(n=50_000, backend="cpu-numpy", packing="odds",
                      n_segments=4, quiet=True,
                      checkpoint_dir=str(sieved_dir))
    ledger = Ledger.open_readonly(cfg)
    segs = sorted(ledger.completed().values(), key=lambda r: r.lo)
    with _store(tmp_path, writer=True) as st:
        idx = SieveIndex("odds", ledger.completed(), lru_segments=1, store=st)
        c1 = QueryCtx()
        f1 = idx.get_flags(segs[0].lo, segs[0].hi, c1)
        assert c1.materialized
        c2 = QueryCtx()
        idx.get_flags(segs[1].lo, segs[1].hi, c2)   # evicts seg 0 -> demote
        assert st.stats()["demotions"] >= 1
        c3 = QueryCtx()
        f3 = idx.get_flags(segs[0].lo, segs[0].hi, c3)
        assert c3.store_hit and not c3.materialized
        assert c3.source() == "index"   # store hits stay in the hot tier
        assert np.array_equal(f1, f3)
        assert idx.store_hits == 1 and idx.materialized == 2


# --- bench_compare scaling floor (satellite 3) --------------------------------


def _scaling_rec(value, cpus, procs_max=4):
    return {"m": {"metric": "m", "value": value, "unit": "scaling_ratio",
                  "cpus": cpus, "procs_max": procs_max}}


def test_bench_compare_scaling_floor_gated_by_cpus():
    from tools.bench_compare import compare
    # enough cores and below the floor: gate fires
    _, reg = compare({}, _scaling_rec(0.4, cpus=8), 0.10)
    assert reg and "scaling floor" in reg[0]
    # enough cores, healthy ratio: no regression
    _, reg = compare({}, _scaling_rec(0.85, cpus=8), 0.10)
    assert not reg
    # 1-core container: the ratio measures the scheduler — report only
    lines, reg = compare({}, _scaling_rec(0.2, cpus=1), 0.10)
    assert not reg
    assert any("ungated" in ln for ln in lines)


# --- store events in the schema ----------------------------------------------


def test_store_event_schema_entries():
    validate_record({"event": "store_demoted", "ts": 0.0,
                     "lo": 2, "hi": 100, "bytes": 6, "tier": 2})
    validate_record({"event": "store_compacted", "ts": 0.0, "gen": 1,
                     "live": 3, "reclaimed_bytes": 64, "downgraded": 0})
    validate_record({"event": "store_torn_entry", "ts": 0.0,
                     "offset": 48, "gen": 0})
    with pytest.raises(ValueError, match="missing keys"):
        validate_record({"event": "store_demoted", "ts": 0.0, "lo": 2})


# --- the multi-process smoke gate --------------------------------------------


def test_store_smoke_tool(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "store_smoke.py"),
         "--keep", str(tmp_path / "work")],
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "STORE_SMOKE_OK" in proc.stdout
