"""The replicated service plane: live follow, failover, drain (ISSUE 8).

Covers: LedgerFollower snapshot swaps (BitsetLRU inheritance, monotonic
covered_hi, identical-rewrite no-ops, corrupt / vanished ledgers as
skipped refreshes with events, svc_refresh_corrupt chaos); graceful
drain (typed ``draining`` sheds, in-flight answers kept, wire
``shutdown``, svc_drain chaos, wait_drained); replica_down chaos at the
connection level; ReplicaSet failover policy (dead replica, draining
replica, bad_request never retried, all-dead => typed unavailable); the
CallTimeout desync regression; the --allow-chaos wire gate; and the new
health freshness fields.
"""

import json
import socket
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from sieve import metrics
from sieve.checkpoint import LEDGER_NAME, Ledger
from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.metrics import MemorySink, validate_record
from sieve.seed import seed_primes
from sieve.service import (
    CallTimeout,
    LedgerFollower,
    ReplicaSet,
    ServiceClient,
    ServiceError,
    ServiceSettings,
    SieveService,
)

N = 50_000
P = seed_primes(200_000)


def o_pi(x):
    return int(np.searchsorted(P, x, side="right"))


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


@pytest.fixture(scope="module")
def src_dir(tmp_path_factory):
    """A fully-sieved source dir; tests copy segments out of it into
    per-test serving dirs a "writer" then extends."""
    path = tmp_path_factory.mktemp("failover_src")
    run_local(_cfg(str(path)))
    return path


def _cfg(checkpoint_dir: str, **kw) -> SieveConfig:
    base = dict(
        n=N, backend="cpu-numpy", packing="wheel30", n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw) -> ServiceSettings:
    base = dict(
        workers=2, queue_limit=16, default_deadline_s=10.0,
        cold_chunk=1 << 16, breaker_cooldown_s=0.4,
        refresh_s=0.0,  # follower driven by hand via poll_once
    )
    base.update(kw)
    return ServiceSettings(**base)


def _seed_serving(src_dir, dest: Path, n_segments: int) -> Ledger:
    """Writer's ledger on ``dest`` holding the first n_segments of src."""
    segs = sorted(
        Ledger.open_readonly(_cfg(str(src_dir))).completed().values(),
        key=lambda r: r.lo,
    )
    wled = Ledger.open(_cfg(str(dest)))
    for r in segs[:n_segments]:
        wled.record(r)
    return wled


def _remaining(src_dir, n_segments: int):
    segs = sorted(
        Ledger.open_readonly(_cfg(str(src_dir))).completed().values(),
        key=lambda r: r.lo,
    )
    return segs[n_segments:]


# --- live follow -------------------------------------------------------------


def test_follower_swaps_and_inherits_lru(src_dir, tmp_path, memsink):
    wled = _seed_serving(src_dir, tmp_path, 2)
    with SieveService(_cfg(str(tmp_path)), _settings()) as svc:
        fol = LedgerFollower(svc, refresh_s=1.0)  # no thread: poll by hand
        old = svc.index
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            assert cli.pi(20_000) == o_pi(20_000)  # warms the LRU
            assert fol.poll_once() == "unchanged"
            for r in _remaining(src_dir, 2):
                wled.record(r)
            assert fol.poll_once() == "swapped"
            new = svc.index
            assert new is not old
            assert new.lru is old.lru  # hot queries stay hot across swaps
            assert new.covered_hi > old.covered_hi
            assert svc._refreshes == 1
            # the freshly covered range answers from the index, exact
            assert cli.pi(N - 1) == o_pi(N - 1)
            h = cli.health()
            assert h["covered_hi"] == new.covered_hi
            assert h["refreshes"] == 1
    ev = [x for x in memsink.records if x["event"] == "service_refreshed"]
    assert len(ev) == 1
    assert ev[0]["covered_hi"] > ev[0]["prev_covered_hi"]
    validate_record(ev[0])


def test_follower_identical_rewrite_is_noop(src_dir, tmp_path):
    wled = _seed_serving(src_dir, tmp_path, 2)
    with SieveService(_cfg(str(tmp_path)), _settings()) as svc:
        fol = LedgerFollower(svc, refresh_s=1.0)
        old = svc.index
        # idempotent re-record: new mtime, identical content/checksum
        wled.record(next(iter(wled.completed().values())))
        assert fol.poll_once() == "unchanged"
        assert svc.index is old
        assert svc._refreshes == 0


def test_follower_corrupt_read_is_skipped_refresh(src_dir, tmp_path, memsink):
    wled = _seed_serving(src_dir, tmp_path, 2)
    ledger_path = tmp_path / LEDGER_NAME
    good = ledger_path.read_text()
    with SieveService(_cfg(str(tmp_path)), _settings()) as svc:
        fol = LedgerFollower(svc, refresh_s=1.0)
        old = svc.index
        ledger_path.write_text(good[: len(good) // 2])  # torn write
        assert fol.poll_once() == "failed"
        assert svc.index is old  # keeps serving the previous snapshot
        assert svc._refresh_failed == 1
        # recovery: the writer restores a (longer) good ledger
        for r in _remaining(src_dir, 2):
            wled.record(r)
        assert fol.poll_once() == "swapped"
        assert svc.index.covered_hi > old.covered_hi
    ev = [x for x in memsink.records
          if x["event"] == "service_refresh_failed"]
    assert len(ev) == 1 and "LedgerCorrupt" in ev[0]["reason"]
    validate_record(ev[0])


def test_follower_vanished_ledger_never_regresses(src_dir, tmp_path, memsink):
    _seed_serving(src_dir, tmp_path, 2)
    ledger_path = tmp_path / LEDGER_NAME
    with SieveService(_cfg(str(tmp_path)), _settings()) as svc:
        fol = LedgerFollower(svc, refresh_s=1.0)
        old = svc.index
        # the coordinator's quarantine window: the file is gone between
        # polls — an empty snapshot would regress covered_hi, so skip
        ledger_path.unlink()
        assert fol.poll_once() == "failed"
        assert svc.index is old
        assert svc.index.covered_hi == old.covered_hi
    ev = [x for x in memsink.records
          if x["event"] == "service_refresh_failed"]
    assert len(ev) == 1 and "regress" in ev[0]["reason"]


def test_svc_refresh_corrupt_chaos_then_recovery(src_dir, tmp_path):
    wled = _seed_serving(src_dir, tmp_path, 2)
    with SieveService(_cfg(str(tmp_path)), _settings()) as svc:
        fol = LedgerFollower(svc, refresh_s=1.0)
        svc.inject_chaos(f"svc_refresh_corrupt:any@s{fol.attempts + 1}")
        for r in _remaining(src_dir, 2):
            wled.record(r)
        assert fol.poll_once() == "failed"  # directive consumed, one-shot
        assert fol.poll_once() == "swapped"  # very next poll recovers
        assert svc.index.covered_hi == N + 1


def test_follower_thread_follows_live_writer(src_dir, tmp_path):
    wled = _seed_serving(src_dir, tmp_path, 2)
    settings = _settings(refresh_s=0.05)
    with SieveService(_cfg(str(tmp_path)), settings) as svc:
        assert svc.follower is not None
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            h0 = cli.health()
            for r in _remaining(src_dir, 2):
                wled.record(r)
                time.sleep(0.1)
            deadline = time.monotonic() + 10
            while cli.health()["refreshes"] < 1:
                assert time.monotonic() < deadline, "follower never swapped"
                time.sleep(0.05)
            h1 = cli.health()
            assert h1["covered_hi"] > h0["covered_hi"]
            assert cli.pi(N - 1) == o_pi(N - 1)


# --- graceful drain ----------------------------------------------------------


def test_drain_sheds_typed_draining(src_dir, memsink):
    with SieveService(_cfg(str(src_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            assert cli.pi(100) == o_pi(100)
            svc.drain()
            r = cli.query("pi", x=100)
            assert r["error"] == "draining"
            assert "draining" in r["detail"]
            assert cli.health()["draining"] is True
            assert svc.wait_drained(5)
            assert svc.stats()["draining_replies"] == 1
            # the listener is closed: new connections are refused
            host, port = svc.addr.split(":")
            with pytest.raises(OSError):
                socket.create_connection((host, int(port)), timeout=1)
    ev = [x for x in memsink.records if x["event"] == "service_drain"]
    assert len(ev) == 1
    validate_record(ev[0])


def test_drain_answers_inflight_queries(src_dir):
    settings = _settings(cold_delay_s=0.3)
    with SieveService(_cfg(str(src_dir)), settings) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli, \
                ServiceClient(svc.addr, timeout_s=30) as cli2:
            box = {}

            def fire():
                box["reply"] = cli.query("pi", x=150_000)  # cold: ~0.3 s

            t = threading.Thread(target=fire)
            t.start()
            time.sleep(0.1)  # inside the simulated cold latency
            svc.drain()
            shed = cli2.query("pi", x=100)
            assert shed["error"] == "draining"
            t.join(timeout=30)
            assert not t.is_alive()
            assert box["reply"]["ok"], box["reply"]
            assert box["reply"]["value"] == o_pi(150_000)
            assert svc.wait_drained(10)


def test_shutdown_wire_message_drains(src_dir):
    with SieveService(_cfg(str(src_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            r = cli.shutdown()
            assert r["ok"] and r["draining"]
            assert cli.query("pi", x=100)["error"] == "draining"
            assert svc.wait_drained(5)


def test_svc_drain_chaos_directive(src_dir):
    with SieveService(_cfg(str(src_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            svc.inject_chaos(f"svc_drain:any@s{svc._seq + 1}")
            r = cli.query("pi", x=100)
            assert r["error"] == "draining"
            assert svc._draining


# --- replica_down + CallTimeout ----------------------------------------------


def test_replica_down_drops_connections(src_dir):
    with SieveService(_cfg(str(src_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=5) as cli:
            svc.inject_chaos(f"replica_down:any@s{svc._seq + 1}:0.4")
            with pytest.raises((ConnectionError, OSError)):
                cli.pi(100)
            # inside the window a fresh connection is dropped too
            with ServiceClient(svc.addr, timeout_s=5) as cli2, \
                    pytest.raises((ConnectionError, OSError)):
                cli2.pi(100)
        deadline = time.monotonic() + 5
        while True:  # after the window the replica answers again
            try:
                with ServiceClient(svc.addr, timeout_s=5) as cli3:
                    assert cli3.pi(100) == o_pi(100)
                break
            except (ConnectionError, OSError):
                assert time.monotonic() < deadline
                time.sleep(0.1)


def test_call_timeout_closes_desynced_socket(src_dir):
    """Regression (ISSUE 8 satellite): a timed-out call used to leave its
    request in flight, so the next call read the PREVIOUS reply."""
    with SieveService(_cfg(str(src_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=0.3) as cli:
            svc.inject_chaos(f"svc_stall:any@s{svc._seq + 1}:1.0")
            with pytest.raises(CallTimeout) as ei:
                cli.pi(100)
            assert ei.value.kind == "timeout"
            # the poisoned connection fails fast — it must never hand the
            # stalled pi(100) reply to a different request
            with pytest.raises(ConnectionError, match="desynced"):
                cli.pi(200_000_000)


# --- ReplicaSet --------------------------------------------------------------


def _dead_addr() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here anymore
    return f"127.0.0.1:{port}"


def test_replicaset_fails_over_from_dead_replica(src_dir):
    with SieveService(_cfg(str(src_dir)), _settings()) as svc:
        with ReplicaSet([_dead_addr(), svc.addr], timeout_s=10,
                        rounds=2, backoff_base_s=0.01) as rs:
            assert rs.pi(30_000) == o_pi(30_000)
            assert rs.nth_prime(100) == int(P[99])


def test_replicaset_fails_over_from_draining_replica(src_dir):
    s1 = SieveService(_cfg(str(src_dir)), _settings()).start()
    s2 = SieveService(_cfg(str(src_dir)), _settings()).start()
    try:
        addrs = [s1.addr, s2.addr]  # before drain closes s1's listener
        s1.drain()
        with ReplicaSet(addrs, timeout_s=10,
                        rounds=2, backoff_base_s=0.01) as rs:
            for _ in range(4):  # round-robin must steer off s1 every time
                assert rs.pi(30_000) == o_pi(30_000)
            assert s2.stats()["requests"] >= 4
    finally:
        s1.stop()
        s2.stop()


def test_replicaset_never_retries_bad_request(src_dir):
    with SieveService(_cfg(str(src_dir)), _settings()) as svc:
        with ReplicaSet([svc.addr, svc.addr], timeout_s=10) as rs:
            r = rs.query("count", lo=9, hi=4)
            assert r["error"] == "bad_request"
            assert rs.failovers == 0  # returned from the first replica
            with pytest.raises(ServiceError) as ei:
                rs.count(9, 4)
            assert ei.value.kind == "bad_request"


def test_replicaset_all_dead_is_typed_unavailable():
    rs = ReplicaSet([_dead_addr(), _dead_addr()], timeout_s=2,
                    rounds=2, backoff_base_s=0.01, backoff_cap_s=0.02)
    with pytest.raises(ServiceError) as ei:
        rs.pi(100)
    assert ei.value.kind == "unavailable"


# --- wire chaos gate + health fields -----------------------------------------


def test_wire_chaos_gate_refuses_and_events(src_dir, memsink):
    with SieveService(_cfg(str(src_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            r = cli.inject_chaos("svc_shed:any@s1")
            assert not r["ok"] and r["error"] == "bad_request"
            assert "--allow-chaos" in r["detail"]
            assert len(svc.chaos) == 0  # nothing was scheduled
            assert cli.pi(100) == o_pi(100)  # and nothing sheds
    ev = [x for x in memsink.records
          if x["event"] == "service_chaos_refused"]
    assert len(ev) == 1 and ev[0]["spec"] == "svc_shed:any@s1"
    validate_record(ev[0])


def test_wire_chaos_allowed_when_enabled(src_dir):
    with SieveService(_cfg(str(src_dir)),
                      _settings(wire_chaos=True)) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            r = cli.inject_chaos(f"svc_shed:any@s{svc._seq + 1}")
            assert r["ok"] and r["injected"] == 1
            assert cli.query("pi", x=100)["error"] == "overloaded"


def test_health_freshness_fields(src_dir):
    with SieveService(_cfg(str(src_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            h = cli.health()
            assert h["covered_hi"] == svc.index.covered_hi
            assert h["refreshes"] == 0
            assert h["draining"] is False
            assert h["snapshot_age_s"] >= 0
            s = cli.stats()
            for key in ("refreshes", "refresh_failed", "refresh_attempts",
                        "snapshot_age_s", "draining"):
                assert key in s


def test_trace_report_prints_refresh_line():
    from tools.trace_report import service_report

    spans = [
        {"name": "service.refresh", "ph": "X", "ts": 1000, "dur": 500,
         "args": {"outcome": "swapped", "covered_hi": 50_001,
                  "prev_covered_hi": 25_000}},
        {"name": "service.refresh", "ph": "X", "ts": 3000, "dur": 200,
         "args": {"outcome": "failed", "reason": "chaos"}},
        {"name": "rpc.query", "ph": "X", "ts": 5000, "dur": 400,
         "args": {"op": "pi", "outcome": "ok", "source": "index"}},
    ]
    lines = service_report(spans)
    joined = "\n".join(lines)
    assert "ledger follow" in joined
    assert "1 refresh(es) swapped" in joined
    assert "covered_hi=50001" in joined
    # refresh-only traces still render the freshness line
    assert "ledger follow" in "\n".join(service_report(spans[:2]))
