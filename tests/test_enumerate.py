"""Prime enumeration tests (SURVEY.md section 0: counting AND enumerating)."""

import json

import numpy as np
import pytest

from sieve.cli import main
from sieve.enumerate import primes_in_range
from sieve.seed import seed_primes


def _collect(packing, lo, hi):
    chunks = list(primes_in_range(packing, lo, hi))
    return np.concatenate(chunks) if chunks else np.zeros(0, np.int64)


@pytest.mark.parametrize("packing", ["plain", "odds", "wheel30"])
def test_enumerate_matches_seed_sieve(packing):
    all_primes = seed_primes(10_000)
    got = _collect(packing, 2, 10_001)
    np.testing.assert_array_equal(got, all_primes)


@pytest.mark.parametrize("packing", ["plain", "odds", "wheel30"])
@pytest.mark.parametrize("lo,hi", [(2, 3), (2, 8), (90, 100), (7919, 7920),
                                   (999_900, 1_000_100), (1, 2)])
def test_enumerate_windows(packing, lo, hi):
    all_primes = seed_primes(max(hi, 2))
    want = all_primes[(all_primes >= lo) & (all_primes < hi)]
    got = _collect(packing, lo, hi)
    np.testing.assert_array_equal(got, want)


def test_enumerate_spans_internal_slices():
    # window wider than one internal slice: chunk boundaries must not drop
    # or duplicate primes
    lo, hi = 10, 2**24 + 1000
    got = _collect("odds", lo, hi)
    assert got[0] == 11
    assert np.all(np.diff(got) > 0)
    want_count = seed_primes(hi - 1).size - 4  # minus 2, 3, 5, 7
    assert got.size == want_count


def test_enumerate_span_cap():
    with pytest.raises(ValueError):
        list(primes_in_range("odds", 2, 2 * 10**9 + 10))


def test_cli_emit_primes(capsys):
    assert main(["--emit-primes", "90:100"]) == 0
    assert capsys.readouterr().out.split() == ["97"]
    assert main(["--emit-primes", "2:30", "--json"]) == 0
    got = json.loads(capsys.readouterr().out)
    assert got == [2, 3, 5, 7, 11, 13, 17, 19, 23, 29]
    assert main(["--emit-primes", "1:10", "--packing", "wheel30"]) == 0
    assert capsys.readouterr().out.split() == ["2", "3", "5", "7"]
