"""Prime enumeration tests (SURVEY.md section 0: counting AND enumerating)."""

import json

import numpy as np
import pytest

from sieve.cli import main
from sieve.enumerate import _SLICE, MAX_HI, primes_in_range
from sieve.seed import seed_primes

_MR_BASES = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37)


def _is_prime(n: int) -> bool:
    # deterministic Miller-Rabin for n < 3.3e24 (bases 2..37) — an oracle
    # independent of every sieve in the repo, cheap at any offset
    if n < 2:
        return False
    for p in _MR_BASES:
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for a in _MR_BASES:
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _collect(packing, lo, hi):
    chunks = list(primes_in_range(packing, lo, hi))
    return np.concatenate(chunks) if chunks else np.zeros(0, np.int64)


@pytest.mark.parametrize("packing", ["plain", "odds", "wheel30"])
def test_enumerate_matches_seed_sieve(packing):
    all_primes = seed_primes(10_000)
    got = _collect(packing, 2, 10_001)
    np.testing.assert_array_equal(got, all_primes)


@pytest.mark.parametrize("packing", ["plain", "odds", "wheel30"])
@pytest.mark.parametrize("lo,hi", [(2, 3), (2, 8), (90, 100), (7919, 7920),
                                   (999_900, 1_000_100), (1, 2)])
def test_enumerate_windows(packing, lo, hi):
    all_primes = seed_primes(max(hi, 2))
    want = all_primes[(all_primes >= lo) & (all_primes < hi)]
    got = _collect(packing, lo, hi)
    np.testing.assert_array_equal(got, want)


def test_enumerate_spans_internal_slices():
    # window wider than one internal slice: chunk boundaries must not drop
    # or duplicate primes
    lo, hi = 10, 2**24 + 1000
    got = _collect("odds", lo, hi)
    assert got[0] == 11
    assert np.all(np.diff(got) > 0)
    want_count = seed_primes(hi - 1).size - 4  # minus 2, 3, 5, 7
    assert got.size == want_count


def test_enumerate_span_cap():
    with pytest.raises(ValueError):
        list(primes_in_range("odds", 2, 2 * 10**9 + 10))


@pytest.mark.parametrize("packing", ["plain", "odds", "wheel30"])
def test_enumerate_empty_and_sub_extra_windows(packing):
    # lo == hi at various offsets: always empty, never an error
    for v in (0, 2, 7, 10_000):
        assert _collect(packing, v, v).size == 0
    # windows entirely below the first prime (and below every layout
    # extra) — [0, 1) and [0, 2) must be empty for all packings
    assert _collect(packing, 0, 1).size == 0
    assert _collect(packing, 0, 2).size == 0
    np.testing.assert_array_equal(_collect(packing, 0, 3), [2])


@pytest.mark.parametrize("packing", ["plain", "odds", "wheel30"])
def test_enumerate_window_ending_exactly_at_max_hi(packing):
    # the documented ceiling itself must work: [MAX_HI - 200, MAX_HI)
    lo, hi = MAX_HI - 200, MAX_HI
    got = _collect(packing, lo, hi)
    want = [v for v in range(lo, hi) if _is_prime(v)]
    np.testing.assert_array_equal(got, want)


def test_enumerate_beyond_max_hi_raises():
    with pytest.raises(ValueError, match="seed sieve"):
        primes_in_range("odds", MAX_HI - 10, MAX_HI + 1)


@pytest.mark.parametrize("packing", ["plain", "odds", "wheel30"])
def test_enumerate_straddles_slice_boundary(packing):
    # a window crossing the internal _SLICE cut must not drop/duplicate
    # primes at the seam
    lo, hi = _SLICE - 60, _SLICE + 60
    got = _collect(packing, lo, hi)
    want = [v for v in range(lo, hi) if _is_prime(v)]
    np.testing.assert_array_equal(got, want)


def test_cli_emit_primes(capsys):
    assert main(["--emit-primes", "90:100"]) == 0
    assert capsys.readouterr().out.split() == ["97"]
    assert main(["--emit-primes", "2:30", "--json"]) == 0
    got = json.loads(capsys.readouterr().out)
    assert got == [2, 3, 5, 7, 11, 13, 17, 19, 23, 29]
    assert main(["--emit-primes", "1:10", "--packing", "wheel30"]) == 0
    assert capsys.readouterr().out.split() == ["2", "3", "5", "7"]
