"""Streaming incremental host-prepare tests: chain-vs-from-scratch parity
across packings and boundary cases, the PrepPipeline producer/consumer, and
the mesh integration (resume skips prepare; kill-mid-run resume is exact;
residency stays bounded)."""

import dataclasses
import threading
import time

import numpy as np
import pytest

from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.kernels.jax_mark import SPEC_BLOCK, TIER1_MAX, WORD_BUCKET
from sieve.kernels.specs import (
    SpecChain,
    TieredChain,
    marking_specs,
    prepare_tiered,
)
from sieve.parallel.pipeline import PrepPipeline
from sieve.seed import seed_primes
from tests.oracles import PI, TWINS

PACKINGS = ["plain", "odds", "wheel30"]


def _n_devices():
    import jax

    try:
        return len(jax.devices("cpu"))
    except RuntimeError:
        return 0


# ---------------------------------------------------------------------------
# chain parity: incremental residue advance == from-scratch, bit for bit
# ---------------------------------------------------------------------------

# Boundary cases: lo crossing p^2 of small seeds (47->49=7^2, 121=11^2,
# 361=19^2), word- and wheel-unaligned cuts, a sub-word sliver, and
# arbitrary forward/backward jumps (the chain's advance is Delta-based, so
# skipped or revisited windows must stay exact).
_CUTS = [2, 47, 49, 121, 128, 360, 361, 1000, 1024, 2310, 5000, 10_007,
         20_000]
_SEGMENTS = list(zip(_CUTS, _CUTS[1:])) + [
    (50_000, 50_003),     # sliver: 0-2 candidate bits depending on packing
    (50_003, 80_000),
    (30_000, 40_000),     # backward jump
    (80_000, 80_000 + 7 * 32 * 3 + 5),  # unaligned span after a re-jump
]


@pytest.mark.parametrize("packing", PACKINGS)
def test_spec_chain_matches_from_scratch(packing):
    seeds = seed_primes(300)
    chain = SpecChain(packing, seeds)
    for lo, hi in _SEGMENTS:
        got = chain.specs(lo, hi)
        want = marking_specs(packing, lo, hi, seeds)
        assert got.nbits == want.nbits, (lo, hi)
        for f in ("m", "r", "s"):
            a, b = getattr(got, f), getattr(want, f)
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b, err_msg=f"{f} at {(lo, hi)}")


def _assert_segment_equal(got, want, ctx):
    for f in dataclasses.fields(want):
        a, b = getattr(got, f.name), getattr(want, f.name)
        if isinstance(b, tuple):
            assert len(a) == len(b), (f.name, ctx)
            for i, (x, y) in enumerate(zip(a, b)):
                assert x.dtype == y.dtype, (f.name, i, ctx)
                np.testing.assert_array_equal(
                    x, y, err_msg=f"{f.name}[{i}] at {ctx}"
                )
        elif isinstance(b, np.ndarray):
            assert a.dtype == b.dtype, (f.name, ctx)
            np.testing.assert_array_equal(a, b, err_msg=f"{f.name} at {ctx}")
        else:
            assert a == b, (f.name, ctx)


@pytest.mark.parametrize("packing", PACKINGS)
def test_tiered_chain_matches_from_scratch(packing):
    seeds = seed_primes(1000)
    chain = TieredChain(packing, seeds, TIER1_MAX, SPEC_BLOCK, WORD_BUCKET)
    for lo, hi in [(2, 10_000), (10_000, 30_000), (30_000, 30_517),
                   (50_000, 90_000), (40_000, 50_000)]:
        got = chain.prepare(lo, hi)
        want = prepare_tiered(
            packing, lo, hi, seeds,
            tier1_max=TIER1_MAX, spec_block=SPEC_BLOCK,
            word_bucket=WORD_BUCKET,
        )
        _assert_segment_equal(got, want, (packing, lo, hi))


@pytest.mark.parametrize("packing", PACKINGS)
def test_pallas_chain_matches_from_scratch(packing):
    from sieve.bitset import get_layout
    from sieve.kernels.pallas_mark import (
        TILE_WORDS,
        PallasChain,
        prepare_pallas,
    )

    seeds = seed_primes(3000)  # strides past 4096 bits -> group D populated
    layout = get_layout(packing)
    bounds = [(2, 200_000), (200_000, 400_000), (600_000, 800_123),
              (400_000, 600_000)]
    W = max(-(-layout.nbits(lo, hi) // 32) for lo, hi in bounds)
    wpad = -(-(W + 1) // TILE_WORDS) * TILE_WORDS
    chain = PallasChain(packing, seeds, wpad)
    for lo, hi in bounds:
        got = chain.prepare(lo, hi)
        want = prepare_pallas(packing, lo, hi, seeds, wpad=wpad)
        _assert_segment_equal(got, want, (packing, lo, hi))


# ---------------------------------------------------------------------------
# PrepPipeline unit behavior
# ---------------------------------------------------------------------------


def test_prep_pipeline_orders_and_bounds_residency():
    rounds = list(range(12))
    done: list[int] = []
    lock = threading.Lock()

    def prep(state, rnd):
        time.sleep(0.002)
        with lock:
            done.append(rnd)
        return rnd * 10

    pipe = PrepPipeline(rounds, list, prep, window=2, threads=2)
    try:
        for rnd in rounds:
            assert pipe.take(rnd) == rnd * 10
    finally:
        pipe.close()
    assert pipe.stats["rounds_prepared"] == 12
    assert 1 <= pipe.stats["peak_resident"] <= 3  # window + 1
    # claimed strictly in order even across two threads
    assert sorted(done) == rounds


def test_prep_pipeline_propagates_worker_errors():
    def prep(state, rnd):
        if rnd == 3:
            raise ValueError("boom")
        return rnd

    pipe = PrepPipeline(list(range(6)), list, prep, window=1, threads=2)
    try:
        with pytest.raises(ValueError, match="boom"):
            for rnd in range(6):
                pipe.take(rnd)
    finally:
        pipe.close()


# ---------------------------------------------------------------------------
# mesh integration (needs the 8-device virtual CPU mesh from conftest)
# ---------------------------------------------------------------------------

needs_mesh = pytest.mark.skipif(
    _n_devices() < 8, reason="needs the 8-device virtual CPU mesh"
)


@needs_mesh
def test_mesh_resume_skips_prepare(tmp_path):
    from sieve.parallel.mesh import run_mesh

    cfg = SieveConfig(
        n=10**5, workers=4, rounds=3, backend="jax", twins=True, quiet=True,
        checkpoint_dir=str(tmp_path),
    )
    res1 = run_mesh(cfg)
    assert res1.pi == PI[10**5]
    assert res1.host_phases["rounds_prepared"] == 3
    # full resume: every round restored from the ledger -> nothing prepared
    cfg2 = SieveConfig(**{**cfg.to_dict(), "resume": True})
    res2 = run_mesh(cfg2)
    assert res2.pi == PI[10**5]
    assert res2.twin_pairs == TWINS[10**5]
    assert res2.host_phases["rounds_prepared"] == 0


@needs_mesh
@pytest.mark.parametrize("packing", ["odds", "wheel30"])
def test_mesh_kill_midrun_resume_exact(tmp_path, monkeypatch, packing):
    from sieve.parallel.mesh import run_mesh

    monkeypatch.setenv("SIEVE_ROUND_WINDOW", "1")
    cfg = SieveConfig(
        n=10**5, workers=4, rounds=4, backend="jax", twins=True, quiet=True,
        checkpoint_dir=str(tmp_path / packing), packing=packing,
    )
    real_record = Ledger.record
    calls = {"n": 0}

    def dying_record(self, res):
        calls["n"] += 1
        if calls["n"] > 6:  # dies mid round 1 (segments record per drain)
            raise RuntimeError("simulated mid-run death")
        return real_record(self, res)

    monkeypatch.setattr(Ledger, "record", dying_record)
    with pytest.raises(RuntimeError, match="simulated"):
        run_mesh(cfg)
    monkeypatch.setattr(Ledger, "record", real_record)

    cfg2 = SieveConfig(**{**cfg.to_dict(), "resume": True})
    res = run_mesh(cfg2)
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]
    # round 0 was fully recorded before the death -> resumed run prepared
    # strictly fewer rounds than the plan, but at least the killed ones
    assert 0 < res.host_phases["rounds_prepared"] < 4


@needs_mesh
def test_mesh_peak_resident_bounded(monkeypatch):
    from sieve.parallel.mesh import run_mesh

    monkeypatch.setenv("SIEVE_ROUND_WINDOW", "1")
    cfg = SieveConfig(
        n=10**5, workers=2, rounds=8, backend="jax", twins=True, quiet=True
    )
    res = run_mesh(cfg)
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]
    ph = res.host_phases
    assert ph["rounds_prepared"] == 8
    assert ph["peak_resident_rounds"] <= 2  # window + 1
