"""The multiplexed wire plane (ISSUE 14): pipelined framing, the
``batch`` op, and connection-level isolation.

Covers: the incremental :class:`~sieve.rpc.FrameDecoder` (byte-by-byte
feeds, multi-frame feeds, oversized/garbage frames); pipelined reply
correlation by id under out-of-order completion; mid-pipeline typed
sheds and deadline partials landing on the RIGHT ids; inline ops
(health/stats) overtaking queued query replies; the ``svc_slow_frame``
chaos kind and a raw-socket slowloris proving one dribbling connection
never head-of-line blocks another; the bounded write queue killing slow
consumers typed; the vectorized ``batch`` op on server and router
(exactness vs oracle, per-member typed outcomes, the ≤1-RPC-per-shard
scatter contract gated on the ``batch_rpcs`` counter, totals-cache
fill); :meth:`ReplicaSet.query_many` suffix-only failover;
:class:`ClientPool` connection reuse; the ``tools/check_wire_ops``
parity gate; and the bench_compare ``qps`` regression rule.
"""

import socket
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from sieve import metrics
from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.coordinator import run_local
from sieve.metrics import MemorySink, validate_record
from sieve.rpc import (MAX_FRAME, FrameDecoder, encode_msg, encode_msg_v2,
                       recv_msg)
from sieve.seed import seed_primes
from sieve.service import (
    ClientPool,
    QueryCtx,
    ReplicaSet,
    RouterSettings,
    ServiceClient,
    ServiceError,
    ServiceSettings,
    Shard,
    ShardMap,
    SieveIndex,
    SieveRouter,
    SieveService,
)

REPO = Path(__file__).parent.parent
N = 50_000
P = seed_primes(200_000)


def o_pi(x):
    return int(np.searchsorted(P, x, side="right"))


def o_count(lo, hi):
    return int(np.searchsorted(P, hi, side="left")
               - np.searchsorted(P, lo, side="left"))


def o_is_prime(x):
    return o_pi(x) - o_pi(x - 1) > 0


@pytest.fixture
def memsink():
    sink = MemorySink()
    metrics.add_sink(sink)
    yield sink
    metrics.remove_sink(sink)


@pytest.fixture(scope="module")
def ledger_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("wire_ledger")
    run_local(_cfg(str(path)))
    return path


def _cfg(checkpoint_dir: str, **kw) -> SieveConfig:
    base = dict(
        n=N, backend="cpu-numpy", packing="wheel30", n_segments=4,
        quiet=True, checkpoint_dir=checkpoint_dir,
    )
    base.update(kw)
    return SieveConfig(**base)


def _settings(**kw) -> ServiceSettings:
    base = dict(
        workers=2, queue_limit=16, default_deadline_s=10.0,
        cold_chunk=1 << 16, breaker_cooldown_s=0.4, refresh_s=0.0,
    )
    base.update(kw)
    return ServiceSettings(**base)


@pytest.fixture
def service(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            yield svc, cli


def _dead_addr():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here anymore
    return f"127.0.0.1:{port}"


# --- FrameDecoder ------------------------------------------------------------


def test_frame_decoder_byte_by_byte():
    msgs = [{"type": "query", "op": "pi", "x": 10**9},
            {"type": "health", "id": 7}]
    wire = b"".join(encode_msg(m) for m in msgs)
    dec = FrameDecoder()
    got = []
    for i in range(len(wire)):
        got.extend(dec.feed(wire[i:i + 1]))
    assert got == msgs
    assert dec.buffered() == 0


def test_frame_decoder_many_frames_one_feed():
    msgs = [{"id": i, "v": "x" * i} for i in range(5)]
    wire = b"".join(encode_msg(m) for m in msgs)
    tail = encode_msg({"id": 99})
    dec = FrameDecoder()
    # every complete frame pops at once; the partial tail stays buffered
    got = dec.feed(wire + tail[:-3])
    assert got == msgs
    assert dec.buffered() == len(tail) - 3
    assert dec.feed(tail[-3:]) == [{"id": 99}]
    assert dec.buffered() == 0


def test_frame_decoder_oversized_frame_is_typed():
    header = (MAX_FRAME + 1).to_bytes(8, "big")
    with pytest.raises(ValueError, match="frame"):
        FrameDecoder().feed(header)


def test_frame_decoder_garbage_body_is_typed():
    body = b"not json at all"
    frame = len(body).to_bytes(8, "big") + body
    with pytest.raises(ValueError):
        FrameDecoder().feed(frame)


# --- pipelined correlation ---------------------------------------------------


def test_pipelined_replies_correlate_out_of_order(ledger_dir):
    """A slow cold query submitted FIRST must not delay — or steal the
    replies of — hot queries pipelined behind it on the same socket."""
    settings = _settings(workers=4, cold_delay_s=0.4)
    with SieveService(_cfg(str(ledger_dir)), settings) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            cold_id = cli.submit("pi", x=90_000)
            hot = [(cli.submit("pi", x=x), x)
                   for x in (100, 5_000, 12_345, 30_000)]
            t0 = time.monotonic()
            hot_replies = cli.drain([rid for rid, _ in hot])
            hot_elapsed = time.monotonic() - t0
            # the hot replies completed (and were collected) while the
            # cold leader was still inside its simulated 0.4 s compute
            assert hot_elapsed < 0.4
            assert cli.pending() == 1
            for rid, x in hot:
                r = hot_replies[rid]
                assert r["ok"] and r["id"] == rid
                assert r["value"] == o_pi(x)
            cold = cli.drain([cold_id])[cold_id]
            assert cold["ok"] and cold["value"] == o_pi(90_000)
            assert cli.pending() == 0


def test_pipelined_deep_inflight_all_exact(ledger_dir):
    # queue sized above the pipeline depth: this measures correlation,
    # not admission control
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(queue_limit=256)) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            xs = [(7919 * (i + 1)) % N for i in range(64)]
            ids = [cli.submit("pi", x=x) for x in xs]
            assert cli.pending() == 64
            replies = cli.drain()
            assert cli.pending() == 0
            for rid, x in zip(ids, xs):
                assert replies[rid]["value"] == o_pi(x), x


def test_mid_pipeline_shed_lands_on_the_right_id(service):
    svc, cli = service
    # the 3rd of 5 pipelined requests is shed; its neighbors answer exact
    svc.inject_chaos(f"svc_shed:any@s{svc._seq + 3}")
    xs = [100, 5_000, 12_345, 30_000, 45_000]
    ids = [cli.submit("pi", x=x) for x in xs]
    replies = cli.drain(ids)
    for k, (rid, x) in enumerate(zip(ids, xs)):
        r = replies[rid]
        if k == 2:
            assert r["ok"] is False and r["error"] == "overloaded"
            assert "svc_shed" in r["detail"]
        else:
            assert r["ok"] and r["value"] == o_pi(x)


def test_mid_pipeline_deadline_partial_lands_on_the_right_id(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(workers=1)) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            svc.inject_chaos(f"svc_stall:any@s{svc._seq + 1}:0.6")
            stalled = cli.submit("pi", x=30_000, deadline_s=0.2)
            ids = [cli.submit("pi", x=x) for x in (100, 12_345)]
            replies = cli.drain([stalled, *ids])
            r = replies[stalled]
            assert r["error"] == "deadline_exceeded"
            assert isinstance(r["partial"], dict)
            assert r["partial"]["answered_hi"] >= 2
            for rid, x in zip(ids, (100, 12_345)):
                assert replies[rid]["value"] == o_pi(x)


def test_inline_ops_overtake_queued_work(ledger_dir):
    """health/stats are answered by the event loop ahead of the queue:
    they return while every pipelined query is still in flight."""
    settings = _settings(workers=1, cold_delay_s=0.5)
    with SieveService(_cfg(str(ledger_dir)), settings) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            ids = [cli.submit("pi", x=90_000 + 10_000 * i)
                   for i in range(3)]
            h = cli.health()
            s = cli.stats()
            # no query reply arrived before the inline ones: all three
            # are still pending and nothing is stashed
            assert h["ok"] and "queue_depth" in h
            assert s["hot_admitted"] + s["cold_admitted"] >= 1
            assert cli.pending() == 3
            assert not cli._replies
            replies = cli.drain(ids)
            for i, rid in enumerate(ids):
                assert replies[rid]["value"] == o_pi(90_000 + 10_000 * i)


def test_inline_reply_never_lost_mid_direct_send(service):
    """Regression: a worker's direct send keeps head_off at 0 until
    send() returns, so a concurrently front-inserted inline reply used
    to land at index 0 mid-send and get destroyed by the sender's
    popleft (the client then hung waiting for it). Hammer the exact
    interleaving: a hot query reply direct-sent by a worker racing a
    loop-inserted health reply on the same connection."""
    svc, _ = service
    with ServiceClient(svc.addr, timeout_s=5) as cli:
        for i in range(60):
            rid = cli.submit("pi", x=20_000 + (i % 7))
            h = cli.health()
            assert h["ok"]
            reply = cli.drain([rid])[rid]
            assert reply["value"] == o_pi(20_000 + (i % 7))


# --- one slow connection never blocks another --------------------------------


def test_svc_slow_frame_throttles_one_conn_not_the_fleet(
        ledger_dir, memsink):
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(workers=4)) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as slow, \
                ServiceClient(svc.addr, timeout_s=30) as fast:
            fast.pi(100)  # warm both the index and fast's connection
            # the NEXT query is slow's, submitted before any fast
            # traffic, so the throttle deterministically lands on
            # slow's connection (2 bytes per 5 ms tick: a >=100-byte
            # reply frame needs >=0.25 s to dribble out)
            svc.inject_chaos(f"svc_slow_frame:any@s{svc._seq + 1}:2")
            rid = slow.submit("pi", x=30_000)
            time.sleep(0.05)  # the server has taken the directive
            box = {}

            def dribbled():
                t0 = time.monotonic()
                box["value"] = slow.drain([rid])[rid]["value"]
                box["elapsed"] = time.monotonic() - t0

            t = threading.Thread(target=dribbled)
            t.start()
            lat = []
            while t.is_alive():
                q0 = time.monotonic()
                assert fast.pi(12_345) == o_pi(12_345)
                lat.append(time.monotonic() - q0)
            t.join(30)
            # the dribbled reply is exact and SLOW; the other
            # connection stayed at full wire speed throughout
            assert box["value"] == o_pi(30_000)
            assert box["elapsed"] >= 0.1
            assert len(lat) >= 3
            p95 = sorted(lat)[max(0, int(len(lat) * 0.95) - 1)]
            assert p95 < box["elapsed"] / 2
    ev = [x for x in memsink.records
          if x["event"] == "service_slow_frame"]
    assert ev and ev[0]["bytes_per_tick"] == 2.0
    for x in ev:
        validate_record(x)


def test_slowloris_reader_never_blocks_normal_clients(service):
    """A client dribbling its REQUEST one byte at a time holds its
    connection open for ~0.4 s; a normal client on another connection
    keeps full-speed service the whole time (non-blocking reads), and
    the dribbled request still answers exact once complete."""
    svc, cli = service
    frame = encode_msg({"type": "query", "id": 1, "op": "pi", "x": 30_000})
    host, port = svc.addr.split(":")
    loris = socket.create_connection((host, int(port)), timeout=30)
    try:
        done = threading.Event()

        def dribble():
            try:
                for i in range(len(frame)):
                    loris.sendall(frame[i:i + 1])
                    time.sleep(0.4 / len(frame))
            finally:
                done.set()

        t = threading.Thread(target=dribble)
        t.start()
        cli.pi(100)  # warm
        lat = []
        while not done.is_set():
            q0 = time.monotonic()
            assert cli.pi(12_345) == o_pi(12_345)
            lat.append(time.monotonic() - q0)
        t.join(30)
        assert len(lat) >= 5
        p95 = sorted(lat)[max(0, int(len(lat) * 0.95) - 1)]
        assert p95 < 0.2  # normal traffic never waited on the slowloris
        reply = recv_msg(loris)
        assert reply["id"] == 1 and reply["value"] == o_pi(30_000)
    finally:
        loris.close()


def test_slow_consumer_overflowing_write_queue_is_killed(
        ledger_dir, memsink):
    # a reply bigger than the whole write-queue budget can never drain:
    # the server closes the connection instead of buffering unboundedly
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(write_queue_bytes=4096)) as svc:
        # v1 JSON on purpose: the binary bitset reply for this window is
        # ~1 KB and would drain fine — the kill needs the 23 KB text form
        with ServiceClient(svc.addr, timeout_s=30,
                           negotiate=False) as cli:
            with pytest.raises((ConnectionError, OSError)):
                cli.primes(2, 30_000)  # ~23 KB reply > 4 KB queue
        with ServiceClient(svc.addr, timeout_s=30) as cli2:
            assert cli2.stats()["slow_consumer_closed"] == 1
            assert cli2.pi(100) == o_pi(100)  # the server itself is fine
    ev = [x for x in memsink.records
          if x["event"] == "service_slow_consumer"]
    assert ev and ev[0]["limit"] == 4096
    for x in ev:
        validate_record(x)


# --- the batch op, single server ---------------------------------------------


def test_batch_exact_vs_oracle_hot_and_cold(service):
    svc, cli = service
    covered = svc.index.covered_hi
    items = [
        {"op": "pi", "x": 0},
        {"op": "pi", "x": 30_000},                 # hot interior
        {"op": "pi", "x": covered - 1},            # hot boundary
        {"op": "pi", "x": 90_000},                 # cold
        {"op": "is_prime", "x": 1},
        {"op": "is_prime", "x": 2},
        {"op": "is_prime", "x": 12_347},
        {"op": "count", "lo": 10_000, "hi": 40_000},
        {"op": "count", "lo": 40_000, "hi": 90_000},  # straddles covered
        {"op": "count", "lo": 7, "hi": 7},
    ]
    s0 = cli.stats()
    out = cli.query_batch(items)
    s1 = cli.stats()
    assert s1["batch_requests"] == s0["batch_requests"] + 1
    assert s1["batch_members"] == s0["batch_members"] + len(items)
    assert [o["ok"] for o in out] == [True] * len(items)
    assert [o["value"] for o in out] == [
        0, o_pi(30_000), o_pi(covered - 1), o_pi(90_000),
        False, True, o_is_prime(12_347),
        o_count(10_000, 40_000), o_count(40_000, 90_000), 0,
    ]
    assert [o["op"] for o in out] == [i["op"] for i in items]


def test_batch_malformed_members_fault_individually(service):
    _svc, cli = service
    out = cli.query_batch([
        {"op": "pi", "x": 100},
        {"op": "nth_prime", "k": 3},          # not a batchable op
        {"op": "count", "lo": 2, "hi": 100, "kind": "twins"},
        {"op": "is_prime"},                    # missing x
        "not an object",
        {"op": "pi", "x": 200},
    ])
    assert out[0]["value"] == o_pi(100)
    assert out[5]["value"] == o_pi(200)
    for k in (1, 2, 3, 4):
        assert out[k]["ok"] is False
        assert out[k]["error"] == "bad_request"
    assert "nth_prime" in out[1]["detail"]
    assert "kind=primes" in out[2]["detail"]


def test_batch_container_faults_are_whole_batch(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(batch_queries=4)) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            r = cli.query("batch", items="nope")
            assert r["ok"] is False and r["error"] == "bad_request"
            r = cli.query("batch", items=[])
            assert r["error"] == "bad_request"
            with pytest.raises(ServiceError) as ei:
                cli.query_batch([{"op": "pi", "x": x}
                                 for x in range(5)])  # 5 > batch_queries=4
            assert ei.value.kind == "bad_request"
            assert "batch_queries=4" in ei.value.detail
            # at the cap is fine
            out = cli.query_batch([{"op": "pi", "x": x}
                                   for x in (10, 20, 30, 40)])
            assert [o["value"] for o in out] == [o_pi(x)
                                                 for x in (10, 20, 30, 40)]


def test_batch_cold_member_faults_spare_hot_members(service):
    svc, cli = service
    svc.inject_chaos(f"backend_down:any@s{svc._seq + 1}:0.6")
    out = cli.query_batch([
        {"op": "pi", "x": 30_000},
        {"op": "pi", "x": 90_000},  # needs a fresh cold chunk
    ])
    assert out[0]["ok"] and out[0]["value"] == o_pi(30_000)
    assert out[1]["ok"] is False and out[1]["error"] == "degraded"


def test_batch_deadline_member_carries_partial(ledger_dir):
    # the cold member's 0.5 s simulated compute blows the 0.2 s budget
    # INSIDE the batch, after the hot members already resolved
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(cold_delay_s=0.5)) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            out = cli.query_batch(
                [{"op": "pi", "x": 30_000}, {"op": "pi", "x": 90_000}],
                deadline_s=0.2,
            )
            # hot members never blow a deadline on the index row; the
            # cold member's fault carries the prefix partial
            assert out[0]["ok"] and out[0]["value"] == o_pi(30_000)
            assert out[1]["error"] == "deadline_exceeded"
            assert isinstance(out[1]["partial"], dict)
            assert out[1]["partial"]["answered_hi"] >= 2


def test_count_upto_batch_matches_scalar(ledger_dir):
    led = Ledger.open_readonly(_cfg(str(ledger_dir)))
    idx = SieveIndex("wheel30", led.completed())
    vs = sorted({2, 3, 100, 12_345, 30_001, idx.covered_hi, *idx.bounds})
    got = idx.count_upto_batch(vs, QueryCtx())
    assert got.dtype == np.int64
    for v, g in zip(vs, got.tolist()):
        assert g == idx.count_upto(v, QueryCtx()) == o_pi(v - 1), v
    assert idx.count_upto_batch([], QueryCtx()).size == 0
    with pytest.raises(ValueError, match="beyond covered_hi"):
        idx.count_upto_batch([idx.covered_hi + 1], QueryCtx())


# --- ReplicaSet: pipelined failover ------------------------------------------


def test_query_many_pipelines_in_request_order(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(queue_limit=64)) as svc:
        with ReplicaSet([svc.addr], timeout_s=30) as rs:
            reqs = [{"op": "pi", "x": 100},
                    {"op": "count", "lo": 10_000, "hi": 40_000},
                    {"op": "is_prime", "x": 12_347},
                    {"op": "pi", "x": 30_000}]
            out = rs.query_many(reqs, window=2)
            assert [r["ok"] for r in out] == [True] * 4
            assert out[0]["value"] == o_pi(100)
            assert out[1]["value"] == o_count(10_000, 40_000)
            assert bool(out[2]["value"]) == o_is_prime(12_347)
            assert out[3]["value"] == o_pi(30_000)
            for r in out:
                assert r["probe"]["addr"] == svc.addr
                assert r["probe"]["t_done"] >= r["probe"]["t_send"]
            assert rs.failovers == 0


def test_query_many_mid_pipeline_kill_fails_over_suffix_only(ledger_dir):
    cfg = _cfg(str(ledger_dir))
    with SieveService(cfg, _settings()) as a, \
            SieveService(cfg, _settings()) as b:
        with ReplicaSet([a.addr, b.addr], timeout_s=30,
                        circuit_cooldown_s=5.0) as rs:
            # replica A's 3rd query cuts the connection (dead-replica
            # chaos) and keeps dropping new ones for 0.5 s, so the
            # unanswered suffix must fail over to B
            a.inject_chaos(f"replica_down:any@s{a._seq + 3}:0.5")
            reqs = [{"op": "pi", "x": x}
                    for x in (100, 5_000, 12_345, 30_000, 45_000, 49_999)]
            out = rs.query_many(reqs, window=2)
            assert [r["value"] for r in out] == [o_pi(r["x"])
                                                 for r in reqs]
            addrs = [r["probe"]["addr"] for r in out]
            # the head was answered by A before the kill and is KEPT —
            # only the unanswered suffix moved to B
            assert addrs[0] == a.addr
            assert addrs[2:] == [b.addr] * 4
            assert rs.failovers >= 1


def test_query_many_typed_finals_and_unavailable(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        with ReplicaSet([svc.addr], timeout_s=30) as rs:
            out = rs.query_many([{"op": "pi", "x": 100},
                                 {"op": "nope"}])
            assert out[0]["value"] == o_pi(100)
            assert out[1]["error"] == "bad_request"  # final, not retried
            assert rs.failovers == 0
    with ReplicaSet([_dead_addr()], timeout_s=2, rounds=1,
                    probe_timeout_s=0.5) as rs:
        out = rs.query_many([{"op": "pi", "x": 100}])
        assert out[0]["ok"] is False
        assert out[0]["error"] == "unavailable"
        assert "no replica answered" in out[0]["detail"]


def test_replicaset_query_batch_fails_over_whole_rpc(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        with ReplicaSet([_dead_addr(), svc.addr], timeout_s=30,
                        probe_timeout_s=0.5) as rs:
            out = rs.query_batch([{"op": "pi", "x": 100},
                                  {"op": "is_prime", "x": 12_347}])
            assert out[0]["value"] == o_pi(100)
            assert bool(out[1]["value"]) == o_is_prime(12_347)


# --- ClientPool --------------------------------------------------------------


def test_client_pool_reuses_connections_across_cycles(ledger_dir):
    cfg = _cfg(str(ledger_dir))
    with SieveService(cfg, _settings()) as a, \
            SieveService(cfg, _settings()) as b:
        with ClientPool(timeout_s=10) as pool:
            first = {addr: pool.get(addr) for addr in (a.addr, b.addr)}
            for _ in range(3):  # three refresh cycles, zero new sockets
                for addr in (a.addr, b.addr):
                    cli = pool.get(addr)
                    assert cli is first[addr]
                    assert cli.health()["ok"]
            assert pool.connects == 2
            assert pool.reconnects == 0
            # a transport failure invalidates ONE entry; only that
            # endpoint reconnects (and the reconnect is counted)
            pool.invalidate(a.addr)
            assert pool.get(a.addr) is not first[a.addr]
            assert pool.get(b.addr) is first[b.addr]
            assert (pool.connects, pool.reconnects) == (3, 1)
            # a client that died in place (server cut it) also
            # reconnects on the next get
            pool.get(a.addr).close()
            assert pool.get(a.addr).health()["ok"]
            assert (pool.connects, pool.reconnects) == (4, 2)


# --- the batch op, routed ----------------------------------------------------


class _Fabric:
    """Two-shard in-process fabric (split 2+2 segments at E)."""

    def __init__(self, ledger_dir, tmp_path, shard1_dead=False,
                 router_settings=None, shard_settings=None):
        segs = sorted(
            Ledger.open_readonly(_cfg(str(ledger_dir)))
            .completed().values(),
            key=lambda r: r.lo,
        )
        self.E = segs[2].lo
        dirs = (tmp_path / "shard0", tmp_path / "shard1")
        for d, part in zip(dirs, (segs[:2], segs[2:])):
            led = Ledger.open(_cfg(str(d)))
            for r in part:
                led.record(r)
        skw = dict(shard_settings or {})
        self.svcs = [
            SieveService(_cfg(str(dirs[0])), _settings(**skw)).start()
        ]
        if shard1_dead:
            s1_addrs = (_dead_addr(),)
        else:
            self.svcs.append(
                SieveService(_cfg(str(dirs[1])),
                             _settings(range_lo=self.E, **skw)).start()
            )
            s1_addrs = (self.svcs[1].addr,)
        self.map = ShardMap([
            Shard(2, self.E, (self.svcs[0].addr,)),
            Shard(self.E, N + 1, s1_addrs),
        ])
        self.router = SieveRouter(
            self.map,
            router_settings or RouterSettings(quiet=True),
        ).start()
        self.cli = ServiceClient(self.router.addr, timeout_s=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.cli.close()
        self.router.stop()
        for s in self.svcs:
            s.stop()


def test_router_batch_one_rpc_per_shard(ledger_dir, tmp_path):
    with _Fabric(ledger_dir, tmp_path) as f:
        items = [
            {"op": "pi", "x": 100},                      # shard 0 only
            {"op": "pi", "x": f.E + 5_000},              # both shards
            {"op": "count", "lo": 100, "hi": f.E + 200},  # straddles E
            {"op": "count", "lo": f.E + 10, "hi": N},    # shard 1 only
            {"op": "is_prime", "x": 12_347},
            {"op": "is_prime", "x": f.E + 7},
            {"op": "pi", "x": 1},
        ]
        s0 = f.cli.stats()
        out = f.cli.query_batch(items)
        s1 = f.cli.stats()
        # the scatter contract: 7 members over 2 shards cost at most
        # ONE downstream batch RPC per shard
        assert s1["batch_rpcs"] - s0["batch_rpcs"] <= 2
        assert s1["batch_requests"] - s0["batch_requests"] == 1
        assert s1["batch_members"] - s0["batch_members"] == len(items)
        assert [o["ok"] for o in out] == [True] * len(items)
        assert out[0]["value"] == o_pi(100)
        assert out[1]["value"] == o_pi(f.E + 5_000)
        assert out[2]["value"] == o_count(100, f.E + 200)
        assert out[3]["value"] == o_count(f.E + 10, N)
        assert out[4]["value"] is o_is_prime(12_347)
        assert out[5]["value"] is o_is_prime(f.E + 7)
        assert out[6]["value"] == 0
        # point members confined to one shard touch ONE shard
        s2 = f.cli.stats()
        f.cli.query_batch([{"op": "is_prime", "x": x}
                           for x in (101, 103, 107)])
        s3 = f.cli.stats()
        assert s3["batch_rpcs"] - s2["batch_rpcs"] == 1


def test_router_batch_fills_and_uses_totals_cache(ledger_dir, tmp_path):
    with _Fabric(ledger_dir, tmp_path) as f:
        s0 = f.cli.stats()
        out = f.cli.query_batch([{"op": "pi", "x": N}])
        s1 = f.cli.stats()
        assert out[0]["value"] == o_pi(N)
        assert s1["batch_rpcs"] - s0["batch_rpcs"] == 2  # both totals miss
        # the full-shard counts rode the batch and filled the totals
        # cache: the SAME batch again costs ZERO downstream RPCs
        out = f.cli.query_batch([{"op": "pi", "x": N}])
        s2 = f.cli.stats()
        assert out[0]["value"] == o_pi(N)
        assert s2["batch_rpcs"] - s1["batch_rpcs"] == 0


def test_router_batch_shard_down_members_tagged(ledger_dir, tmp_path):
    with _Fabric(ledger_dir, tmp_path, shard1_dead=True,
                 router_settings=RouterSettings(
                     quiet=True, rounds=1, probe_timeout_s=1.0)) as f:
        out = f.cli.query_batch([
            {"op": "count", "lo": 10_000, "hi": 20_000},  # shard 0: fine
            {"op": "count", "lo": f.E + 10, "hi": N},     # shard 1: dead
            {"op": "pi", "x": N},                         # touches both
        ])
        assert out[0]["ok"] and out[0]["value"] == o_count(10_000, 20_000)
        assert out[1]["ok"] is False
        assert out[1]["error"] == "unavailable"
        assert out[1]["shard"] == 1
        assert out[2]["ok"] is False and out[2]["shard"] == 1
        assert f.cli.stats()["shard_errors"] >= 1


def test_router_rejects_malformed_batch_members_typed(ledger_dir,
                                                      tmp_path):
    with _Fabric(ledger_dir, tmp_path) as f:
        out = f.cli.query_batch([
            {"op": "pi", "x": 100},
            {"op": "nth_prime", "k": 1},
            {"op": "count", "lo": 2, "hi": 100, "kind": "twins"},
        ])
        assert out[0]["value"] == o_pi(100)
        assert out[1]["error"] == "bad_request"
        assert out[2]["error"] == "bad_request"
        r = f.cli.query("batch", items="nope")
        assert r["error"] == "bad_request"


# --- static parity + bench gates ---------------------------------------------


def test_wire_surface_parity_gate():
    from tools.check_wire_ops import check
    assert check() == []


def test_bench_compare_gates_qps_regressions():
    from tools.bench_compare import compare

    def rec(v):
        return {"service_hot_qps": {
            "metric": "service_hot_qps", "value": v, "unit": "qps"}}

    _lines, regressions = compare(rec(50_000.0), rec(40_000.0), 0.10)
    assert regressions and "service_hot_qps" in regressions[0]
    _lines, regressions = compare(rec(50_000.0), rec(48_000.0), 0.10)
    assert regressions == []
    _lines, regressions = compare(rec(50_000.0), rec(65_000.0), 0.10)
    assert regressions == []


def test_bench_compare_gates_wire_bytes_ceiling_and_growth():
    from tools.bench_compare import compare

    def rec(v):
        return {"service_wire_bytes_per_member": {
            "metric": "service_wire_bytes_per_member", "value": v,
            "unit": "bytes_per_member"}}

    # absolute ceiling fires even on a metric's first round
    _lines, regressions = compare({}, rec(70.0), 0.10)
    assert regressions and "48" in regressions[0]
    _lines, regressions = compare({}, rec(27.0), 0.10)
    assert regressions == []
    # round-over-round: lower is better, gate on increases
    _lines, regressions = compare(rec(27.0), rec(33.0), 0.10)
    assert regressions and "bytes/member" in regressions[0]
    _lines, regressions = compare(rec(27.0), rec(26.0), 0.10)
    assert regressions == []


# --- binary wire v2 (ISSUE 16) -----------------------------------------------


def _decoded_equal(got: dict, want_msg: dict, want_cols: dict) -> None:
    """A decoded v2 frame carries the header fields verbatim plus one
    ndarray per manifest column (and the manifest itself)."""
    for k, v in want_msg.items():
        assert got[k] == v, k
    assert [e[0] for e in got["_cols"]] == list(want_cols)
    for name, arr in want_cols.items():
        assert np.array_equal(got[name], np.asarray(arr)), name


def test_frame_decoder_v2_interleaved_byte_by_byte():
    """v1 and v2 frames interleaved on one connection, delivered one
    byte at a time: every frame decodes, in order, at the exact byte
    that completes it."""
    j1 = {"type": "query", "op": "pi", "x": 10**9, "id": 1}
    m2 = {"type": "query", "op": "batch", "id": 2}
    c2 = {"b_op": np.array([0, 1, 2], np.uint8),
          "b_a": np.array([10, 97, -5], np.int64),
          "b_b": np.array([0, 0, 50], np.int64)}
    j3 = {"type": "health", "id": 3}
    m4 = {"type": "reply", "id": 4, "ok": True, "vkind": "primes",
          "prepr": "values"}
    c4 = {"p_vals": np.arange(257, dtype=np.int64) * 3 + 2}
    m5 = {"type": "reply", "id": 5, "ok": True}  # v2 body, zero columns
    c5 = {"r_ok": np.zeros(0, np.uint8)}
    wire = (encode_msg(j1) + encode_msg_v2(m2, c2) + encode_msg(j3)
            + encode_msg_v2(m4, c4) + encode_msg_v2(m5, c5))
    dec = FrameDecoder()
    got = []
    for i in range(len(wire)):
        got.extend(dec.feed(wire[i:i + 1]))
    assert dec.buffered() == 0
    assert len(got) == 5
    assert got[0] == j1 and got[2] == j3
    _decoded_equal(got[1], m2, c2)
    _decoded_equal(got[3], m4, c4)
    _decoded_equal(got[4], m5, c5)


def test_frame_decoder_v2_split_frames_keep_zero_copy_views():
    # a frame assembled from fragments still yields real int64 columns
    frame = encode_msg_v2({"type": "reply", "id": 9, "ok": True},
                          {"p_vals": np.array([2, 3, 5, 7], np.int64)})
    for cut in (1, 8, 9, 12, len(frame) - 1):
        dec = FrameDecoder()
        assert dec.feed(frame[:cut]) == []
        (msg,) = dec.feed(frame[cut:])
        assert msg["p_vals"].tolist() == [2, 3, 5, 7]
        assert dec.buffered() == 0


def _v2_body_frame(body: bytes) -> bytes:
    return len(body).to_bytes(8, "big") + body


def test_frame_decoder_v2_truncated_and_malformed_bodies_are_typed():
    import json as _json
    import struct as _struct

    def hdr(obj) -> bytes:
        blob = _json.dumps(obj).encode()
        return b"\x02" + _struct.pack("<I", len(blob)) + blob

    bad_bodies = [
        b"\x02",                                   # nothing after magic
        b"\x02\xff\xff",                           # truncated header len
        b"\x02" + _struct.pack("<I", 99) + b"{}",  # header overruns frame
        hdr([1, 2, 3]),                            # header not an object
        hdr({"_cols": {"not": "a list"}}),         # manifest not a list
        hdr({"_cols": [["x", "<i8"]]}),            # entry missing count
        hdr({"_cols": [["x", ">i8", 1]]}) + b"\0" * 8,   # big-endian dtype
        hdr({"_cols": [["x", "<i8", True]]}) + b"\0" * 8,  # bool count
        hdr({"_cols": [["x", "<i8", -1]]}),        # negative count
        hdr({"_cols": [["x", "<i8", 4]]}) + b"\0" * 8,   # column overrun
        hdr({"_cols": [["x", "<i8", 1]]}) + b"\0" * 16,  # trailing bytes
    ]
    for body in bad_bodies:
        with pytest.raises(ValueError):
            FrameDecoder().feed(_v2_body_frame(body))


def test_frame_decoder_v2_oversized_header_hits_max_frame():
    # a v2 frame whose length prefix exceeds MAX_FRAME is refused at
    # the prefix, before any column header is even parsed — exactly
    # the JSON garbage-prefix rule
    prefix = (MAX_FRAME + 1).to_bytes(8, "big") + b"\x02"
    with pytest.raises(ValueError, match="frame"):
        FrameDecoder().feed(prefix)


def test_wire_negotiation_picks_highest_mutual(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as v2, \
                ServiceClient(svc.addr, timeout_s=30,
                              negotiate=False) as v1:
            assert v2.wire_v == 2 and not v2.downgraded
            assert v1.wire_v == 1
            assert svc.stats()["wire_v2_conns"] == 1
            # both speak to the same server, both stay exact
            for x in (2, 97, 30_000):
                assert v1.query("pi", x=x)["value"] == o_pi(x)
                assert v2.query("pi", x=x)["value"] == o_pi(x)


def test_wire_downgrade_is_logged_not_silent(ledger_dir, memsink):
    """A v2-capable client landing on a v1-pinned server emits exactly
    one schema-valid wire_downgrade event and flags itself."""
    with SieveService(_cfg(str(ledger_dir)),
                      _settings(wire_v2=False)) as svc:
        with ServiceClient(svc.addr, timeout_s=30) as cli:
            assert cli.wire_v == 1 and cli.downgraded
            assert cli.query("pi", x=97)["value"] == o_pi(97)
            assert svc.stats()["wire_v2_conns"] == 0
    events = [r for r in memsink.records if r["event"] == "wire_downgrade"]
    assert len(events) == 1
    validate_record(events[0])
    assert events[0]["negotiated"] == 1


def test_dual_encoding_parity_primes_both_reprs(ledger_dir):
    """v1 JSON vs v2 binary primes replies are value-identical for both
    v2 payload shapes (values column and wheel bitset words)."""
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        with ServiceClient(svc.addr, timeout_s=30,
                           negotiate=False) as v1, \
                ServiceClient(svc.addr, timeout_s=30) as v2:
            # tiny window -> values column; wide window -> bitset words
            for lo, hi in ((0, 30), (17, 18), (40_000, 40_001),
                           (2, 20_000), (25_000, 50_000)):
                a = v1.query("primes", lo=lo, hi=hi)["value"]
                b = v2.query("primes", lo=lo, hi=hi)["value"]
                assert a == b, (lo, hi)
                assert a == [int(p) for p in P
                             if max(lo, 2) <= p < hi], (lo, hi)


def test_dual_encoding_parity_batch_typed_members(ledger_dir):
    with SieveService(_cfg(str(ledger_dir)), _settings()) as svc:
        items = [
            {"op": "pi", "x": 30_000},
            {"op": "is_prime", "x": 12_347},
            {"op": "count", "lo": 100, "hi": 20_000, "kind": "primes"},
            {"op": "count", "lo": 20_000, "hi": 100, "kind": "primes"},
            {"op": "pi", "x": "nope"},
            {"op": "nosuch"},
            {"op": "is_prime", "x": 4},
        ]
        with ServiceClient(svc.addr, timeout_s=30,
                           negotiate=False) as v1, \
                ServiceClient(svc.addr, timeout_s=30) as v2:
            a = v1.query_batch(items)
            b = v2.query_batch(items)
            assert a == b
            assert b[0]["value"] == o_pi(30_000)
            assert b[1]["value"] is True and b[6]["value"] is False
            assert b[3]["ok"] is False and b[4]["ok"] is False
            assert b[5]["ok"] is False


def _assert_fleet_exact(f):
    for x in (100, f.E + 5_000, 1):
        assert f.cli.query("pi", x=x)["value"] == o_pi(x)
    got = f.cli.query("primes", lo=f.E - 500, hi=f.E + 500)["value"]
    assert got == [int(p) for p in P if f.E - 500 <= p < f.E + 500]
    items = [
        {"op": "pi", "x": 100},
        {"op": "count", "lo": 100, "hi": f.E + 200, "kind": "primes"},
        {"op": "count", "lo": 900, "hi": 100, "kind": "primes"},
        {"op": "is_prime", "x": 12_347},
    ]
    out = f.cli.query_batch(items)
    assert out[0]["value"] == o_pi(100)
    assert out[1]["value"] == o_count(100, f.E + 200)
    assert out[2]["ok"] is False
    assert out[3]["value"] is o_is_prime(12_347)


def test_mixed_fleet_v1_router_v2_shards(ledger_dir, tmp_path):
    """A v1-pinned router in front of v2 shards: its shard legs stay
    JSON, its own clients get downgraded — answers stay exact."""
    with _Fabric(ledger_dir, tmp_path,
                 router_settings=RouterSettings(quiet=True,
                                                wire_v2=False)) as f:
        assert f.cli.wire_v == 1 and f.cli.downgraded
        _assert_fleet_exact(f)


def test_mixed_fleet_v2_router_v1_shards(ledger_dir, tmp_path):
    """v1-pinned shards behind a v2 router: the shard legs downgrade
    (counted in router stats), the client leg still speaks binary."""
    with _Fabric(ledger_dir, tmp_path,
                 shard_settings={"wire_v2": False}) as f:
        assert f.cli.wire_v == 2 and not f.cli.downgraded
        _assert_fleet_exact(f)
        assert f.cli.stats()["wire_downgrades"] >= 1


def test_all_v2_fleet_no_downgrades(ledger_dir, tmp_path):
    with _Fabric(ledger_dir, tmp_path) as f:
        assert f.cli.wire_v == 2
        _assert_fleet_exact(f)
        assert f.cli.stats()["wire_downgrades"] == 0
