"""cpu-cluster transport tests: workers as local subprocesses (SURVEY.md
section 4.2 item 4), including the kill-a-worker fault-injection path."""

import numpy as np
import pytest

from sieve.cluster import run_cluster
from sieve.config import SieveConfig
from tests.oracles import PI, TWINS

ADDR = "127.0.0.1:0"  # port 0: the coordinator binds an ephemeral port


def _cfg(**kw):
    base = dict(
        n=10**5,
        backend="cpu-cluster",
        workers=2,
        n_segments=8,
        twins=True,
        quiet=True,
        coordinator_addr=ADDR,
    )
    base.update(kw)
    return SieveConfig(**base)


def test_cluster_basic():
    res = run_cluster(_cfg())
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]


def test_cluster_three_workers_wheel30():
    res = run_cluster(_cfg(workers=3, packing="wheel30", n=10**6, n_segments=12))
    assert res.pi == PI[10**6]
    assert res.twin_pairs == TWINS[10**6]


def test_cluster_chaos_kill_reassigns():
    # worker 0 hard-exits on segment 2; the run must still be exact
    res = run_cluster(_cfg(chaos_kill="0@2"))
    assert res.pi == PI[10**5]
    assert res.twin_pairs == TWINS[10**5]


def test_cluster_deterministic_failure_aborts(monkeypatch):
    # a segment that raises on every owner must abort the run with the
    # underlying error after MAX_ATTEMPTS, not hang until the deadline
    monkeypatch.setenv("SIEVE_CHAOS_RAISE", "3")
    with pytest.raises(RuntimeError, match="segment 3 failed"):
        run_cluster(_cfg())


def test_cluster_checkpoint_resume(tmp_path):
    cfg = _cfg(checkpoint_dir=str(tmp_path))
    res = run_cluster(cfg)
    assert res.pi == PI[10**5]
    cfg2 = SieveConfig(**{**cfg.to_dict(), "resume": True})
    res2 = run_cluster(cfg2)  # fully restored from ledger, no workers needed
    assert res2.pi == PI[10**5]
    assert res2.twin_pairs == TWINS[10**5]
