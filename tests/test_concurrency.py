"""Concurrency analyzer + runtime lock sanitizer (ISSUE 15).

Covers: the static pass end to end on four committed fixtures (a
seeded lock-order cycle, a blocking call reachable from an event-loop
role, a ``# guard:``-annotated attribute touched without its lock, and
a clean package that must produce zero findings); the real repo being
clean against the committed baseline (the ratchet gate itself);
``tools/check_all`` aggregating every static gate; the env-var
discipline checker's two rules (raw-read detection, README coverage);
and the ``SIEVE_LOCK_DEBUG`` wrappers — recording, RLock reentry,
Condition.wait release/reacquire, and ``check_static_consistency``
agreeing/disagreeing with a canonical order.
"""

import ast
import threading
from pathlib import Path

import pytest

from sieve.analysis import checks, core, lockdebug
from sieve.analysis.model import Model, default_model

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures_concurrency"


def _analyze_fixture(name: str, **model_kw) -> list[checks.Finding]:
    prog = core.scan(str(FIXTURES / name), pkg=name)
    return checks.analyze(prog, Model(**model_kw))


# --- static pass on the fixtures ---------------------------------------------


def test_fixture_lock_cycle_detected():
    findings = _analyze_fixture(
        "fx_cycle",
        canonical_lock_order=("C._a", "C._b"),
        app_role_classes=frozenset({"C"}),
    )
    kinds = {f.kind for f in findings}
    assert "lock-cycle" in kinds
    # the edge against the canonical order is reported at its site
    assert any(f.key == "lock-order:C._b->C._a@fx_cycle.app:C.backward"
               for f in findings)


def test_fixture_loop_blocking_detected():
    findings = _analyze_fixture(
        "fx_loopblock",
        loop_roles=frozenset({"ev-loop"}),
        blocking_calls=frozenset({"time.sleep"}),
    )
    assert [f.key for f in findings] == [
        "loop-blocking:ev-loop:fx_loopblock.app:Loop._tick:time.sleep"
    ]


def test_fixture_unguarded_attr_detected():
    findings = _analyze_fixture(
        "fx_unguarded",
        canonical_lock_order=("Worker._lock",),
        app_role_classes=frozenset({"Worker"}),
    )
    assert [f.key for f in findings] == [
        "guard:Worker.count@fx_unguarded.app:Worker.bump"
    ]


def test_fixture_clean_has_no_findings():
    findings = _analyze_fixture(
        "fx_clean",
        canonical_lock_order=("W._a", "W._b"),
        app_role_classes=frozenset({"W"}),
    )
    assert findings == []


def test_fixture_roles_derive_from_spawn_names():
    prog = core.scan(str(FIXTURES / "fx_loopblock"), pkg="fx_loopblock")
    roles = checks.assign_roles(prog, Model(loop_roles=frozenset({"ev-loop"})))
    assert roles["fx_loopblock.app:Loop._loop"] == {"ev-loop"}
    assert roles["fx_loopblock.app:Loop._tick"] == {"ev-loop"}
    # the spawning function itself is not the spawned role
    assert "ev-loop" not in roles.get("fx_loopblock.app:Loop.start", set())


# --- the repo itself ---------------------------------------------------------


def test_repo_is_clean_against_baseline():
    import tools.check_concurrency as cc
    new, stale = cc.check()
    assert new == [], "\n".join(str(f) for f in new)
    assert stale == []


def test_repo_lock_graph_is_acyclic_and_listed():
    prog = core.scan(str(REPO / "sieve"), pkg="sieve")
    model = default_model()
    edges = checks.lock_edges(prog)
    idx = {lk: i for i, lk in enumerate(model.canonical_lock_order)}
    for a, b in edges:
        assert a in idx, f"unlisted lock {a}"
        assert b in idx, f"unlisted lock {b}"
        assert idx[a] < idx[b], f"edge {a} -> {b} against canonical order"


def test_check_all_passes():
    import tools.check_all as ca
    assert ca.main([]) == 0


# --- env-var discipline ------------------------------------------------------


def test_env_vars_check_is_clean():
    import tools.check_env_vars as cev
    problems, names = cev.scan()
    assert problems == []
    assert cev.undocumented(names) == []
    assert "SIEVE_LOCK_DEBUG" in names


def test_env_vars_check_catches_raw_reads():
    import tools.check_env_vars as cev
    src = (
        "import os\n"
        "a = os.environ.get('SIEVE_FAKE_A')\n"
        "b = os.environ['SIEVE_FAKE_B']\n"
        "c = os.getenv('SIEVE_FAKE_C', '1')\n"
        # writes are legal: defaults for children, child-env dicts
        "os.environ.setdefault('SIEVE_FAKE_D', '1')\n"
        "os.environ['SIEVE_FAKE_E'] = '1'\n"
        "wenv = {**os.environ, 'SIEVE_FAKE_F': '1'}\n"
    )
    sc = cev._Scanner("fake.py")
    sc.visit(ast.parse(src))
    assert sorted(n for _, n in sc.raw_reads) == [
        "SIEVE_FAKE_A", "SIEVE_FAKE_B", "SIEVE_FAKE_C"
    ]


# --- runtime sanitizer -------------------------------------------------------


@pytest.fixture
def fresh_recorder():
    rec = lockdebug.recorder()
    rec.reset()
    yield rec
    rec.reset()


def test_named_lock_is_plain_threading_when_disabled(monkeypatch):
    monkeypatch.delenv("SIEVE_LOCK_DEBUG", raising=False)
    assert type(lockdebug.named_lock("X.a")) is type(threading.Lock())
    assert isinstance(lockdebug.named_condition("X.c"), threading.Condition)


def test_debug_lock_records_nesting(monkeypatch, fresh_recorder):
    monkeypatch.setenv("SIEVE_LOCK_DEBUG", "1")
    a = lockdebug.named_lock("T.a")
    b = lockdebug.named_lock("T.b")
    with a:
        with b:
            pass
    with a:
        pass  # no pair: nothing else held
    assert lockdebug.observed_pairs() == {("T.a", "T.b"): 1}
    assert lockdebug.check_static_consistency(("T.a", "T.b")) == []
    problems = lockdebug.check_static_consistency(("T.b", "T.a"))
    assert problems and "against the canonical order" in problems[0]


def test_debug_lock_unknown_lock_is_a_problem(monkeypatch, fresh_recorder):
    monkeypatch.setenv("SIEVE_LOCK_DEBUG", "1")
    a = lockdebug.named_lock("T.a")
    b = lockdebug.named_lock("T.rogue")
    with a, b:
        pass
    problems = lockdebug.check_static_consistency(("T.a",))
    assert any("not in canonical order" in p for p in problems)


def test_debug_rlock_reentry_not_a_self_pair(monkeypatch, fresh_recorder):
    monkeypatch.setenv("SIEVE_LOCK_DEBUG", "1")
    r = lockdebug.named_rlock("T.r")
    with r:
        with r:  # legal reentry must not record (T.r, T.r)
            pass
    assert lockdebug.observed_pairs() == {}
    assert lockdebug.check_static_consistency(("T.r",)) == []


def test_debug_condition_wait_releases_for_ordering(monkeypatch,
                                                    fresh_recorder):
    monkeypatch.setenv("SIEVE_LOCK_DEBUG", "1")
    outer = lockdebug.named_lock("T.outer")
    cond = lockdebug.named_condition("T.cond")

    def waker():
        with cond:
            cond.notify_all()

    with outer:
        with cond:
            t = threading.Thread(target=waker)
            t.start()
            cond.wait(timeout=5.0)
            t.join()
    pairs = lockdebug.observed_pairs()
    # entry nesting plus the reacquire after the wake — both are
    # outer -> cond (deduped per thread), which the order must allow
    assert ("T.outer", "T.cond") in pairs
    assert lockdebug.check_static_consistency(("T.outer", "T.cond")) == []


def test_smoke_scripts_assert_lock_orders():
    # the dynamic half is wired into both smokes, right before their
    # success banner — keep it that way
    for smoke in ("service_smoke.py", "chaos_smoke.py"):
        src = (REPO / "tools" / smoke).read_text()
        assert "check_static_consistency" in src, smoke
        body = src[src.index("def _assert_lock_orders"):]
        assert "_assert_lock_orders()" in body, smoke
