"""Coordinator (control plane): seed sieve, partition, dispatch, merge.

SURVEY.md section 1a L4: the coordinator computes seed primes once on the
host, cuts [2, n+1) into contiguous segments, hands them to workers through
the SieveWorker boundary, tracks completion, and merges per-segment counts
plus boundary bitwords into the final result. ``merge_results`` is a
standalone pure function so the TPU mesh path can reuse the identical merge
semantics (the north-star requires the merge step "unchanged at the API
surface", BASELINE.json).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterable

import numpy as np

from sieve import trace
from sieve.bitset import get_layout
from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.metrics import MetricsLogger
from sieve.seed import seed_primes
from sieve.segments import Segment, plan_segments, validate_plan
from sieve.twins import straddle_pairs
from sieve.worker import SegmentResult, SieveWorker


@dataclasses.dataclass
class SieveResult:
    n: int
    pi: int
    twin_pairs: int | None
    backend: str
    packing: str
    n_segments: int
    elapsed_s: float
    values_per_sec: float
    segments: list[SegmentResult] = dataclasses.field(default_factory=list)
    # host prepare / overlap metrics (mesh streaming pipeline; local runs
    # carry the worker's incremental-prepare phase totals) — optional so
    # callers predating the pipeline keep working
    host_phases: dict | None = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["segments"] = [s.to_dict() for s in self.segments]
        return d


def merge_results(
    config: SieveConfig, results: Iterable[SegmentResult]
) -> tuple[int, int | None]:
    """Merge per-segment results into (pi, twin_pairs).

    Validates that the results tile [2, n+1) exactly, sums counts, and
    resolves cross-boundary twin pairs from boundary bitwords.
    """
    layout = get_layout(config.packing)
    segs = sorted(results, key=lambda r: r.lo)
    if not segs:
        raise ValueError("no segment results to merge")
    if segs[0].lo != 2 or segs[-1].hi != config.n + 1:
        raise ValueError(
            f"results cover [{segs[0].lo}, {segs[-1].hi}), "
            f"expected [2, {config.n + 1})"
        )
    for a, b in zip(segs, segs[1:]):
        if a.hi != b.lo:
            raise ValueError(f"results gap/overlap at {a.hi} vs {b.lo}")
    pi = sum(r.count for r in segs)
    twins: int | None = None
    if config.twins:
        gap = getattr(config, "pair_gap", 2) or 2
        twins = sum(r.twin_count for r in segs)
        for a, b in zip(segs, segs[1:]):
            twins += straddle_pairs(layout, a, b, config.n, gap)
    return pi, twins


class Coordinator:
    """Single-process coordinator: runs segments through one worker.

    The distributed CPU-cluster coordinator (sieve/cluster.py) and the TPU
    mesh path (sieve/parallel/mesh.py) reuse plan_segments + merge_results;
    this class is the degenerate local form (SURVEY.md section 3.1).
    """

    def __init__(
        self,
        config: SieveConfig,
        worker_factory: Callable[[SieveConfig], SieveWorker] | None = None,
    ):
        self.config = config
        if worker_factory is None:
            from sieve.backends import make_worker

            worker_factory = make_worker
        self._worker_factory = worker_factory
        self.metrics = MetricsLogger(config)

    def plan(self) -> list[Segment]:
        segs = plan_segments(
            self.config.n,
            self.config.resolved_n_segments(),
            n_workers=self.config.workers,
        )
        validate_plan(segs, self.config.n)
        return segs

    def run(self) -> SieveResult:
        cfg = self.config
        t0 = time.perf_counter()
        with trace.span("run.seed", backend=cfg.backend):
            seeds = seed_primes(cfg.seed_limit)
        with trace.span("run.plan"):
            segs = self.plan()

        ledger = Ledger.open(cfg) if cfg.checkpoint_dir else None
        if ledger is not None and ledger.salvaged:
            self.metrics.event(
                "ledger_salvaged", salvaged=ledger.salvaged,
                quarantined=ledger.quarantined,
            )
        done: dict[int, SegmentResult] = {}
        if ledger is not None and cfg.resume:
            done = ledger.completed()
            self.metrics.event("resume", restored=len(done))

        worker = self._worker_factory(cfg)
        try:
            for seg in segs:
                if seg.seg_id in done:
                    continue
                with trace.span(
                    "segment.process", seg=seg.seg_id, backend=cfg.backend
                ):
                    res = worker.process_segment(
                        seg.lo, seg.hi, seeds, seg.seg_id
                    )
                done[seg.seg_id] = res
                if ledger is not None:
                    ledger.record(res)
                self.metrics.segment(res)
        finally:
            worker.close()

        results = [done[s.seg_id] for s in segs]
        with trace.span("run.merge"):
            pi, twins = merge_results(cfg, results)
        elapsed = time.perf_counter() - t0
        phases = getattr(worker, "phase_seconds", None) or None
        host_phases = (
            {
                "prep_s": round(sum(phases.values()), 6),
                **{f"prep_{k}_s": round(v, 6) for k, v in phases.items()},
            }
            if phases
            else None
        )
        mode = getattr(worker, "reduction_mode", None)
        if mode is not None:
            host_phases = dict(host_phases or {})
            host_phases["reduction_mode"] = mode
        reduce_s = getattr(worker, "reduce_seconds", None)
        if reduce_s:
            host_phases = dict(host_phases or {})
            host_phases.update(
                {f"{k}_s": round(v, 6) for k, v in reduce_s.items()}
            )
        result = SieveResult(
            n=cfg.n,
            pi=pi,
            twin_pairs=twins,
            backend=cfg.backend,
            packing=cfg.packing,
            n_segments=len(segs),
            elapsed_s=elapsed,
            values_per_sec=(cfg.n - 1) / elapsed if elapsed > 0 else float("inf"),
            segments=results,
            host_phases=host_phases,
        )
        self.metrics.run_summary(result)
        return result


def run_local(config: SieveConfig) -> SieveResult:
    """SURVEY.md section 3.1 entry point: single-process run."""
    return Coordinator(config).run()
