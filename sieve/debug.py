"""Black-box flight recorder: triggered postmortem bundles (ISSUE 13).

A :class:`FlightRecorder` runs inside every server and router,
continuously holding the cheap-to-keep tail of what the process was
doing: the newest span-ring events, the last structured EVENT_SCHEMA
records (the recorder registers itself as a global metrics sink), the
bounded :class:`~sieve.metrics.MetricsHistory` trend window, and a
redacted copy of the config. Edge triggers — an op entering SLO burn,
the cold-plane circuit breaker opening, a shard going dark on the
router, or a crash (``sys.excepthook`` + ``threading.excepthook``,
plus ``faulthandler`` for interpreter-level faults) — freeze that
state into a timestamped bundle directory under ``--debug-dir``,
throttled to one bundle per trigger kind per cooldown so a burn storm
cannot fill the disk.

The ``debug`` wire op snapshots the same state inline (no disk, no
throttle), answered by the reader thread like ``metrics`` — a wedged
worker pool still dumps. tools/fleet_debug.py pulls every process's
inline bundle into one merged fleet bundle; ``tools/trace_report.py
--bundle`` renders either form.
"""

from __future__ import annotations

import collections
import dataclasses
import faulthandler
import json
import os
import sys
import threading
import time
from typing import Any

from sieve import metrics, trace
from sieve.analysis.lockdebug import named_lock

BUNDLE_VERSION = "sieve-debug/1"
FLEET_BUNDLE_VERSION = "sieve-fleet-debug/1"
BUNDLE_FILE = "bundle.json"

TRIGGER_KINDS = ("slo_burn", "breaker_open", "shard_down", "crash", "manual")

# config keys that smell like credentials are masked, never shipped in a
# bundle (bundles leave the machine: fleet_debug, bug reports)
_REDACT_MARKERS = ("secret", "token", "password", "credential", "api_key",
                   "auth")
# event kinds matching any of these substrings count as "last errors"
_ERRORISH = ("error", "failed", "down", "refused", "crash", "burn",
             "unverified", "gap", "drop", "shed", "salvaged")


def redact(obj: Any) -> Any:
    """JSON-safe copy of a config-ish object with secret-looking keys
    masked. Dataclasses flatten to dicts; anything non-JSON becomes its
    repr — a bundle must always serialize."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        try:
            obj = dataclasses.asdict(obj)
        except Exception:  # noqa: BLE001 — unpicklable field values
            obj = dict(vars(obj))
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            key = str(k)
            if any(m in key.lower() for m in _REDACT_MARKERS):
                out[key] = "<redacted>"
            else:
                out[key] = redact(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [redact(v) for v in obj]
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    return repr(obj)


def _errorish(kind: Any) -> bool:
    return isinstance(kind, str) and any(m in kind for m in _ERRORISH)


class FlightRecorder:
    """Continuous bounded capture + edge-triggered postmortem freeze.

    The recorder is cheap while armed: one deque append per metrics
    event (it is a sink), zero cost per span (the tracer ring already
    exists). All the work happens at trigger time — and triggers are
    throttled per kind, so the steady-state overhead stays inside the
    bench line 9 budget."""

    def __init__(
        self,
        role: str,
        *,
        debug_dir: str | None = None,
        history: "metrics.MetricsHistory | None" = None,
        config: Any = None,
        logger: "metrics.MetricsLogger | None" = None,
        cooldown_s: float = 30.0,
        span_tail: int = 256,
        event_tail: int = 256,
        history_window_s: float = 600.0,
        profiler: Any = None,
    ):
        self.role = role
        self.debug_dir = debug_dir
        self.history = history
        # continuous profiler (ISSUE 20): any object with .snapshot();
        # its collapsed-stack table rides every bundle
        self.profiler = profiler
        self.config = redact(config) if config is not None else None
        self.cooldown_s = cooldown_s
        self.span_tail = span_tail
        self.history_window_s = history_window_s
        self._logger = logger
        self._events: collections.deque = collections.deque(maxlen=event_tail)
        self._last_fire: dict[str, float] = {}
        self._lock = named_lock("FlightRecorder._lock")
        self._installed = False
        self._bundles = 0  # guard: _lock
        self._suppressed = 0  # guard: _lock
        self.last_bundle: dict | None = None  # guard: _lock
        self._sys_hook = None
        self._thread_hook = None
        self._prev_sys_hook = None
        self._prev_thread_hook = None
        self._fault_file = None

    # --- sink protocol (metrics.add_sink) --------------------------------

    def emit(self, record: dict) -> None:
        self._events.append(record)  # deque append: atomic, bounded

    def close(self) -> None:
        pass

    # --- lifecycle -------------------------------------------------------

    def install(self) -> "FlightRecorder":
        """Arm the recorder: register as a global metrics sink and chain
        the crash hooks (previous hooks still run — recorders nest).
        Idempotent; :meth:`uninstall` unwinds."""
        if self._installed:
            return self
        self._installed = True
        metrics.add_sink(self)

        self._prev_sys_hook = sys.excepthook

        def _sys_hook(tp, val, tb):
            if self._installed:
                self._on_crash(tp, val)
            (self._prev_sys_hook or sys.__excepthook__)(tp, val, tb)

        self._sys_hook = _sys_hook
        sys.excepthook = _sys_hook

        self._prev_thread_hook = threading.excepthook

        def _thread_hook(args):
            if self._installed and args.exc_type is not SystemExit:
                self._on_crash(
                    args.exc_type, args.exc_value,
                    thread=getattr(args.thread, "name", None),
                )
            self._prev_thread_hook(args)

        self._thread_hook = _thread_hook
        threading.excepthook = _thread_hook

        if self.debug_dir:
            try:
                os.makedirs(self.debug_dir, exist_ok=True)
                # interpreter-level faults (segfault, deadlock dumps)
                # land next to the bundles the python-level hooks write
                self._fault_file = open(
                    os.path.join(self.debug_dir, "faulthandler.log"), "a"
                )
                faulthandler.enable(file=self._fault_file)
            except OSError:
                self._fault_file = None
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False  # stale chained hooks become pass-through
        metrics.remove_sink(self)
        if sys.excepthook is self._sys_hook:
            sys.excepthook = self._prev_sys_hook or sys.__excepthook__
        if threading.excepthook is self._thread_hook:
            threading.excepthook = self._prev_thread_hook
        if self._fault_file is not None:
            try:
                faulthandler.disable()
                self._fault_file.close()
            except (OSError, ValueError):
                pass
            self._fault_file = None

    def _on_crash(self, tp, val, thread: str | None = None) -> None:
        try:
            self.trigger(
                "crash",
                error=f"{getattr(tp, '__name__', tp)}: {val}",
                thread=thread,
            )
        except Exception:  # noqa: BLE001
            pass  # the recorder must never mask the original failure

    # --- capture ---------------------------------------------------------

    def snapshot(self, trigger: str = "manual",
                 detail: dict | None = None) -> dict:
        """Freeze the current black-box state into one JSON-able bundle
        (no disk, no throttle — the ``debug`` wire op calls this)."""
        tr = trace.get_tracer()
        events = list(self._events)
        rows = (self.history.rows(self.history_window_s)
                if self.history is not None else [])
        profile = (self.profiler.snapshot()
                   if self.profiler is not None else None)
        if profile is not None and self._logger is not None:
            try:
                self._logger.event(
                    "profile_captured", quietable=True, role=self.role,
                    samples=profile.get("samples"),
                    stacks=len(profile.get("stacks") or ()),
                )
            except Exception:  # noqa: BLE001 — snapshots run inline
                pass
        with self._lock:
            bundles, suppressed = self._bundles, self._suppressed
        return {
            "bundle": BUNDLE_VERSION,
            "role": self.role,
            "trigger": trigger,
            "detail": redact(detail) if detail else None,
            "ts": round(trace.now_s(), 4),
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "pid": os.getpid(),
            "config": self.config,
            "spans": tr.tail(self.span_tail),
            "spans_dropped": tr.dropped,
            "events": events,
            "errors": [e for e in events if _errorish(e.get("event"))][-20:],
            "metrics": metrics.registry().snapshot(),
            "history": [{"ts": ts, "metrics": snap} for ts, snap in rows],
            "profile": profile,
            "recorder": {
                "bundles": bundles,
                "suppressed": suppressed,
                "cooldown_s": self.cooldown_s,
                "debug_dir": self.debug_dir,
            },
        }

    def trigger(self, kind: str, **detail: Any) -> dict | None:
        """Edge trigger: freeze a bundle for ``kind``, throttled to one
        per trigger kind per cooldown. Returns the bundle (its ``path``
        key names the directory when ``debug_dir`` is set), or None
        when the cooldown suppressed it."""
        now = trace.now_s()
        with self._lock:
            last = self._last_fire.get(kind)
            if last is not None and now - last < self.cooldown_s:
                self._suppressed += 1
                return None
            self._last_fire[kind] = now
        bundle = self.snapshot(kind, detail or None)
        path = self._write(bundle) if self.debug_dir else None
        bundle["path"] = path
        with self._lock:
            self._bundles += 1
            self.last_bundle = bundle
        if self._logger is not None:
            try:
                self._logger.event("debug_bundle", trigger=kind, path=path)
            except Exception:  # noqa: BLE001 — triggers run on hot paths
                pass
        return bundle

    def _write(self, bundle: dict) -> str | None:
        stamp = time.strftime("%Y%m%d-%H%M%S")
        base = os.path.join(
            self.debug_dir,
            f"bundle-{bundle['trigger']}-{stamp}-{os.getpid()}",
        )
        path, n = base, 0
        while os.path.exists(path):  # same kind, same second: suffix
            n += 1
            path = f"{base}.{n}"
        try:
            os.makedirs(path, exist_ok=True)
            bundle["path"] = path
            with open(os.path.join(path, BUNDLE_FILE), "w") as f:
                json.dump(bundle, f, indent=1)
        except OSError:
            return None  # a full disk must not take the trigger path down
        return path
