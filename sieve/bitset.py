"""Bit-packed segment layouts: plain / odds-only / wheel-30.

SURVEY.md section 7.3 gives the validated value<->bit maps this module
implements:

  - plain:   bit b of a segment starting at lo  <->  value lo + b
  - odds:    bit b of a segment whose first odd is f  <->  value f + 2b;
             a prime stride p in value space is stride p in bit space
  - wheel30: candidates are v with v % 30 in {1,7,11,13,17,19,23,29};
             global flag index of v is 8*(v//30) + RES_IDX[v % 30];
             each prime marks along 8 residue-class progressions with
             bit stride 8p (v += 30p  =>  gidx += 8p)

A layout exposes only *candidate* values; primes it cannot represent
(2 for odds; 2, 3, 5 for wheel30) are ``extra_primes`` handled by the
worker/merge layers. Flags are boolean, True = "still possibly prime";
packed words are uint32 with bit k of word w = flag[32*w + k].
"""

from __future__ import annotations

import numpy as np

WORD_BITS = 32

WHEEL30_RESIDUES = (1, 7, 11, 13, 17, 19, 23, 29)
_W30_IDX = np.full(30, -1, dtype=np.int64)
for _i, _r in enumerate(WHEEL30_RESIDUES):
    _W30_IDX[_r] = _i
# Candidate count in one 30-block below residue r (for first_candidate math).
_W30_COUNT_BELOW = np.zeros(31, dtype=np.int64)
for _v in range(1, 31):
    _W30_COUNT_BELOW[_v] = _W30_COUNT_BELOW[_v - 1] + (1 if _W30_IDX[_v - 1] >= 0 else 0)


class Layout:
    """Candidate-value <-> bit-index map plus the numpy marking recipe."""

    name: str = ""
    extra_primes: tuple[int, ...] = ()
    wheel_primes: tuple[int, ...] = ()  # seed primes that must NOT mark

    # --- candidate/value mapping -------------------------------------------------
    def is_candidate(self, v: int) -> bool:
        raise NotImplementedError

    def gidx(self, v: int) -> int:
        """Global flag index of candidate v (monotonic over candidates)."""
        raise NotImplementedError

    def gidx_np(self, v: np.ndarray) -> np.ndarray:
        """Vectorized gidx over an int64 array of candidate values."""
        raise NotImplementedError

    def first_candidate(self, lo: int) -> int:
        """Smallest candidate value >= lo."""
        raise NotImplementedError

    def nbits(self, lo: int, hi: int) -> int:
        """Number of candidate values in [lo, hi)."""
        f = self.first_candidate(lo)
        if f >= hi:
            return 0
        l = self.last_candidate(hi)
        return self.gidx(l) - self.gidx(f) + 1

    def last_candidate(self, hi: int) -> int:
        """Largest candidate value < hi (requires one to exist)."""
        v = hi - 1
        while not self.is_candidate(v):
            v -= 1
        return v

    def bit_of(self, v: int, lo: int) -> int:
        """Segment-local bit index of candidate v in segment starting at lo."""
        return self.gidx(v) - self.gidx(self.first_candidate(lo))

    def candidates(self, lo: int, hi: int) -> np.ndarray:
        """All candidate values in [lo, hi) — small segments / tests only."""
        v = np.arange(lo, hi, dtype=np.int64)
        return v[[self.is_candidate(int(x)) for x in v]]

    def values_np(self, lo: int, bit_idx: np.ndarray) -> np.ndarray:
        """Candidate values at segment-local bit indices (vectorized inverse
        of bit_of; used by prime enumeration)."""
        raise NotImplementedError

    # --- marking -----------------------------------------------------------------
    def mark_numpy(self, flags: np.ndarray, lo: int, hi: int, p: int) -> None:
        """Clear composite bits for prime p (p not in wheel_primes).

        Marks multiples p*m with m >= p (i.e. from p^2 up), restricted to
        candidates in [lo, hi). The classic start computation
        ``start = max(p*p, ceil(lo/p)*p)`` (SURVEY.md section 4.2) underlies
        each variant.
        """
        raise NotImplementedError

    def extras_in(self, lo: int, hi: int) -> int:
        return sum(1 for p in self.extra_primes if lo <= p < hi)

    def extra_pairs(self, lo: int, hi: int, gap: int = 2) -> int:
        """Prime pairs (v, v+gap) invisible to this packing's flag array
        because a member is a wheel prime (wheel30: (3,5)/(5,7) for twins,
        (3,7) for cousins). Counted when lo <= v and v+gap < hi."""
        return 0

    def extra_twin_pairs(self, lo: int, hi: int) -> int:
        return self.extra_pairs(lo, hi, 2)

    # --- prime pairs -------------------------------------------------------------
    def pairs_internal(self, flags: np.ndarray, lo: int, hi: int,
                       gap: int = 2) -> int:
        """Pairs (v, v+gap) both prime with v, v+gap in [lo, hi); gap is 2
        (twins) or 4 (cousins). Includes pairs involving extra primes."""
        raise NotImplementedError

    def twins_internal(self, flags: np.ndarray, lo: int, hi: int) -> int:
        return self.pairs_internal(flags, lo, hi, 2)


class PlainLayout(Layout):
    """One bit per integer. bit b <-> value lo + b."""

    name = "plain"
    extra_primes = ()
    wheel_primes = ()

    def is_candidate(self, v: int) -> bool:
        return v >= 2

    def gidx(self, v: int) -> int:
        return v

    def gidx_np(self, v: np.ndarray) -> np.ndarray:
        return v.astype(np.int64)

    def first_candidate(self, lo: int) -> int:
        return max(lo, 2)

    def last_candidate(self, hi: int) -> int:
        return hi - 1

    def mark_numpy(self, flags: np.ndarray, lo: int, hi: int, p: int) -> None:
        first = self.first_candidate(lo)
        start = max(p * p, -(-lo // p) * p)
        if start >= hi:
            return
        flags[start - first :: p] = False

    def values_np(self, lo: int, bit_idx: np.ndarray) -> np.ndarray:
        return self.first_candidate(lo) + bit_idx.astype(np.int64)

    def pairs_internal(self, flags: np.ndarray, lo: int, hi: int,
                       gap: int = 2) -> int:
        if flags.size <= gap:
            # fall back to direct check on tiny segments
            return _pairs_direct(self, flags, lo, hi, gap)
        return int(np.count_nonzero(flags[:-gap] & flags[gap:]))


class OddsLayout(Layout):
    """One bit per odd integer (the default; SURVEY.md section 7.2 decision).

    Segment of nbits odd values starting at odd f: bit b <-> value f + 2b.
    """

    name = "odds"
    extra_primes = (2,)
    wheel_primes = (2,)

    def is_candidate(self, v: int) -> bool:
        return v >= 3 and v % 2 == 1

    def gidx(self, v: int) -> int:
        return (v - 3) // 2

    def gidx_np(self, v: np.ndarray) -> np.ndarray:
        return (v.astype(np.int64) - 3) // 2

    def first_candidate(self, lo: int) -> int:
        lo = max(lo, 3)
        return lo if lo % 2 == 1 else lo + 1

    def last_candidate(self, hi: int) -> int:
        v = hi - 1
        return v if v % 2 == 1 else v - 1

    def mark_numpy(self, flags: np.ndarray, lo: int, hi: int, p: int) -> None:
        first = self.first_candidate(lo)
        start = max(p * p, -(-lo // p) * p)
        if start % 2 == 0:
            start += p
        if start >= hi:
            return
        b0 = (start - first) // 2
        flags[b0::p] = False  # stride p in value space == stride p in bit space

    def values_np(self, lo: int, bit_idx: np.ndarray) -> np.ndarray:
        return self.first_candidate(lo) + 2 * bit_idx.astype(np.int64)

    def pairs_internal(self, flags: np.ndarray, lo: int, hi: int,
                       gap: int = 2) -> int:
        b = gap // 2  # value gap 2k == bit gap k in the odds layout
        if flags.size < b + 1:
            return 0
        return int(np.count_nonzero(flags[:-b] & flags[b:]))


class Wheel30Layout(Layout):
    """One bit per v coprime to 30. gidx(v) = 8*(v//30) + RES_IDX[v%30]."""

    name = "wheel30"
    extra_primes = (2, 3, 5)
    wheel_primes = (2, 3, 5)

    def is_candidate(self, v: int) -> bool:
        return v > 1 and _W30_IDX[v % 30] >= 0

    def gidx(self, v: int) -> int:
        return 8 * (v // 30) + int(_W30_IDX[v % 30])

    def gidx_np(self, v: np.ndarray) -> np.ndarray:
        v = v.astype(np.int64)
        return 8 * (v // 30) + _W30_IDX[v % 30]

    def first_candidate(self, lo: int) -> int:
        lo = max(lo, 7)  # 1 is a unit, not a candidate; first real candidate is 7
        v = lo
        while not self.is_candidate(v):
            v += 1
        return v

    def mark_numpy(self, flags: np.ndarray, lo: int, hi: int, p: int) -> None:
        first = self.first_candidate(lo)
        g0 = self.gidx(first)
        pinv = pow(p, -1, 30)
        m_lo = max(p, -(-lo // p))
        for r in WHEEL30_RESIDUES:
            c = (r * pinv) % 30  # m residue class whose multiples land on r
            m0 = m_lo + ((c - m_lo) % 30)
            v0 = p * m0
            if v0 >= hi:
                continue
            b0 = self.gidx(v0) - g0
            flags[b0 :: 8 * p] = False  # v += 30p  =>  gidx += 8p

    def values_np(self, lo: int, bit_idx: np.ndarray) -> np.ndarray:
        res = np.array(WHEEL30_RESIDUES, dtype=np.int64)
        g = self.gidx(self.first_candidate(lo)) + bit_idx.astype(np.int64)
        return 30 * (g // 8) + res[g % 8]

    # residue indices whose gidx-NEXT candidate sits exactly `gap` above:
    # gap=2 -> (11,13), (17,19), (29,31); gap=4 -> (7,11), (13,17), (19,23)
    _PAIR_IDX = {2: (2, 4, 7), 4: (1, 3, 5)}

    def pairs_internal(self, flags: np.ndarray, lo: int, hi: int,
                       gap: int = 2) -> int:
        # Candidate pairs differing by `gap` are exactly gidx-adjacent with
        # the left member's residue index in _PAIR_IDX[gap].
        idxset = self._PAIR_IDX[gap]
        total = 0
        if flags.size >= 2:
            first = self.first_candidate(lo)
            g0 = self.gidx(first)
            pos = np.arange(flags.size - 1, dtype=np.int64)
            resind = (g0 + pos) % 8
            pairmask = np.isin(resind, idxset)
            total += int(np.count_nonzero(flags[:-1] & flags[1:] & pairmask))
        return total + self.extra_pairs(lo, hi, gap)

    def extra_pairs(self, lo: int, hi: int, gap: int = 2) -> int:
        # Pairs involving the always-prime wheel primes 3, 5:
        # twins (3,5), (5,7); cousins (3,7).
        total = 0
        if gap == 2:
            if lo <= 3 and 5 < hi:
                total += 1
            if lo <= 5 and 7 < hi:
                total += 1
        elif gap == 4:
            if lo <= 3 and 7 < hi:
                total += 1
        return total


def _pairs_direct(layout: Layout, flags: np.ndarray, lo: int, hi: int,
                  gap: int = 2) -> int:
    """O(candidates) direct pair count for tiny segments."""
    vals = layout.candidates(lo, hi)
    primeset = {int(v) for v, f in zip(vals, flags[: vals.size]) if f}
    primeset |= {p for p in layout.extra_primes if lo <= p < hi}
    return sum(1 for v in primeset if v + gap in primeset)


LAYOUTS: dict[str, Layout] = {
    "plain": PlainLayout(),
    "odds": OddsLayout(),
    "wheel30": Wheel30Layout(),
}


def get_layout(name: str) -> Layout:
    return LAYOUTS[name]


# --- packing -------------------------------------------------------------------


def pack_words(flags: np.ndarray) -> np.ndarray:
    """Pack boolean flags into uint32 words, bit k of word w = flag[32w+k]."""
    nbits = flags.size
    pad = (-nbits) % WORD_BITS
    if pad:
        flags = np.concatenate([flags, np.zeros(pad, dtype=bool)])
    return np.packbits(flags, bitorder="little").view("<u4")


def unpack_words(words: np.ndarray, nbits: int) -> np.ndarray:
    """Inverse of pack_words."""
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return bits[:nbits].astype(bool)


def boundary_words(flags: np.ndarray) -> tuple[int, int]:
    """(first_word, last_word) of a flag array.

    first_word bit k = flag[k]; last_word bit k = flag[nbits-32+k] for
    nbits >= 32, else last_word == first_word. These are the boundary
    bitwords the coordinator's merge uses for cross-segment twin pairs
    (SURVEY.md section 2 "merge ... boundary bitwords").
    """
    nbits = flags.size
    if nbits == 0:
        return 0, 0
    words = pack_words(flags)
    first_word = int(words[0])
    if nbits <= WORD_BITS:
        return first_word, first_word
    start = nbits - WORD_BITS
    w0, sh = divmod(start, WORD_BITS)
    if sh == 0:
        last_word = int(words[w0])
    else:
        hi_part = int(words[w0 + 1]) << (WORD_BITS - sh) if w0 + 1 < words.size else 0
        last_word = ((int(words[w0]) >> sh) | hi_part) & 0xFFFFFFFF
    return first_word, last_word


def popcount_words(words: np.ndarray) -> int:
    """Population count of a uint32 word array (byte-LUT, SURVEY section 2)."""
    return int(np.unpackbits(words.view(np.uint8)).sum())


# --- wheel-210 value-space codec (ISSUE 17) ------------------------------------
#
# The tiered segment store compresses prime sets in *value* space with a
# mod-210 wheel: 48 of every 210 integers are coprime to 2*3*5*7, so a
# set of primes >= 11 over [lo, hi) costs 48 bits (6 bytes) per
# 210-block regardless of which flag layout materialized it. The four
# wheel primes {2, 3, 5, 7} cannot be represented on the wheel and ride
# in a 4-bit side mask. This is the Cache-Aware Hybrid Sieve's
# bit-packing (PAPERS.md) applied to at-rest storage rather than the
# marking loop.

WHEEL210_RESIDUES = tuple(
    r for r in range(210)
    if r % 2 and r % 3 and r % 5 and r % 7
)
assert len(WHEEL210_RESIDUES) == 48
_W210_IDX = np.full(210, -1, dtype=np.int64)
for _i, _r in enumerate(WHEEL210_RESIDUES):
    _W210_IDX[_r] = _i
_W210_RES = np.array(WHEEL210_RESIDUES, dtype=np.int64)
_W210_SMALL = (2, 3, 5, 7)


def _w210_nbits(lo: int, hi: int) -> int:
    if hi <= lo:
        return 0
    return 48 * ((hi - 1) // 210 - lo // 210 + 1)


def pack_wheel210(lo: int, hi: int, values: np.ndarray) -> tuple[bytes, int]:
    """Pack a set of prime values in [lo, hi) -> (payload, small_mask).

    ``values`` must all be prime (every value >= 11 must be coprime to
    210 — a composite candidate that survived would be silently lost, so
    this raises instead). ``small_mask`` bit i records the presence of
    ``(2, 3, 5, 7)[i]``. Payload is 6 bytes per 210-block covering
    [lo, hi), bit ``48*(v//210 - lo//210) + idx(v % 210)`` = v present.
    """
    values = np.asarray(values, dtype=np.int64)
    small_mask = 0
    for i, p in enumerate(_W210_SMALL):
        if np.any(values == p):
            small_mask |= 1 << i
    wl = values[values >= 11]
    res_idx = _W210_IDX[wl % 210]
    if res_idx.size and int(res_idx.min()) < 0:
        bad = wl[res_idx < 0][:3]
        raise ValueError(
            f"pack_wheel210: non-prime values {bad.tolist()} share a factor "
            "with 210 and cannot ride the wheel"
        )
    nbits = _w210_nbits(lo, hi)
    bits = np.zeros(nbits, dtype=bool)
    bits[48 * (wl // 210 - lo // 210) + res_idx] = True
    return np.packbits(bits, bitorder="little").tobytes(), small_mask


def unpack_wheel210(lo: int, hi: int, payload: bytes,
                    small_mask: int) -> np.ndarray:
    """Inverse of pack_wheel210: sorted int64 prime values in [lo, hi)."""
    nbits = _w210_nbits(lo, hi)
    need = (nbits + 7) // 8
    if len(payload) < need:
        raise ValueError(
            f"unpack_wheel210: payload {len(payload)}B < {need}B "
            f"for [{lo}, {hi})"
        )
    bits = np.unpackbits(
        np.frombuffer(payload, dtype=np.uint8), bitorder="little"
    )[:nbits]
    g = np.flatnonzero(bits)
    vals = 210 * (lo // 210 + g // 48) + _W210_RES[g % 48]
    small = np.array(
        [p for i, p in enumerate(_W210_SMALL) if small_mask >> i & 1],
        dtype=np.int64,
    )
    if small.size:
        vals = np.concatenate([small, vals])
    return vals[(vals >= lo) & (vals < hi)]
