"""CLI entry point (SURVEY.md section 1a L6).

Example:
    python -m sieve --n 1e9 --backend jax --segments 256 --packing odds --twins

The ``serve`` subcommand starts the persistent query plane over a sieved
checkpoint dir (sieve/service/):

    python -m sieve serve --n 1e9 --segments 256 --checkpoint-dir ck \\
        --addr 127.0.0.1:7723

The ``route`` subcommand fronts several such servers as one range-sharded
fabric (sieve/service/router.py) — same wire protocol, zero client
changes:

    python -m sieve route --addr 127.0.0.1:7733 \\
        --shard 2:5e8=127.0.0.1:7723,127.0.0.1:7724 \\
        --shard 5e8:1e9=127.0.0.1:7725

The ``observe`` subcommand runs the capacity observatory against such a
fabric (sieve/service/observe.py) — fleet trend ring + anomaly engine:

    python -m sieve observe --router 127.0.0.1:7733 --observe-dir obs
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

from sieve import env
from sieve.config import BACKENDS, PACKINGS, SieveConfig


def _parse_n(text: str) -> int:
    """Accept 1000000, 1e9, 10**12 style values."""
    try:
        return int(text)
    except ValueError:
        pass
    if "**" in text:
        base, exp = text.split("**")
        return int(base) ** int(exp)
    val = float(text)
    n = int(val)
    if n != val:
        raise argparse.ArgumentTypeError(f"--n must be an integer, got {text}")
    return n


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sieve",
        description="TPU-native distributed segmented Sieve of Eratosthenes",
    )
    p.add_argument("--n", type=_parse_n, default=None, help="sieve [2, N] inclusive (1e9 ok)")
    p.add_argument("--emit-primes", default=None, metavar="LO:HI",
                   help="print the primes in [LO, HI] inclusive (one per "
                        "line; --json for a JSON array) instead of counting")
    p.add_argument("--backend", choices=BACKENDS, default="cpu-numpy")
    p.add_argument("--segments", type=int, default=None, dest="n_segments")
    p.add_argument("--segment-size", type=int, default=None, dest="segment_values",
                   help="values per segment (alternative to --segments)")
    p.add_argument("--packing", choices=PACKINGS, default="odds")
    p.add_argument("--twins", action="store_true", help="also count twin-prime pairs")
    p.add_argument("--count-kind", choices=("primes", "twins", "cousins"),
                   default=None, dest="count_kind",
                   help="pair reduction at the postlude: primes (count "
                        "only), twins (p, p+2), cousins (p, p+4); same "
                        "marking kernels either way (--twins is shorthand "
                        "for --count-kind twins)")
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--multihost", action="store_true",
                   help="multi-host SPMD: jax.distributed.initialize() "
                        "first (coordinator/process env-configured, or "
                        "--jax-coordinator/--jax-processes/--jax-process-id); "
                        "--workers is then the GLOBAL device count")
    p.add_argument("--jax-coordinator", default=None,
                   help="coordinator address for --multihost (host:port)")
    p.add_argument("--jax-processes", type=int, default=None)
    p.add_argument("--jax-process-id", type=int, default=None)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--rounds", type=int, default=1,
                   help="TPU dispatch rounds (failure-recovery granularity)")
    p.add_argument("--profile-dir", default=None)
    p.add_argument("--trace", default=None, dest="trace_file", metavar="FILE",
                   help="write host-side spans (round phases, prepare "
                        "threads, per-segment timings) as Chrome "
                        "trace-event JSON — open in Perfetto or "
                        "chrome://tracing; on the cpu-cluster backend the "
                        "file is the MERGED cluster timeline (coordinator "
                        "+ one rebased track per worker); see "
                        "tools/trace_report.py [--cluster]")
    p.add_argument("--metrics-file", default=None, dest="metrics_file",
                   metavar="FILE",
                   help="append every metrics event as JSONL (including "
                        "per-segment events suppressed on stderr by "
                        "--quiet)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-segment stderr lines (the run "
                        "summary and robustness events still print)")
    p.add_argument("--json", action="store_true", dest="json_output")
    p.add_argument("--chaos", default=None,
                   help="composable fault-injection schedule, e.g. "
                        "'kill:1@s4,stall:2@s7:3.0,drop_hb:any@s9,"
                        "disconnect:0@s2' (kind:worker@s<seg>[:param]; "
                        "see sieve/chaos.py)")
    p.add_argument("--chaos-kill-worker", default=None, dest="chaos_kill",
                   help="fault injection: 'k@s' kills worker k at segment s "
                        "('any@s': whichever worker draws segment s); "
                        "legacy shorthand for --chaos 'kill:k@s<s>'")
    p.add_argument("--role", choices=("auto", "coordinator", "worker"), default="auto",
                   help="cpu-cluster role (worker processes connect to --coordinator-addr)")
    p.add_argument("--coordinator-addr", default="127.0.0.1:7621")
    return p


def config_from_args(args: argparse.Namespace) -> SieveConfig:
    count_kind = getattr(args, "count_kind", None)
    if count_kind is None:
        count_kind = "twins" if args.twins else "primes"
    elif args.twins and count_kind == "cousins":
        raise ValueError("--twins conflicts with --count-kind cousins")
    return SieveConfig(
        n=args.n,
        multihost=args.multihost,
        backend=args.backend,
        packing=args.packing,
        n_segments=args.n_segments,
        segment_values=args.segment_values,
        twins=args.twins,
        count_kind=count_kind,
        workers=args.workers,
        checkpoint_dir=args.checkpoint_dir,
        resume=args.resume,
        rounds=args.rounds,
        profile_dir=args.profile_dir,
        trace_file=args.trace_file,
        metrics_file=args.metrics_file,
        quiet=args.quiet,
        json_output=args.json_output,
        chaos=args.chaos,
        chaos_kill=args.chaos_kill,
        coordinator_addr=args.coordinator_addr,
    )


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        try:
            return _serve(argv[1:])
        except (ValueError, RuntimeError, ImportError) as e:
            print(f"sieve: error: {e}", file=sys.stderr)
            return 2
    if argv and argv[0] == "route":
        try:
            return _route(argv[1:])
        except (ValueError, RuntimeError, ImportError) as e:
            print(f"sieve: error: {e}", file=sys.stderr)
            return 2
    if argv and argv[0] == "observe":
        try:
            return _observe(argv[1:])
        except (ValueError, RuntimeError, ImportError) as e:
            print(f"sieve: error: {e}", file=sys.stderr)
            return 2
    args = build_parser().parse_args(argv)
    try:
        if args.emit_primes is not None:
            return _emit_primes(args)
        if args.n is None:
            print("sieve: error: --n is required (or use --emit-primes)",
                  file=sys.stderr)
            return 2
        return _run(args)
    except (ValueError, RuntimeError, ImportError) as e:
        print(f"sieve: error: {e}", file=sys.stderr)
        return 2


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sieve serve",
        description="Persistent query server: pi / count / nth_prime / "
                    "primes over the RPC plane (sieve/service/)",
    )
    p.add_argument("--addr", default="127.0.0.1:7723",
                   help="listen address host:port (port 0 picks a free one; "
                        "the chosen address is printed as a JSON line)")
    p.add_argument("--n", type=_parse_n, required=True,
                   help="the sieved range [2, N] the checkpoint dir covers "
                        "(must match the sieving run for its config hash)")
    p.add_argument("--packing", choices=PACKINGS, default="odds")
    p.add_argument("--segments", type=int, default=None, dest="n_segments")
    p.add_argument("--segment-size", type=int, default=None,
                   dest="segment_values")
    p.add_argument("--checkpoint-dir", default=None,
                   help="sieved checkpoint dir to index (omit for a "
                        "cold-only server)")
    p.add_argument("--backend", choices=[b for b in BACKENDS
                                         if b != "cpu-cluster"],
                   default="cpu-numpy",
                   help="cold-tier compute backend for uncovered ranges")
    p.add_argument("--cold-backend", choices=("loop", "mesh"), default=None,
                   dest="cold_backend",
                   help="cold-plane dispatch: 'mesh' issues ONE shard_map "
                        "SPMD launch spanning every device per drain slice "
                        "(falls back typed to the loop worker when the mesh "
                        "can't init or a launch fails); 'loop' is the "
                        "single-worker path (default "
                        "SIEVE_SVC_COLD_BACKEND/loop)")
    p.add_argument("--queue-limit", type=int, default=None,
                   help="admission queue bound (default SIEVE_SVC_QUEUE/64; "
                        "beyond it requests get a typed overloaded reply)")
    p.add_argument("--service-workers", type=int, default=None,
                   help="handler threads (default SIEVE_SVC_WORKERS/4)")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="default per-request deadline "
                        "(default SIEVE_SVC_DEADLINE_S/30)")
    p.add_argument("--refresh-s", type=float, default=None,
                   help="ledger live-follow poll period (default "
                        "SIEVE_SVC_REFRESH_S/2.0; 0 disables the follower)")
    p.add_argument("--drain-s", type=float, default=None,
                   help="graceful-drain budget after SIGTERM/shutdown "
                        "(default SIEVE_SVC_DRAIN_S/5.0)")
    p.add_argument("--persist-cold", action="store_true",
                   help="write cold chunk results back into the checkpoint "
                        "dir's ledger (this server becomes its designated "
                        "writer; covered_hi grows under read traffic and "
                        "replicas following the file inherit the work). "
                        "Default OFF / SIEVE_SVC_PERSIST_COLD")
    p.add_argument("--range-lo", type=_parse_n, default=None, dest="range_lo",
                   help="serve as a range SHARD covering [RANGE_LO, N]: "
                        "count/primes below RANGE_LO are rejected typed, "
                        "counts anchor at RANGE_LO instead of 2, and pi "
                        "(a global-prefix op) is refused — the router "
                        "(python -m sieve route) owns global composition "
                        "(default SIEVE_SVC_RANGE_LO/2)")
    p.add_argument("--allow-chaos", action="store_true",
                   help="accept wire-injected chaos messages (default OFF: "
                        "a refused injection gets a typed bad_request and "
                        "a service_chaos_refused event)")
    p.add_argument("--chaos", default=None,
                   help="service fault schedule, e.g. 'svc_stall:any@s3:2.0,"
                        "svc_shed:any@s5,backend_down:any@s7:1.0' (segment "
                        "number = request sequence number)")
    p.add_argument("--trace", default=None, dest="trace_file", metavar="FILE",
                   help="write rpc.query / queue-wait / materialize / cold "
                        "spans as Chrome trace-event JSON on shutdown")
    p.add_argument("--debug-dir", default=None, dest="debug_dir",
                   help="flight-recorder bundle directory: edge triggers "
                        "(SLO burn, breaker open, crash) freeze a "
                        "timestamped postmortem bundle here (default "
                        "SIEVE_SVC_DEBUG_DIR; without a dir the recorder "
                        "still runs and serves the debug wire op / "
                        "tools/fleet_debug.py inline)")
    p.add_argument("--prof-hz", type=float, default=None, dest="prof_hz",
                   help="continuous-profiler sampling rate (default "
                        "SIEVE_PROF_HZ/19; 0 disables the sampler — the "
                        "profile wire op then answers null)")
    p.add_argument("--metrics-file", default=None, dest="metrics_file")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-request stderr event lines")
    p.add_argument("--procs", type=int, default=None,
                   help="serving processes SO_REUSEPORT-bound to ONE port "
                        "(escapes the GIL: each runs its own event loop "
                        "and worker pool over the shared mmap'd segment "
                        "store; process 0 is the designated writer for "
                        "persist-cold and store compaction, the rest "
                        "follow store/ledger generations read-only). "
                        "Default SIEVE_SVC_PROCS/1")
    # internal: set by the --procs supervisor when it re-execs itself as
    # child i; never set by hand
    p.add_argument("--proc-index", type=int, default=None,
                   help=argparse.SUPPRESS)
    return p


def _serve(argv: list[str]) -> int:
    args = build_serve_parser().parse_args(argv)
    procs = args.procs if args.procs is not None \
        else env.env_int("SIEVE_SVC_PROCS", 1)
    if procs > 1 and args.proc_index is None:
        # supervisor: spawn N SO_REUSEPORT children on one port and
        # babysit them; this process never serves traffic itself
        return _serve_supervisor(argv, args, procs)
    config = SieveConfig(
        n=args.n,
        backend=args.backend,
        packing=args.packing,
        n_segments=args.n_segments,
        segment_values=args.segment_values,
        checkpoint_dir=args.checkpoint_dir,
        trace_file=args.trace_file,
        metrics_file=args.metrics_file,
        quiet=args.quiet,
        chaos=args.chaos,
    )

    from sieve import metrics, trace
    from sieve.service import ServiceSettings, SieveService

    overrides = {}
    if args.queue_limit is not None:
        overrides["queue_limit"] = args.queue_limit
    if args.service_workers is not None:
        overrides["workers"] = args.service_workers
    if args.deadline_s is not None:
        overrides["default_deadline_s"] = args.deadline_s
    if args.refresh_s is not None:
        overrides["refresh_s"] = args.refresh_s
    if args.drain_s is not None:
        overrides["drain_s"] = args.drain_s
    if args.allow_chaos:
        overrides["wire_chaos"] = True
    if args.range_lo is not None:
        overrides["range_lo"] = args.range_lo
    if args.persist_cold:
        if not args.checkpoint_dir:
            raise ValueError("--persist-cold needs --checkpoint-dir (the "
                             "ledger is the write-back target)")
        overrides["persist_cold"] = True
    if args.debug_dir is not None:
        overrides["debug_dir"] = args.debug_dir
    if args.prof_hz is not None:
        overrides["prof_hz"] = args.prof_hz
    if args.cold_backend is not None:
        overrides["cold_backend"] = args.cold_backend
    if procs > 1:
        # child of the --procs supervisor: everyone binds the SAME port
        # via SO_REUSEPORT; only process 0 writes (persist-cold ledger
        # appends + store compaction), the rest follow read-only
        overrides["procs"] = procs
        overrides["proc_index"] = args.proc_index or 0
        overrides["reuse_port"] = True
    settings = ServiceSettings.from_env(**overrides)

    file_sink = None
    if config.metrics_file:
        file_sink = metrics.FileSink(config.metrics_file)
        metrics.add_sink(file_sink)
    if config.trace_file:
        trace.enable()
    service = SieveService(config, settings, addr=args.addr)
    try:
        service.start()
        # one parseable line so wrappers (tools/service_smoke.py) can find
        # the bound port when --addr uses port 0
        print(json.dumps({
            "event": "serving",
            "addr": service.addr,
            "covered_hi": service.index.covered_hi,
            "total_primes": service.index.total_primes,
            "segments": len(service.index.segments),
            "proc": settings.proc_index,
            "procs": settings.procs,
        }), flush=True)
        import signal

        # SIGTERM = graceful drain (rolling restarts send it): answer
        # queued work, shed new queries typed, exit 0 within --drain-s.
        # SIGINT/KeyboardInterrupt stays the fast ctrl-C path.
        signal.signal(signal.SIGTERM, lambda *_: service.drain())
        service.drain_event.wait()  # serve until SIGTERM/shutdown
        drained = service.wait_drained(settings.drain_s)
        # the stats subset carries what per-process observers need when
        # N procs share one port (per-proc wire stats are unreachable
        # from outside: the kernel picks which process answers a
        # connection) — tools/store_smoke.py asserts on these
        final = service.stats()
        print(json.dumps({
            "event": "drained",
            "clean": drained,
            "proc": settings.proc_index,
            "stats": {k: final[k]
                      for k in ("requests", "draining_replies",
                                "materialized", "cold_computes",
                                "cold_dispatches", "lru_hits",
                                "store_hits")},
            "store": final["store"] and {
                k: final["store"][k]
                for k in ("gen", "writer", "hits", "demotions", "torn")},
        }), flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        service.stop()
        if config.trace_file:
            trace.disable()
            trace.save(config.trace_file)
        if file_sink is not None:
            metrics.remove_sink(file_sink)
            file_sink.close()
    return 0


def _serve_supervisor(argv: list[str], args, procs: int) -> int:
    """``serve --procs N``: N serving processes, ONE port.

    Python threads share one GIL, so a single process tops out near one
    core no matter how many worker threads it runs. The supervisor
    escapes that by spawning N full server processes that each bind the
    same TCP port with SO_REUSEPORT — the kernel load-balances incoming
    connections across them, and the mmap'd segment store keeps their
    hot tiers shared through the page cache instead of N private copies.

    Mechanics: when --addr asks for port 0 the supervisor reserves a
    concrete port first (SO_REUSEPORT-bound, never listening, so it
    receives no connections) and pins every child to it; children are
    re-execs of this very command line with --proc-index i added.
    Child serving lines are swallowed into one consolidated supervisor
    line; everything else (drained lines, metrics) is forwarded verbatim
    so per-process stats stay observable. SIGTERM/SIGINT fan out as
    SIGTERM (graceful drain) to every child; the exit code is 0 only if
    every child drained cleanly.
    """
    import signal
    import socket
    import subprocess
    import threading

    from sieve.rpc import parse_addr

    if not hasattr(socket, "SO_REUSEPORT"):
        print(json.dumps({"event": "error",
                          "detail": "--procs needs SO_REUSEPORT, which "
                                    "this platform lacks"}), flush=True)
        return 2
    host, port = parse_addr(args.addr)
    reserve = None
    if port == 0:
        # reserve a concrete port for the whole fleet: bound (so the
        # kernel won't hand it to anyone else) but never listening (so
        # it steals no connections from the children)
        reserve = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        reserve.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        reserve.bind((host, 0))
        port = reserve.getsockname()[1]
    addr = f"{host}:{port}"

    # child argv = this argv with addr pinned and proc identity added
    base: list[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in ("--addr", "--procs", "--proc-index"):
            skip = True
            continue
        if a.startswith(("--addr=", "--procs=", "--proc-index=")):
            continue
        base.append(a)

    children: list[subprocess.Popen] = []
    serving: list[threading.Event] = []
    first_line: list[dict | None] = [None] * procs

    def _forward(i: int, proc: subprocess.Popen) -> None:
        assert proc.stdout is not None
        for line in proc.stdout:
            line = line.rstrip("\n")
            if first_line[i] is None:
                try:
                    doc = json.loads(line)
                except ValueError:
                    doc = None
                if doc is not None and doc.get("event") == "serving":
                    # swallowed: the consolidated supervisor line below
                    # is THE serving announcement wrappers parse
                    first_line[i] = doc
                    serving[i].set()
                    continue
            print(line, flush=True)

    try:
        for i in range(procs):
            cmd = [sys.executable, "-m", "sieve", "serve", *base,
                   "--addr", addr, "--procs", str(procs),
                   "--proc-index", str(i)]
            p = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
            children.append(p)
            serving.append(threading.Event())
            threading.Thread(target=_forward, args=(i, children[i]),
                             daemon=True, name=f"serve-fwd-{i}").start()
        for i, ev in enumerate(serving):
            while not ev.wait(0.2):
                if children[i].poll() is not None:
                    raise RuntimeError(f"proc {i} exited "
                                       f"rc={children[i].returncode} "
                                       "before serving")
        if reserve is not None:
            reserve.close()  # every child holds the port now
            reserve = None
        doc0 = first_line[0] or {}
        print(json.dumps({
            "event": "serving",
            "addr": addr,
            "covered_hi": doc0.get("covered_hi"),
            "total_primes": doc0.get("total_primes"),
            "segments": doc0.get("segments"),
            "procs": procs,
            "supervisor": True,
        }), flush=True)

        stop = threading.Event()

        def _fan_out(*_sig) -> None:
            stop.set()
            for p in children:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)

        signal.signal(signal.SIGTERM, _fan_out)
        signal.signal(signal.SIGINT, _fan_out)
        while not stop.is_set():
            if any(p.poll() is not None for p in children):
                _fan_out()  # one child died: drain the rest, report
                break
            stop.wait(0.2)
        rcs = [p.wait() for p in children]
        print(json.dumps({"event": "drained", "supervisor": True,
                          "clean": all(rc == 0 for rc in rcs),
                          "rcs": rcs}), flush=True)
        return 0 if all(rc == 0 for rc in rcs) else 1
    finally:
        if reserve is not None:
            reserve.close()
        for p in children:
            if p.poll() is None:
                p.kill()


def build_route_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sieve route",
        description="Range-shard router: one RPC front door over shard "
                    "replica sets (sieve/service/router.py). Speaks the "
                    "same wire protocol as serve on both sides, so "
                    "existing clients need zero changes.",
    )
    p.add_argument("--addr", default="127.0.0.1:7733",
                   help="listen address host:port (port 0 picks a free one; "
                        "the chosen address is printed as a JSON line)")
    p.add_argument("--shard", action="append", default=None, metavar="LO:HI=ADDRS",
                   help="one shard covering [LO, HI) backed by comma-"
                        "separated replica addresses, e.g. "
                        "--shard 2:1e6=127.0.0.1:7723,127.0.0.1:7724 "
                        "(repeat per shard; shards must tile the range "
                        "contiguously). Alternative to --shard-map")
    p.add_argument("--shard-map", default=None, metavar="FILE",
                   help="JSON shard map file: {\"shards\": [{\"lo\", \"hi\", "
                        "\"addrs\"}, ...]}")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="default per-request deadline; the REMAINING budget "
                        "is forwarded to every downstream shard call "
                        "(default 30)")
    p.add_argument("--timeout-s", type=float, default=None,
                   help="downstream socket timeout (default 60)")
    p.add_argument("--probe-ttl-s", type=float, default=None,
                   help="shard health-probe cache TTL; 0 re-probes every "
                        "selection (default 2.0)")
    p.add_argument("--rounds", type=int, default=None,
                   help="failover sweeps across each shard's replicas "
                        "before giving up (default 2)")
    p.add_argument("--drain-s", type=float, default=None,
                   help="graceful-drain budget after SIGTERM/shutdown "
                        "(default 5.0)")
    p.add_argument("--allow-chaos", action="store_true",
                   help="accept wire-injected chaos messages (default OFF)")
    p.add_argument("--chaos", default=None,
                   help="router fault schedule, e.g. 'svc_shard_down:1@s3:"
                        "2.0' (segment number = router request sequence; "
                        "worker = shard index, any = every shard)")
    p.add_argument("--trace", default=None, dest="trace_file", metavar="FILE",
                   help="write rpc.route / route.scatter spans as Chrome "
                        "trace-event JSON on shutdown")
    p.add_argument("--debug-dir", default=None, dest="debug_dir",
                   help="flight-recorder bundle directory: a shard going "
                        "dark (router_shard_down) or a crash freezes a "
                        "timestamped postmortem bundle here")
    p.add_argument("--prof-hz", type=float, default=None, dest="prof_hz",
                   help="continuous-profiler sampling rate (default "
                        "SIEVE_PROF_HZ/19; 0 disables the sampler)")
    p.add_argument("--metrics-file", default=None, dest="metrics_file")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-request stderr event lines")
    return p


def _route(argv: list[str]) -> int:
    args = build_route_parser().parse_args(argv)

    from sieve import metrics, trace
    from sieve.service import RouterSettings, ShardMap, SieveRouter

    if bool(args.shard) == bool(args.shard_map):
        raise ValueError("route needs exactly one of --shard (repeatable) "
                         "or --shard-map FILE")
    if args.shard_map:
        shardmap = ShardMap.from_json(args.shard_map)
    else:
        shardmap = ShardMap.from_flags(args.shard)

    overrides = {}
    if args.deadline_s is not None:
        overrides["default_deadline_s"] = args.deadline_s
    if args.timeout_s is not None:
        overrides["timeout_s"] = args.timeout_s
    if args.probe_ttl_s is not None:
        overrides["probe_ttl_s"] = args.probe_ttl_s
    if args.rounds is not None:
        overrides["rounds"] = args.rounds
    if args.drain_s is not None:
        overrides["drain_s"] = args.drain_s
    if args.allow_chaos:
        overrides["wire_chaos"] = True
    if args.quiet:
        overrides["quiet"] = True
    if args.debug_dir is not None:
        overrides["debug_dir"] = args.debug_dir
    if args.prof_hz is not None:
        overrides["prof_hz"] = args.prof_hz
    settings = RouterSettings.from_env(**overrides)

    file_sink = None
    if args.metrics_file:
        file_sink = metrics.FileSink(args.metrics_file)
        metrics.add_sink(file_sink)
    if args.trace_file:
        trace.enable()
    router = SieveRouter(shardmap, settings, addr=args.addr,
                         chaos_spec=args.chaos or "")
    try:
        router.start()
        # one parseable line so wrappers (tools/shard_smoke.py) can find
        # the bound port when --addr uses port 0
        print(json.dumps({
            "event": "routing",
            "addr": router.addr,
            "range": [shardmap.lo, shardmap.hi],
            "shards": [s.to_dict() for s in shardmap],
        }), flush=True)
        import signal

        signal.signal(signal.SIGTERM, lambda *_: router.drain())
        router.drain_event.wait()  # route until SIGTERM/shutdown
        drained = router.wait_drained(settings.drain_s)
        print(json.dumps({
            "event": "drained",
            "clean": drained,
            "stats": {k: router.stats()[k]
                      for k in ("requests", "draining_replies")},
        }), flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
        if args.trace_file:
            trace.disable()
            trace.save(args.trace_file)
        if file_sink is not None:
            metrics.remove_sink(file_sink)
            file_sink.close()
    return 0


def build_observe_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sieve observe",
        description="Capacity observatory daemon: scrape a router and "
                    "every advertised shard replica on a cadence, persist "
                    "a CRC'd ring of downsampled fleet snapshots, and run "
                    "the EWMA anomaly engine (fleet_anomaly / "
                    "scaling_advice events; sieve/service/observe.py)",
    )
    p.add_argument("--router", required=True, metavar="ADDR",
                   help="router host:port to scrape (shard replicas are "
                        "discovered from its health reply)")
    p.add_argument("--observe-dir", default=None,
                   help="directory for the snapshot ring (fleet_ring.bin) "
                        "and anomaly-triggered fleet debug bundles; "
                        "omitted = in-memory trends only")
    p.add_argument("--scrape-s", type=float, default=None,
                   help="seconds between scrape cycles (default 1.0)")
    p.add_argument("--timeout-s", type=float, default=None,
                   help="per-endpoint RPC timeout (default 5.0)")
    p.add_argument("--scrapes", type=int, default=0, metavar="N",
                   help="stop after N scrape cycles (0 = run until "
                        "SIGTERM; N > 0 runs the cycles inline and exits "
                        "— the smoke-test mode)")
    p.add_argument("--chaos", default=None,
                   help="observer fault schedule, e.g. "
                        "'svc_scrape_gap:any@s3' (segment number = the "
                        "observer's scrape counter; worker = target index "
                        "in discovery order, any = every target)")
    p.add_argument("--metrics-file", default=None, dest="metrics_file")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-scrape stderr event lines")
    return p


def _observe(argv: list[str]) -> int:
    args = build_observe_parser().parse_args(argv)

    from sieve import metrics
    from sieve.chaos import ChaosSchedule, parse_chaos
    from sieve.service.observe import FleetObserver, ObserverSettings

    overrides: dict = {}
    if args.scrape_s is not None:
        overrides["scrape_s"] = args.scrape_s
    if args.timeout_s is not None:
        overrides["timeout_s"] = args.timeout_s
    if args.observe_dir is not None:
        overrides["observe_dir"] = args.observe_dir
    if args.quiet:
        overrides["quiet"] = True
    settings = ObserverSettings.from_env(**overrides)
    chaos = ChaosSchedule(parse_chaos(args.chaos or ""))

    file_sink = None
    if args.metrics_file:
        file_sink = metrics.FileSink(args.metrics_file)
        metrics.add_sink(file_sink)
    obs = FleetObserver(args.router, settings, chaos=chaos)
    try:
        print(json.dumps({
            "event": "observing",
            "router": args.router,
            "observe_dir": settings.observe_dir,
            "scrape_s": settings.scrape_s,
        }), flush=True)
        if args.scrapes > 0:
            # bounded inline mode: deterministic for smoke tests and cron
            for _ in range(args.scrapes):
                obs.scrape_once()
                if _ < args.scrapes - 1:
                    time.sleep(settings.scrape_s)
        else:
            import signal

            stop = threading.Event()
            signal.signal(signal.SIGTERM, lambda *_: stop.set())
            signal.signal(signal.SIGINT, lambda *_: stop.set())
            obs.start()
            stop.wait()
        print(json.dumps({"event": "observed", **obs.stats()}),
              flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        obs.stop()
        if file_sink is not None:
            metrics.remove_sink(file_sink)
            file_sink.close()
    return 0


def _emit_primes(args: argparse.Namespace) -> int:
    from sieve.enumerate import primes_in_range

    try:
        lo_s, hi_s = args.emit_primes.split(":")
        lo, hi = _parse_n(lo_s), _parse_n(hi_s)
    except (ValueError, argparse.ArgumentTypeError):
        raise ValueError(f"--emit-primes expects LO:HI, got {args.emit_primes!r}")
    chunks = primes_in_range(args.packing, lo, hi + 1)
    if args.json_output:
        # stream the array chunk-by-chunk: the max span's output is GBs
        sys.stdout.write("[")
        first = True
        for c in chunks:
            if c.size:
                if not first:
                    sys.stdout.write(", ")
                sys.stdout.write(", ".join(map(str, c.tolist())))
                first = False
        sys.stdout.write("]\n")
    else:
        for c in chunks:
            sys.stdout.write("\n".join(map(str, c.tolist())))
            if c.size:
                sys.stdout.write("\n")
    return 0


def _run(args: argparse.Namespace) -> int:
    config = config_from_args(args)

    if config.multihost:
        # DCN path (SURVEY.md section 5.8): same program, collectives routed
        # across hosts by JAX. Must happen before any device query.
        import jax

        jax.distributed.initialize(
            coordinator_address=args.jax_coordinator,
            num_processes=args.jax_processes,
            process_id=args.jax_process_id,
        )
        ndev = jax.device_count()
        if config.backend not in ("jax", "tpu-pallas"):
            raise ValueError("--multihost requires --backend jax/tpu-pallas")
        if config.workers != ndev:
            raise ValueError(
                f"--multihost: --workers must equal the global device count "
                f"({ndev}); got {config.workers}. Every process runs the "
                "same SPMD program over the full mesh."
            )

    import contextlib

    profile_ctx = contextlib.nullcontext()
    if config.profile_dir and config.backend in ("jax", "tpu-pallas"):
        # SURVEY.md section 5.1: wrap the dispatch so the marking kernel
        # shows up in Perfetto/XProf
        import jax

        profile_ctx = jax.profiler.trace(config.profile_dir)

    from sieve import metrics, trace

    file_sink = None
    if config.metrics_file:
        file_sink = metrics.FileSink(config.metrics_file)
        metrics.add_sink(file_sink)
    if config.trace_file:
        trace.enable()
    try:
        with profile_ctx:
            return _dispatch(args, config)
    finally:
        if config.trace_file:
            trace.disable()
            trace.save(config.trace_file)
        if file_sink is not None:
            metrics.remove_sink(file_sink)
            file_sink.close()


def _dispatch(args: argparse.Namespace, config: SieveConfig) -> int:
    if args.role == "worker":
        from sieve.cluster import serve_worker

        serve_worker(config)
        return 0

    if config.backend == "cpu-cluster":
        from sieve.cluster import run_cluster

        result = run_cluster(config)
        dropped = (result.host_phases or {}).get("telemetry_dropped_events")
        if dropped:
            print(
                f"sieve: warning: worker telemetry truncated ({dropped} "
                "trace events dropped by the ship ring); the merged "
                "--trace timeline is incomplete — raise "
                "SIEVE_TELEMETRY_RING to keep more events per worker",
                file=sys.stderr,
            )
    elif config.backend in ("jax", "tpu-pallas") and (
        config.workers > 1 or config.rounds > 1
    ):
        # rounds > 1 on a single device is the streaming path (SURVEY.md
        # section 5.7): the mesh runner owns round dispatch either way
        from sieve.parallel.mesh import run_mesh

        result = run_mesh(config)
    else:
        from sieve.coordinator import run_local

        result = run_local(config)

    if config.multihost:
        import jax

        if jax.process_index() != 0:
            return 0  # every process computes the same result; one prints

    if config.json_output:
        out = result.to_dict()
        out.pop("segments", None)
        print(json.dumps(out))
    else:
        print(f"pi({result.n}) = {result.pi}")
        if result.twin_pairs is not None:
            gap = config.pair_gap or 2
            name = "cousin" if config.count_kind == "cousins" else "twin"
            print(f"{name} pairs (p, p+{gap} <= {result.n}) = "
                  f"{result.twin_pairs}")
        print(
            f"backend={result.backend} packing={result.packing} "
            f"segments={result.n_segments} elapsed={result.elapsed_s:.3f}s "
            f"({result.values_per_sec:.3e} values/s)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
