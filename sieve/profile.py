"""Always-on statistical profiler: sampled stacks, wire-pullable
(ISSUE 20).

The fleet can detect that a shard is slow (the observer's anomaly
engine) and retain the slow request's spans (exemplars), but nothing
answers "where is the CPU actually going?". A :class:`StackProfiler`
runs inside every server and router: a daemon thread samples
``sys._current_frames()`` at a low default rate (``SIEVE_PROF_HZ``,
~19 Hz — deliberately off round scheduler frequencies; 0 disables) and
folds each observed stack into a bounded collapsed-stack table
(stack -> count, drop-coldest on overflow). Each sample is tagged with

* the sampled thread's role — event-loop / worker / writer / sampler,
  derived from the fleet's canonical thread names (the PR 15 role
  classes the lock sanitizer uses), and
* the tracer's active span label for that thread (``sieve/trace.py``
  keeps a per-thread open-span stack), so a flame cell reads
  ``svc-wire ▸ rpc.query ▸ server._execute_batch_cols``.

Idle parks (a worker waiting on its lane condition, the main thread in
``Event.wait``, the selector blocked in ``select``) are skipped by
default — the table answers "where does the CPU go", not "where do
threads sleep" (``include_idle=True`` keeps them, tagged ``idle``).

The table is served inline by the ``profile`` wire op on both serving
tiers (same contract as ``debug``/``metrics`` — a wedged worker pool
still profiles), snapshotted into every FlightRecorder bundle, and
pulled fleet-wide by ``tools/fleet_profile.py`` (merge + top-N
self-time + ``--diff`` share deltas) and by the FleetObserver on
``fleet_anomaly``. The module-level helpers (:func:`merge_stacks`,
:func:`collapse_lines`, :func:`self_times`, :func:`diff_shares`) are
the shared math for those tools and the tests.

Locking: one leaf lock guards the fold table and pause/beat counters;
the sampler holds it only to fold already-extracted stacks (never
while walking frames or enumerating threads), and ``snapshot()`` takes
it briefly to copy — safe inline on the wire event loop.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any

from sieve import trace
from sieve.analysis.lockdebug import named_lock

PROFILE_VERSION = "sieve-profile/1"

# ~19 Hz: low enough to be an always-on tax nobody can measure (the
# <=1.05 bench gate prices it), prime-ish so the beat never locks onto
# 10/20/50/100 Hz schedulers or pollers and samples the same frame
DEFAULT_HZ = 19.0
DEFAULT_STACKS = 512
# frames kept per stack, leaf-most wins: deep recursion must not turn
# one sample into an unbounded collapsed key
MAX_DEPTH = 24

# thread-role classes (PR 15): the canonical thread names across the
# serving plane, mapped to the role the flame's first cell carries
_LOOP_NAMES = ("svc-wire", "router-accept", "router-conn")
_WORKER_MARKS = ("svc-worker",)
_WRITER_MARKS = ("exemplar-writer", "svc-batcher", "svc-follower",
                 "store-compact", "serve-fwd")
_SAMPLER_MARKS = ("prof-sampler", "sieve-observer", "metrics-history")

# leaf frames that mean "parked, not computing": the default profile
# skips these samples entirely (py-spy's --idle model)
_IDLE_LEAVES = frozenset({
    ("threading", "wait"),
    ("threading", "_wait_for_tstate_lock"),
    ("selectors", "select"),
    ("socket", "accept"),
})


def thread_role(name: str) -> str | None:
    """The PR 15 role class of a thread name, or None when unknown.

    ``main`` covers each process's MainThread (parked on the drain
    event in a serving process — visible only with ``include_idle``).
    """
    if any(name.startswith(p) for p in _LOOP_NAMES):
        return "loop"
    if any(m in name for m in _WORKER_MARKS):
        return "worker"
    if any(m in name for m in _WRITER_MARKS):
        return "writer"
    if any(m in name for m in _SAMPLER_MARKS):
        return "sampler"
    if name == "MainThread":
        return "main"
    return None


def thread_label(name: str) -> str:
    """Flame-cell label for a thread: its name with any trailing
    ``-<digits>`` instance suffix stripped, so ``svc-worker-hot-0`` and
    ``svc-worker-hot-3`` fold into one ``svc-worker-hot`` cell."""
    base = name.rstrip("0123456789")
    if base != name and base.endswith("-"):
        return base[:-1]
    return name


def _frame_label(code: Any) -> str:
    """``<module>.<function>`` for one frame's code object."""
    fn = code.co_filename
    base = os.path.basename(fn)
    if base.endswith(".py"):
        base = base[:-3]
    return f"{base}.{code.co_name}"


def _walk_stack(frame: Any) -> list[str]:
    """Root-first frame labels, leaf-most :data:`MAX_DEPTH` kept."""
    labels: list[str] = []  # leaf-first while walking
    while frame is not None and len(labels) < MAX_DEPTH:
        labels.append(_frame_label(frame.f_code))
        frame = frame.f_back
    labels.reverse()
    return labels


class StackProfiler:
    """Sampling profiler daemon + bounded collapsed-stack fold table."""

    def __init__(
        self,
        role: str,
        *,
        hz: float = DEFAULT_HZ,
        max_stacks: int = DEFAULT_STACKS,
        include_idle: bool = False,
    ) -> None:
        if not (isinstance(hz, (int, float)) and not isinstance(hz, bool)
                and hz >= 0):
            raise ValueError(f"profiler hz must be >= 0, got {hz!r}")
        self.role = role
        self.hz = float(hz)
        self.max_stacks = max(1, int(max_stacks))
        self.include_idle = bool(include_idle)
        self._lock = named_lock("StackProfiler._lock")
        self._table: dict[str, list] = {}  # guard: _lock — collapsed
        #   stack key -> [count, role-or-None]
        self._beats = 0        # guard: _lock — sampling iterations run
        self._samples = 0      # guard: _lock — thread samples folded
        self._evicted = 0      # guard: _lock — drop-coldest evictions
        self._paused_beats = 0  # guard: _lock — beats left to skip
        self._pauses = 0       # guard: _lock — pause() calls (chaos)
        self._t0 = time.time()
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None  # guard: _lock

    # --- lifecycle -------------------------------------------------------

    def start(self) -> "StackProfiler":
        """Spawn the sampler daemon. Idempotent; a no-op at ``hz=0``."""
        if self.hz <= 0:
            return self
        with self._lock:
            if self._thread is not None:
                return self
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"prof-sampler-{self.role}",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Retire the sampler daemon. Idempotent; the fold table stays
        readable after stop (bundles freeze it post-drain)."""
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            self._stop_evt.set()
            t.join(timeout=5)

    @property
    def running(self) -> bool:
        with self._lock:
            return self._thread is not None

    def pause(self, beats: int = 1) -> None:
        """Skip the next ``beats`` sampling beats (the ``svc_prof_gap``
        chaos kind rides this: a dropped profile reply plus one silent
        beat, healed by the next pull)."""
        with self._lock:
            self._paused_beats = max(self._paused_beats, int(beats))
            self._pauses += 1

    def _run(self) -> None:
        period = 1.0 / self.hz
        while not self._stop_evt.wait(period):
            with self._lock:
                if self._thread is None:
                    return
                if self._paused_beats > 0:
                    self._paused_beats -= 1
                    continue
            self.sample_once()

    # --- sampling --------------------------------------------------------

    def sample_once(self) -> int:
        """Take one sampling beat across every thread; returns how many
        thread samples folded in. Exposed so tests drive deterministic
        beats without a live daemon."""
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        folded: list[tuple[str, str | None]] = []
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the profiler never profiles its own beat
            code = frame.f_code
            idle = (os.path.splitext(os.path.basename(code.co_filename))[0],
                    code.co_name) in _IDLE_LEAVES
            if idle and not self.include_idle:
                continue
            name = names.get(tid) or f"tid-{tid}"
            role = thread_role(name)
            span = trace.active_label(tid)
            cells = [thread_label(name)]
            if span:
                cells.append(span)
            if idle:
                cells.append("idle")
            cells.extend(_walk_stack(frame))
            folded.append((";".join(cells), role))
        with self._lock:
            self._beats += 1
            for key, role in folded:
                ent = self._table.get(key)
                if ent is not None:
                    ent[0] += 1
                else:
                    if len(self._table) >= self.max_stacks:
                        self._evict_coldest_locked()
                    self._table[key] = [1, role]
                self._samples += 1
        return len(folded)

    def _evict_coldest_locked(self) -> None:  # holds: _lock
        # O(table) scan, but only on overflow of a table bounded at
        # max_stacks — at 19 Hz this is noise
        coldest = min(self._table, key=lambda k: self._table[k][0])
        del self._table[coldest]
        self._evicted += 1

    # --- reads -----------------------------------------------------------

    def snapshot(self) -> dict:
        """One JSON-able profile document (the ``profile`` wire op and
        the FlightRecorder bundle embed call this inline)."""
        with self._lock:
            stacks = [
                {"stack": k, "count": v[0], "role": v[1]}
                for k, v in self._table.items()
            ]
            beats, samples = self._beats, self._samples
            evicted, pauses = self._evicted, self._pauses
        stacks.sort(key=lambda r: (-r["count"], r["stack"]))
        return {
            "profile": PROFILE_VERSION,
            "role": self.role,
            "hz": self.hz,
            "pid": os.getpid(),
            "ts": round(time.time() - self._t0, 3),
            "beats": beats,
            "samples": samples,
            "evicted": evicted,
            "pauses": pauses,
            "stacks": stacks,
        }

    def stats(self) -> dict:
        with self._lock:
            return {
                "beats": self._beats,
                "samples": self._samples,
                "stacks": len(self._table),
                "evicted": self._evicted,
                "pauses": self._pauses,
                "running": self._thread is not None,
            }


# --- fleet merge / report math (fleet_profile, fleet_top, tests) -----------


def merge_stacks(profiles: list[tuple[str, dict]]) -> dict[str, dict]:
    """Merge per-process profile documents into one table.

    ``profiles`` is ``[(process_label, snapshot_doc), ...]``; each
    stack key is prefixed with its process label so the merged flame
    keeps one cell per process. Returns ``key -> {"count", "role"}``.
    """
    out: dict[str, dict] = {}
    for label, doc in profiles:
        for row in (doc or {}).get("stacks") or []:
            key = f"{label};{row['stack']}"
            ent = out.get(key)
            if ent is None:
                out[key] = {"count": int(row["count"]),
                            "role": row.get("role")}
            else:
                ent["count"] += int(row["count"])
    return out


def collapse_lines(merged: dict[str, dict]) -> list[str]:
    """Flamegraph-compatible collapsed lines (``stack count``), hottest
    first — ``flamegraph.pl`` / speedscope load the joined text."""
    rows = sorted(merged.items(), key=lambda kv: (-kv[1]["count"], kv[0]))
    return [f"{k} {v['count']}" for k, v in rows]


def self_times(merged: dict[str, dict], n: int = 0) -> list[dict]:
    """Per-frame SELF-time table from a merged (or single) stack table.

    A frame's self count is the samples where it was the LEAF — time
    spent in the frame itself, not in callees. Rows carry the frame's
    share of all samples; ``n`` > 0 keeps the top n."""
    self_counts: dict[str, int] = {}
    total = 0
    for key, ent in merged.items():
        leaf = key.rsplit(";", 1)[-1]
        self_counts[leaf] = self_counts.get(leaf, 0) + ent["count"]
        total += ent["count"]
    rows = [
        {"frame": f, "self": c,
         "share": (c / total) if total else 0.0}
        for f, c in self_counts.items()
    ]
    rows.sort(key=lambda r: (-r["self"], r["frame"]))
    return rows[:n] if n > 0 else rows


def diff_shares(old: dict[str, dict], new: dict[str, dict],
                n: int = 0) -> list[dict]:
    """Per-frame self-time SHARE deltas between two captures.

    Shares (not raw counts) so captures of different lengths compare;
    positive delta = the frame got hotter. Sorted most-positive first;
    ``n`` > 0 keeps the top n by absolute delta."""
    a = {r["frame"]: r["share"] for r in self_times(old)}
    b = {r["frame"]: r["share"] for r in self_times(new)}
    rows = [
        {"frame": f, "before": a.get(f, 0.0), "after": b.get(f, 0.0),
         "delta": b.get(f, 0.0) - a.get(f, 0.0)}
        for f in set(a) | set(b)
    ]
    rows.sort(key=lambda r: (-r["delta"], r["frame"]))
    if n > 0:
        rows = sorted(rows, key=lambda r: -abs(r["delta"]))[:n]
        rows.sort(key=lambda r: (-r["delta"], r["frame"]))
    return rows


def role_tagged_fraction(merged: dict[str, dict]) -> float:
    """Fraction of merged samples whose thread carried a known role tag
    (the acceptance bar: >= 0.9 on a loaded fleet)."""
    total = tagged = 0
    for ent in merged.values():
        total += ent["count"]
        if ent.get("role"):
            tagged += ent["count"]
    return (tagged / total) if total else 0.0


__all__ = [
    "DEFAULT_HZ",
    "DEFAULT_STACKS",
    "PROFILE_VERSION",
    "StackProfiler",
    "collapse_lines",
    "diff_shares",
    "merge_stacks",
    "role_tagged_fraction",
    "self_times",
    "thread_label",
    "thread_role",
]
