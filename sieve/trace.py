"""Span-based tracing: every phase of the sieve, visible.

One process-wide :class:`Tracer` times named spans on any thread::

    from sieve import trace
    with trace.span("round.prep_wait", round=k):
        preps = pipeline.take(k)

Two cost tiers, by design:

* **Aggregation is always on.** Every span's duration folds into a
  ``name -> (total_seconds, count)`` table under a lock — a pair of
  ``perf_counter`` calls and a dict update, well under 2 us per span.
  This is what lets ``run_mesh`` derive ``host_phases`` from spans
  instead of hand-rolled bookkeeping, with or without ``--trace``.
* **Event capture is opt-in** (``trace.enable()`` / ``--trace FILE``).
  Only then does each span also append a Chrome trace-event record
  (complete "X" event with microsecond ``ts``/``dur``, real ``tid`` so
  pipeline producer threads and the mesh loop land on separate tracks).
  ``trace.save(path)`` writes ``{"traceEvents": [...]}`` — loadable in
  Perfetto / ``chrome://tracing`` directly.

All timestamps come from ``time.perf_counter()`` relative to one
process-wide epoch, so span times, instant events, counter samples, and
MetricsLogger ``ts`` fields are mutually comparable (no wall-clock /
monotonic mixing).

Per-run accounting over the process-wide tracer uses snapshot diffs::

    snap = trace.snapshot()
    ...           # run spans on any number of threads
    agg = trace.since(snap)   # {name: (delta_seconds, delta_count)}

Distributed runs (sieve/cluster.py) extend this to one timeline per
*cluster*: each worker process captures its own spans into a bounded
drop-oldest ring (:meth:`Tracer.set_event_limit` /
:meth:`Tracer.drain_events`), ships them on its RPC replies, and the
coordinator rebases the timestamps onto its own epoch (clock offsets are
estimated NTP-style from the RPC legs) before folding them back in with
:meth:`Tracer.ingest` — so a single ``--trace`` file carries coordinator
+ per-worker tracks.
"""

from __future__ import annotations

import collections
import json
import os
import threading

from sieve.analysis.lockdebug import named_lock
import time
from typing import Any, TextIO

# One monotonic epoch for the whole process: spans, counters, instants
# and metrics timestamps all subtract this, so they share one timeline.
_EPOCH = time.perf_counter()


def now_s() -> float:
    """Seconds since the process trace epoch (monotonic)."""
    return time.perf_counter() - _EPOCH


# Per-thread open-span stacks (ISSUE 20): the continuous profiler tags
# each sampled thread with its innermost active span label. Keyed by
# thread ident; each thread only ever mutates ITS OWN list, and every
# operation is a single GIL-atomic dict/list op, so neither the span
# hot path nor the sampler's cross-thread read takes a lock. Entries
# for finished threads linger (bounded by peak thread count) — idents
# are reused, so a successor thread simply adopts the empty list.
_ACTIVE_SPANS: dict[int, list] = {}


def active_label(tid: int | None = None) -> str | None:
    """The innermost open span name on thread ``tid`` (calling thread
    when None), or None when no span is open. Safe from any thread: a
    race with the owner's push/pop yields a momentarily-stale label,
    never a crash."""
    stack = _ACTIVE_SPANS.get(
        tid if tid is not None else threading.get_ident()
    )
    if not stack:
        return None
    try:
        return stack[-1]
    except IndexError:
        return None  # owner popped between the check and the read


def _ex_root(ctx: str) -> str:
    """The root request context of a span ctx: ``run_id/seq.attempt``,
    i.e. the first two ``/``-separated components. Child contexts append
    ``/s<shard>.<call>`` / per-attempt suffixes, so every span of one
    request tree shares this root — the exemplar ring's bucket key."""
    first = ctx.find("/")
    if first < 0:
        return ctx
    second = ctx.find("/", first + 1)
    return ctx if second < 0 else ctx[:second]


class Span:
    """Context manager for one timed span.

    ``elapsed`` (seconds) is valid after ``__exit__`` so callers that
    also need the measurement (e.g. per-mode device timers) read it
    from the span instead of timing twice.
    """

    __slots__ = ("_tracer", "name", "args", "t0", "elapsed")

    def __init__(self, tracer: "Tracer", name: str, args: dict | None):
        self._tracer = tracer
        self.name = name
        self.args = args
        self.elapsed = 0.0

    def __enter__(self) -> "Span":
        # push onto this thread's open-span stack (profiler tag, ISSUE
        # 20): single-dict-op per direction, no lock — see _ACTIVE_SPANS
        tid = threading.get_ident()
        stack = _ACTIVE_SPANS.get(tid)
        if stack is None:
            stack = _ACTIVE_SPANS[tid] = []
        stack.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        self.elapsed = t1 - self.t0
        stack = _ACTIVE_SPANS.get(threading.get_ident())
        if stack:
            stack.pop()
        self._tracer._record(
            self.name, self.t0 - _EPOCH, t1 - _EPOCH, self.args
        )
        return False


class Tracer:
    """Thread-safe span tracer with always-on aggregation and optional
    Chrome trace-event capture."""

    def __init__(self) -> None:
        self._lock = named_lock("Tracer._lock")
        self.enabled = False
        self._events: list[dict] = []
        self._totals: dict[str, list] = {}  # name -> [total_s, count]
        self._tids_named: set[int] = set()
        # bounded capture (telemetry shipping): keep at most this many
        # events, dropping the oldest non-metadata event on overflow
        self._max_events: int | None = None
        self._dropped = 0
        # counter tracks are SAMPLED, not transition-logged: at most one
        # event per track per interval. Metric mirrors fire on every
        # inc()/set() — several per request on a serving hot path — and
        # unthrottled they dominate both the ring and the traced-request
        # latency (the 5% fleet-tracing budget). Perfetto renders a
        # counter track identically from periodic samples.
        self._counter_interval_us = 10_000.0
        self._counter_seen: dict[str, float] = {}
        # exemplar capture (ISSUE 19): a SECOND bounded ring holding only
        # request-scoped spans (args carry a ``ctx``), fed regardless of
        # ``enabled`` — tail sampling must see every request's spans
        # without turning on full event capture (which would also arm
        # telemetry piggybacks and --trace side effects). 0 disables.
        # Spans are bucketed by their root request context so a keep's
        # :meth:`exemplar_collect` touches one request's spans, not the
        # whole ring — collection runs on the request's critical path
        # and must stay O(request), not O(ring). Eviction drops whole
        # oldest-request buckets (a request's tree lives and dies
        # together).
        self._ex_limit = 0  # guard: _lock
        self._ex_spans: collections.OrderedDict[str, list[tuple]] = \
            collections.OrderedDict()  # guard: _lock — root ctx ->
        #   [(name, t0, t1, tid, ctx, arg_pairs), ...] raw span tuples
        self._ex_count = 0  # guard: _lock
        self._ex_dropped = 0  # guard: _lock

    # --- recording -----------------------------------------------------------

    def span(self, name: str, **args: Any) -> Span:
        return Span(self, name, args or None)

    def _record(
        self, name: str, t0: float, t1: float, args: dict | None
    ) -> None:
        # t0/t1 are epoch-relative seconds (the ``now_s()`` clock)
        with self._lock:
            tot = self._totals.get(name)
            if tot is None:
                tot = self._totals[name] = [0.0, 0]
            tot[0] += t1 - t0
            tot[1] += 1
            if self.enabled:
                self._append_event(name, t0, t1, args)
            if self._ex_limit and args and "ctx" in args:
                root = _ex_root(str(args["ctx"]))
                bucket = self._ex_spans.get(root)
                if bucket is None:
                    bucket = self._ex_spans[root] = []
                # raw tuple, not the trace-event dict: this branch runs
                # on every ctx-carrying span of every served request, so
                # the dict literal + round()s are deferred to the rare
                # collect. args is flattened to a tuple of pairs so the
                # whole entry is atomic-only — CPython untracks such
                # tuples at the first GC scan, which keeps a full 2048-
                # entry ring from turning every young-gen collection
                # into a scan of the ring's churn (measured as a ~25%
                # sequential-QPS hit when entries held live dicts).
                bucket.append((
                    name, t0, t1, threading.get_ident(),
                    str(args["ctx"]), tuple(args.items()),
                ))
                self._ex_count += 1
                self._ex_trim_locked()

    def add_span(
        self, name: str, t0: float, duration_s: float, **args: Any
    ) -> None:
        """Record an already-measured interval (``t0`` is epoch-relative,
        i.e. a :func:`now_s` value) — for synthetic spans like device-idle
        windows whose bounds were observed rather than entered/exited."""
        self._record(name, t0, t0 + duration_s, args or None)

    def instant(self, name: str, **args: Any) -> None:
        """Zero-duration marker (heartbeats, resume points)."""
        if not self.enabled:
            return
        with self._lock:
            if not self.enabled:
                return
            self._events.append(
                {
                    "name": name,
                    "ph": "i",
                    "s": "t",
                    "ts": round((time.perf_counter() - _EPOCH) * 1e6, 3),
                    "pid": os.getpid(),
                    "tid": threading.get_ident(),
                    **({"args": args} if args else {}),
                }
            )
            self._trim()

    def counter(self, name: str, value: float) -> None:
        """Sample a counter/gauge value onto the trace timeline.

        Throttled per track: samples landing within the counter
        interval of the previous admitted one are dropped (the first
        sample of a track always lands)."""
        if not self.enabled:
            return
        # lock-free throttle fast path: dict reads are GIL-atomic, and
        # the worst race outcome is one extra sample in an interval —
        # harmless, while skipping the lock (and the round below) keeps
        # the per-inc() cost off the serving hot path
        ts = (time.perf_counter() - _EPOCH) * 1e6
        last = self._counter_seen.get(name)
        if last is not None and ts - last < self._counter_interval_us:
            return
        ts = round(ts, 3)
        with self._lock:
            if not self.enabled:
                return
            self._counter_seen[name] = ts
            self._events.append(
                {
                    "name": name,
                    "ph": "C",
                    "ts": ts,
                    "pid": os.getpid(),
                    "tid": threading.get_ident(),
                    "args": {"value": value},
                }
            )
            self._trim()

    def _append_event(
        self, name: str, t0: float, t1: float, args: dict | None
    ) -> None:
        # caller holds the lock
        tid = threading.get_ident()
        if tid not in self._tids_named:
            self._tids_named.add(tid)
            self._events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": os.getpid(),
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                }
            )
        ev = {
            "name": name,
            "ph": "X",
            "ts": round(t0 * 1e6, 3),
            "dur": round((t1 - t0) * 1e6, 3),
            "pid": os.getpid(),
            "tid": tid,
        }
        if args:
            ev["args"] = args
        self._events.append(ev)
        self._trim()

    def _trim(self) -> None:
        # caller holds the lock; metadata ("M") events are never evicted —
        # they name the tracks every surviving event still needs
        if self._max_events is None:
            return
        while len(self._events) > self._max_events:
            for i, e in enumerate(self._events):
                if e.get("ph") != "M":
                    del self._events[i]
                    self._dropped += 1
                    break
            else:
                return  # only metadata left; nothing evictable

    # --- control / export ----------------------------------------------------

    def enable(self, clear: bool = True) -> None:
        """Start capturing events. By default the event buffer is
        cleared so each capture session (one ``--trace`` run) stands
        alone; aggregation totals are never cleared here."""
        with self._lock:
            if clear:
                self._events.clear()
                self._tids_named.clear()
                self._counter_seen.clear()
                self._dropped = 0
            self.enabled = True

    def disable(self) -> None:
        with self._lock:
            self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._totals.clear()
            self._tids_named.clear()
            self._counter_seen.clear()
            self._dropped = 0
            self._ex_spans.clear()
            self._ex_count = 0
            self._ex_dropped = 0

    def set_event_limit(self, max_events: int | None) -> None:
        """Bound the capture buffer to ``max_events`` (None = unbounded).

        On overflow the oldest non-metadata event is dropped and counted
        in :attr:`dropped` — worker processes run with a bounded ring so
        telemetry payloads shipped over the cluster RPC stay small."""
        with self._lock:
            self._max_events = max_events
            self._trim()

    @property
    def dropped(self) -> int:
        """Events evicted by the ring limit since the last fresh enable."""
        with self._lock:
            return self._dropped

    def pending(self) -> int:
        """Captured events not yet drained — lets a telemetry shipper
        batch payloads (only piggyback once enough accumulated) instead
        of paying a serialize on every reply."""
        with self._lock:
            return len(self._events)

    def drain_events(self) -> tuple[list[dict], int]:
        """Take (and clear) the captured events; returns ``(events,
        cumulative_dropped)``. Thread-name bookkeeping is kept so a later
        drain does not re-emit metadata already shipped — the consumer is
        expected to accumulate successive drains in order."""
        with self._lock:
            events, self._events = self._events, []
            return events, self._dropped

    def ingest(self, events: list[dict]) -> None:
        """Merge foreign, already-rebased events (a worker's shipped
        telemetry) into this tracer: complete-span durations fold into
        the aggregate totals, and the raw events join the capture buffer
        when capture is on. The ring limit is not applied here — merged
        cluster traces are bounded by each worker's ship ring instead."""
        with self._lock:
            for e in events:
                if e.get("ph") == "X":
                    tot = self._totals.get(e["name"])
                    if tot is None:
                        tot = self._totals[e["name"]] = [0.0, 0]
                    tot[0] += e.get("dur", 0.0) / 1e6
                    tot[1] += 1
                if self.enabled:
                    self._events.append(e)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def tail(self, n: int) -> list[dict]:
        """The newest ``n`` captured events without draining the ring —
        the flight recorder's span tail (ISSUE 13). Empty when capture
        is off or ``n`` <= 0."""
        if n <= 0:
            return []
        with self._lock:
            return self._events[-n:]

    # --- exemplar capture (ISSUE 19) -------------------------------------

    def _ex_trim_locked(self) -> None:
        while self._ex_count > self._ex_limit:
            root = next(iter(self._ex_spans))
            bucket = self._ex_spans[root]
            if len(self._ex_spans) == 1:
                # one giant request owns the whole ring: age its oldest
                # spans individually instead of dropping its live tree
                n_drop = self._ex_count - self._ex_limit
                del bucket[:n_drop]
                self._ex_count -= n_drop
                self._ex_dropped += n_drop
                return
            del self._ex_spans[root]
            self._ex_count -= len(bucket)
            self._ex_dropped += len(bucket)

    def exemplar_enable(self, limit: int) -> None:
        """Arm the exemplar ring: keep the newest ``limit`` ctx-carrying
        spans for tail sampling. Independent of :meth:`enable` — full
        event capture stays off. ``limit <= 0`` disarms and clears."""
        with self._lock:
            self._ex_limit = max(0, limit)
            if self._ex_limit == 0:
                self._ex_spans.clear()
                self._ex_count = 0
            else:
                self._ex_trim_locked()

    def exemplar_disable(self) -> None:
        self.exemplar_enable(0)

    def exemplar_collect(self, ctx_prefix: str | None = None) -> list[dict]:
        """Spans in the exemplar ring whose ``args.ctx`` starts with
        ``ctx_prefix`` (all of them when None), oldest request first.
        Non-draining: a request's spans stay visible to a later
        ``exemplars`` wire pull until the ring ages them out. Runs on
        the keep path, so only the candidate request buckets are
        scanned — the prefix narrows to one root for the full request
        contexts the samplers pass."""
        pid = os.getpid()

        def mat(e: tuple) -> dict:
            name, t0, t1, tid, _ctx, pairs = e
            return {
                "name": name,
                "ph": "X",
                "ts": round(t0 * 1e6, 3),
                "dur": round((t1 - t0) * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "args": dict(pairs),
            }

        with self._lock:
            if ctx_prefix is None:
                return [mat(e) for bucket in self._ex_spans.values()
                        for e in bucket]
            if "/" in ctx_prefix:
                # a full request ctx (or deeper): every matching span's
                # root IS _ex_root(ctx_prefix), so the whole collect is
                # one dict lookup — this is the keep-path shape, and it
                # must stay O(one request), not O(ring)
                bucket = self._ex_spans.get(_ex_root(ctx_prefix))
                if not bucket:
                    return []
                return [
                    mat(e) for e in bucket
                    if e[4].startswith(ctx_prefix)
                ]
            # a bare run-id prefix can span many request buckets: scan
            out: list[dict] = []
            for root, bucket in self._ex_spans.items():
                if not root.startswith(ctx_prefix):
                    continue
                out.extend(
                    mat(e) for e in bucket
                    if e[4].startswith(ctx_prefix)
                )
            return out

    @property
    def exemplar_dropped(self) -> int:
        with self._lock:
            return self._ex_dropped

    def save(self, path_or_file: str | TextIO) -> None:
        """Write the captured events as Chrome trace-event JSON."""
        doc = {"traceEvents": self.events(), "displayTimeUnit": "ms"}
        if hasattr(path_or_file, "write"):
            json.dump(doc, path_or_file)
        else:
            with open(path_or_file, "w") as f:
                json.dump(doc, f)

    # --- aggregation ---------------------------------------------------------

    def snapshot(self) -> dict[str, tuple[float, int]]:
        """Copy of the (total_seconds, count) aggregate per span name."""
        with self._lock:
            return {k: (v[0], v[1]) for k, v in self._totals.items()}

    def since(
        self, snap: dict[str, tuple[float, int]]
    ) -> dict[str, tuple[float, int]]:
        """Aggregate delta since a :meth:`snapshot` (per-run accounting
        over the process-wide tracer)."""
        out: dict[str, tuple[float, int]] = {}
        for name, (tot, cnt) in self.snapshot().items():
            b_tot, b_cnt = snap.get(name, (0.0, 0))
            if cnt > b_cnt:
                out[name] = (tot - b_tot, cnt - b_cnt)
        return out

    def total_s(
        self, name: str, snap: dict[str, tuple[float, int]] | None = None
    ) -> float:
        agg = self.since(snap) if snap is not None else self.snapshot()
        return agg.get(name, (0.0, 0))[0]


class ClockAlign:
    """NTP-style clock alignment against one remote process.

    Each RPC exchange yields four timestamps: local send, remote
    receive, remote send, local done (all on their own process trace
    epochs). The sample with the smallest round-trip gives the best
    offset estimate; the error bound is half that minimal RTT.

    ``remote_clock ~= local_clock + offset_s``, so rebasing a remote
    event onto the local timeline subtracts ``offset_s``.
    """

    __slots__ = ("offset_s", "rtt_s", "samples")

    def __init__(self) -> None:
        self.offset_s = 0.0
        self.rtt_s = float("inf")
        self.samples = 0

    def sample(
        self,
        t_send: float,
        t_remote_recv: float,
        t_remote_send: float,
        t_done: float,
    ) -> None:
        rtt = max(0.0, (t_done - t_send) - (t_remote_send - t_remote_recv))
        self.samples += 1
        # ties refresh to the newest sample so the estimate tracks drift
        if rtt <= self.rtt_s:
            self.rtt_s = rtt
            self.offset_s = (
                (t_remote_recv - t_send) + (t_remote_send - t_done)
            ) / 2

    @property
    def err_s(self) -> float:
        """Worst-case offset error: half the best round-trip seen."""
        return self.rtt_s / 2 if self.samples else float("inf")


# Process-wide tracer and module-level conveniences (the instrumented
# call sites all go through these).
_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def span(name: str, **args: Any) -> Span:
    return _TRACER.span(name, **args)


def add_span(name: str, t0: float, duration_s: float, **args: Any) -> None:
    _TRACER.add_span(name, t0, duration_s, **args)


def instant(name: str, **args: Any) -> None:
    _TRACER.instant(name, **args)


def counter(name: str, value: float) -> None:
    _TRACER.counter(name, value)


def enable() -> None:
    _TRACER.enable()


def disable() -> None:
    _TRACER.disable()


def enabled() -> bool:
    return _TRACER.enabled


def save(path_or_file: str | TextIO) -> None:
    _TRACER.save(path_or_file)


def set_event_limit(max_events: int | None) -> None:
    _TRACER.set_event_limit(max_events)


def drain_events() -> tuple[list[dict], int]:
    return _TRACER.drain_events()


def pending_events() -> int:
    return _TRACER.pending()


def ingest(events: list[dict]) -> None:
    _TRACER.ingest(events)


def tail(n: int) -> list[dict]:
    return _TRACER.tail(n)


def exemplar_enable(limit: int) -> None:
    _TRACER.exemplar_enable(limit)


def exemplar_collect(ctx_prefix: str | None = None) -> list[dict]:
    return _TRACER.exemplar_collect(ctx_prefix)


def snapshot() -> dict[str, tuple[float, int]]:
    return _TRACER.snapshot()


def since(snap: dict[str, tuple[float, int]]) -> dict[str, tuple[float, int]]:
    return _TRACER.since(snap)


def total_s(
    name: str, snap: dict[str, tuple[float, int]] | None = None
) -> float:
    return _TRACER.total_s(name, snap)
