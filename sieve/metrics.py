"""Metrics / structured logging (SURVEY.md section 5.5).

One JSON line per segment (id, owner, lo, hi, ms, count) plus an end-of-run
summary carrying the north-star metric, primes/sec/chip. ``--quiet``
suppresses per-segment lines; ``--json`` makes the final result a single
machine-readable line.
"""

from __future__ import annotations

import json
import sys
import time
from typing import TYPE_CHECKING, Any, TextIO

if TYPE_CHECKING:
    from sieve.config import SieveConfig
    from sieve.coordinator import SieveResult
    from sieve.worker import SegmentResult


class MetricsLogger:
    def __init__(self, config: "SieveConfig", stream: TextIO | None = None):
        self.config = config
        self.stream = stream if stream is not None else sys.stderr
        self.t_start = time.time()

    def _emit(self, record: dict[str, Any]) -> None:
        if self.config.quiet:
            return
        record.setdefault("ts", round(time.time() - self.t_start, 4))
        self.stream.write(json.dumps(record) + "\n")
        self.stream.flush()

    def event(self, kind: str, **fields: Any) -> None:
        self._emit({"event": kind, **fields})

    def segment(self, res: "SegmentResult") -> None:
        self._emit(
            {
                "event": "segment",
                "id": res.seg_id,
                "lo": res.lo,
                "hi": res.hi,
                "ms": round(res.elapsed_s * 1000, 3),
                "count": res.count,
            }
        )

    def run_summary(self, result: "SieveResult") -> None:
        chips = max(1, self.config.workers)
        record = {
            "event": "run",
            "n": result.n,
            "pi": result.pi,
            "twins": result.twin_pairs,
            "backend": result.backend,
            "packing": result.packing,
            "elapsed_s": round(result.elapsed_s, 4),
            "values_per_sec": round(result.values_per_sec, 1),
            "primes_per_sec_per_chip": round(result.pi / result.elapsed_s / chips, 1)
            if result.elapsed_s > 0
            else None,
        }
        kind = getattr(self.config, "count_kind", "primes")
        if kind not in (None, "primes", "twins"):
            record["count_kind"] = kind
        phases = getattr(result, "host_phases", None)
        if phases:
            # host-prepare pipeline health alongside the headline rate
            for key in (
                "prep_s",
                "prep_values_per_sec",
                "device_idle_frac",
                "overlap_efficiency",
                "reduction_mode",
                "postlude_fused_s",
                "postlude_split_s",
            ):
                if key in phases:
                    record[key] = phases[key]
        self._emit(record)
