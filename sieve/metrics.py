"""Metrics: a registry of counters/gauges/histograms + pluggable sinks.

Two layers (SURVEY.md section 5.5, reworked):

* :class:`MetricsRegistry` — named instruments (:class:`Counter`,
  :class:`Gauge`, :class:`Histogram`) updated from anywhere in the
  stack (cluster heartbeats, straggler watches, segment timings).
  ``registry().snapshot()`` returns plain JSON-able values.
* Event sinks — every structured event record (one JSON object per
  event) is fanned out to the emitting logger's own stream (stderr by
  default, as before) **and** to every globally registered sink:
  ``--metrics-file`` installs a :class:`FileSink` (JSONL), tests
  install a :class:`MemorySink`.

Event schema: every record carries ``event`` (the kind) and ``ts``
(seconds since the process trace epoch — ``time.perf_counter`` based,
monotonic, directly comparable with span times in a ``--trace`` file).
Required per-kind keys are documented in :data:`EVENT_SCHEMA` and
enforced by tests through the in-memory sink.

``--quiet`` drops only the per-segment console lines; the run summary
and robustness events (``worker_failed``, ``segment_error``,
``reassign``, ``resume``) always reach the console stream, and global
sinks receive *every* record regardless of quiet.
"""

from __future__ import annotations

import json
import sys
import threading
from typing import TYPE_CHECKING, Any, TextIO

from sieve import trace

if TYPE_CHECKING:
    from sieve.config import SieveConfig
    from sieve.coordinator import SieveResult
    from sieve.worker import SegmentResult

# Required keys per event kind ("event" and "ts" are implicit on every
# record). Kinds may carry extra keys; these are the stable contract.
# Cluster robustness events carry the run id and the per-attempt trace
# context ("ctx", None when the failure wasn't mid-assignment) so
# tools/trace_report.py can pin them onto the merged timeline;
# "worker_telemetry" records each worker's shipped span batch (plus
# clock offset/err when an alignment sample exists).
EVENT_SCHEMA: dict[str, set[str]] = {
    "segment": {"id", "lo", "hi", "ms", "count"},
    "run": {"n", "pi", "backend", "packing", "elapsed_s", "values_per_sec"},
    "resume": {"restored"},
    "worker_failed": {"worker", "reason", "run_id", "ctx"},
    "segment_error": {"reason", "run_id", "ctx"},
    "reassign": {"seg_id", "run_id", "ctx"},
    "host_prepare": {"prep_s"},
    "worker_telemetry": {"worker", "events", "dropped"},
    # elastic membership + adaptive deadlines + ledger salvage (ISSUE 6);
    # "active" is the live worker count after the join/leave
    "worker_joined": {"worker", "run_id", "active"},
    "worker_left": {"worker", "reason", "run_id", "active"},
    "deadline_adjusted": {"deadline_s", "prev_s", "p95_s", "run_id"},
    "ledger_salvaged": {"salvaged", "quarantined"},
    # query service (ISSUE 7): one service_request per admitted request
    # ("outcome" is ok/deadline_exceeded/degraded/bad_request/internal,
    # "source" index/cold/mixed/none); shed requests get service_shed
    # instead (never both). service_coalesced marks a follower joining a
    # leader's in-flight cold range; service_degraded marks health
    # transitions (entering=True/False).
    "service_request": {"op", "outcome", "source", "ms"},
    "service_shed": {"op", "queue_depth"},
    "service_coalesced": {"op", "lo", "hi"},
    "service_degraded": {"entering", "reason"},
    # replication plane (ISSUE 8): service_refreshed marks each live
    # snapshot swap (covered_hi is monotonic per process);
    # service_refresh_failed a skipped refresh (corrupt / mid-quarantine
    # / regressing read); service_drain the flip to draining;
    # service_chaos_refused a wire chaos injection denied by the
    # --allow-chaos gate; ledger_unverified a checksum-less v1 read-only
    # open (loads, but never silently).
    "service_refreshed": {"covered_hi", "prev_covered_hi", "segments",
                          "refreshes"},
    "service_refresh_failed": {"reason"},
    "service_drain": {"queued", "inflight"},
    "service_chaos_refused": {"spec"},
    "ledger_unverified": {"path"},
    # batched cold plane (ISSUE 9): one service_batched per backend
    # dispatch — "chunks" is the batch size (also observed by the
    # service.batch_chunks histogram), "persisted" how many results were
    # written back to the ledger (0 unless --persist-cold), "failed" how
    # many chunks were chaos-failed out of the batch pre-dispatch.
    "service_batched": {"chunks", "lo", "hi", "ms", "persisted", "failed"},
    # priority lanes (ISSUE 10): service_lane_shed marks a per-lane
    # admission refusal (queue_depth is THAT lane's depth; a lane shed
    # also emits the lane-less service_shed for continuity);
    # service_demoted marks a misclassified hot request re-enqueued on
    # the cold lane ("chunks" = how many chunks needed a dispatch).
    "service_lane_shed": {"op", "lane", "queue_depth"},
    "service_demoted": {"op", "chunks"},
    # router fabric (ISSUE 11): one router_request per routed query
    # ("shards" = how many shards the scatter touched; point routes say
    # 1); router_shard_down marks a shard held unreachable (chaos window
    # or exhausted replicas — "reason" says which); router_spliced marks
    # a cross-shard pair stitch at a shard edge ("pair_kind" twins /
    # cousins, "pairs" = pairs crossing that edge). router_drain and
    # router_chaos_refused mirror their service_ counterparts.
    "router_request": {"op", "outcome", "shards", "ms"},
    "router_shard_down": {"shard", "reason"},
    "router_spliced": {"edge", "pair_kind", "pairs"},
    "router_drain": {"inflight"},
    "router_chaos_refused": {"spec"},
    # fleet trace/telemetry plane (ISSUE 12): service_trace_drop marks a
    # reply whose piggybacked telemetry was chaos-dropped (query result
    # still exact); router_trace_gap the router-side degradation for a
    # reply that should have carried telemetry but didn't ("reason"
    # dropped/malformed); router_telemetry one merged shard-replica span
    # batch (rebased onto the router timeline — the service analogue of
    # worker_telemetry); service_slo_burn the transition of one op's
    # rolling p95 above its configured SLO.
    "service_trace_drop": {"op"},
    "router_trace_gap": {"shard", "reason"},
    "router_telemetry": {"shard", "replica", "events", "dropped"},
    "service_slo_burn": {"op", "p95_ms", "slo_ms", "window"},
}


def validate_record(record: dict[str, Any]) -> None:
    """Raise ValueError if a record violates the documented schema."""
    kind = record.get("event")
    if not isinstance(kind, str):
        raise ValueError(f"record missing 'event' kind: {record!r}")
    if "ts" not in record:
        raise ValueError(f"record missing 'ts': {record!r}")
    required = EVENT_SCHEMA.get(kind, set())
    missing = required - record.keys()
    if missing:
        raise ValueError(f"{kind!r} record missing keys {sorted(missing)}")
    json.dumps(record)  # every value must be JSON-serializable


# --- instruments -------------------------------------------------------------


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n
        trace.counter(self.name, self.value)

    def snapshot(self) -> dict[str, Any]:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-observed value (heartbeat age, straggler lag, queue depth)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value: float | None = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v
        trace.counter(self.name, v)

    def max(self, v: float) -> None:
        """Keep the running maximum (straggler watermarks)."""
        with self._lock:
            if self.value is None or v > self.value:
                self.value = v

    def snapshot(self) -> dict[str, Any]:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Streaming summary: count/sum/min/max (no buckets — the sieve's
    distributions are summarized, full timelines belong in ``--trace``)."""

    __slots__ = ("name", "count", "sum", "min", "max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    def snapshot(self) -> dict[str, Any]:
        return {
            "type": "histogram",
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.sum / self.count if self.count else None,
        }


class MetricsRegistry:
    """Named instruments; one process-wide instance by default."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: dict[str, Any] = {}

    def _get(self, name: str, cls):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {k: v.snapshot() for k, v in self._instruments.items()}


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


# --- sinks -------------------------------------------------------------------


class MemorySink:
    """Collects records in memory — the test/inspection sink."""

    def __init__(self) -> None:
        self.records: list[dict] = []
        self._lock = threading.Lock()

    def emit(self, record: dict) -> None:
        with self._lock:
            self.records.append(record)

    def close(self) -> None:
        pass


class StreamSink:
    """JSONL onto an open text stream."""

    def __init__(self, stream: TextIO):
        self.stream = stream
        self._lock = threading.Lock()

    def emit(self, record: dict) -> None:
        with self._lock:
            self.stream.write(json.dumps(record) + "\n")
            self.stream.flush()

    def close(self) -> None:
        pass


class FileSink(StreamSink):
    """JSONL appended to a file (``--metrics-file``)."""

    def __init__(self, path: str):
        super().__init__(open(path, "a"))

    def close(self) -> None:
        self.stream.close()


_SINKS: list = []
_SINKS_LOCK = threading.Lock()


def add_sink(sink) -> None:
    """Register a global sink; every MetricsLogger fans records into it."""
    with _SINKS_LOCK:
        _SINKS.append(sink)


def remove_sink(sink) -> None:
    with _SINKS_LOCK:
        if sink in _SINKS:
            _SINKS.remove(sink)


def _global_sinks() -> list:
    with _SINKS_LOCK:
        return list(_SINKS)


# --- the event logger --------------------------------------------------------


class MetricsLogger:
    """Structured event emitter for one run.

    Console behavior matches the original module: one JSON line per
    segment plus an end-of-run summary on stderr. ``--quiet`` now only
    suppresses the per-segment console lines — the summary and
    robustness events always print, and global sinks always get
    everything.
    """

    def __init__(self, config: "SieveConfig", stream: TextIO | None = None):
        self.config = config
        self.stream = stream if stream is not None else sys.stderr
        self.t_start = trace.now_s()

    def _emit(self, record: dict[str, Any], per_segment: bool = False) -> None:
        # monotonic, trace-epoch-relative: comparable with span times
        record.setdefault("ts", round(trace.now_s(), 4))
        for sink in _global_sinks():
            sink.emit(record)
        if per_segment and self.config.quiet:
            return
        self.stream.write(json.dumps(record) + "\n")
        self.stream.flush()

    def event(self, kind: str, quietable: bool = False, **fields: Any) -> None:
        """Emit one structured record. ``quietable=True`` marks it as
        per-request/per-segment chatter that ``--quiet`` drops from the
        console (sinks always get it)."""
        self._emit({"event": kind, **fields}, per_segment=quietable)

    def segment(self, res: "SegmentResult") -> None:
        reg = registry()
        reg.counter("segments_done").inc()
        reg.histogram("segment_ms").observe(res.elapsed_s * 1000)
        self._emit(
            {
                "event": "segment",
                "id": res.seg_id,
                "lo": res.lo,
                "hi": res.hi,
                "ms": round(res.elapsed_s * 1000, 3),
                "count": res.count,
            },
            per_segment=True,
        )

    def run_summary(self, result: "SieveResult") -> None:
        chips = max(1, self.config.workers)
        record = {
            "event": "run",
            "n": result.n,
            "pi": result.pi,
            "twins": result.twin_pairs,
            "backend": result.backend,
            "packing": result.packing,
            "elapsed_s": round(result.elapsed_s, 4),
            "values_per_sec": round(result.values_per_sec, 1),
            "primes_per_sec_per_chip": round(result.pi / result.elapsed_s / chips, 1)
            if result.elapsed_s > 0
            else None,
        }
        kind = getattr(self.config, "count_kind", "primes")
        if kind not in (None, "primes", "twins"):
            record["count_kind"] = kind
        phases = getattr(result, "host_phases", None)
        if phases:
            # host-prepare pipeline health alongside the headline rate;
            # cluster runs add telemetry-shipping / clock-alignment health
            for key in (
                "prep_s",
                "prep_values_per_sec",
                "device_idle_frac",
                "overlap_efficiency",
                "reduction_mode",
                "postlude_fused_s",
                "postlude_split_s",
                "telemetry_workers",
                "telemetry_dropped_events",
                "clock_err_max_s",
                "workers_joined",
                "workers_left",
            ):
                if key in phases:
                    record[key] = phases[key]
        self._emit(record)
