"""Metrics: a registry of counters/gauges/histograms + pluggable sinks.

Two layers (SURVEY.md section 5.5, reworked):

* :class:`MetricsRegistry` — named instruments (:class:`Counter`,
  :class:`Gauge`, :class:`Histogram`) updated from anywhere in the
  stack (cluster heartbeats, straggler watches, segment timings).
  ``registry().snapshot()`` returns plain JSON-able values.
* Event sinks — every structured event record (one JSON object per
  event) is fanned out to the emitting logger's own stream (stderr by
  default, as before) **and** to every globally registered sink:
  ``--metrics-file`` installs a :class:`FileSink` (JSONL), tests
  install a :class:`MemorySink`.

Event schema: every record carries ``event`` (the kind) and ``ts``
(seconds since the process trace epoch — ``time.perf_counter`` based,
monotonic, directly comparable with span times in a ``--trace`` file).
Required per-kind keys are documented in :data:`EVENT_SCHEMA` and
enforced by tests through the in-memory sink.

``--quiet`` drops only the per-segment console lines; the run summary
and robustness events (``worker_failed``, ``segment_error``,
``reassign``, ``resume``) always reach the console stream, and global
sinks receive *every* record regardless of quiet.
"""

from __future__ import annotations

import collections
import itertools
import json
import math
import os
import random
import sys
import threading
import zlib
from typing import TYPE_CHECKING, Any, TextIO

from sieve import env, trace
from sieve.analysis.lockdebug import named_lock

if TYPE_CHECKING:
    from sieve.config import SieveConfig
    from sieve.coordinator import SieveResult
    from sieve.worker import SegmentResult

# Required keys per event kind ("event" and "ts" are implicit on every
# record). Kinds may carry extra keys; these are the stable contract.
# Cluster robustness events carry the run id and the per-attempt trace
# context ("ctx", None when the failure wasn't mid-assignment) so
# tools/trace_report.py can pin them onto the merged timeline;
# "worker_telemetry" records each worker's shipped span batch (plus
# clock offset/err when an alignment sample exists).
EVENT_SCHEMA: dict[str, set[str]] = {
    "segment": {"id", "lo", "hi", "ms", "count"},
    "run": {"n", "pi", "backend", "packing", "elapsed_s", "values_per_sec"},
    "resume": {"restored"},
    "worker_failed": {"worker", "reason", "run_id", "ctx"},
    "segment_error": {"reason", "run_id", "ctx"},
    "reassign": {"seg_id", "run_id", "ctx"},
    "host_prepare": {"prep_s"},
    "worker_telemetry": {"worker", "events", "dropped"},
    # elastic membership + adaptive deadlines + ledger salvage (ISSUE 6);
    # "active" is the live worker count after the join/leave
    "worker_joined": {"worker", "run_id", "active"},
    "worker_left": {"worker", "reason", "run_id", "active"},
    "deadline_adjusted": {"deadline_s", "prev_s", "p95_s", "run_id"},
    "ledger_salvaged": {"salvaged", "quarantined"},
    # query service (ISSUE 7): one service_request per admitted request
    # ("outcome" is ok/deadline_exceeded/degraded/bad_request/internal,
    # "source" index/cold/mixed/none); shed requests get service_shed
    # instead (never both). service_coalesced marks a follower joining a
    # leader's in-flight cold range; service_degraded marks health
    # transitions (entering=True/False).
    "service_request": {"op", "outcome", "source", "ms"},
    "service_shed": {"op", "queue_depth"},
    "service_coalesced": {"op", "lo", "hi"},
    "service_degraded": {"entering", "reason"},
    # replication plane (ISSUE 8): service_refreshed marks each live
    # snapshot swap (covered_hi is monotonic per process);
    # service_refresh_failed a skipped refresh (corrupt / mid-quarantine
    # / regressing read); service_drain the flip to draining;
    # service_chaos_refused a wire chaos injection denied by the
    # --allow-chaos gate; ledger_unverified a checksum-less v1 read-only
    # open (loads, but never silently).
    "service_refreshed": {"covered_hi", "prev_covered_hi", "segments",
                          "refreshes"},
    "service_refresh_failed": {"reason"},
    "service_drain": {"queued", "inflight"},
    "service_chaos_refused": {"spec"},
    "ledger_unverified": {"path"},
    # batched cold plane (ISSUE 9): one service_batched per backend
    # dispatch — "chunks" is the batch size (also observed by the
    # service.batch_chunks histogram), "persisted" how many results were
    # written back to the ledger (0 unless --persist-cold), "failed" how
    # many chunks were chaos-failed out of the batch pre-dispatch.
    "service_batched": {"chunks", "lo", "hi", "ms", "persisted", "failed"},
    # priority lanes (ISSUE 10): service_lane_shed marks a per-lane
    # admission refusal (queue_depth is THAT lane's depth; a lane shed
    # also emits the lane-less service_shed for continuity);
    # service_demoted marks a misclassified hot request re-enqueued on
    # the cold lane ("chunks" = how many chunks needed a dispatch).
    "service_lane_shed": {"op", "lane", "queue_depth"},
    "service_demoted": {"op", "chunks"},
    # router fabric (ISSUE 11): one router_request per routed query
    # ("shards" = how many shards the scatter touched; point routes say
    # 1); router_shard_down marks a shard held unreachable (chaos window
    # or exhausted replicas — "reason" says which); router_spliced marks
    # a cross-shard pair stitch at a shard edge ("pair_kind" twins /
    # cousins, "pairs" = pairs crossing that edge). router_drain and
    # router_chaos_refused mirror their service_ counterparts.
    "router_request": {"op", "outcome", "shards", "ms"},
    "router_shard_down": {"shard", "reason"},
    "router_spliced": {"edge", "pair_kind", "pairs"},
    "router_drain": {"inflight"},
    "router_chaos_refused": {"spec"},
    # fleet trace/telemetry plane (ISSUE 12): service_trace_drop marks a
    # reply whose piggybacked telemetry was chaos-dropped (query result
    # still exact); router_trace_gap the router-side degradation for a
    # reply that should have carried telemetry but didn't ("reason"
    # dropped/malformed); router_telemetry one merged shard-replica span
    # batch (rebased onto the router timeline — the service analogue of
    # worker_telemetry); service_slo_burn the transition of one op's
    # rolling p95 above its configured SLO.
    "service_trace_drop": {"op"},
    "router_trace_gap": {"shard", "reason"},
    "router_telemetry": {"shard", "replica", "events", "dropped"},
    "service_slo_burn": {"op", "p95_ms", "slo_ms", "window"},
    # flight recorder (ISSUE 13): one debug_bundle per frozen postmortem
    # bundle — "trigger" names the edge that fired (slo_burn /
    # breaker_open / shard_down / crash), "path" the bundle directory
    # (None when no --debug-dir is set and the freeze stayed in memory)
    "debug_bundle": {"trigger", "path"},
    # wire plane (ISSUE 14): service_slow_frame marks a connection put
    # under the svc_slow_frame chaos throttle (its replies dribble at
    # "bytes_per_tick" per event-loop tick); service_slow_consumer
    # marks a connection killed because its bounded write queue
    # overflowed ("queued_bytes" = bytes pending when the cap tripped).
    "service_slow_frame": {"bytes_per_tick"},
    "service_slow_consumer": {"queued_bytes"},
    # binary wire v2 (ISSUE 16): a v2-capable client whose hello came
    # back v1-only — "negotiated" is the version the peer settled on.
    # Emitted once per downgraded connection so a supposedly-binary
    # fleet silently running JSON is visible in the metrics stream.
    "wire_downgrade": {"addr", "negotiated"},
    # tiered segment store (ISSUE 17): store_demoted marks an LRU/_pv
    # eviction landing in tier 2 (bytes = wheel-compressed payload);
    # store_compacted one generation swap by the elected writer
    # (reclaimed_bytes may be negative if peers appended mid-compaction);
    # store_torn_entry one checksum-failed record skipped by a reader or
    # deliberately written torn by the store_torn_write chaos kind —
    # counted, never fatal, the chunk simply re-materializes.
    "store_demoted": {"lo", "hi", "bytes", "tier"},
    "store_compacted": {"gen", "live", "reclaimed_bytes", "downgraded"},
    "store_torn_entry": {"offset", "gen"},
    # mesh cold plane (ISSUE 18): service_mesh_dispatch is one SPMD
    # launch over the device mesh ("chunks" = drain-slice fanout,
    # "launch" = the ColdBackend's mesh-launch counter — the
    # svc_mesh_fail chaos key); service_mesh_fallback is a typed
    # degradation to the local loop worker ("reason" names mesh init vs
    # launch failure; chunks=0 for the one-shot init fallback) — the
    # answers stay exact either way.
    "service_mesh_dispatch": {"chunks", "devices", "launch", "ms"},
    "service_mesh_fallback": {"reason", "chunks"},
    # capacity observatory (ISSUE 19): service_exemplar_kept is one
    # tail-sampled span tree retained at request completion ("reason" is
    # the retention rule: error/flagged/slow/baseline); observer_scrape_gap
    # is one failed observer poll (chaos or a genuinely down endpoint) —
    # counted, never fabricated into a sample; fleet_anomaly is an
    # edge-triggered robust z-score breach with its evidence row (and the
    # fleet debug bundle it pulled); scaling_advice a split/merge/
    # add-replica advisory derived from the same trend windows.
    "service_exemplar_kept": {"role", "ctx", "op", "outcome", "reason",
                              "ms", "spans"},
    "observer_scrape_gap": {"addr", "scrape", "gap"},
    "fleet_anomaly": {"addr", "signal", "value", "mean", "dev", "z",
                      "scrape", "bundle"},
    "scaling_advice": {"advice", "shard", "qps", "shed_rate", "share",
                       "scrape"},
    # a scrape cycle that raised past the per-endpoint nets: counted so
    # a silently wedged observer is visible, never fatal to the daemon
    "observer_error": {"error"},
    # continuous profiler (ISSUE 20): profile_captured is one sampler
    # table frozen into a FlightRecorder bundle ("samples" = thread
    # samples folded since start, "stacks" = distinct collapsed stacks
    # held); profile_pulled is one inline ``profile`` wire reply (or
    # the observer's fleet-wide anomaly pull, role="observer") —
    # "gap" flags a reply the svc_prof_gap chaos kind dropped.
    "profile_captured": {"role", "samples", "stacks"},
    "profile_pulled": {"role", "samples", "stacks", "gap"},
}


def validate_record(record: dict[str, Any]) -> None:
    """Raise ValueError if a record violates the documented schema."""
    kind = record.get("event")
    if not isinstance(kind, str):
        raise ValueError(f"record missing 'event' kind: {record!r}")
    if "ts" not in record:
        raise ValueError(f"record missing 'ts': {record!r}")
    required = EVENT_SCHEMA.get(kind, set())
    missing = required - record.keys()
    if missing:
        raise ValueError(f"{kind!r} record missing keys {sorted(missing)}")
    json.dumps(record)  # every value must be JSON-serializable


# --- instruments -------------------------------------------------------------


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = named_lock("Counter._lock")

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n
        trace.counter(self.name, self.value)

    def snapshot(self) -> dict[str, Any]:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-observed value (heartbeat age, straggler lag, queue depth)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value: float | None = None
        self._lock = named_lock("Gauge._lock")

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v
        trace.counter(self.name, v)

    def max(self, v: float) -> None:
        """Keep the running maximum (straggler watermarks)."""
        with self._lock:
            if self.value is None or v > self.value:
                self.value = v

    def snapshot(self) -> dict[str, Any]:
        return {"type": "gauge", "value": self.value}


# Fixed reservoir size (ISSUE 13): bounds a long-lived server's
# histogram memory while keeping p50/p95/p99 within ~±2% — at 4096
# samples the nearest-rank p99's rank error is ~0.16% (one sigma).
HISTOGRAM_RESERVOIR = 4096


def _pctile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted non-empty list
    (same convention as bench.py and the server's SLO windows)."""
    return sorted_vals[max(0, math.ceil(q * len(sorted_vals)) - 1)]


class Histogram:
    """Streaming summary (count/sum/min/max) plus a fixed-size
    reservoir for percentiles.

    Observations beyond the reservoir size replace a uniformly random
    slot (Algorithm R), so memory stays bounded on long-lived servers
    while p50/p95/p99 stay within a couple of percent of the true
    distribution. The replacement stream is seeded from the metric
    name: snapshots are reproducible run to run."""

    __slots__ = ("name", "count", "sum", "min", "max", "_lock",
                 "_reservoir", "_cap", "_rng")

    def __init__(self, name: str, reservoir: int = HISTOGRAM_RESERVOIR):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._reservoir: list[float] = []
        self._cap = max(1, reservoir)
        self._rng = random.Random(zlib.crc32(name.encode()))
        self._lock = named_lock("Histogram._lock")

    def observe(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._reservoir) < self._cap:
                self._reservoir.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self._reservoir[j] = v

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            count, total = self.count, self.sum
            lo, hi = self.min, self.max
            vals = sorted(self._reservoir)
        return {
            "type": "histogram",
            "count": count,
            "sum": total,
            "min": lo,
            "max": hi,
            "mean": total / count if count else None,
            "p50": _pctile(vals, 0.50) if vals else None,
            "p95": _pctile(vals, 0.95) if vals else None,
            "p99": _pctile(vals, 0.99) if vals else None,
        }


class MetricsRegistry:
    """Named instruments; one process-wide instance by default."""

    def __init__(self) -> None:
        self._lock = named_lock("MetricsRegistry._lock")
        self._instruments: dict[str, Any] = {}

    def _get(self, name: str, cls):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {k: v.snapshot() for k, v in self._instruments.items()}


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


# --- metrics history (ISSUE 13) ----------------------------------------------

# two-tier ring shape: the newest HISTORY_RECENT samples stay dense (one
# per tick); as a sample ages out of the dense tier every
# HISTORY_DECIMATE-th one is promoted into a coarse tier of
# HISTORY_COARSE slots — an hour of 1 s sampling costs ~660 snapshots,
# not 3600, and trend queries still see the whole hour.
HISTORY_RECENT = 300
HISTORY_COARSE = 360
HISTORY_DECIMATE = 10


def sample_interval_s() -> float:
    """The MetricsHistory tick from ``SIEVE_METRICS_SAMPLE_S`` (seconds;
    default 1.0; 0 disables sampling). Parse failures name the env var."""
    v = env.env_float("SIEVE_METRICS_SAMPLE_S", 1.0)
    if v < 0 or not math.isfinite(v):
        raise ValueError(
            f"env SIEVE_METRICS_SAMPLE_S={v!r}: must be a non-negative "
            "finite number of seconds"
        )
    return v


class MetricsHistory:
    """Daemon sampler: periodic registry snapshots into a bounded,
    time-downsampled ring.

    This is the trend input the flight recorder bundles and a future
    SLO-driven autoscaler reads (ROADMAP elasticity item): recent
    samples dense, older samples decimated, memory bounded regardless
    of process lifetime. ``start``/``stop`` are idempotent; a 0 sample
    interval disables the sampler entirely (zero samples, zero
    threads); ``stop`` takes one final synchronous sample so whatever
    changed since the last timer tick is not lost."""

    def __init__(
        self,
        reg: MetricsRegistry | None = None,
        sample_s: float | None = None,
        recent: int = HISTORY_RECENT,
        coarse: int = HISTORY_COARSE,
        decimate: int = HISTORY_DECIMATE,
    ):
        self._reg = reg if reg is not None else registry()
        self.sample_s = (
            sample_interval_s() if sample_s is None else float(sample_s)
        )
        self._recent: collections.deque = collections.deque(maxlen=recent)
        self._coarse: collections.deque = collections.deque(maxlen=coarse)
        self._decimate = max(1, decimate)
        self._taken = 0  # guard: _lock
        self._lock = named_lock("MetricsHistory._lock")
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    # --- lifecycle -------------------------------------------------------

    def start(self) -> "MetricsHistory":
        if self.sample_s <= 0:
            return self  # disabled: no thread, no samples
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self  # idempotent
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, args=(self._stop_evt,), daemon=True,
                name="metrics-history",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
            self._stop_evt.set()
        if t is not None:
            t.join(timeout=5)
            self.sample_now()  # drain-on-stop: the partial tick lands

    def _loop(self, stop_evt: threading.Event) -> None:
        while not stop_evt.wait(self.sample_s):
            self.sample_now()

    # --- sampling --------------------------------------------------------

    def sample_now(self) -> float:
        """Take one sample immediately (the timer thread's tick body;
        also the test/drain hook). Returns the sample timestamp."""
        snap = self._reg.snapshot()
        ts = round(trace.now_s(), 4)
        with self._lock:
            self._taken += 1
            if len(self._recent) == self._recent.maxlen:
                aged = self._recent[0]  # about to be evicted by append
                if aged[2] % self._decimate == 0:
                    self._coarse.append(aged)
            self._recent.append((ts, snap, self._taken))
        return ts

    @property
    def samples(self) -> int:
        """Samples ever taken (monotonic; survives ring eviction)."""
        with self._lock:
            return self._taken

    # --- queries ---------------------------------------------------------

    def rows(self, window_s: float | None = None) -> list[tuple[float, dict]]:
        """Raw ``(ts, registry-snapshot)`` rows, oldest first (coarse
        tier then dense), optionally limited to the trailing window —
        the flight recorder bundles this verbatim."""
        cutoff = None if window_s is None else trace.now_s() - window_s
        with self._lock:
            rows = list(itertools.chain(self._coarse, self._recent))
        return [
            (ts, snap) for ts, snap, _ in rows
            if cutoff is None or ts >= cutoff
        ]

    def history(self, name: str, window_s: float) -> list[tuple[float, Any]]:
        """Trend rows for one instrument over the trailing window:
        ``(ts, value)`` for counters and gauges, ``(ts, snapshot-dict)``
        for histograms. Samples predating the instrument's registration
        are absent, not None — registry churn is expected."""
        out: list[tuple[float, Any]] = []
        for ts, snap in self.rows(window_s):
            inst = snap.get(name)
            if inst is None:
                continue
            out.append((ts, inst["value"] if "value" in inst else inst))
        return out


# --- sinks -------------------------------------------------------------------


class MemorySink:
    """Collects records in memory — the test/inspection sink."""

    def __init__(self) -> None:
        self.records: list[dict] = []
        self._lock = named_lock("MemorySink._lock")

    def emit(self, record: dict) -> None:
        with self._lock:
            self.records.append(record)

    def close(self) -> None:
        pass


class StreamSink:
    """JSONL onto an open text stream."""

    def __init__(self, stream: TextIO):
        self.stream = stream
        self._lock = named_lock("StreamSink._lock")

    def emit(self, record: dict) -> None:
        with self._lock:
            self.stream.write(json.dumps(record) + "\n")
            self.stream.flush()

    def close(self) -> None:
        pass


class FileSink(StreamSink):
    """JSONL appended to a file (``--metrics-file``)."""

    def __init__(self, path: str):
        super().__init__(open(path, "a"))

    def close(self) -> None:
        self.stream.close()


_SINKS: list = []
_SINKS_LOCK = named_lock("metrics._SINKS_LOCK")


def add_sink(sink) -> None:
    """Register a global sink; every MetricsLogger fans records into it."""
    with _SINKS_LOCK:
        _SINKS.append(sink)


def remove_sink(sink) -> None:
    with _SINKS_LOCK:
        if sink in _SINKS:
            _SINKS.remove(sink)


def _global_sinks() -> list:
    with _SINKS_LOCK:
        return list(_SINKS)


# --- the event logger --------------------------------------------------------


class MetricsLogger:
    """Structured event emitter for one run.

    Console behavior matches the original module: one JSON line per
    segment plus an end-of-run summary on stderr. ``--quiet`` now only
    suppresses the per-segment console lines — the summary and
    robustness events always print, and global sinks always get
    everything.
    """

    def __init__(self, config: "SieveConfig", stream: TextIO | None = None):
        self.config = config
        self.stream = stream if stream is not None else sys.stderr
        self.t_start = trace.now_s()

    def _emit(self, record: dict[str, Any], per_segment: bool = False) -> None:
        # monotonic, trace-epoch-relative: comparable with span times
        record.setdefault("ts", round(trace.now_s(), 4))
        for sink in _global_sinks():
            sink.emit(record)
        if per_segment and self.config.quiet:
            return
        self.stream.write(json.dumps(record) + "\n")
        self.stream.flush()

    def event(self, kind: str, quietable: bool = False, **fields: Any) -> None:
        """Emit one structured record. ``quietable=True`` marks it as
        per-request/per-segment chatter that ``--quiet`` drops from the
        console (sinks always get it)."""
        self._emit({"event": kind, **fields}, per_segment=quietable)

    def segment(self, res: "SegmentResult") -> None:
        reg = registry()
        reg.counter("segments_done").inc()
        reg.histogram("segment_ms").observe(res.elapsed_s * 1000)
        self._emit(
            {
                "event": "segment",
                "id": res.seg_id,
                "lo": res.lo,
                "hi": res.hi,
                "ms": round(res.elapsed_s * 1000, 3),
                "count": res.count,
            },
            per_segment=True,
        )

    def run_summary(self, result: "SieveResult") -> None:
        chips = max(1, self.config.workers)
        record = {
            "event": "run",
            "n": result.n,
            "pi": result.pi,
            "twins": result.twin_pairs,
            "backend": result.backend,
            "packing": result.packing,
            "elapsed_s": round(result.elapsed_s, 4),
            "values_per_sec": round(result.values_per_sec, 1),
            "primes_per_sec_per_chip": round(result.pi / result.elapsed_s / chips, 1)
            if result.elapsed_s > 0
            else None,
        }
        kind = getattr(self.config, "count_kind", "primes")
        if kind not in (None, "primes", "twins"):
            record["count_kind"] = kind
        phases = getattr(result, "host_phases", None)
        if phases:
            # host-prepare pipeline health alongside the headline rate;
            # cluster runs add telemetry-shipping / clock-alignment health
            for key in (
                "prep_s",
                "prep_values_per_sec",
                "device_idle_frac",
                "overlap_efficiency",
                "reduction_mode",
                "postlude_fused_s",
                "postlude_split_s",
                "telemetry_workers",
                "telemetry_dropped_events",
                "clock_err_max_s",
                "workers_joined",
                "workers_left",
            ):
                if key in phases:
                    record[key] = phases[key]
        self._emit(record)
