"""cpu-cluster backend: socket transport, worker processes, fault handling.

SURVEY.md section 3.2 — the reference's main distributed path, preserved
"alongside" the TPU backend (BASELINE.json): a coordinator ships seed
primes + segment assignments to worker processes over TCP and collects
per-segment results; control crosses the network exactly twice per segment
(assign, done). Section 5.3: each assignment carries a deadline refreshed
by progress heartbeats; a dead or silent worker's segment returns to the
queue for a different owner. Results are idempotent (keyed on seg_id), so
double-processing after reassignment cannot double-count.

Wire protocol: 8-byte big-endian length prefix + JSON. Messages:
  worker -> coordinator: {"type": "hello", "worker_id": i}
                         {"type": "progress", "seg_id": s}
                         {"type": "done", "result": SegmentResult dict}
  coordinator -> worker: {"type": "config", "config": .., "seeds": [..]}
                         {"type": "assign", "seg_id", "lo", "hi", "chaos_die"}
                         {"type": "shutdown"}

Fault injection (section 5.3): ``--chaos-kill-worker k@s`` makes worker k
hard-exit (os._exit) when it receives segment s — exercising detection,
reassignment, and exact-parity recovery in tests.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np

from sieve import trace
from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.coordinator import SieveResult, merge_results
from sieve.metrics import MetricsLogger, registry
from sieve.seed import seed_primes
from sieve.segments import plan_segments, validate_plan
from sieve.worker import SegmentResult

HEARTBEAT_S = 1.0
DEADLINE_S = float(os.environ.get("SIEVE_CLUSTER_DEADLINE_S", "60"))
ANY_WORKER = -1  # chaos_kill "any@s": whichever worker draws segment s


# --- framing -----------------------------------------------------------------


def send_msg(sock: socket.socket, msg: dict) -> None:
    blob = json.dumps(msg).encode()
    sock.sendall(struct.pack(">Q", len(blob)) + blob)


def recv_msg(sock: socket.socket) -> dict | None:
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (length,) = struct.unpack(">Q", header)
    blob = _recv_exact(sock, length)
    if blob is None:
        return None
    return json.loads(blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _parse_addr(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


# --- worker role -------------------------------------------------------------


def serve_worker(config: SieveConfig, worker_id: int | None = None) -> None:
    """Connect to the coordinator and process assignments until shutdown."""
    if worker_id is None:
        worker_id = int(os.environ.get("SIEVE_WORKER_ID", "0"))
    host, port = _parse_addr(config.coordinator_addr)
    sock = socket.create_connection((host, port), timeout=30)
    sock.settimeout(None)
    send_msg(sock, {"type": "hello", "worker_id": worker_id})
    msg = recv_msg(sock)
    assert msg and msg["type"] == "config", f"bad handshake: {msg}"
    run_cfg = SieveConfig.from_dict(msg["config"])
    seeds = np.asarray(msg["seeds"], dtype=np.int64)

    from sieve.backends import make_worker

    compute_cfg = SieveConfig.from_dict(
        {**run_cfg.to_dict(), "backend": _worker_backend()}
    )
    worker = make_worker(compute_cfg)
    try:
        while True:
            msg = recv_msg(sock)
            if msg is None or msg["type"] == "shutdown":
                return
            assert msg["type"] == "assign", msg
            if msg.get("chaos_die"):
                os._exit(17)  # simulated hard crash, no cleanup
            result: list[SegmentResult] = []
            failure: list[str] = []

            def _work(m=msg):
                try:
                    if os.environ.get("SIEVE_CHAOS_RAISE") == str(m["seg_id"]):
                        raise RuntimeError("chaos: injected segment failure")
                    with trace.span(
                        "worker.segment", seg=m["seg_id"], worker=worker_id
                    ):
                        result.append(
                            worker.process_segment(
                                m["lo"], m["hi"], seeds, m["seg_id"]
                            )
                        )
                except Exception as e:  # report, don't die: the coordinator
                    import traceback     # decides whether to retry or abort

                    failure.append(f"{e!r}\n{traceback.format_exc()}")

            t = threading.Thread(target=_work, daemon=True)
            t.start()
            while t.is_alive():
                t.join(HEARTBEAT_S)
                if t.is_alive():
                    send_msg(sock, {"type": "progress", "seg_id": msg["seg_id"]})
            if failure:
                send_msg(
                    sock,
                    {"type": "error", "seg_id": msg["seg_id"], "error": failure[0]},
                )
            else:
                send_msg(sock, {"type": "done", "result": result[0].to_dict()})
    finally:
        worker.close()
        sock.close()


def _worker_backend() -> str:
    """Compute backend used inside cluster workers: native if it builds."""
    forced = os.environ.get("SIEVE_CLUSTER_WORKER_BACKEND")
    if forced:
        return forced
    try:
        from sieve.backends.cpu_native import _build_and_load

        _build_and_load()
        return "cpu-native"
    except Exception:
        return "cpu-numpy"


# --- coordinator role --------------------------------------------------------


class _WorkerConn(threading.Thread):
    """One coordinator-side thread per connected worker: assigns segments
    from the shared queue, enforces the progress deadline, requeues on
    failure."""

    def __init__(self, cluster: "_Cluster", sock: socket.socket):
        super().__init__(daemon=True)
        self.cluster = cluster
        self.sock = sock
        self.worker_id = -1

    def run(self) -> None:
        cl = self.cluster
        current: tuple[int, int, int] | None = None  # (seg_id, lo, hi)
        try:
            hello = recv_msg(self.sock)
            if not hello or hello["type"] != "hello":
                return
            self.worker_id = hello["worker_id"]
            send_msg(
                self.sock,
                {
                    "type": "config",
                    "config": cl.config.to_dict(),
                    "seeds": cl.seeds.tolist(),
                },
            )
            self.sock.settimeout(DEADLINE_S)
            # keep serving until the whole run is done: a segment requeued by
            # another worker's failure must find a live owner even if this
            # thread saw an empty queue earlier
            while not cl.all_done.is_set():
                try:
                    seg = cl.queue.get(timeout=0.5)
                except queue.Empty:
                    continue
                if seg.seg_id in cl.done:
                    continue
                current = (seg.seg_id, seg.lo, seg.hi)
                chaos = cl.chaos is not None and cl.chaos[1] == seg.seg_id \
                    and cl.chaos[0] in (ANY_WORKER, self.worker_id)
                reg = registry()
                t_assign = time.perf_counter()
                send_msg(
                    self.sock,
                    {
                        "type": "assign",
                        "seg_id": seg.seg_id,
                        "lo": seg.lo,
                        "hi": seg.hi,
                        "chaos_die": chaos,
                    },
                )
                while True:
                    msg = recv_msg(self.sock)
                    inflight = time.perf_counter() - t_assign
                    if msg is None:
                        raise ConnectionError("worker closed mid-assignment")
                    if msg["type"] == "progress":
                        # deadline refreshed by settimeout per recv; the
                        # heartbeat also feeds the straggler watermark:
                        # the longest any in-flight assignment has run
                        reg.counter("cluster.heartbeats").inc()
                        reg.gauge(
                            f"cluster.worker{self.worker_id}.inflight_s"
                        ).set(round(inflight, 4))
                        reg.gauge("cluster.straggler_s").max(
                            round(inflight, 4)
                        )
                        trace.instant(
                            "cluster.heartbeat",
                            worker=self.worker_id,
                            seg=seg.seg_id,
                        )
                        continue
                    if msg["type"] in ("done", "error"):
                        # one RPC round-trip: assign -> terminal reply
                        trace.add_span(
                            "rpc.assign",
                            t_assign,
                            inflight,
                            worker=self.worker_id,
                            seg=seg.seg_id,
                            outcome=msg["type"],
                        )
                        reg.histogram("cluster.rpc_ms").observe(
                            inflight * 1000
                        )
                        reg.gauge(
                            f"cluster.worker{self.worker_id}.inflight_s"
                        ).set(0.0)
                    if msg["type"] == "done":
                        cl.complete(SegmentResult.from_dict(msg["result"]))
                        current = None
                        break
                    if msg["type"] == "error":
                        cl.segment_error(current, msg["error"])
                        current = None
                        break
                    raise ConnectionError(f"unexpected message {msg['type']}")
        except (ConnectionError, OSError, socket.timeout) as e:
            cl.worker_failed(self.worker_id, current, repr(e))
        finally:
            try:
                send_msg(self.sock, {"type": "shutdown"})
            except OSError:
                pass
            self.sock.close()


class _Cluster:
    def __init__(self, config: SieveConfig, seeds, segments, metrics, ledger):
        self.config = config
        self.seeds = seeds
        self.metrics = metrics
        self.ledger = ledger
        self.queue: queue.Queue = queue.Queue()
        self.done: dict[int, SegmentResult] = {}
        self.lock = threading.Lock()
        self.n_expected = len(segments)
        self.all_done = threading.Event()
        self.attempts: dict[int, int] = {}
        self.fatal: str | None = None
        self.chaos: tuple[int, int] | None = None
        if config.chaos_kill:
            k, s = config.chaos_kill.split("@")
            # "any@s": kill whichever worker draws segment s — the pull
            # model makes "k@s" probabilistic, "any@s" deterministic
            self.chaos = (ANY_WORKER if k in ("any", "*") else int(k), int(s))
        for seg in segments:
            self.queue.put(seg)

    def complete(self, res: SegmentResult) -> None:
        with self.lock:
            if res.seg_id in self.done:
                return  # idempotent: reassigned segment finished twice
            self.done[res.seg_id] = res
            if self.ledger is not None:
                self.ledger.record(res)
            self.metrics.segment(res)
            if len(self.done) >= self.n_expected:
                self.all_done.set()

    MAX_ATTEMPTS = 4

    def worker_failed(self, worker_id, current, reason: str) -> None:
        registry().counter("cluster.worker_failures").inc()
        self.metrics.event("worker_failed", worker=worker_id, reason=reason)
        self._requeue(current, reason)

    def segment_error(self, current, reason: str) -> None:
        """A worker survived but its segment raised: retry elsewhere, abort
        the run if the failure looks deterministic (MAX_ATTEMPTS strikes)."""
        registry().counter("cluster.segment_errors").inc()
        self.metrics.event("segment_error", reason=reason.splitlines()[0])
        self._requeue(current, reason)

    def _requeue(self, current, reason: str) -> None:
        if current is None:
            return
        seg_id, lo, hi = current
        with self.lock:
            if seg_id in self.done:
                return
            self.attempts[seg_id] = self.attempts.get(seg_id, 0) + 1
            if self.attempts[seg_id] >= self.MAX_ATTEMPTS:
                self.fatal = (
                    f"segment {seg_id} failed {self.attempts[seg_id]} times; "
                    f"last error: {reason}"
                )
                self.all_done.set()
                return
        from sieve.segments import Segment

        registry().counter("cluster.reassigned").inc()
        self.metrics.event("reassign", seg_id=seg_id)
        # one-shot chaos: don't re-kill the replacement owner
        if self.chaos and self.chaos[1] == seg_id:
            self.chaos = None
        self.queue.put(Segment(seg_id=seg_id, lo=lo, hi=hi))


def run_cluster(config: SieveConfig) -> SieveResult:
    """Coordinator entry: serve assignments, spawn local workers (unless
    SIEVE_CLUSTER_NO_SPAWN=1 for externally-launched / multi-host workers),
    merge results."""
    cfg = config
    t0 = time.perf_counter()
    metrics = MetricsLogger(cfg)
    with trace.span("run.seed", backend=cfg.backend):
        seeds = seed_primes(cfg.seed_limit)
    n_segments = cfg.resolved_n_segments()
    if cfg.n_segments is None and cfg.segment_values is None:
        n_segments = max(cfg.workers * 4, 16)  # sensible default for pull model
    segs = plan_segments(cfg.n, n_segments)
    validate_plan(segs, cfg.n)
    eff = SieveConfig(**{**cfg.to_dict(), "n_segments": len(segs)})

    ledger = Ledger.open(eff) if eff.checkpoint_dir else None
    restored: dict[int, SegmentResult] = {}
    if ledger is not None and eff.resume:
        restored = ledger.completed()
        metrics.event("resume", restored=len(restored))

    todo = [s for s in segs if s.seg_id not in restored]
    cluster = _Cluster(eff, seeds, todo, metrics, ledger)
    cluster.done.update(restored)
    if len(cluster.done) >= len(segs):
        cluster.n_expected = len(segs)
        cluster.all_done.set()
    else:
        cluster.n_expected = len(segs)

    host, port = _parse_addr(eff.coordinator_addr)
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind((host, port))
    actual_addr = f"{server.getsockname()[0]}:{server.getsockname()[1]}"
    server.listen(64)
    server.settimeout(0.5)

    procs: list[subprocess.Popen] = []
    if not cluster.all_done.is_set() and not os.environ.get("SIEVE_CLUSTER_NO_SPAWN"):
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for i in range(eff.workers):
            env = {**os.environ, "SIEVE_WORKER_ID": str(i)}
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m", "sieve",
                        "--n", str(eff.n),
                        "--role", "worker",
                        "--coordinator-addr", actual_addr,
                        "--packing", eff.packing,
                    ]
                    + (["--twins"] if eff.twins else []),
                    cwd=repo_root,
                    env=env,
                )
            )

    threads: list[_WorkerConn] = []
    try:
        # Workload-scaled global deadline: the old fixed ~300 s cap aborted
        # honest large-N runs. Budget assumes each worker sustains at least
        # SIEVE_CLUSTER_FLOOR_VPS values/s (default 1e6, ~100x below the
        # measured numpy kernel floor of 1.3e8 — see BASELINE.md), added to
        # the fixed grace for spawn + handshake so tiny runs keep the old
        # behavior.
        floor_vps = float(os.environ.get("SIEVE_CLUSTER_FLOOR_VPS", "1e6"))
        workload_s = eff.n / (floor_vps * max(1, eff.workers))
        deadline = time.time() + max(DEADLINE_S * 4, 300) + workload_s
        while not cluster.all_done.is_set():
            if time.time() > deadline:
                raise RuntimeError(
                    f"cluster run timed out with {cluster.n_expected - len(cluster.done)}"
                    f" segments outstanding"
                )
            try:
                sock, _ = server.accept()
            except socket.timeout:
                continue
            conn = _WorkerConn(cluster, sock)
            conn.start()
            threads.append(conn)
        cluster.all_done.wait()
    finally:
        server.close()
        for t in threads:
            t.join(timeout=2)
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    if cluster.fatal:
        raise RuntimeError(f"cluster run aborted: {cluster.fatal}")
    results = [cluster.done[s.seg_id] for s in segs]
    with trace.span("run.merge"):
        pi, twins = merge_results(eff, results)
    elapsed = time.perf_counter() - t0
    result = SieveResult(
        n=eff.n,
        pi=pi,
        twin_pairs=twins,
        backend="cpu-cluster",
        packing=eff.packing,
        n_segments=len(segs),
        elapsed_s=elapsed,
        values_per_sec=(eff.n - 1) / elapsed if elapsed > 0 else float("inf"),
        segments=results,
    )
    metrics.run_summary(result)
    return result
