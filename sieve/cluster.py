"""cpu-cluster backend: socket transport, worker processes, fault handling.

SURVEY.md section 3.2 — the reference's main distributed path, preserved
"alongside" the TPU backend (BASELINE.json): a coordinator ships seed
primes + segment assignments to worker processes over TCP and collects
per-segment results; control crosses the network exactly twice per segment
(assign, done). Section 5.3: each assignment carries a deadline refreshed
by progress heartbeats; a dead or silent worker's segment returns to the
queue for a different owner. Results are idempotent (keyed on seg_id), so
double-processing after reassignment cannot double-count.

Wire protocol: 8-byte big-endian length prefix + JSON. Messages:
  worker -> coordinator: {"type": "hello", "worker_id": i}
                         {"type": "progress", "seg_id", "t_recv", "t_hb"}
                         {"type": "done", "result": SegmentResult dict,
                          "ctx", "t_recv", "t_reply", "telemetry"}
  coordinator -> worker: {"type": "config", "config": .., "seeds": [..]}
                         {"type": "assign", "seg_id", "lo", "hi",
                          "chaos_die", "run_id", "ctx", "t_send"}
                         {"type": "shutdown"}

Distributed trace plane: every ``assign`` carries a trace context
(``run_id`` + per-attempt span id ``ctx``) that the worker attaches to
its ``worker.recv``/``worker.segment``/``worker.reply`` spans, so each
coordinator ``rpc.assign`` span correlates 1:1 with the worker-side
spans of that attempt. Replies and heartbeats carry worker-clock
timestamps; the coordinator keeps a min-RTT NTP-style sample per worker
(offset error bounded by RTT/2) and, at end of run, rebases the shipped
worker events onto its own trace epoch and merges them under per-worker
Perfetto process tracks — one ``--trace`` file for the whole cluster.
Telemetry rides the terminal ``done``/``error`` reply (bounded
drop-oldest ring, see sieve/worker.py), so a worker that dies
mid-assignment loses only its unshipped spans.

Fault injection (section 5.3): ``--chaos-kill-worker k@s`` makes worker k
hard-exit (os._exit) when it receives segment s — exercising detection,
reassignment, and exact-parity recovery in tests.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import subprocess
import sys
import threading

import numpy as np

from sieve import trace
from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.coordinator import SieveResult, merge_results
from sieve.metrics import MetricsLogger, registry
from sieve.seed import seed_primes
from sieve.segments import plan_segments, validate_plan
from sieve.worker import SegmentResult

HEARTBEAT_S = 1.0
DEADLINE_S = float(os.environ.get("SIEVE_CLUSTER_DEADLINE_S", "60"))
ANY_WORKER = -1  # chaos_kill "any@s": whichever worker draws segment s


# --- framing -----------------------------------------------------------------


def send_msg(sock: socket.socket, msg: dict) -> None:
    blob = json.dumps(msg).encode()
    sock.sendall(struct.pack(">Q", len(blob)) + blob)


def recv_msg(sock: socket.socket) -> dict | None:
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (length,) = struct.unpack(">Q", header)
    blob = _recv_exact(sock, length)
    if blob is None:
        return None
    return json.loads(blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _parse_addr(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


# --- worker role -------------------------------------------------------------


def serve_worker(config: SieveConfig, worker_id: int | None = None) -> None:
    """Connect to the coordinator and process assignments until shutdown."""
    if worker_id is None:
        worker_id = int(os.environ.get("SIEVE_WORKER_ID", "0"))
    host, port = _parse_addr(config.coordinator_addr)
    sock = socket.create_connection((host, port), timeout=30)
    sock.settimeout(None)
    send_msg(sock, {"type": "hello", "worker_id": worker_id})
    msg = recv_msg(sock)
    assert msg and msg["type"] == "config", f"bad handshake: {msg}"
    run_cfg = SieveConfig.from_dict(msg["config"])
    seeds = np.asarray(msg["seeds"], dtype=np.int64)

    from sieve.backends import make_worker
    from sieve.worker import telemetry_payload, telemetry_start

    compute_cfg = SieveConfig.from_dict(
        {**run_cfg.to_dict(), "backend": _worker_backend()}
    )
    worker = make_worker(compute_cfg)
    shipping = telemetry_start()
    reg = registry()
    try:
        while True:
            t_wait0 = trace.now_s()
            msg = recv_msg(sock)
            t_recv = trace.now_s()
            if msg is None or msg["type"] == "shutdown":
                return
            assert msg["type"] == "assign", msg
            if msg.get("chaos_die"):
                os._exit(17)  # simulated hard crash, no cleanup
            ctx = msg.get("ctx")
            # idle-wait + message receive: the worker-side view of "no
            # work assigned" that per-host idle accounting needs
            trace.add_span(
                "worker.recv", t_wait0, t_recv - t_wait0,
                seg=msg["seg_id"], worker=worker_id, ctx=ctx,
            )
            reg.histogram("worker.recv_wait_ms").observe(
                round((t_recv - t_wait0) * 1000, 3)
            )
            result: list[SegmentResult] = []
            failure: list[str] = []

            def _work(m=msg, ctx=ctx):
                try:
                    if os.environ.get("SIEVE_CHAOS_RAISE") == str(m["seg_id"]):
                        raise RuntimeError("chaos: injected segment failure")
                    with trace.span(
                        "worker.segment",
                        seg=m["seg_id"], worker=worker_id, ctx=ctx,
                    ):
                        result.append(
                            worker.process_segment(
                                m["lo"], m["hi"], seeds, m["seg_id"]
                            )
                        )
                except Exception as e:  # report, don't die: the coordinator
                    import traceback     # decides whether to retry or abort

                    failure.append(f"{e!r}\n{traceback.format_exc()}")

            t = threading.Thread(target=_work, daemon=True)
            t.start()
            while t.is_alive():
                t.join(HEARTBEAT_S)
                if t.is_alive():
                    # t_recv/t_hb give the coordinator a payload-free NTP
                    # sample mid-assignment (long segments refresh the
                    # clock offset without waiting for the reply)
                    send_msg(sock, {
                        "type": "progress", "seg_id": msg["seg_id"],
                        "t_recv": t_recv, "t_hb": trace.now_s(),
                    })
            if failure:
                reg.counter("worker.segment_errors").inc()
                reply = {
                    "type": "error", "seg_id": msg["seg_id"],
                    "error": failure[0],
                }
            else:
                res = result[0]
                reg.counter("worker.segments_done").inc()
                reg.histogram("worker.segment_ms").observe(
                    round(res.elapsed_s * 1000, 3)
                )
                reply = {"type": "done", "result": res.to_dict()}
            reply["ctx"] = ctx
            reply["t_recv"] = t_recv
            if shipping:
                # piggyback: this drains worker.recv + worker.segment of
                # THIS attempt (plus any earlier worker.reply) — a span
                # that closes after the send ships on the next reply
                reply["telemetry"] = telemetry_payload(worker_id)
            t_reply = trace.now_s()
            reply["t_reply"] = t_reply
            send_msg(sock, reply)
            trace.add_span(
                "worker.reply", t_reply, trace.now_s() - t_reply,
                seg=msg["seg_id"], worker=worker_id, ctx=ctx,
            )
    finally:
        worker.close()
        sock.close()


def _worker_backend() -> str:
    """Compute backend used inside cluster workers: native if it builds."""
    forced = os.environ.get("SIEVE_CLUSTER_WORKER_BACKEND")
    if forced:
        return forced
    try:
        from sieve.backends.cpu_native import _build_and_load

        _build_and_load()
        return "cpu-native"
    except Exception:
        return "cpu-numpy"


# --- coordinator role --------------------------------------------------------


class _ClockAlign:
    """Per-worker clock-offset estimate from RPC timestamp pairs.

    NTP-style: a pair (assign -> heartbeat/reply) gives
    ``rtt = (t_done - t_send) - (t_remote_send - t_remote_recv)`` and
    ``offset = ((t_remote_recv - t_send) + (t_remote_send - t_done)) / 2``
    with ``worker_clock ≈ coordinator_clock + offset``. The estimate kept
    is the one from the lowest-RTT sample seen so far (ties refresh to the
    newest, so equal-quality samples track slow drift); its error is
    bounded by RTT/2 plus any send/receive asymmetry.
    """

    __slots__ = ("offset_s", "rtt_s", "samples")

    def __init__(self) -> None:
        self.offset_s = 0.0
        self.rtt_s = float("inf")
        self.samples = 0

    def sample(
        self,
        t_send: float,
        t_remote_recv: float,
        t_remote_send: float,
        t_done: float,
    ) -> None:
        rtt = max(0.0, (t_done - t_send) - (t_remote_send - t_remote_recv))
        self.samples += 1
        if rtt <= self.rtt_s:
            self.rtt_s = rtt
            self.offset_s = (
                (t_remote_recv - t_send) + (t_remote_send - t_done)
            ) / 2

    @property
    def err_s(self) -> float:
        """Alignment-error bound for the kept sample (RTT/2)."""
        return self.rtt_s / 2 if self.samples else float("inf")


class _WorkerConn(threading.Thread):
    """One coordinator-side thread per connected worker: assigns segments
    from the shared queue, enforces the progress deadline, requeues on
    failure."""

    def __init__(self, cluster: "_Cluster", sock: socket.socket):
        super().__init__(daemon=True)
        self.cluster = cluster
        self.sock = sock
        self.worker_id = -1

    def run(self) -> None:
        cl = self.cluster
        # (seg_id, lo, hi, ctx): the in-flight assignment + its trace
        # context, so failure events correlate with the timeline
        current: tuple[int, int, int, str] | None = None
        try:
            hello = recv_msg(self.sock)
            if not hello or hello["type"] != "hello":
                return
            self.worker_id = hello["worker_id"]
            send_msg(
                self.sock,
                {
                    "type": "config",
                    "config": cl.config.to_dict(),
                    "seeds": cl.seeds.tolist(),
                },
            )
            self.sock.settimeout(DEADLINE_S)
            # keep serving until the whole run is done: a segment requeued by
            # another worker's failure must find a live owner even if this
            # thread saw an empty queue earlier
            while not cl.all_done.is_set():
                try:
                    seg = cl.queue.get(timeout=0.5)
                except queue.Empty:
                    continue
                if seg.seg_id in cl.done:
                    continue
                # per-attempt span id: rpc.assign here and worker.segment
                # over there carry the same ctx, so the merged trace (and
                # reassignments of the same segment) correlate exactly
                attempt = cl.attempts.get(seg.seg_id, 0)
                ctx = f"{cl.run_id}/{seg.seg_id}.{attempt}"
                current = (seg.seg_id, seg.lo, seg.hi, ctx)
                chaos = cl.chaos is not None and cl.chaos[1] == seg.seg_id \
                    and cl.chaos[0] in (ANY_WORKER, self.worker_id)
                reg = registry()
                t_assign = trace.now_s()
                send_msg(
                    self.sock,
                    {
                        "type": "assign",
                        "seg_id": seg.seg_id,
                        "lo": seg.lo,
                        "hi": seg.hi,
                        "chaos_die": chaos,
                        "run_id": cl.run_id,
                        "ctx": ctx,
                        "t_send": t_assign,
                    },
                )
                while True:
                    msg = recv_msg(self.sock)
                    t_now = trace.now_s()
                    inflight = t_now - t_assign
                    if msg is None:
                        raise ConnectionError("worker closed mid-assignment")
                    if msg["type"] == "progress":
                        # deadline refreshed by settimeout per recv; the
                        # heartbeat also feeds the straggler watermark:
                        # the longest any in-flight assignment has run
                        reg.counter("cluster.heartbeats").inc()
                        reg.gauge(
                            f"cluster.worker{self.worker_id}.inflight_s"
                        ).set(round(inflight, 4))
                        reg.gauge("cluster.straggler_s").max(
                            round(inflight, 4)
                        )
                        trace.instant(
                            "cluster.heartbeat",
                            worker=self.worker_id,
                            seg=seg.seg_id,
                        )
                        if "t_hb" in msg and "t_recv" in msg:
                            cl.clock_sample(
                                self.worker_id, t_assign,
                                msg["t_recv"], msg["t_hb"], t_now,
                            )
                        continue
                    if msg["type"] in ("done", "error"):
                        if "t_reply" in msg and "t_recv" in msg:
                            cl.clock_sample(
                                self.worker_id, t_assign,
                                msg["t_recv"], msg["t_reply"], t_now,
                            )
                        if msg.get("telemetry"):
                            cl.ship(self.worker_id, msg["telemetry"])
                        # one RPC round-trip: assign -> terminal reply
                        trace.add_span(
                            "rpc.assign",
                            t_assign,
                            inflight,
                            worker=self.worker_id,
                            seg=seg.seg_id,
                            ctx=ctx,
                            outcome=msg["type"],
                        )
                        reg.histogram("cluster.rpc_ms").observe(
                            inflight * 1000
                        )
                        reg.gauge(
                            f"cluster.worker{self.worker_id}.inflight_s"
                        ).set(0.0)
                    if msg["type"] == "done":
                        cl.complete(SegmentResult.from_dict(msg["result"]))
                        current = None
                        break
                    if msg["type"] == "error":
                        cl.segment_error(current, msg["error"])
                        current = None
                        break
                    raise ConnectionError(f"unexpected message {msg['type']}")
        except (ConnectionError, OSError, socket.timeout) as e:
            cl.worker_failed(self.worker_id, current, repr(e))
        finally:
            try:
                send_msg(self.sock, {"type": "shutdown"})
            except OSError:
                pass
            self.sock.close()


class _Cluster:
    def __init__(self, config: SieveConfig, seeds, segments, metrics, ledger):
        self.config = config
        self.seeds = seeds
        self.metrics = metrics
        self.ledger = ledger
        self.queue: queue.Queue = queue.Queue()
        self.done: dict[int, SegmentResult] = {}
        self.lock = threading.Lock()
        self.n_expected = len(segments)
        self.all_done = threading.Event()
        self.attempts: dict[int, int] = {}
        self.fatal: str | None = None
        # distributed trace plane: one run id stamps every assign's trace
        # context; shipped telemetry and clock samples accumulate here per
        # worker until the end-of-run merge
        self.run_id = os.urandom(4).hex()
        self.tele_lock = threading.Lock()
        self.telemetry: dict[int, list[dict]] = {}   # worker -> raw events
        self.worker_registry: dict[int, dict] = {}   # latest snapshot
        self.tele_dropped: dict[int, int] = {}       # cumulative per worker
        self.clock: dict[int, _ClockAlign] = {}
        self.chaos: tuple[int, int] | None = None
        if config.chaos_kill:
            k, s = config.chaos_kill.split("@")
            # "any@s": kill whichever worker draws segment s — the pull
            # model makes "k@s" probabilistic, "any@s" deterministic
            self.chaos = (ANY_WORKER if k in ("any", "*") else int(k), int(s))
        for seg in segments:
            self.queue.put(seg)

    def ship(self, worker_id: int, payload: dict) -> None:
        """Accumulate a worker's piggybacked telemetry (raw worker-clock
        events; rebasing happens once, at the end-of-run merge, with the
        final best offset estimate)."""
        with self.tele_lock:
            self.telemetry.setdefault(worker_id, []).extend(
                payload.get("events") or []
            )
            self.worker_registry[worker_id] = payload.get("registry") or {}
            self.tele_dropped[worker_id] = int(payload.get("dropped") or 0)

    def clock_sample(
        self, worker_id: int, t_send, t_remote_recv, t_remote_send, t_done
    ) -> None:
        with self.tele_lock:
            align = self.clock.get(worker_id)
            if align is None:
                align = self.clock[worker_id] = _ClockAlign()
        align.sample(t_send, t_remote_recv, t_remote_send, t_done)

    def complete(self, res: SegmentResult) -> None:
        with self.lock:
            if res.seg_id in self.done:
                return  # idempotent: reassigned segment finished twice
            self.done[res.seg_id] = res
            if self.ledger is not None:
                self.ledger.record(res)
            self.metrics.segment(res)
            if len(self.done) >= self.n_expected:
                self.all_done.set()

    MAX_ATTEMPTS = 4

    def worker_failed(self, worker_id, current, reason: str) -> None:
        # run_id + ctx let trace_report correlate the failure with the
        # exact rpc.assign attempt on the merged timeline (ctx is None
        # for failures between assignments)
        registry().counter("cluster.worker_failures").inc()
        self.metrics.event(
            "worker_failed", worker=worker_id, reason=reason,
            run_id=self.run_id, ctx=current[3] if current else None,
        )
        self._requeue(current, reason)

    def segment_error(self, current, reason: str) -> None:
        """A worker survived but its segment raised: retry elsewhere, abort
        the run if the failure looks deterministic (MAX_ATTEMPTS strikes)."""
        registry().counter("cluster.segment_errors").inc()
        self.metrics.event(
            "segment_error", reason=reason.splitlines()[0],
            run_id=self.run_id, ctx=current[3] if current else None,
        )
        self._requeue(current, reason)

    def _requeue(self, current, reason: str) -> None:
        if current is None:
            return
        seg_id, lo, hi, ctx = current
        with self.lock:
            if seg_id in self.done:
                return
            self.attempts[seg_id] = self.attempts.get(seg_id, 0) + 1
            if self.attempts[seg_id] >= self.MAX_ATTEMPTS:
                self.fatal = (
                    f"segment {seg_id} failed {self.attempts[seg_id]} times; "
                    f"last error: {reason}"
                )
                self.all_done.set()
                return
        from sieve.segments import Segment

        registry().counter("cluster.reassigned").inc()
        self.metrics.event(
            "reassign", seg_id=seg_id, run_id=self.run_id, ctx=ctx
        )
        # one-shot chaos: don't re-kill the replacement owner
        if self.chaos and self.chaos[1] == seg_id:
            self.chaos = None
        self.queue.put(Segment(seg_id=seg_id, lo=lo, hi=hi))


# Merged-trace layout: each worker's events land under a synthetic pid
# (coordinator keeps its real one) so Perfetto shows one process track
# per worker — disjoint from any real OS pid, and collision-free even
# when workers on different hosts share pid numbers.
_WORKER_PID_BASE = 1_000_000


def _merge_worker_telemetry(cluster: _Cluster, metrics: MetricsLogger) -> dict:
    """Rebase + merge every worker's shipped telemetry into the
    coordinator's tracer and registry; returns the summary that rides
    ``SieveResult.host_phases``.

    Rebasing: ``coordinator_time = worker_time - offset`` with the
    per-worker min-RTT NTP offset (error <= RTT/2). Each worker also gets
    a ``clock.align`` instant carrying offset/rtt/err/dropped so
    tools/trace_report.py --cluster can print the alignment report from
    the trace file alone."""
    tr = trace.get_tracer()
    reg = registry()
    merged: list[dict] = []
    total_events = 0
    total_dropped = 0
    max_err = None
    for wid in sorted(set(cluster.telemetry) | set(cluster.clock)):
        events = cluster.telemetry.get(wid, [])
        align = cluster.clock.get(wid)
        off_us = (align.offset_s if align else 0.0) * 1e6
        pid = _WORKER_PID_BASE + wid
        merged.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"worker {wid}"},
        })
        for e in events:
            e = dict(e)
            e["pid"] = pid
            if "ts" in e:
                e["ts"] = round(e["ts"] - off_us, 3)
            merged.append(e)
        dropped = cluster.tele_dropped.get(wid, 0)
        info: dict = {"worker": wid, "events": len(events),
                      "dropped": dropped}
        if align is not None and align.samples:
            info.update(
                offset_s=round(align.offset_s, 6),
                rtt_s=round(align.rtt_s, 6),
                err_s=round(align.err_s, 6),
                samples=align.samples,
            )
            reg.gauge(f"cluster.worker{wid}.clock_offset_s").set(
                round(align.offset_s, 6)
            )
            reg.gauge(f"cluster.worker{wid}.clock_err_s").set(
                round(align.err_s, 6)
            )
            max_err = (
                align.err_s if max_err is None else max(max_err, align.err_s)
            )
        merged.append({
            "name": "clock.align", "ph": "i", "s": "p",
            "ts": round(trace.now_s() * 1e6, 3), "pid": pid, "tid": 0,
            "args": info,
        })
        # worker registry snapshot -> namespaced coordinator gauges, so
        # `registry().snapshot()` covers the whole cluster
        for name, snap in (cluster.worker_registry.get(wid) or {}).items():
            base = f"cluster.worker{wid}.{name}"
            if snap.get("type") in ("counter", "gauge"):
                val = snap.get("value")
                if isinstance(val, (int, float)):
                    reg.gauge(base).set(val)
            elif snap.get("type") == "histogram" and snap.get("count"):
                reg.gauge(f"{base}.count").set(snap["count"])
                reg.gauge(f"{base}.mean").set(round(snap["mean"], 4))
        if dropped:
            reg.counter("cluster.telemetry_dropped").inc(dropped)
        reg.gauge(f"cluster.worker{wid}.telemetry_dropped").set(dropped)
        total_events += len(events)
        total_dropped += dropped
        metrics.event("worker_telemetry", **info)
    if merged:
        tr.ingest(merged)
    summary = {
        "telemetry_workers": sum(
            1 for w, ev in cluster.telemetry.items() if ev
        ),
        "telemetry_events": total_events,
        "telemetry_dropped_events": total_dropped,
    }
    if max_err is not None:
        summary["clock_err_max_s"] = round(max_err, 6)
        reg.gauge("cluster.clock_err_max_s").set(summary["clock_err_max_s"])
    return summary


def run_cluster(config: SieveConfig) -> SieveResult:
    """Coordinator entry: serve assignments, spawn local workers (unless
    SIEVE_CLUSTER_NO_SPAWN=1 for externally-launched / multi-host workers),
    merge results. With ``--trace`` the written file is the *merged*
    cluster timeline: coordinator spans plus every worker's rebased
    spans, one Perfetto process track per worker."""
    cfg = config
    t0 = trace.now_s()
    metrics = MetricsLogger(cfg)
    with trace.span("run.seed", backend=cfg.backend):
        seeds = seed_primes(cfg.seed_limit)
    n_segments = cfg.resolved_n_segments()
    if cfg.n_segments is None and cfg.segment_values is None:
        n_segments = max(cfg.workers * 4, 16)  # sensible default for pull model
    segs = plan_segments(cfg.n, n_segments)
    validate_plan(segs, cfg.n)
    eff = SieveConfig(**{**cfg.to_dict(), "n_segments": len(segs)})

    ledger = Ledger.open(eff) if eff.checkpoint_dir else None
    restored: dict[int, SegmentResult] = {}
    if ledger is not None and eff.resume:
        restored = ledger.completed()
        metrics.event("resume", restored=len(restored))

    todo = [s for s in segs if s.seg_id not in restored]
    cluster = _Cluster(eff, seeds, todo, metrics, ledger)
    trace.instant("cluster.run", run_id=cluster.run_id, workers=eff.workers)
    cluster.done.update(restored)
    if len(cluster.done) >= len(segs):
        cluster.n_expected = len(segs)
        cluster.all_done.set()
    else:
        cluster.n_expected = len(segs)

    host, port = _parse_addr(eff.coordinator_addr)
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind((host, port))
    actual_addr = f"{server.getsockname()[0]}:{server.getsockname()[1]}"
    server.listen(64)
    server.settimeout(0.5)

    procs: list[subprocess.Popen] = []
    if not cluster.all_done.is_set() and not os.environ.get("SIEVE_CLUSTER_NO_SPAWN"):
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for i in range(eff.workers):
            env = {**os.environ, "SIEVE_WORKER_ID": str(i)}
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m", "sieve",
                        "--n", str(eff.n),
                        "--role", "worker",
                        "--coordinator-addr", actual_addr,
                        "--packing", eff.packing,
                    ]
                    + (["--twins"] if eff.twins else []),
                    cwd=repo_root,
                    env=env,
                )
            )

    threads: list[_WorkerConn] = []
    try:
        # Workload-scaled global deadline: the old fixed ~300 s cap aborted
        # honest large-N runs. Budget assumes each worker sustains at least
        # SIEVE_CLUSTER_FLOOR_VPS values/s (default 1e6, ~100x below the
        # measured numpy kernel floor of 1.3e8 — see BASELINE.md), added to
        # the fixed grace for spawn + handshake so tiny runs keep the old
        # behavior.
        floor_vps = float(os.environ.get("SIEVE_CLUSTER_FLOOR_VPS", "1e6"))
        workload_s = eff.n / (floor_vps * max(1, eff.workers))
        # a *duration* budget, not a wall-clock appointment: it rides the
        # monotonic trace clock like every other timestamp (a true wall
        # deadline — e.g. a maintenance-window cutoff — would keep
        # time.time() here, with this comment saying why)
        deadline = trace.now_s() + max(DEADLINE_S * 4, 300) + workload_s
        while not cluster.all_done.is_set():
            if trace.now_s() > deadline:
                raise RuntimeError(
                    f"cluster run timed out with {cluster.n_expected - len(cluster.done)}"
                    f" segments outstanding"
                )
            try:
                sock, _ = server.accept()
            except socket.timeout:
                continue
            conn = _WorkerConn(cluster, sock)
            conn.start()
            threads.append(conn)
        cluster.all_done.wait()
    finally:
        server.close()
        for t in threads:
            t.join(timeout=2)
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    # merge after every conn thread has delivered its last ship(); doing
    # it before the fatal check keeps worker-side context in the trace
    # even when the run aborts
    telemetry = _merge_worker_telemetry(cluster, metrics)
    if cluster.fatal:
        raise RuntimeError(f"cluster run aborted: {cluster.fatal}")
    results = [cluster.done[s.seg_id] for s in segs]
    with trace.span("run.merge"):
        pi, twins = merge_results(eff, results)
    elapsed = trace.now_s() - t0
    result = SieveResult(
        n=eff.n,
        pi=pi,
        twin_pairs=twins,
        backend="cpu-cluster",
        packing=eff.packing,
        n_segments=len(segs),
        elapsed_s=elapsed,
        values_per_sec=(eff.n - 1) / elapsed if elapsed > 0 else float("inf"),
        segments=results,
        host_phases=telemetry,
    )
    metrics.run_summary(result)
    return result
