"""cpu-cluster backend: socket transport, worker processes, fault handling.

SURVEY.md section 3.2 — the reference's main distributed path, preserved
"alongside" the TPU backend (BASELINE.json): a coordinator ships seed
primes + segment assignments to worker processes over TCP and collects
per-segment results; control crosses the network exactly twice per segment
(assign, done). Section 5.3: each assignment carries a deadline refreshed
by progress heartbeats; a dead or silent worker's segment returns to the
queue for a different owner. Results are idempotent (keyed on seg_id), so
double-processing after reassignment cannot double-count.

Wire protocol: the shared length-prefixed JSON framing (sieve/rpc.py,
also used by the query service). Messages:
  worker -> coordinator: {"type": "hello", "worker_id": i, "capacity": c}
                         {"type": "progress", "seg_id", "t_recv", "t_hb"}
                         {"type": "done", "result": SegmentResult dict,
                          "extras": [SegmentResult dict, ..],
                          "ctx", "t_recv", "t_reply", "telemetry"}
  coordinator -> worker: {"type": "config", "config": .., "seeds": [..]}
                         {"type": "assign", "seg_id", "lo", "hi",
                          "extra": [{"seg_id", "lo", "hi", "ctx"}, ..],
                          "chaos_die", "run_id", "ctx", "t_send"}
                         {"type": "shutdown"}

Capacity-scaled assignment (ISSUE 18): the hello handshake advertises a
worker *class* — ``capacity`` is the number of segments the host can
mark in one launch (device count for mesh/jax workers, 1 for scalar CPU
workers; ``SIEVE_WORKER_CAPACITY`` overrides). The coordinator sizes
each assignment with ``_Cluster.assign_batch_size``: capacity is the
ceiling, and the ramp is seeded from the PR 5 straggler/RTT evidence —
half the ceiling until at least 4 attempt samples and a clock alignment
exist, then halved while the projected silent window (p95 × slack ×
batch) would outrun the deadline budget. Extra segments ride the same
``assign`` message (``"extra"``) and come back in the same ``done``
(``"extras"``); the worker computes the whole batch through the
``process_segments`` seam, so a mesh worker pays ONE SPMD round for the
lot. Requeue-on-failure covers every in-flight segment of a batch.

Distributed trace plane: every ``assign`` carries a trace context
(``run_id`` + per-attempt span id ``ctx``) that the worker attaches to
its ``worker.recv``/``worker.segment``/``worker.reply`` spans, so each
coordinator ``rpc.assign`` span correlates 1:1 with the worker-side
spans of that attempt. Replies and heartbeats carry worker-clock
timestamps; the coordinator keeps a min-RTT NTP-style sample per worker
(offset error bounded by RTT/2) and, at end of run, rebases the shipped
worker events onto its own trace epoch and merges them under per-worker
Perfetto process tracks — one ``--trace`` file for the whole cluster.
Telemetry rides the terminal ``done``/``error`` reply (bounded
drop-oldest ring, see sieve/worker.py), so a worker that dies
mid-assignment loses only its unshipped spans.

Elastic membership (ISSUE 6): the coordinator keeps accepting ``hello``s
for the whole run, so workers may join late or rejoin after a drop — each
connection gets the config/seeds handshake and its own serving thread,
and departures drain (requeue + ``worker_left``) without aborting the
run. External workers survive coordinator restarts and network blips by
reconnecting with capped exponential backoff + jitter, and every socket
read is bounded so a dead peer can never park a worker in ``recv``
forever.

Adaptive deadlines: the per-assignment *silence* deadline (how long a
worker may go without any message before it is declared dead) is derived
from live estimates — p95 observed assignment duration × slack and the
worker's min-RTT from the PR 5 clock-alignment samples — floored at the
static ``SIEVE_CLUSTER_DEADLINE_S`` constant and at a few heartbeat
intervals. Heartbeats keep refreshing it, so a slow-but-alive worker is
never falsely declared dead, while operators can drop the static floor
far below the old 60 s for fast dead-worker detection. Every effective
change emits an auditable ``deadline_adjusted`` event.

Fault injection (section 5.3): ``--chaos`` composes a schedule of kills,
reply stalls, heartbeat suppression, and mid-segment disconnects
(sieve/chaos.py); ``--chaos-kill-worker k@s`` remains as the legacy
one-shot kill spelling. Directives ride the ``assign`` message and are
consumed at assign time, so reassigned segments run fault-free.
"""

from __future__ import annotations

import collections
import math
import os
import queue
import random
import socket
import subprocess
import sys
import threading
import time

from sieve import env

import numpy as np

from sieve import trace
from sieve.analysis.lockdebug import named_lock
from sieve.chaos import ANY_WORKER, ChaosSchedule
from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.coordinator import SieveResult, merge_results
from sieve.metrics import MetricsLogger, registry
from sieve.rpc import parse_addr as _parse_addr
from sieve.rpc import recv_msg, send_msg
from sieve.seed import seed_primes
from sieve.segments import plan_segments, validate_plan
from sieve.worker import SegmentResult

HEARTBEAT_S = 1.0
# import-time snapshot kept for backwards compatibility; the live floor
# is _base_deadline_s(), re-read per call so runs/tests can tune it
DEADLINE_S = env.env_float("SIEVE_CLUSTER_DEADLINE_S", 60.0)
_HANDSHAKE_TIMEOUT_S = 30.0


def _base_deadline_s() -> float:
    """Static silence-deadline floor (the pre-adaptive constant)."""
    return env.env_float("SIEVE_CLUSTER_DEADLINE_S", 60.0)


def _worker_recv_timeout_s() -> float:
    """Worker-side bound on any single socket read: an idle worker whose
    coordinator went silent reconnects (or gives up) instead of blocking
    in recv forever."""
    return env.env_float("SIEVE_WORKER_RECV_TIMEOUT_S", 30.0)


# --- worker role -------------------------------------------------------------


def serve_worker(config: SieveConfig, worker_id: int | None = None) -> None:
    """Worker main: connect (and reconnect) to the coordinator, process
    assignments until an explicit shutdown.

    Elastic membership (ISSUE 6): any connection loss — a refused connect
    while the coordinator is still binding, a coordinator restart, a
    chaos-injected mid-segment drop — is retried with capped exponential
    backoff + jitter (``SIEVE_WORKER_BACKOFF_S`` base, doubled per try up
    to ``SIEVE_WORKER_BACKOFF_CAP_S``, at most
    ``SIEVE_WORKER_RECONNECT_MAX`` consecutive failures). Exhausting the
    budget logs to stderr and returns cleanly instead of dying on a
    traceback. Every socket read is bounded by
    ``SIEVE_WORKER_RECV_TIMEOUT_S`` so a dead coordinator can never park
    the worker in ``recv`` forever.
    """
    if worker_id is None:
        worker_id = env.env_int("SIEVE_WORKER_ID", 0)
    host, port = _parse_addr(config.coordinator_addr)
    base = env.env_float("SIEVE_WORKER_BACKOFF_S", 0.1)
    cap = env.env_float("SIEVE_WORKER_BACKOFF_CAP_S", 5.0)
    max_tries = env.env_int("SIEVE_WORKER_RECONNECT_MAX", 6)

    from sieve.worker import telemetry_start

    session = _WorkerSession(config, worker_id, shipping=telemetry_start())
    tries = 0
    try:
        while True:
            err: BaseException | None = None
            sock: socket.socket | None = None
            try:
                sock = socket.create_connection((host, port), timeout=10)
                sock.settimeout(_worker_recv_timeout_s())
                if session.serve(sock):
                    return  # explicit shutdown from the coordinator
                err = ConnectionError("coordinator closed the connection")
            except (ConnectionError, OSError) as e:
                err = e
            finally:
                if sock is not None:
                    sock.close()
            if session.handshaken:
                tries = 0  # a fresh outage after a healthy session
                session.handshaken = False
            tries += 1
            if tries > max_tries:
                print(
                    f"sieve worker {worker_id}: giving up after "
                    f"{tries - 1} reconnect attempts: {err!r}",
                    file=sys.stderr, flush=True,
                )
                return
            # capped exponential backoff + jitter: a fleet retrying a
            # restarted coordinator must not reconnect in lockstep
            delay = min(cap, base * (2 ** (tries - 1)))
            time.sleep(delay * (0.5 + random.random()))
    finally:
        session.close()


class _WorkerSession:
    """Worker-side state that survives reconnects: the compute backend,
    the telemetry-shipping flag, and the last handshake."""

    def __init__(self, config: SieveConfig, worker_id: int, shipping: bool):
        self.config = config
        self.worker_id = worker_id
        self.shipping = shipping
        self.worker = None  # compute backend, created on first config
        self.seeds: np.ndarray | None = None
        self.handshaken = False

    def close(self) -> None:
        if self.worker is not None:
            self.worker.close()

    def serve(self, sock: socket.socket) -> bool:
        """One connected session; True means explicit shutdown (exit)."""
        from sieve.backends import make_worker

        send_msg(sock, {
            "type": "hello", "worker_id": self.worker_id,
            # worker class (ISSUE 18): how many segments this host can
            # mark in one launch; scales coordinator batch sizing
            "capacity": _worker_capacity(),
        })
        try:
            msg = recv_msg(sock)
        except socket.timeout:
            raise ConnectionError("coordinator silent during handshake")
        if msg is None:
            raise ConnectionError("coordinator closed during handshake")
        if msg["type"] == "shutdown":
            return True
        assert msg["type"] == "config", f"bad handshake: {msg}"
        self.handshaken = True
        run_cfg = SieveConfig.from_dict(msg["config"])
        self.seeds = np.asarray(msg["seeds"], dtype=np.int64)
        if self.worker is None:
            compute_cfg = SieveConfig.from_dict(
                {**run_cfg.to_dict(), "backend": _worker_backend()}
            )
            self.worker = make_worker(compute_cfg)
        while True:
            t_wait0 = trace.now_s()
            try:
                msg = recv_msg(sock)
            except socket.timeout:
                # bounded recv: a silent coordinator (dead host, wedged
                # process) can't block us forever — reconnect or give up
                raise ConnectionError(
                    f"no traffic from coordinator for "
                    f"{_worker_recv_timeout_s():.0f}s"
                )
            t_recv = trace.now_s()
            if msg is None:
                raise ConnectionError("coordinator closed the connection")
            if msg["type"] == "shutdown":
                return True
            assert msg["type"] == "assign", msg
            self._assignment(sock, msg, t_wait0, t_recv)

    def _assignment(
        self, sock: socket.socket, msg: dict, t_wait0: float, t_recv: float
    ) -> None:
        worker_id = self.worker_id
        chaos = msg.get("chaos") or []
        if msg.get("chaos_die") or any(c["kind"] == "kill" for c in chaos):
            os._exit(17)  # simulated hard crash, no cleanup
        ctx = msg.get("ctx")
        # idle-wait + message receive: the worker-side view of "no
        # work assigned" that per-host idle accounting needs
        trace.add_span(
            "worker.recv", t_wait0, t_recv - t_wait0,
            seg=msg["seg_id"], worker=worker_id, ctx=ctx,
        )
        reg = registry()
        reg.histogram("worker.recv_wait_ms").observe(
            round((t_recv - t_wait0) * 1000, 3)
        )
        disconnect = next(
            (c for c in chaos if c["kind"] == "disconnect"), None
        )
        if disconnect is not None:
            # mid-segment network blip: the assignment is in flight, the
            # connection drops, the coordinator requeues, we reconnect
            time.sleep(float(disconnect.get("param") or 0.05))
            raise ConnectionError("chaos: injected mid-segment disconnect")
        drop_hb = any(c["kind"] == "drop_hb" for c in chaos)
        stall_s = max(
            (float(c.get("param") or 1.0)
             for c in chaos if c["kind"] == "stall"),
            default=0.0,
        )
        # capacity batch (ISSUE 18): the assignment may carry extra
        # segments for a high-capacity worker; the whole batch goes
        # through the process_segments seam, so a mesh/jax backend pays
        # one SPMD launch for the lot instead of one per segment
        batch = [(msg["seg_id"], msg["lo"], msg["hi"])] + [
            (e["seg_id"], e["lo"], e["hi"]) for e in msg.get("extra") or []
        ]
        result: list[SegmentResult] = []
        failure: list[str] = []

        def _work(m=msg, ctx=ctx, batch=batch):
            try:
                raise_seg = env.env_str("SIEVE_CHAOS_RAISE")
                if any(raise_seg == str(sid) for sid, _, _ in batch):
                    raise RuntimeError("chaos: injected segment failure")
                with trace.span(
                    "worker.segment",
                    seg=m["seg_id"], worker=worker_id, ctx=ctx,
                    batch=len(batch),
                ):
                    result.extend(
                        self.worker.process_segments(
                            [(lo, hi) for _, lo, hi in batch],
                            self.seeds,
                            seg_ids=[sid for sid, _, _ in batch],
                        )
                    )
            except Exception as e:  # report, don't die: the coordinator
                import traceback     # decides whether to retry or abort

                failure.append(f"{e!r}\n{traceback.format_exc()}")

        t = threading.Thread(target=_work, daemon=True)
        t.start()
        while t.is_alive():
            t.join(HEARTBEAT_S)
            if t.is_alive() and not drop_hb:
                # t_recv/t_hb give the coordinator a payload-free NTP
                # sample mid-assignment (long segments refresh the
                # clock offset without waiting for the reply)
                send_msg(sock, {
                    "type": "progress", "seg_id": msg["seg_id"],
                    "t_recv": t_recv, "t_hb": trace.now_s(),
                })
        if stall_s:
            # silent straggle: compute is done, heartbeats have stopped,
            # the reply is late — the adaptive silence deadline must ride
            # this out without declaring us dead
            time.sleep(stall_s)
        if failure:
            reg.counter("worker.segment_errors").inc()
            reply = {
                "type": "error", "seg_id": msg["seg_id"],
                "error": failure[0],
            }
        else:
            res = result[0]
            reg.counter("worker.segments_done").inc(len(result))
            for r in result:
                reg.histogram("worker.segment_ms").observe(
                    round(r.elapsed_s * 1000, 3)
                )
            reply = {"type": "done", "result": res.to_dict()}
            if len(result) > 1:
                reply["extras"] = [r.to_dict() for r in result[1:]]
        reply["ctx"] = ctx
        reply["t_recv"] = t_recv
        if self.shipping:
            from sieve.worker import telemetry_payload

            # piggyback: this drains worker.recv + worker.segment of
            # THIS attempt (plus any earlier worker.reply) — a span
            # that closes after the send ships on the next reply
            reply["telemetry"] = telemetry_payload(worker_id)
        t_reply = trace.now_s()
        reply["t_reply"] = t_reply
        send_msg(sock, reply)
        trace.add_span(
            "worker.reply", t_reply, trace.now_s() - t_reply,
            seg=msg["seg_id"], worker=worker_id, ctx=ctx,
        )


def _worker_backend() -> str:
    """Compute backend used inside cluster workers: native if it builds."""
    forced = env.env_str("SIEVE_CLUSTER_WORKER_BACKEND")
    if forced:
        return forced
    try:
        from sieve.backends.cpu_native import _build_and_load

        _build_and_load()
        return "cpu-native"
    except Exception:
        return "cpu-numpy"


def _worker_capacity() -> int:
    """Worker class advertised in the hello handshake (ISSUE 18): the
    number of segments this host can mark in one launch.

    ``SIEVE_WORKER_CAPACITY`` forces it (operators and tests); otherwise
    device-backed workers (jax / tpu-pallas / mesh) advertise their
    device count — one chunk per chip per SPMD round — and scalar CPU
    workers advertise 1, which keeps the coordinator's sizing identical
    to the pre-capacity protocol for a classic fleet."""
    forced = env.env_int("SIEVE_WORKER_CAPACITY", 0)
    if forced > 0:
        return forced
    if _worker_backend() in ("jax", "tpu-pallas", "mesh"):
        try:
            import jax

            return max(1, jax.device_count())
        except Exception:
            return 1
    return 1


# --- coordinator role --------------------------------------------------------


def _ctx_of(current) -> str | None:
    """Primary trace context of an in-flight assignment (batch or single)."""
    if not current:
        return None
    if isinstance(current, list):
        return current[0][3]
    return current[3]


# Per-worker clock-offset estimation moved to trace.ClockAlign so the
# service router (sieve/service/router.py) shares the same estimator;
# kept under the old name for callers and tests.
_ClockAlign = trace.ClockAlign


class _WorkerConn(threading.Thread):
    """One coordinator-side thread per connected worker: assigns segments
    from the shared queue, enforces the adaptive silence deadline,
    requeues on failure, and reports membership (join/leave) to the
    cluster."""

    def __init__(self, cluster: "_Cluster", sock: socket.socket):
        super().__init__(daemon=True)
        self.cluster = cluster
        self.sock = sock
        self.worker_id = -1

    def run(self) -> None:
        cl = self.cluster
        # [(seg_id, lo, hi, ctx), ..]: the in-flight assignment batch +
        # per-segment trace contexts, so failure events correlate with
        # the timeline and a dead worker requeues its WHOLE batch
        current: list[tuple[int, int, int, str]] | None = None
        joined = False
        leave_reason = "run complete"
        try:
            self.sock.settimeout(_HANDSHAKE_TIMEOUT_S)
            hello = recv_msg(self.sock)
            if not hello or hello["type"] != "hello":
                return
            self.worker_id = hello["worker_id"]
            # worker class (ISSUE 18): absent on old workers -> 1, which
            # reproduces the classic one-segment-per-RPC protocol
            cl.set_capacity(self.worker_id, hello.get("capacity", 1))
            send_msg(
                self.sock,
                {
                    "type": "config",
                    "config": cl.config.to_dict(),
                    "seeds": cl.seeds.tolist(),
                },
            )
            # membership: a hello at any point in the run is a join — late
            # arrivals and post-drop rejoins get the same handshake
            cl.worker_joined(self.worker_id)
            joined = True
            # keep serving until the whole run is done: a segment requeued by
            # another worker's failure must find a live owner even if this
            # thread saw an empty queue earlier
            while not cl.all_done.is_set():
                try:
                    seg = cl.queue.get(timeout=0.5)
                except queue.Empty:
                    continue
                if seg.seg_id in cl.done:
                    continue
                # capacity-scaled batch (ISSUE 18): a high-capacity
                # worker (mesh/jax host) pulls extra segments so one RPC
                # round feeds every chip; get_nowait never blocks, so a
                # thin queue degrades to the classic one-segment assign
                segs = [seg]
                want = cl.assign_batch_size(self.worker_id)
                while len(segs) < want:
                    try:
                        nxt = cl.queue.get_nowait()
                    except queue.Empty:
                        break
                    if nxt.seg_id in cl.done:
                        continue
                    segs.append(nxt)
                # per-attempt span id: rpc.assign here and worker.segment
                # over there carry the same ctx, so the merged trace (and
                # reassignments of the same segment) correlate exactly
                current = []
                for s in segs:
                    attempt = cl.attempts.get(s.seg_id, 0)
                    current.append(
                        (s.seg_id, s.lo, s.hi,
                         f"{cl.run_id}/{s.seg_id}.{attempt}")
                    )
                ctx = current[0][3]
                chaos = []
                for s in segs:
                    chaos.extend(cl.chaos.take(self.worker_id, s.seg_id))
                # adaptive silence deadline: any message (heartbeat or
                # reply) refreshes it via settimeout-per-recv, so only a
                # *silent* worker can breach it
                deadline_s = cl.assign_deadline_s(self.worker_id)
                self.sock.settimeout(deadline_s)
                reg = registry()
                t_assign = trace.now_s()
                send_msg(
                    self.sock,
                    {
                        "type": "assign",
                        "seg_id": seg.seg_id,
                        "lo": seg.lo,
                        "hi": seg.hi,
                        "chaos": chaos,
                        "chaos_die": any(
                            c["kind"] == "kill" for c in chaos
                        ),
                        "run_id": cl.run_id,
                        "ctx": ctx,
                        "t_send": t_assign,
                        "extra": [
                            {"seg_id": sid, "lo": lo, "hi": hi, "ctx": c}
                            for sid, lo, hi, c in current[1:]
                        ],
                    },
                )
                while True:
                    try:
                        msg = recv_msg(self.sock)
                    except socket.timeout:
                        raise ConnectionError(
                            f"worker {self.worker_id} silent for "
                            f"{deadline_s:.1f}s on segment {seg.seg_id} "
                            f"(adaptive deadline)"
                        )
                    t_now = trace.now_s()
                    inflight = t_now - t_assign
                    if msg is None:
                        raise ConnectionError("worker closed mid-assignment")
                    if msg["type"] == "progress":
                        # deadline refreshed by settimeout per recv; the
                        # heartbeat also feeds the straggler watermark:
                        # the longest any in-flight assignment has run
                        reg.counter("cluster.heartbeats").inc()
                        reg.gauge(
                            f"cluster.worker{self.worker_id}.inflight_s"
                        ).set(round(inflight, 4))
                        reg.gauge("cluster.straggler_s").max(
                            round(inflight, 4)
                        )
                        trace.instant(
                            "cluster.heartbeat",
                            worker=self.worker_id,
                            seg=seg.seg_id,
                        )
                        if "t_hb" in msg and "t_recv" in msg:
                            cl.clock_sample(
                                self.worker_id, t_assign,
                                msg["t_recv"], msg["t_hb"], t_now,
                            )
                        continue
                    if msg["type"] in ("done", "error"):
                        if "t_reply" in msg and "t_recv" in msg:
                            cl.clock_sample(
                                self.worker_id, t_assign,
                                msg["t_recv"], msg["t_reply"], t_now,
                            )
                        if msg.get("telemetry"):
                            cl.ship(self.worker_id, msg["telemetry"])
                        # one RPC round-trip: assign -> terminal reply
                        trace.add_span(
                            "rpc.assign",
                            t_assign,
                            inflight,
                            worker=self.worker_id,
                            seg=seg.seg_id,
                            ctx=ctx,
                            outcome=msg["type"],
                            batch=len(current),
                        )
                        reg.histogram("cluster.rpc_ms").observe(
                            inflight * 1000
                        )
                        reg.gauge(
                            f"cluster.worker{self.worker_id}.inflight_s"
                        ).set(0.0)
                    if msg["type"] == "done":
                        # per-segment duration feeds the deadline model:
                        # a batched round is one wire round-trip but
                        # len(current) segments of compute
                        cl.observe_attempt(inflight / max(1, len(current)))
                        cl.complete(SegmentResult.from_dict(msg["result"]))
                        for r in msg.get("extras") or []:
                            cl.complete(SegmentResult.from_dict(r))
                        current = None
                        break
                    if msg["type"] == "error":
                        cl.observe_attempt(inflight / max(1, len(current)))
                        cl.segment_error(current, msg["error"])
                        current = None
                        break
                    raise ConnectionError(f"unexpected message {msg['type']}")
        except (ConnectionError, OSError, socket.timeout) as e:
            leave_reason = repr(e)
            cl.worker_failed(self.worker_id, current, leave_reason)
        finally:
            # only tell the worker to exit when the run is over: a worker
            # dropped for a deadline breach (or any transport error) may
            # still be alive and should reconnect, not terminate
            if cl.all_done.is_set():
                try:
                    send_msg(self.sock, {"type": "shutdown"})
                except OSError:
                    pass
            self.sock.close()
            if joined:
                cl.worker_left(self.worker_id, leave_reason)


class _Cluster:
    def __init__(self, config: SieveConfig, seeds, segments, metrics, ledger):
        self.config = config
        self.seeds = seeds
        self.metrics = metrics
        self.ledger = ledger
        self.queue: queue.Queue = queue.Queue()
        self.done: dict[int, SegmentResult] = {}
        self.lock = named_lock("_Cluster.lock")
        self.n_expected = len(segments)
        self.all_done = threading.Event()
        self.attempts: dict[int, int] = {}
        self.fatal: str | None = None
        # distributed trace plane: one run id stamps every assign's trace
        # context; shipped telemetry and clock samples accumulate here per
        # worker until the end-of-run merge
        self.run_id = os.urandom(4).hex()
        self.tele_lock = named_lock("_Cluster.tele_lock")
        self.telemetry: dict[int, list[dict]] = {}   # worker -> raw events
        self.worker_registry: dict[int, dict] = {}   # latest snapshot
        self.tele_dropped: dict[int, int] = {}       # cumulative per worker
        self.clock: dict[int, _ClockAlign] = {}
        # composable fault-injection schedule (sieve/chaos.py); directives
        # are consumed at assign time, so requeued segments run fault-free
        self.chaos = ChaosSchedule(config.chaos_directives())
        # membership + adaptive-deadline state: recent attempt durations
        # feed the p95 term; joins/leaves feed events and the run summary
        self._attempt_s: collections.deque = collections.deque(maxlen=256)
        self._deadline_last: float | None = None
        self._active_workers = 0
        # worker class from the hello handshake (ISSUE 18): ceiling for
        # assign_batch_size, per connected worker id
        self.worker_capacity: dict[int, int] = {}  # guard: lock
        self.joins = 0
        self.leaves = 0
        for seg in segments:
            self.queue.put(seg)

    # --- membership + adaptive deadline --------------------------------------

    def worker_joined(self, worker_id: int) -> None:
        with self.lock:
            self._active_workers += 1
            self.joins += 1
            active = self._active_workers
        registry().counter("cluster.worker_joins").inc()
        registry().gauge("cluster.active_workers").set(active)
        self.metrics.event(
            "worker_joined", worker=worker_id, run_id=self.run_id,
            active=active,
        )
        trace.instant(
            "cluster.worker_joined", worker=worker_id, active=active
        )

    def worker_left(self, worker_id: int, reason: str) -> None:
        with self.lock:
            self._active_workers -= 1
            self.leaves += 1
            active = self._active_workers
        registry().counter("cluster.worker_leaves").inc()
        registry().gauge("cluster.active_workers").set(active)
        self.metrics.event(
            "worker_left", worker=worker_id, reason=reason.splitlines()[0],
            run_id=self.run_id, active=active,
        )
        trace.instant(
            "cluster.worker_left", worker=worker_id, active=active
        )

    def set_capacity(self, worker_id: int, capacity) -> None:
        """Record a worker's advertised class from the hello handshake."""
        try:
            cap = max(1, int(capacity))
        except (TypeError, ValueError):
            cap = 1  # malformed hello never breaks assignment
        with self.lock:
            self.worker_capacity[worker_id] = cap
        registry().gauge(f"cluster.worker{worker_id}.capacity").set(cap)

    def assign_batch_size(self, worker_id: int) -> int:
        """Segments per assignment for ``worker_id`` (ISSUE 18).

        Capacity — the worker's advertised device count — is the
        ceiling: a mesh-backed host marks ``capacity`` chunks in one
        SPMD launch, so handing it fewer wastes chips. The ramp is
        seeded from the PR 5 straggler/RTT evidence: with under 4
        attempt samples or no clock alignment yet, hand out half the
        ceiling (a misadvertised fat worker cannot starve the queue
        before the model has data); once evidence exists, halve the
        batch while the projected silent window (p95 × slack × batch)
        would exceed the deadline budget (static floor vs 8× min-RTT),
        so batching never outruns the straggler detector."""
        with self.lock:
            cap = self.worker_capacity.get(worker_id, 1)
            samples = sorted(self._attempt_s)
        if cap <= 1:
            return 1
        align = self.clock.get(worker_id)
        if len(samples) < 4 or align is None or not align.samples:
            return max(1, cap // 2)
        slack = env.env_float("SIEVE_CLUSTER_DEADLINE_SLACK", 4.0)
        p95 = samples[min(len(samples) - 1, math.ceil(0.95 * len(samples)) - 1)]
        budget = max(_base_deadline_s(), align.rtt_s * 8)
        batch = cap
        while batch > 1 and p95 * slack * batch > budget:
            batch //= 2
        return max(1, batch)

    def observe_attempt(self, dur_s: float) -> None:
        """Feed one completed assignment's duration to the deadline model."""
        with self.lock:
            self._attempt_s.append(dur_s)

    def assign_deadline_s(self, worker_id: int) -> float:
        """Silence deadline for one assignment to ``worker_id``.

        max of: the static floor (``SIEVE_CLUSTER_DEADLINE_S``), a few
        heartbeat intervals (``SIEVE_CLUSTER_HB_MISS``, so a worker is
        never declared dead for missing fewer than that many heartbeats),
        p95 observed attempt duration × ``SIEVE_CLUSTER_DEADLINE_SLACK``
        (a straggler still sending heartbeats keeps refreshing; this term
        covers the worst *silent* gap a healthy segment produces), and
        8× the worker's min-RTT (transport jitter). Operators lower the
        static floor for fast dead-worker detection; the live terms keep
        it safe."""
        hb_miss = env.env_float("SIEVE_CLUSTER_HB_MISS", 4.0)
        slack = env.env_float("SIEVE_CLUSTER_DEADLINE_SLACK", 4.0)
        with self.lock:
            samples = sorted(self._attempt_s)
        p95 = 0.0
        if len(samples) >= 4:
            p95 = samples[min(len(samples) - 1, math.ceil(0.95 * len(samples)) - 1)]
        align = self.clock.get(worker_id)
        rtt = align.rtt_s if align is not None and align.samples else 0.0
        deadline = max(
            _base_deadline_s(),
            HEARTBEAT_S * hb_miss,
            p95 * slack,
            rtt * 8,
        )
        self._note_deadline(deadline, p95)
        return deadline

    def _note_deadline(self, deadline_s: float, p95_s: float) -> None:
        """Audit trail: emit ``deadline_adjusted`` on the first computed
        deadline and on every >20% change since the last emission."""
        with self.lock:
            prev = self._deadline_last
            if prev is not None and abs(deadline_s - prev) <= 0.2 * prev:
                return
            self._deadline_last = deadline_s
        registry().gauge("cluster.deadline_s").set(round(deadline_s, 3))
        self.metrics.event(
            "deadline_adjusted",
            deadline_s=round(deadline_s, 3),
            prev_s=round(prev, 3) if prev is not None else None,
            p95_s=round(p95_s, 3),
            run_id=self.run_id,
        )
        trace.instant(
            "cluster.deadline_adjusted",
            deadline_s=round(deadline_s, 3),
            prev_s=round(prev, 3) if prev is not None else None,
        )

    def ship(self, worker_id: int, payload: dict) -> None:
        """Accumulate a worker's piggybacked telemetry (raw worker-clock
        events; rebasing happens once, at the end-of-run merge, with the
        final best offset estimate)."""
        with self.tele_lock:
            self.telemetry.setdefault(worker_id, []).extend(
                payload.get("events") or []
            )
            self.worker_registry[worker_id] = payload.get("registry") or {}
            self.tele_dropped[worker_id] = int(payload.get("dropped") or 0)

    def clock_sample(
        self, worker_id: int, t_send, t_remote_recv, t_remote_send, t_done
    ) -> None:
        with self.tele_lock:
            align = self.clock.get(worker_id)
            if align is None:
                align = self.clock[worker_id] = _ClockAlign()
        align.sample(t_send, t_remote_recv, t_remote_send, t_done)

    def complete(self, res: SegmentResult) -> None:
        with self.lock:
            if res.seg_id in self.done:
                return  # idempotent: reassigned segment finished twice
            self.done[res.seg_id] = res
            if self.ledger is not None:
                self.ledger.record(res)
            self.metrics.segment(res)
            if len(self.done) >= self.n_expected:
                self.all_done.set()

    MAX_ATTEMPTS = 4

    def worker_failed(self, worker_id, current, reason: str) -> None:
        # run_id + ctx let trace_report correlate the failure with the
        # exact rpc.assign attempt on the merged timeline (ctx is None
        # for failures between assignments)
        registry().counter("cluster.worker_failures").inc()
        self.metrics.event(
            "worker_failed", worker=worker_id, reason=reason,
            run_id=self.run_id, ctx=_ctx_of(current),
        )
        self._requeue(current, reason)

    def segment_error(self, current, reason: str) -> None:
        """A worker survived but its segment raised: retry elsewhere, abort
        the run if the failure looks deterministic (MAX_ATTEMPTS strikes)."""
        registry().counter("cluster.segment_errors").inc()
        self.metrics.event(
            "segment_error", reason=reason.splitlines()[0],
            run_id=self.run_id, ctx=_ctx_of(current),
        )
        self._requeue(current, reason)

    def _requeue(self, current, reason: str) -> None:
        if current is None:
            return
        if isinstance(current, list):
            # capacity batch (ISSUE 18): every in-flight segment of a
            # failed batched assignment goes back, each with its own
            # attempt count — one flaky fat worker costs one strike per
            # segment, exactly like n sequential failures would
            for item in current:
                self._requeue(item, reason)
            return
        seg_id, lo, hi, ctx = current
        with self.lock:
            if seg_id in self.done:
                return
            self.attempts[seg_id] = self.attempts.get(seg_id, 0) + 1
            if self.attempts[seg_id] >= self.MAX_ATTEMPTS:
                self.fatal = (
                    f"segment {seg_id} failed {self.attempts[seg_id]} times; "
                    f"last error: {reason}"
                )
                self.all_done.set()
                return
        from sieve.segments import Segment

        registry().counter("cluster.reassigned").inc()
        self.metrics.event(
            "reassign", seg_id=seg_id, run_id=self.run_id, ctx=ctx
        )
        self.queue.put(Segment(seg_id=seg_id, lo=lo, hi=hi))


# Merged-trace layout: each worker's events land under a synthetic pid
# (coordinator keeps its real one) so Perfetto shows one process track
# per worker — disjoint from any real OS pid, and collision-free even
# when workers on different hosts share pid numbers.
_WORKER_PID_BASE = 1_000_000


def _merge_worker_telemetry(cluster: _Cluster, metrics: MetricsLogger) -> dict:
    """Rebase + merge every worker's shipped telemetry into the
    coordinator's tracer and registry; returns the summary that rides
    ``SieveResult.host_phases``.

    Rebasing: ``coordinator_time = worker_time - offset`` with the
    per-worker min-RTT NTP offset (error <= RTT/2). Each worker also gets
    a ``clock.align`` instant carrying offset/rtt/err/dropped so
    tools/trace_report.py --cluster can print the alignment report from
    the trace file alone."""
    tr = trace.get_tracer()
    reg = registry()
    merged: list[dict] = []
    total_events = 0
    total_dropped = 0
    max_err = None
    for wid in sorted(set(cluster.telemetry) | set(cluster.clock)):
        events = cluster.telemetry.get(wid, [])
        align = cluster.clock.get(wid)
        off_us = (align.offset_s if align else 0.0) * 1e6
        pid = _WORKER_PID_BASE + wid
        merged.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"worker {wid}"},
        })
        for e in events:
            e = dict(e)
            e["pid"] = pid
            if "ts" in e:
                e["ts"] = round(e["ts"] - off_us, 3)
            merged.append(e)
        dropped = cluster.tele_dropped.get(wid, 0)
        info: dict = {"worker": wid, "events": len(events),
                      "dropped": dropped}
        if align is not None and align.samples:
            info.update(
                offset_s=round(align.offset_s, 6),
                rtt_s=round(align.rtt_s, 6),
                err_s=round(align.err_s, 6),
                samples=align.samples,
            )
            reg.gauge(f"cluster.worker{wid}.clock_offset_s").set(
                round(align.offset_s, 6)
            )
            reg.gauge(f"cluster.worker{wid}.clock_err_s").set(
                round(align.err_s, 6)
            )
            max_err = (
                align.err_s if max_err is None else max(max_err, align.err_s)
            )
        merged.append({
            "name": "clock.align", "ph": "i", "s": "p",
            "ts": round(trace.now_s() * 1e6, 3), "pid": pid, "tid": 0,
            "args": info,
        })
        # worker registry snapshot -> namespaced coordinator gauges, so
        # `registry().snapshot()` covers the whole cluster
        for name, snap in (cluster.worker_registry.get(wid) or {}).items():
            base = f"cluster.worker{wid}.{name}"
            if snap.get("type") in ("counter", "gauge"):
                val = snap.get("value")
                if isinstance(val, (int, float)):
                    reg.gauge(base).set(val)
            elif snap.get("type") == "histogram" and snap.get("count"):
                reg.gauge(f"{base}.count").set(snap["count"])
                reg.gauge(f"{base}.mean").set(round(snap["mean"], 4))
        if dropped:
            reg.counter("cluster.telemetry_dropped").inc(dropped)
        reg.gauge(f"cluster.worker{wid}.telemetry_dropped").set(dropped)
        total_events += len(events)
        total_dropped += dropped
        metrics.event("worker_telemetry", **info)
    if merged:
        tr.ingest(merged)
    summary = {
        "telemetry_workers": sum(
            1 for w, ev in cluster.telemetry.items() if ev
        ),
        "telemetry_events": total_events,
        "telemetry_dropped_events": total_dropped,
        "workers_joined": cluster.joins,
        "workers_left": cluster.leaves,
    }
    if max_err is not None:
        summary["clock_err_max_s"] = round(max_err, 6)
        reg.gauge("cluster.clock_err_max_s").set(summary["clock_err_max_s"])
    return summary


def run_cluster(config: SieveConfig) -> SieveResult:
    """Coordinator entry: serve assignments, spawn local workers (unless
    SIEVE_CLUSTER_NO_SPAWN=1 for externally-launched / multi-host workers),
    merge results. With ``--trace`` the written file is the *merged*
    cluster timeline: coordinator spans plus every worker's rebased
    spans, one Perfetto process track per worker."""
    cfg = config
    t0 = trace.now_s()
    metrics = MetricsLogger(cfg)
    with trace.span("run.seed", backend=cfg.backend):
        seeds = seed_primes(cfg.seed_limit)
    n_segments = cfg.resolved_n_segments()
    if cfg.n_segments is None and cfg.segment_values is None:
        n_segments = max(cfg.workers * 4, 16)  # sensible default for pull model
    segs = plan_segments(cfg.n, n_segments)
    validate_plan(segs, cfg.n)
    eff = SieveConfig(**{**cfg.to_dict(), "n_segments": len(segs)})

    ledger = Ledger.open(eff) if eff.checkpoint_dir else None
    if ledger is not None and ledger.salvaged:
        metrics.event(
            "ledger_salvaged", salvaged=ledger.salvaged,
            quarantined=ledger.quarantined,
        )
    restored: dict[int, SegmentResult] = {}
    if ledger is not None and eff.resume:
        restored = ledger.completed()
        metrics.event("resume", restored=len(restored))

    todo = [s for s in segs if s.seg_id not in restored]
    cluster = _Cluster(eff, seeds, todo, metrics, ledger)
    trace.instant("cluster.run", run_id=cluster.run_id, workers=eff.workers)
    cluster.done.update(restored)
    if len(cluster.done) >= len(segs):
        cluster.n_expected = len(segs)
        cluster.all_done.set()
    else:
        cluster.n_expected = len(segs)

    host, port = _parse_addr(eff.coordinator_addr)
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind((host, port))
    actual_addr = f"{server.getsockname()[0]}:{server.getsockname()[1]}"
    server.listen(64)
    server.settimeout(0.5)

    procs: list[subprocess.Popen] = []
    if not cluster.all_done.is_set() and not env.env_str("SIEVE_CLUSTER_NO_SPAWN"):
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for i in range(eff.workers):
            wenv = {**os.environ, "SIEVE_WORKER_ID": str(i)}
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m", "sieve",
                        "--n", str(eff.n),
                        "--role", "worker",
                        "--coordinator-addr", actual_addr,
                        "--packing", eff.packing,
                    ]
                    + (["--twins"] if eff.twins else []),
                    cwd=repo_root,
                    env=wenv,
                )
            )

    threads: list[_WorkerConn] = []
    try:
        # Workload-scaled global deadline: the old fixed ~300 s cap aborted
        # honest large-N runs. Budget assumes each worker sustains at least
        # SIEVE_CLUSTER_FLOOR_VPS values/s (default 1e6, ~100x below the
        # measured numpy kernel floor of 1.3e8 — see BASELINE.md), added to
        # the fixed grace for spawn + handshake so tiny runs keep the old
        # behavior.
        floor_vps = env.env_float("SIEVE_CLUSTER_FLOOR_VPS", 1e6)
        workload_s = eff.n / (floor_vps * max(1, eff.workers))
        # a *duration* budget, not a wall-clock appointment: it rides the
        # monotonic trace clock like every other timestamp (a true wall
        # deadline — e.g. a maintenance-window cutoff — would keep
        # time.time() here, with this comment saying why)
        deadline = trace.now_s() + max(_base_deadline_s() * 4, 300) + workload_s
        while not cluster.all_done.is_set():
            if trace.now_s() > deadline:
                raise RuntimeError(
                    f"cluster run timed out with {cluster.n_expected - len(cluster.done)}"
                    f" segments outstanding"
                )
            try:
                sock, _ = server.accept()
            except socket.timeout:
                continue
            conn = _WorkerConn(cluster, sock)
            conn.start()
            threads.append(conn)
        cluster.all_done.wait()
    finally:
        server.close()
        for t in threads:
            t.join(timeout=2)
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    # merge after every conn thread has delivered its last ship(); doing
    # it before the fatal check keeps worker-side context in the trace
    # even when the run aborts
    telemetry = _merge_worker_telemetry(cluster, metrics)
    if cluster.fatal:
        raise RuntimeError(f"cluster run aborted: {cluster.fatal}")
    results = [cluster.done[s.seg_id] for s in segs]
    with trace.span("run.merge"):
        pi, twins = merge_results(eff, results)
    elapsed = trace.now_s() - t0
    result = SieveResult(
        n=eff.n,
        pi=pi,
        twin_pairs=twins,
        backend="cpu-cluster",
        packing=eff.packing,
        n_segments=len(segs),
        elapsed_s=elapsed,
        values_per_sec=(eff.n - 1) / elapsed if elapsed > 0 else float("inf"),
        segments=results,
        host_phases=telemetry,
    )
    metrics.run_summary(result)
    return result
