"""Typed run configuration shared by every role and backend.

SURVEY.md section 2 ("Config system"): one frozen dataclass, serializable,
everything on the config and nothing ambient. The CLI (sieve/cli.py) maps
flags 1:1 onto these fields.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Any

PACKINGS = ("plain", "odds", "wheel30")
BACKENDS = ("cpu-numpy", "cpu-native", "cpu-cluster", "jax", "tpu-pallas")
# --count-kind: which reduction runs on the marked bitset. All kinds share
# the same marking specs/kernels — only the splice shift and pair mask at
# the reduction differ (primes = count only; twins = p, p+2; cousins =
# p, p+4). The gap between PAIR_GAPS entries is what the device splices.
COUNT_KINDS = ("primes", "twins", "cousins")
PAIR_GAPS = {"primes": 0, "twins": 2, "cousins": 4}


@dataclasses.dataclass(frozen=True)
class SieveConfig:
    """Configuration for one sieve run.

    ``n`` is inclusive: the run computes pi(n) (= count of primes in [2, n]).
    Internally every range is half-open [lo, hi) with the global range being
    [2, n + 1).
    """

    n: int
    backend: str = "cpu-numpy"
    packing: str = "odds"
    # Segmentation: give either a segment count or a per-segment value span.
    n_segments: int | None = None
    segment_values: int | None = None
    twins: bool = False
    # Pair-counting plug point: "primes" (count only), "twins" (p, p+2),
    # "cousins" (p, p+4). ``twins=True`` is kept as the legacy spelling of
    # count_kind="twins"; __post_init__ normalizes the two fields so either
    # spelling yields the same config.
    count_kind: str = "primes"
    # Workers / devices.
    workers: int = 1
    # Checkpoint / resume (SURVEY.md section 5.4).
    checkpoint_dir: str | None = None
    resume: bool = False
    # Rounds: TPU dispatch granularity for failure recovery (section 5.3).
    rounds: int = 1
    # Multi-host SPMD over DCN (SURVEY.md section 5.8): when True the CLI
    # calls jax.distributed.initialize() before touching devices; workers
    # must equal the GLOBAL device count.
    multihost: bool = False
    # Observability. ``trace_file`` writes a Chrome trace-event JSON of
    # host-side spans (sieve/trace.py); ``metrics_file`` appends every
    # metrics event as JSONL regardless of --quiet. Neither affects the
    # math (both are excluded from config_hash like the rest).
    profile_dir: str | None = None
    trace_file: str | None = None
    metrics_file: str | None = None
    quiet: bool = False
    json_output: bool = False
    # Fault injection (section 5.3). ``chaos`` is the composable schedule
    # ("kill:1@s4,stall:2@s7:3.0,drop_hb:any@s9,disconnect:0@s2", see
    # sieve/chaos.py); ``chaos_kill`` is the legacy one-shot spelling
    # "k@s" kept as shorthand for "kill:k@s<s>". Both may be given; they
    # merge into one schedule via :meth:`chaos_directives`.
    chaos: str | None = None
    chaos_kill: str | None = None
    # cpu-cluster transport endpoints.
    coordinator_addr: str = "127.0.0.1:7621"

    def __post_init__(self) -> None:
        if self.n < 2:
            raise ValueError(f"n must be >= 2, got {self.n}")
        if self.packing not in PACKINGS:
            raise ValueError(f"packing must be one of {PACKINGS}, got {self.packing!r}")
        if self.backend not in BACKENDS:
            raise ValueError(f"backend must be one of {BACKENDS}, got {self.backend!r}")
        if self.n_segments is not None and self.n_segments < 1:
            raise ValueError("n_segments must be >= 1")
        if self.segment_values is not None and self.segment_values < 4:
            raise ValueError("segment_values must be >= 4")
        if self.workers < 1:
            raise ValueError("workers must be >= 1")
        if self.count_kind not in COUNT_KINDS:
            raise ValueError(
                f"count_kind must be one of {COUNT_KINDS}, got "
                f"{self.count_kind!r}"
            )
        # normalize the two pair-counting spellings (frozen dataclass)
        if self.count_kind == "primes" and self.twins:
            object.__setattr__(self, "count_kind", "twins")
        elif self.count_kind in ("twins", "cousins") and not self.twins:
            object.__setattr__(self, "twins", True)
        # parse the chaos schedule eagerly so bad grammar fails at config
        # construction, not mid-run on a worker
        if self.chaos or self.chaos_kill:
            self.chaos_directives()

    def chaos_directives(self) -> list:
        """The merged fault-injection schedule (``chaos`` plus the legacy
        ``chaos_kill`` spelling) as :class:`sieve.chaos.ChaosDirective`s."""
        from sieve.chaos import parse_chaos

        spec = self.chaos or ""
        if self.chaos_kill:
            if "@" not in self.chaos_kill:
                raise ValueError(
                    f"chaos_kill must be 'k@s', got {self.chaos_kill!r}"
                )
            who, seg = self.chaos_kill.split("@", 1)
            legacy = f"kill:{who}@s{seg}"
            spec = f"{spec},{legacy}" if spec else legacy
        return parse_chaos(spec) if spec else []

    @property
    def pair_gap(self) -> int:
        """Prime-pair difference counted at the reduction (0 = none)."""
        return PAIR_GAPS[self.count_kind]

    @property
    def seed_limit(self) -> int:
        return math.isqrt(self.n)

    def resolved_n_segments(self) -> int:
        """Segment count after resolving n_segments/segment_values defaults."""
        if self.n_segments is not None:
            return self.n_segments
        if self.segment_values is not None:
            span = self.n - 1  # values in [2, n+1)
            return max(1, -(-span // self.segment_values))
        return 1

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SieveConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    def config_hash(self) -> str:
        """Stable hash of the result-affecting fields (checkpoint ledger key).

        Deliberately excludes backend/workers/observability fields: a resume
        may switch backends, the math must not change (SURVEY.md section 5.4).
        """
        payload = {
            "n": self.n,
            "packing": self.packing,
            "n_segments": self.resolved_n_segments(),
            "segment_values": self.segment_values,
            "twins": self.twins,
        }
        if self.count_kind == "cousins":
            # key added only for the new kind so every pre-existing
            # primes/twins ledger hash stays valid across the upgrade
            payload["count_kind"] = self.count_kind
        blob = json.dumps(payload, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]
