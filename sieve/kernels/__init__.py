"""Sieve kernels: host-side marking-spec computation + device marking.

The key TPU-first design decision (SURVEY.md section 7.4): TPUs punish
scatter, so ``mark_multiples`` is reformulated scatter-free. For every
(prime, residue-class) progression the host emits one *marking spec*
``(m, r, s)`` meaning "clear every flag bit b with b % m == r and b >= s".
All three packings reduce to this shape:

  - plain/odds: one spec per prime (stride p in bit space),
  - wheel30:    eight specs per prime (stride 8p, one per residue class).

On device, marking is then a pure vector compare over the bit index —
`lax.scan` over specs of an elementwise `(b % m == r) & (b >= s)` mask —
which XLA fuses and tiles onto the VPU. The Pallas kernel keeps the same
spec contract but loops specs over a VMEM-resident tile to drop HBM traffic.
"""

from sieve.kernels.specs import marking_specs

__all__ = ["marking_specs"]
