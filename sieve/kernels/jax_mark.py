"""Device-side `mark_multiples`: tiered, scatter-free, over packed words.

Strategy A of SURVEY.md section 7.4, realized on uint32 words (bit k of
word w = flag 32w+k) so HBM traffic is 1/32 of a boolean-flags design:

  Tier 1 — small strides (m <= TIER1_MAX): the marking pattern of spec
  (m, r) is periodic with period lcm(m,32)/32 words. The host pre-builds
  each pattern *with the segment's phase baked in* (sieve/kernels/specs.py);
  the device just `jnp.tile`s it to segment length and ANDs it out. Pure
  vector ops, >= 32 marked bits per op for the primes that carry most of
  the crossing mass (SURVEY 7.2: half of all crossings come from p < ~40).

  Tier 2 — mid strides (m > TIER1_MAX >= 1024): each spec hits at most one
  bit per word. For word w the hit bit is t = (r - 32w) mod m when t < 32.
  The mod is computed WITHOUT integer division (TPUs have none worth
  using): t = y - m*floor(y * (1/m)) with y = (r - 32w) + K*m >= 0 and the
  f32 reciprocal's off-by-one fixed by two selects — exact for
  m > 1024, y < 2^30 (proof sketch: |q_err| <= (y/m)*3*2^-24 < 1).

  Self-mark correction: both tiers deliberately ignore the "start at p^2"
  bound (every bit below it is a composite already marked by a smaller
  prime — except the seed prime itself when it lies inside the segment).
  The host emits (word, mask) pairs re-setting those seed bits; applied
  with a tiny scatter-max (associative, duplicate-safe).

Counting, twin pairs, and boundary words all happen on the packed words:
popcount via lax.population_count; twins as popcount(words & shifted &
pair_mask) where `shifted` splices each word with its right neighbor.

No scatter in the hot path, no dynamic shapes, no data-dependent control
flow: everything XLA needs to keep the VPU busy.
"""

from __future__ import annotations

import functools
import os

from sieve import env

import jax
import jax.numpy as jnp
from jax import lax

TWIN_NONE = 0
TWIN_PLAIN = 1  # pairs (b, b+2): adjacent candidates differ by 1
TWIN_ADJ = 2    # pairs (b, b+1): odds layout, adjacent candidates differ by 2
TWIN_W30 = 3    # pairs (b, b+1) masked to residue indices {2, 4, 7}
# --count-kind=cousins (p, p+4) reuses the same splice reduction with a
# different shift/mask (wheel30: gidx-adjacent residue pairs (7,11),
# (13,17), (19,23) -> left indices {1, 3, 5}; see specs._pair_mask):
COUSIN_PLAIN = 4  # pairs (b, b+4)
COUSIN_ADJ = 5    # pairs (b, b+2): odds layout, candidates differ by 4
COUSIN_W30 = 6    # pairs (b, b+1) masked to residue indices {1, 3, 5}

# How far the word array is spliced right so bit j pairs with the
# candidate `gap` values above it, per pair kind.
PAIR_SHIFT = {
    TWIN_PLAIN: 2, TWIN_ADJ: 1, TWIN_W30: 1,
    COUSIN_PLAIN: 4, COUSIN_ADJ: 2, COUSIN_W30: 1,
}

# Tuning knobs (env-overridable for microbenchmarking on real hardware):
# specs with m <= TIER1_MAX become periodic word patterns (each is an
# unrolled tile+AND op in the graph — the main compile-time cost);
# SPEC_BLOCK tier-2 specs are processed per scan step.
# Microbenchmarked on TPU v5e (tools/microbench.py, n=1e9 single segment):
# TIER1_MAX 1024 -> ~190s compile; 256 -> 147s; 64 -> 5.6s with the best
# runtime of the three (1.77e9 values/s) — the unrolled pattern ops were
# nearly all compile cost, and the tier-2 scan handles m in (64, 1024] fine.
TIER1_MAX = env.env_int("SIEVE_TIER1_MAX", 64)
SPEC_BLOCK = env.env_int("SIEVE_SPEC_BLOCK", 16)
WORD_BUCKET = 8192    # word-count padding granularity (jit cache bound)

_U32 = jnp.uint32


def _splice_right(words, shift: int):
    """words[w] >> shift with the low `shift` bits of words[w+1] spliced in
    at the top — pairs bit j of word w with flag bit 32w+j+shift."""
    nxt = jnp.concatenate([words[1:], jnp.zeros((1,), _U32)])
    return (words >> _U32(shift)) | (nxt & _U32((1 << shift) - 1)) << _U32(32 - shift)


def mark_words_impl(
    Wpad: int,
    twin_kind: int,
    periods: tuple[int, ...],
    nbits,        # int32 scalar (traced)
    patterns,     # tuple of uint32 arrays, len == len(periods)
    m2, r2, K2, rcp2, act2,  # tier-2 specs: i32/i32/i32/f32/u32 [S2]
    corr_idx, corr_mask,  # int32 [C], uint32 [C] self-mark corrections
    pair_mask,    # uint32 scalar: twin pairability per bit position
):
    w = lax.iota(jnp.int32, Wpad)
    words = jnp.full((Wpad,), 0xFFFFFFFF, _U32)

    # --- tier 1: tiled periodic patterns ---------------------------------
    for pat, period in zip(patterns, periods):
        reps = Wpad // period + 1
        tiled = jnp.tile(pat, reps)[:Wpad]
        words = words & ~tiled

    # --- tier 2: one-bit-per-word strides, mod-free ----------------------
    S2 = m2.shape[0]
    if S2:
        assert S2 % SPEC_BLOCK == 0

        def body(ws, spec):
            mm, rr, kk, rc, ac = spec
            hit = jnp.zeros_like(ws)
            for i in range(SPEC_BLOCK):
                y = rr[i] - 32 * w + kk[i] * mm[i]
                q = jnp.floor(y.astype(jnp.float32) * rc[i]).astype(jnp.int32)
                t = y - q * mm[i]
                t = jnp.where(t < 0, t + mm[i], t)
                t = jnp.where(t >= mm[i], t - mm[i], t)
                hit = hit | (
                    jnp.where(
                        t < 32,
                        _U32(1) << jnp.minimum(t, 31).astype(_U32),
                        _U32(0),
                    )
                    & ac[i]
                )
            return ws & ~hit, None

        blocks = tuple(
            a.reshape(-1, SPEC_BLOCK) for a in (m2, r2, K2, rcp2, act2)
        )
        words, _ = lax.scan(body, words, blocks)

    return reduce_packed(words, nbits, twin_kind, pair_mask,
                         corr_idx, corr_mask)


def reduce_packed(words, nbits, twin_kind: int, pair_mask,
                  corr_idx=None, corr_mask=None,
                  flat_idx=None, flat_mask=None):
    """Shared tail for both device kernels: flat wide-stride clears,
    self-mark corrections, validity mask beyond nbits, popcount, twin
    reduction, boundary words.

    ``words`` is the flat uint32 word array of one segment (padded); the
    Pallas kernel emits raw marked words and runs this as an XLA postlude
    (one extra HBM read per round — the in-kernel alternative was a
    CC-unrolled correction loop whose live ranges blew VMEM at 1e12 scale).
    """
    w = lax.iota(jnp.int32, words.shape[0])

    # --- flat crossing-list clears (pallas wide-stride path) --------------
    # Must precede the corrections: a flat class can cross its own seed
    # prime's bit, which the correction then re-sets. scatter-MIN because
    # clearing only ever decreases a word, so duplicate indices — the
    # (0, 0) padding entries colliding with a real word-0 entry — resolve
    # to the cleared value instead of racing (scatter-set would).
    if flat_idx is not None and flat_idx.shape[0]:
        cur = words[flat_idx]
        words = words.at[flat_idx].min(cur & ~flat_mask)

    # --- self-mark correction (seed primes inside the segment) -----------
    if corr_idx is not None and corr_idx.shape[0]:
        cur = words[corr_idx]
        words = words.at[corr_idx].max(cur | corr_mask)

    # --- mask bits beyond nbits ------------------------------------------
    bits_valid = jnp.clip(nbits - 32 * w, 0, 32)
    full = bits_valid >= 32
    part = (_U32(1) << jnp.minimum(bits_valid, 31).astype(_U32)) - _U32(1)
    words = words & jnp.where(full, _U32(0xFFFFFFFF), part)

    # --- reductions ------------------------------------------------------
    count = jnp.sum(lax.population_count(words), dtype=jnp.int32)
    if twin_kind == TWIN_NONE:
        twins = jnp.int32(0)
    else:
        shift = PAIR_SHIFT[twin_kind]
        adj = words & _splice_right(words, shift) & pair_mask
        twins = jnp.sum(lax.population_count(adj), dtype=jnp.int32)

    # --- boundary words --------------------------------------------------
    first_word = words[0]
    off = nbits - 32
    wl = off // 32
    sh = (off % 32).astype(_U32)
    pair = lax.dynamic_slice(words, (wl,), (2,))
    spliced = (pair[0] >> sh) | jnp.where(
        sh == 0, _U32(0), pair[1] << (_U32(32) - sh)
    )
    return count, twins, first_word, spliced


def pack4(count, twins, first_word, last_word):
    """Pack the four per-segment results into ONE uint32[4] so the host
    fetches them in a single device->host transfer. Over a tunneled device
    (axon) each separate int() costs a full round trip (~70 ms measured);
    four scalars fetched separately dominated end-to-end wall-clock."""
    return jnp.stack([
        count.astype(_U32), twins.astype(_U32),
        first_word.astype(_U32), last_word.astype(_U32),
    ])


@functools.partial(
    jax.jit, static_argnames=("Wpad", "twin_kind", "periods")
)
def mark_words(
    Wpad, twin_kind, periods, nbits, patterns, m2, r2, K2, rcp2, act2,
    corr_idx, corr_mask, pair_mask,
):
    return pack4(*mark_words_impl(
        Wpad, twin_kind, periods, nbits, patterns, m2, r2, K2, rcp2, act2,
        corr_idx, corr_mask, pair_mask,
    ))


@functools.partial(
    jax.jit, static_argnames=("Wpad", "twin_kind", "periods")
)
def mark_words_batch(
    Wpad, twin_kind, periods, nbits, patterns, m2, r2, K2, rcp2, act2,
    corr_idx, corr_mask, pair_mask,
):
    """Batched `mark_words`: every traced argument gains a leading batch
    axis (``patterns`` is a tuple of ``[B, period]`` arrays) and the
    whole batch runs as ONE device dispatch via vmap — the cold-compute
    plane (ISSUE 9) stacks the distinct chunks of a queue drain here so
    N chunks cost one launch instead of N round trips. Returns
    ``uint32[B, 4]`` (count, pairs, first32, last32 per segment)."""

    def one(nbits_i, patterns_i, m2_i, r2_i, K2_i, rcp2_i, act2_i,
            ci_i, cm_i, pm_i):
        return pack4(*mark_words_impl(
            Wpad, twin_kind, periods, nbits_i, patterns_i,
            m2_i, r2_i, K2_i, rcp2_i, act2_i, ci_i, cm_i, pm_i,
        ))

    return jax.vmap(one)(
        nbits, patterns, m2, r2, K2, rcp2, act2,
        corr_idx, corr_mask, pair_mask,
    )


def next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()
