"""Host-side computation of marking specs (numpy-vectorized, O(#primes)).

A spec (m, r, s) over a segment's bit space instructs the device kernel to
clear flag bits {b : b % m == r, b >= s}. See sieve/kernels/__init__.py for
why this shape: it makes composite-marking scatter-free on TPU.

The start computation is the classic nest validated in SURVEY.md section
4.2: start = max(p^2, ceil(lo/p)*p), bumped into the candidate class.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from sieve.bitset import WHEEL30_RESIDUES, get_layout

# modular inverses of the units mod 30 (u * inv == 1 mod 30)
_W30_INV = {1: 1, 7: 13, 11: 11, 13: 7, 17: 23, 19: 19, 23: 17, 29: 29}
_W30_INV_ARR = np.zeros(30, dtype=np.int64)
for _u, _v in _W30_INV.items():
    _W30_INV_ARR[_u] = _v


@dataclasses.dataclass(frozen=True)
class SpecSet:
    """Marking specs for one segment: clear bits {b % m == r, b >= s}."""

    m: np.ndarray  # int32 [S] moduli (bit-space strides)
    r: np.ndarray  # int32 [S] residues
    s: np.ndarray  # int32 [S] start bits
    nbits: int

    @property
    def count(self) -> int:
        return int(self.m.size)


def marking_specs(
    packing: str, lo: int, hi: int, seeds: np.ndarray
) -> SpecSet:
    """Specs for marking all composites among candidates of [lo, hi)."""
    layout = get_layout(packing)
    nbits = layout.nbits(lo, hi)
    if nbits >= 2**31:
        raise ValueError(f"segment too large: {nbits} bits >= 2^31")
    if nbits == 0:
        z = np.zeros(0, np.int32)
        return SpecSet(z, z, z, 0)
    p = seeds.astype(np.int64)
    if packing == "plain":
        p = p[p * p < hi]
        first = max(lo, 2)
        start = np.maximum(p * p, -(-lo // p) * p)
        keep = start < hi
        p, start = p[keep], start[keep]
        b0 = start - first
        m = p
    elif packing == "odds":
        p = p[(p > 2) & (p * p < hi)]
        first = layout.first_candidate(lo)
        start = np.maximum(p * p, -(-lo // p) * p)
        start = np.where(start % 2 == 0, start + p, start)
        keep = start < hi
        p, start = p[keep], start[keep]
        b0 = (start - first) // 2
        m = p
    elif packing == "wheel30":
        p = p[(p > 5) & (p * p < hi)]
        g0 = layout.gidx(layout.first_candidate(lo))
        pinv = _W30_INV_ARR[p % 30]
        res = np.array(WHEEL30_RESIDUES, dtype=np.int64)
        # grid over (prime, residue class): m-class c whose multiples hit r
        c = (res[None, :] * pinv[:, None]) % 30
        m_lo = np.maximum(p, -(-lo // p))[:, None]
        m0 = m_lo + (c - m_lo) % 30
        v0 = p[:, None] * m0
        keep = v0 < hi
        v0k = v0[keep]
        pk = np.broadcast_to(p[:, None], v0.shape)[keep]
        gid = 8 * (v0k // 30) + _w30_idx(v0k % 30)
        b0 = gid - g0
        m = 8 * pk
    else:
        raise ValueError(f"unknown packing {packing!r}")
    r = b0 % m
    return SpecSet(
        m=m.astype(np.int32),
        r=r.astype(np.int32),
        s=b0.astype(np.int32),
        nbits=nbits,
    )


def _w30_idx(res: np.ndarray) -> np.ndarray:
    from sieve.bitset import _W30_IDX

    return _W30_IDX[res]


# ---------------------------------------------------------------------------
# Incremental residue advancement (streaming prepare pipeline).
# ---------------------------------------------------------------------------


class DeltaModCache:
    """``delta % m`` over a fixed stride vector, cached per distinct delta.

    Advancing a bit-space residue vector from one segment origin to the next
    is ``r' = (r - delta) mod m``; once ``delta % m`` is known that is a
    subtract plus one conditional add — no per-seed division.  Contiguous
    equal-span segments share a handful of distinct deltas (plan_segments
    aligns interior boundaries, so spans differ by at most the alignment),
    so steady-state advancement costs O(1) vector ops per seed."""

    def __init__(self, m: np.ndarray):
        self.m = np.asarray(m, np.int64)
        self._dm: dict[int, np.ndarray] = {}

    def advance(self, r: np.ndarray, delta: int) -> np.ndarray:
        if delta == 0:
            return r
        dm = self._dm.get(delta)
        if dm is None:
            if len(self._dm) >= 64:  # bound the cache on erratic jump chains
                self._dm.clear()
            dm = self._dm[delta] = delta % self.m  # in [0, m) even for delta<0
        r = r - dm
        return np.where(r < 0, r + self.m, r)


class SpecChain:
    """Incremental ``marking_specs`` over a chain of segments.

    A seed prime's marking spec changes between segments only through the
    segment origin bit g0 = gidx(first_candidate(lo)): the bit-space residue
    class of a prime is a *global* arithmetic progression, so the local
    residue advances as ``r' = (r - delta) mod m`` with delta = g0' - g0
    (see DeltaModCache).  The start bound — max(p^2, lo), the classic nest of
    SURVEY.md section 4.2 — is restored exactly from ``g_start``, the global
    bit of each spec's first admissible multiple (>= p^2), which is
    segment-independent and precomputed once.  The per-segment output is
    bit-identical to from-scratch ``marking_specs`` (tests/test_prepare_stream
    proves it across packings and boundary cases) while doing none of the
    per-seed ``ceil(lo/p)`` divisions that made upfront prep O(seeds) worth
    of latency per segment."""

    def __init__(self, packing: str, seeds: np.ndarray):
        self.packing = packing
        self.layout = get_layout(packing)
        p = seeds.astype(np.int64)
        if packing == "plain":
            self.m = p
            self._g_start = p * p  # gidx(v) == v for plain
        elif packing == "odds":
            p = p[p > 2]
            self.m = p
            self._g_start = (p * p - 3) // 2  # gidx(p^2), p odd => p^2 odd
        elif packing == "wheel30":
            p = p[p > 5]
            pinv = _W30_INV_ARR[p % 30]
            res = np.array(WHEEL30_RESIDUES, dtype=np.int64)
            c = (res[None, :] * pinv[:, None]) % 30
            # first admissible multiple per (prime, class): m0 >= p, m0 == c
            m0 = p[:, None] + (c - p[:, None]) % 30
            v0 = p[:, None] * m0
            gs = 8 * (v0 // 30) + _w30_idx(v0 % 30)
            self.m = np.repeat(8 * p, 8)
            self._g_start = gs.ravel()
        else:
            raise ValueError(f"unknown packing {packing!r}")
        self._dm = DeltaModCache(self.m)
        self._r: np.ndarray | None = None
        self._g0: int | None = None

    def residues(self, lo: int, hi: int) -> tuple[int, np.ndarray, np.ndarray]:
        """(nbits, r, s) over the FULL chain spec set, unfiltered.

        ``r`` is the segment-local residue of every spec; ``s`` its start bit
        (first bit the from-scratch nest would mark).  A spec is live in this
        segment iff ``s < nbits``."""
        layout = self.layout
        nbits = layout.nbits(lo, hi)
        if nbits >= 2**31:
            raise ValueError(f"segment too large: {nbits} bits >= 2^31")
        g0 = layout.gidx(layout.first_candidate(lo))
        if self._r is None:
            self._r = (self._g_start - g0) % self.m  # one-time vectorized mod
        else:
            self._r = self._dm.advance(self._r, g0 - self._g0)
        self._g0 = g0
        s = np.where(self._g_start > g0, self._g_start - g0, self._r)
        return nbits, self._r, s

    def specs(self, lo: int, hi: int) -> SpecSet:
        """Drop-in replacement for ``marking_specs(packing, lo, hi, seeds)``."""
        nbits_probe = self.layout.nbits(lo, hi)
        if nbits_probe == 0:
            z = np.zeros(0, np.int32)
            return SpecSet(z, z, z, 0)
        nbits, r, s = self.residues(lo, hi)
        keep = s < nbits
        return SpecSet(
            m=self.m[keep].astype(np.int32),
            r=r[keep].astype(np.int32),
            s=s[keep].astype(np.int32),
            nbits=nbits,
        )


# ---------------------------------------------------------------------------
# Tiered preparation for the word kernel (sieve/kernels/jax_mark.py).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TieredSegment:
    """Everything the word kernel needs for one segment, host-prepared."""

    nbits: int
    Wpad: int
    periods: tuple[int, ...]          # static: tier-1 pattern lengths (words)
    patterns: tuple[np.ndarray, ...]  # uint32, one per period, phase baked in
    m2: np.ndarray                    # int32 [S2] tier-2 moduli
    r2: np.ndarray                    # int32 [S2] tier-2 residues
    K2: np.ndarray                    # int32 [S2] y-offset multipliers
    rcp2: np.ndarray                  # float32 [S2] 1/m
    act2: np.ndarray                  # uint32 [S2] 0xFFFFFFFF real / 0 padding
    corr_idx: np.ndarray              # int32 [C] self-mark correction words
    corr_mask: np.ndarray             # uint32 [C] bits to re-set
    pair_mask: int                    # uint32 scalar: twin pairability

    def with_spec_count(self, target: int) -> "TieredSegment":
        """Re-pad the tier-2 spec arrays to `target` (shape bucketing)."""
        S = self.m2.size
        if target == S:
            return self
        if target < S:
            raise ValueError(f"cannot shrink {S} specs to {target}")
        pad = target - S
        K_pad = -(-32 * self.Wpad // _PAD_M)
        return dataclasses.replace(
            self,
            m2=np.concatenate([self.m2, np.full(pad, _PAD_M, np.int32)]),
            r2=np.concatenate([self.r2, np.zeros(pad, np.int32)]),
            K2=np.concatenate([self.K2, np.full(pad, K_pad, np.int32)]),
            rcp2=np.concatenate(
                [self.rcp2, np.full(pad, 1.0 / _PAD_M, np.float32)]
            ),
            act2=np.concatenate([self.act2, np.zeros(pad, np.uint32)]),
        )


_PAD_M = 1 << 20  # tier-2 padding modulus (inert: act2 == 0 masks its hits)

# Segment-size ceiling for the word kernel: 32*Wpad must stay < 2^30 so the
# f32 reciprocal-mod error bound in jax_mark.py holds.
MAX_WORDS = 1 << 25


def tier1_specs(
    packing: str, lo: int, seeds: np.ndarray, tier1_max: int
) -> tuple[np.ndarray, np.ndarray]:
    """(m, r) for every small-stride prime, *unconditionally* (no p^2 < hi
    cut): the periodic pattern of a prime with no crossings in the segment
    marks nothing in range (its residue class has no candidate members
    there), so including it is harmless — and it keeps the static `periods`
    tuple identical across all shards of a run, which is what lets every
    mesh shard share one compiled kernel."""
    layout = get_layout(packing)
    f = layout.first_candidate(lo)
    p = seeds.astype(np.int64)
    if packing == "plain":
        p = p[p <= tier1_max]
        m = p
        r = (p - f % p) % p  # f + b == 0 (mod p)
    elif packing == "odds":
        p = p[(p > 2) & (p <= tier1_max)]
        m = p
        inv2 = (p + 1) // 2
        r = ((p - f % p) % p) * inv2 % p  # f + 2b == 0 (mod p)
    elif packing == "wheel30":
        p = p[(p > 5) & (8 * p <= tier1_max)]
        g0 = layout.gidx(f)
        pinv = _W30_INV_ARR[p % 30]
        res = np.array(WHEEL30_RESIDUES, dtype=np.int64)
        c = (res[None, :] * pinv[:, None]) % 30
        m_lo = np.maximum(1, -(-lo // p))[:, None]
        m0 = m_lo + (c - m_lo) % 30
        v0 = p[:, None] * m0  # smallest candidate multiple >= lo, per class
        gid = 8 * (v0 // 30) + _w30_idx(v0 % 30)
        b0 = (gid - g0).ravel()
        m = np.repeat(8 * p, 8)
        r = b0 % m
    else:
        raise ValueError(f"unknown packing {packing!r}")
    return m, r


def _tier1_patterns(
    m: np.ndarray, r: np.ndarray
) -> tuple[tuple[int, ...], tuple[np.ndarray, ...]]:
    """Periodic word patterns (marks=1) for small-stride specs, merged by
    period. Pattern word w covers bits [32w, 32w+32) of a buffer that tiles
    the segment exactly because 32*period == lcm(m, 32) == 0 (mod m)."""
    by_period: dict[int, np.ndarray] = {}
    for mi, ri in zip(m.tolist(), r.tolist()):
        period = mi // np.gcd(mi, 32)
        bits = np.zeros(32 * period, dtype=bool)
        bits[ri % mi :: mi] = True
        pat = np.packbits(bits, bitorder="little").view("<u4")
        if period in by_period:
            by_period[period] = by_period[period] | pat
        else:
            by_period[period] = pat
    periods = tuple(sorted(by_period))
    return periods, tuple(by_period[p] for p in periods)


def _merge_word_masks(bits: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Merge non-negative bit indices into sorted per-word
    (word_idx, OR-mask) pairs: one argsort + ``np.bitwise_or.reduceat``,
    no python loop. Shared by ``_corrections`` and ``flat_crossings``."""
    words = bits >> 5
    masks = np.uint32(1) << (bits & 31).astype(np.uint32)
    order = np.argsort(words, kind="stable")
    ws, ms = words[order], masks[order]
    new = np.empty(ws.size, bool)
    new[0] = True
    new[1:] = ws[1:] != ws[:-1]
    grp = np.flatnonzero(new)
    return ws[grp].astype(np.int32), np.bitwise_or.reduceat(ms, grp)


def _corrections(
    packing: str, lo: int, hi: int, seeds: np.ndarray, pad_to: int = 32
) -> tuple[np.ndarray, np.ndarray]:
    """(word_idx, bitmask) pairs re-setting seed primes' own bits — the only
    bits the start-free tiers can wrongly clear (see jax_mark.py docstring).
    Grouped by word (scatter-max is duplicate-safe, this just shrinks C)."""
    layout = get_layout(packing)
    p = seeds[(seeds >= lo) & (seeds < hi)]
    for wp in layout.wheel_primes:
        p = p[p != wp]
    if p.size:
        g0 = layout.gidx(layout.first_candidate(lo))
        bits = layout.gidx_np(p) - g0
        idx, msk = _merge_word_masks(bits)
    else:
        idx = np.zeros(0, np.int32)
        msk = np.zeros(0, np.uint32)
    C = max(pad_to, -(-idx.size // pad_to) * pad_to)
    pad = C - idx.size
    return (
        np.concatenate([idx, np.zeros(pad, np.int32)]),
        np.concatenate([msk, np.zeros(pad, np.uint32)]),
    )


def flat_crossings(
    m: np.ndarray, r: np.ndarray, nbits: int, pad_to: int = 128
) -> tuple[np.ndarray, np.ndarray]:
    """Host-enumerated crossing list for very wide strides (the pallas
    flat path): every bit {r, r+m, r+2m, ...} < nbits of every spec,
    merged into per-word (word_idx, clear_mask) pairs — the same
    enumerate-and-merge idiom as ``_corrections``, but for clears instead
    of re-sets. Same start-free contract as the kernel groups (bits below
    p^2 are composites a smaller prime already marks; the seed's own bit
    is re-set by the corrections that run after these clears).

    Padded with (0, 0) entries: a zero mask clears nothing, so padding is
    inert under the postlude's scatter-min (see jax_mark.reduce_packed).
    """
    m = np.asarray(m, np.int64)
    r = np.asarray(r, np.int64)
    counts = np.maximum(0, -(-(nbits - r) // np.maximum(m, 1)))
    tot = int(counts.sum())
    if tot:
        spec = np.repeat(np.arange(m.size), counts)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        offs = np.arange(tot) - np.repeat(starts, counts)
        bits = r[spec] + offs * m[spec]
        idx, msk = _merge_word_masks(bits)
    else:
        idx = np.zeros(0, np.int32)
        msk = np.zeros(0, np.uint32)
    F = max(pad_to, -(-idx.size // pad_to) * pad_to)
    pad = F - idx.size
    return (
        np.concatenate([idx, np.zeros(pad, np.int32)]),
        np.concatenate([msk, np.zeros(pad, np.uint32)]),
    )


# wheel30 residue indices whose gidx-NEXT neighbor sits `gap` above it:
# gap=2 -> (11,13), (17,19), (29,31); gap=4 -> (7,11), (13,17), (19,23).
_W30_PAIR_IDX = {2: (2, 4, 7), 4: (1, 3, 5)}


def _pair_mask(packing: str, lo: int, gap: int = 2) -> int:
    """uint32 mask of bit positions whose (b, b+shift) splice pair is a
    prime pair with difference ``gap`` (2 = twins, 4 = cousins)."""
    if packing != "wheel30":
        return 0xFFFFFFFF
    idxset = _W30_PAIR_IDX[gap]
    layout = get_layout(packing)
    g0 = layout.gidx(layout.first_candidate(lo))
    mask = 0
    for j in range(32):
        if (g0 + j) % 8 in idxset:
            mask |= 1 << j
    return mask


def prepare_tiered(
    packing: str,
    lo: int,
    hi: int,
    seeds: np.ndarray,
    tier1_max: int,
    spec_block: int,
    word_bucket: int,
    pair_gap: int = 2,
) -> TieredSegment:
    """Host-side preparation of one segment for the word kernel."""
    specs = marking_specs(packing, lo, hi, seeds)
    nbits = specs.nbits
    W = -(-nbits // 32)
    Wpad = -(-(W + 1) // word_bucket) * word_bucket
    if Wpad > MAX_WORDS:
        raise ValueError(
            f"segment too large for word kernel: {nbits} bits "
            f"(> {32 * MAX_WORDS}); use more segments/rounds"
        )

    t1m, t1r = tier1_specs(packing, lo, seeds, tier1_max)
    periods, patterns = _tier1_patterns(t1m, t1r)

    big = specs.m > tier1_max
    m2 = specs.m[big].astype(np.int64)
    r2 = specs.r[big].astype(np.int64)
    S2 = int(m2.size)
    S2p = max(spec_block, -(-S2 // spec_block) * spec_block)
    pad = S2p - S2
    m2 = np.concatenate([m2, np.full(pad, _PAD_M, np.int64)])
    r2 = np.concatenate([r2, np.zeros(pad, np.int64)])
    act2 = np.concatenate(
        [np.full(S2, 0xFFFFFFFF, np.uint32), np.zeros(pad, np.uint32)]
    )
    K2 = -(-32 * Wpad // m2)
    rcp2 = (1.0 / m2).astype(np.float32)

    corr_idx, corr_mask = _corrections(packing, lo, hi, seeds)
    return TieredSegment(
        nbits=nbits,
        Wpad=Wpad,
        periods=periods,
        patterns=patterns,
        m2=m2.astype(np.int32),
        r2=r2.astype(np.int32),
        K2=K2.astype(np.int32),
        rcp2=rcp2,
        act2=act2,
        corr_idx=corr_idx,
        corr_mask=corr_mask,
        pair_mask=_pair_mask(packing, lo, pair_gap),
    )


def _tier1_strides(packing: str, seeds: np.ndarray, tier1_max: int) -> np.ndarray:
    """The stride column of ``tier1_specs`` — lo-independent."""
    p = seeds.astype(np.int64)
    if packing == "plain":
        return p[p <= tier1_max]
    if packing == "odds":
        return p[(p > 2) & (p <= tier1_max)]
    if packing == "wheel30":
        p = p[(p > 5) & (8 * p <= tier1_max)]
        return np.repeat(8 * p, 8)
    raise ValueError(f"unknown packing {packing!r}")


class TieredChain:
    """Incremental ``prepare_tiered`` over a chain of segments.

    Stride-dependent structure is built once: the full marking-spec stride
    vector and its tier-2 membership (segment-independent), per-spec f32
    reciprocals, and the tier-1 stride set — hence ``periods`` is known
    before any segment is prepared, so a mesh shard can build its compiled
    kernel without a throwaway prepare. Per segment only the residues
    advance (SpecChain / DeltaModCache) and the genuinely per-segment
    pieces are rebuilt: tier-1 patterns, the K2 column for the segment's
    Wpad (cached per distinct Wpad), self-mark corrections, pair_mask.
    Output is identical to from-scratch ``prepare_tiered``."""

    def __init__(
        self,
        packing: str,
        seeds: np.ndarray,
        tier1_max: int,
        spec_block: int,
        word_bucket: int,
        pair_gap: int = 2,
    ):
        self.packing = packing
        self.seeds = seeds
        self.tier1_max = tier1_max
        self.spec_block = spec_block
        self.word_bucket = word_bucket
        self.pair_gap = pair_gap
        self.layout = get_layout(packing)
        self._spec = SpecChain(packing, seeds)
        self._big_idx = np.flatnonzero(self._spec.m > tier1_max)
        m2_all = self._spec.m[self._big_idx]
        self._m2_all = m2_all
        self.n_tier2 = int(m2_all.size)  # upper bound on any segment's live set
        self.phase_seconds = {"residue": 0.0, "group": 0.0, "corrections": 0.0}
        self.segments_prepared = 0
        self._rcp_all = (1.0 / m2_all).astype(np.float32)
        self._t1_m = _tier1_strides(packing, seeds, tier1_max)
        self.periods, _ = _tier1_patterns(
            self._t1_m, np.zeros_like(self._t1_m)
        )
        self._t1_r: np.ndarray | None = None
        self._t1_g0: int | None = None
        self._t1_dm = DeltaModCache(self._t1_m)
        self._K2_cache: dict[int, np.ndarray] = {}

    def _tier1_residues(self, lo: int) -> np.ndarray:
        g0 = self.layout.gidx(self.layout.first_candidate(lo))
        if self._t1_r is None:
            m1, self._t1_r = tier1_specs(
                self.packing, lo, self.seeds, self.tier1_max
            )
            assert m1.shape == self._t1_m.shape
        else:
            self._t1_r = self._t1_dm.advance(self._t1_r, g0 - self._t1_g0)
        self._t1_g0 = g0
        return self._t1_r

    def prepare(self, lo: int, hi: int) -> TieredSegment:
        import time

        t0 = time.perf_counter()
        nbits, r_full, s_full = self._spec.residues(lo, hi)
        W = -(-nbits // 32)
        Wpad = -(-(W + 1) // self.word_bucket) * self.word_bucket
        if Wpad > MAX_WORDS:
            raise ValueError(
                f"segment too large for word kernel: {nbits} bits "
                f"(> {32 * MAX_WORDS}); use more segments/rounds"
            )

        r1 = self._tier1_residues(lo)
        t1 = time.perf_counter()
        periods, patterns = _tier1_patterns(self._t1_m, r1)

        K_all = self._K2_cache.get(Wpad)
        if K_all is None:
            K_all = self._K2_cache[Wpad] = -(
                -32 * Wpad // self._m2_all.astype(np.int64)
            )
        live = s_full[self._big_idx] < nbits
        m2 = self._m2_all[live]
        S2 = int(m2.size)
        S2p = max(self.spec_block, -(-S2 // self.spec_block) * self.spec_block)
        pad = S2p - S2
        K_pad = -(-32 * Wpad // _PAD_M)
        m2 = np.concatenate([m2, np.full(pad, _PAD_M, np.int64)])
        r2 = np.concatenate([r_full[self._big_idx][live], np.zeros(pad, np.int64)])
        K2 = np.concatenate([K_all[live], np.full(pad, K_pad, np.int64)])
        rcp2 = np.concatenate(
            [self._rcp_all[live], np.full(pad, 1.0 / _PAD_M, np.float32)]
        )
        act2 = np.concatenate(
            [np.full(S2, 0xFFFFFFFF, np.uint32), np.zeros(pad, np.uint32)]
        )
        t2 = time.perf_counter()

        corr_idx, corr_mask = _corrections(self.packing, lo, hi, self.seeds)
        ph = self.phase_seconds
        ph["residue"] += t1 - t0
        ph["group"] += t2 - t1
        ph["corrections"] += time.perf_counter() - t2
        self.segments_prepared += 1
        return TieredSegment(
            nbits=nbits,
            Wpad=Wpad,
            periods=periods,
            patterns=patterns,
            m2=m2.astype(np.int32),
            r2=r2.astype(np.int32),
            K2=K2.astype(np.int32),
            rcp2=rcp2,
            act2=act2,
            corr_idx=corr_idx,
            corr_mask=corr_mask,
            pair_mask=_pair_mask(self.packing, lo, self.pair_gap),
        )


