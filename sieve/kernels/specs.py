"""Host-side computation of marking specs (numpy-vectorized, O(#primes)).

A spec (m, r, s) over a segment's bit space instructs the device kernel to
clear flag bits {b : b % m == r, b >= s}. See sieve/kernels/__init__.py for
why this shape: it makes composite-marking scatter-free on TPU.

The start computation is the classic nest validated in SURVEY.md section
4.2: start = max(p^2, ceil(lo/p)*p), bumped into the candidate class.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from sieve.bitset import WHEEL30_RESIDUES, get_layout

# modular inverses of the units mod 30 (u * inv == 1 mod 30)
_W30_INV = {1: 1, 7: 13, 11: 11, 13: 7, 17: 23, 19: 19, 23: 17, 29: 29}
_W30_INV_ARR = np.zeros(30, dtype=np.int64)
for _u, _v in _W30_INV.items():
    _W30_INV_ARR[_u] = _v


@dataclasses.dataclass(frozen=True)
class SpecSet:
    """Marking specs for one segment: clear bits {b % m == r, b >= s}."""

    m: np.ndarray  # int32 [S] moduli (bit-space strides)
    r: np.ndarray  # int32 [S] residues
    s: np.ndarray  # int32 [S] start bits
    nbits: int

    @property
    def count(self) -> int:
        return int(self.m.size)


def marking_specs(
    packing: str, lo: int, hi: int, seeds: np.ndarray
) -> SpecSet:
    """Specs for marking all composites among candidates of [lo, hi)."""
    layout = get_layout(packing)
    nbits = layout.nbits(lo, hi)
    if nbits >= 2**31:
        raise ValueError(f"segment too large: {nbits} bits >= 2^31")
    if nbits == 0:
        z = np.zeros(0, np.int32)
        return SpecSet(z, z, z, 0)
    p = seeds.astype(np.int64)
    if packing == "plain":
        p = p[p * p < hi]
        first = max(lo, 2)
        start = np.maximum(p * p, -(-lo // p) * p)
        keep = start < hi
        p, start = p[keep], start[keep]
        b0 = start - first
        m = p
    elif packing == "odds":
        p = p[(p > 2) & (p * p < hi)]
        first = layout.first_candidate(lo)
        start = np.maximum(p * p, -(-lo // p) * p)
        start = np.where(start % 2 == 0, start + p, start)
        keep = start < hi
        p, start = p[keep], start[keep]
        b0 = (start - first) // 2
        m = p
    elif packing == "wheel30":
        p = p[(p > 5) & (p * p < hi)]
        g0 = layout.gidx(layout.first_candidate(lo))
        pinv = _W30_INV_ARR[p % 30]
        res = np.array(WHEEL30_RESIDUES, dtype=np.int64)
        # grid over (prime, residue class): m-class c whose multiples hit r
        c = (res[None, :] * pinv[:, None]) % 30
        m_lo = np.maximum(p, -(-lo // p))[:, None]
        m0 = m_lo + (c - m_lo) % 30
        v0 = p[:, None] * m0
        keep = v0 < hi
        v0k = v0[keep]
        pk = np.broadcast_to(p[:, None], v0.shape)[keep]
        gid = 8 * (v0k // 30) + _w30_idx(v0k % 30)
        b0 = gid - g0
        m = 8 * pk
    else:
        raise ValueError(f"unknown packing {packing!r}")
    r = b0 % m
    return SpecSet(
        m=m.astype(np.int32),
        r=r.astype(np.int32),
        s=b0.astype(np.int32),
        nbits=nbits,
    )


def _w30_idx(res: np.ndarray) -> np.ndarray:
    from sieve.bitset import _W30_IDX

    return _W30_IDX[res]


# ---------------------------------------------------------------------------
# Tiered preparation for the word kernel (sieve/kernels/jax_mark.py).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TieredSegment:
    """Everything the word kernel needs for one segment, host-prepared."""

    nbits: int
    Wpad: int
    periods: tuple[int, ...]          # static: tier-1 pattern lengths (words)
    patterns: tuple[np.ndarray, ...]  # uint32, one per period, phase baked in
    m2: np.ndarray                    # int32 [S2] tier-2 moduli
    r2: np.ndarray                    # int32 [S2] tier-2 residues
    K2: np.ndarray                    # int32 [S2] y-offset multipliers
    rcp2: np.ndarray                  # float32 [S2] 1/m
    act2: np.ndarray                  # uint32 [S2] 0xFFFFFFFF real / 0 padding
    corr_idx: np.ndarray              # int32 [C] self-mark correction words
    corr_mask: np.ndarray             # uint32 [C] bits to re-set
    pair_mask: int                    # uint32 scalar: twin pairability

    def with_spec_count(self, target: int) -> "TieredSegment":
        """Re-pad the tier-2 spec arrays to `target` (shape bucketing)."""
        S = self.m2.size
        if target == S:
            return self
        if target < S:
            raise ValueError(f"cannot shrink {S} specs to {target}")
        pad = target - S
        K_pad = -(-32 * self.Wpad // _PAD_M)
        return dataclasses.replace(
            self,
            m2=np.concatenate([self.m2, np.full(pad, _PAD_M, np.int32)]),
            r2=np.concatenate([self.r2, np.zeros(pad, np.int32)]),
            K2=np.concatenate([self.K2, np.full(pad, K_pad, np.int32)]),
            rcp2=np.concatenate(
                [self.rcp2, np.full(pad, 1.0 / _PAD_M, np.float32)]
            ),
            act2=np.concatenate([self.act2, np.zeros(pad, np.uint32)]),
        )


_PAD_M = 1 << 20  # tier-2 padding modulus (inert: act2 == 0 masks its hits)

# Segment-size ceiling for the word kernel: 32*Wpad must stay < 2^30 so the
# f32 reciprocal-mod error bound in jax_mark.py holds.
MAX_WORDS = 1 << 25


def tier1_specs(
    packing: str, lo: int, seeds: np.ndarray, tier1_max: int
) -> tuple[np.ndarray, np.ndarray]:
    """(m, r) for every small-stride prime, *unconditionally* (no p^2 < hi
    cut): the periodic pattern of a prime with no crossings in the segment
    marks nothing in range (its residue class has no candidate members
    there), so including it is harmless — and it keeps the static `periods`
    tuple identical across all shards of a run, which is what lets every
    mesh shard share one compiled kernel."""
    layout = get_layout(packing)
    f = layout.first_candidate(lo)
    p = seeds.astype(np.int64)
    if packing == "plain":
        p = p[p <= tier1_max]
        m = p
        r = (p - f % p) % p  # f + b == 0 (mod p)
    elif packing == "odds":
        p = p[(p > 2) & (p <= tier1_max)]
        m = p
        inv2 = (p + 1) // 2
        r = ((p - f % p) % p) * inv2 % p  # f + 2b == 0 (mod p)
    elif packing == "wheel30":
        p = p[(p > 5) & (8 * p <= tier1_max)]
        g0 = layout.gidx(f)
        pinv = _W30_INV_ARR[p % 30]
        res = np.array(WHEEL30_RESIDUES, dtype=np.int64)
        c = (res[None, :] * pinv[:, None]) % 30
        m_lo = np.maximum(1, -(-lo // p))[:, None]
        m0 = m_lo + (c - m_lo) % 30
        v0 = p[:, None] * m0  # smallest candidate multiple >= lo, per class
        gid = 8 * (v0 // 30) + _w30_idx(v0 % 30)
        b0 = (gid - g0).ravel()
        m = np.repeat(8 * p, 8)
        r = b0 % m
    else:
        raise ValueError(f"unknown packing {packing!r}")
    return m, r


def _tier1_patterns(
    m: np.ndarray, r: np.ndarray
) -> tuple[tuple[int, ...], tuple[np.ndarray, ...]]:
    """Periodic word patterns (marks=1) for small-stride specs, merged by
    period. Pattern word w covers bits [32w, 32w+32) of a buffer that tiles
    the segment exactly because 32*period == lcm(m, 32) == 0 (mod m)."""
    by_period: dict[int, np.ndarray] = {}
    for mi, ri in zip(m.tolist(), r.tolist()):
        period = mi // np.gcd(mi, 32)
        bits = np.zeros(32 * period, dtype=bool)
        bits[ri % mi :: mi] = True
        pat = np.packbits(bits, bitorder="little").view("<u4")
        if period in by_period:
            by_period[period] = by_period[period] | pat
        else:
            by_period[period] = pat
    periods = tuple(sorted(by_period))
    return periods, tuple(by_period[p] for p in periods)


def _corrections(
    packing: str, lo: int, hi: int, seeds: np.ndarray, pad_to: int = 32
) -> tuple[np.ndarray, np.ndarray]:
    """(word_idx, bitmask) pairs re-setting seed primes' own bits — the only
    bits the start-free tiers can wrongly clear (see jax_mark.py docstring).
    Grouped by word (scatter-max is duplicate-safe, this just shrinks C)."""
    layout = get_layout(packing)
    p = seeds[(seeds >= lo) & (seeds < hi)]
    for wp in layout.wheel_primes:
        p = p[p != wp]
    if p.size:
        g0 = layout.gidx(layout.first_candidate(lo))
        bits = layout.gidx_np(p) - g0
        words = (bits // 32).astype(np.int64)
        masks = np.uint32(1) << (bits % 32).astype(np.uint32)
        uniq = np.unique(words)
        merged = np.zeros(uniq.size, dtype=np.uint32)
        for i, u in enumerate(uniq):
            merged[i] = np.bitwise_or.reduce(masks[words == u])
        idx, msk = uniq.astype(np.int32), merged
    else:
        idx = np.zeros(0, np.int32)
        msk = np.zeros(0, np.uint32)
    C = max(pad_to, -(-idx.size // pad_to) * pad_to)
    pad = C - idx.size
    return (
        np.concatenate([idx, np.zeros(pad, np.int32)]),
        np.concatenate([msk, np.zeros(pad, np.uint32)]),
    )


def flat_crossings(
    m: np.ndarray, r: np.ndarray, nbits: int, pad_to: int = 128
) -> tuple[np.ndarray, np.ndarray]:
    """Host-enumerated crossing list for very wide strides (the pallas
    flat path): every bit {r, r+m, r+2m, ...} < nbits of every spec,
    merged into per-word (word_idx, clear_mask) pairs — the same
    enumerate-and-merge idiom as ``_corrections``, but for clears instead
    of re-sets. Same start-free contract as the kernel groups (bits below
    p^2 are composites a smaller prime already marks; the seed's own bit
    is re-set by the corrections that run after these clears).

    Padded with (0, 0) entries: a zero mask clears nothing, so padding is
    inert under the postlude's scatter-min (see jax_mark.reduce_packed).
    """
    m = np.asarray(m, np.int64)
    r = np.asarray(r, np.int64)
    counts = np.maximum(0, -(-(nbits - r) // np.maximum(m, 1)))
    tot = int(counts.sum())
    if tot:
        spec = np.repeat(np.arange(m.size), counts)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        offs = np.arange(tot) - np.repeat(starts, counts)
        bits = r[spec] + offs * m[spec]
        words = bits >> 5
        masks = (np.uint32(1) << (bits & 31).astype(np.uint32))
        order = np.argsort(words, kind="stable")
        ws, ms = words[order], masks[order]
        new = np.empty(tot, bool)
        new[0] = True
        new[1:] = ws[1:] != ws[:-1]
        grp = np.flatnonzero(new)
        idx = ws[grp].astype(np.int32)
        msk = np.bitwise_or.reduceat(ms, grp)
    else:
        idx = np.zeros(0, np.int32)
        msk = np.zeros(0, np.uint32)
    F = max(pad_to, -(-idx.size // pad_to) * pad_to)
    pad = F - idx.size
    return (
        np.concatenate([idx, np.zeros(pad, np.int32)]),
        np.concatenate([msk, np.zeros(pad, np.uint32)]),
    )


def _pair_mask(packing: str, lo: int) -> int:
    """uint32 mask of bit positions whose (b, b+shift) pair is a twin pair."""
    if packing != "wheel30":
        return 0xFFFFFFFF
    layout = get_layout(packing)
    g0 = layout.gidx(layout.first_candidate(lo))
    mask = 0
    for j in range(32):
        if (g0 + j) % 8 in (2, 4, 7):  # (11,13), (17,19), (29,31) classes
            mask |= 1 << j
    return mask


def prepare_tiered(
    packing: str,
    lo: int,
    hi: int,
    seeds: np.ndarray,
    tier1_max: int,
    spec_block: int,
    word_bucket: int,
) -> TieredSegment:
    """Host-side preparation of one segment for the word kernel."""
    specs = marking_specs(packing, lo, hi, seeds)
    nbits = specs.nbits
    W = -(-nbits // 32)
    Wpad = -(-(W + 1) // word_bucket) * word_bucket
    if Wpad > MAX_WORDS:
        raise ValueError(
            f"segment too large for word kernel: {nbits} bits "
            f"(> {32 * MAX_WORDS}); use more segments/rounds"
        )

    t1m, t1r = tier1_specs(packing, lo, seeds, tier1_max)
    periods, patterns = _tier1_patterns(t1m, t1r)

    big = specs.m > tier1_max
    m2 = specs.m[big].astype(np.int64)
    r2 = specs.r[big].astype(np.int64)
    S2 = int(m2.size)
    S2p = max(spec_block, -(-S2 // spec_block) * spec_block)
    pad = S2p - S2
    m2 = np.concatenate([m2, np.full(pad, _PAD_M, np.int64)])
    r2 = np.concatenate([r2, np.zeros(pad, np.int64)])
    act2 = np.concatenate(
        [np.full(S2, 0xFFFFFFFF, np.uint32), np.zeros(pad, np.uint32)]
    )
    K2 = -(-32 * Wpad // m2)
    rcp2 = (1.0 / m2).astype(np.float32)

    corr_idx, corr_mask = _corrections(packing, lo, hi, seeds)
    return TieredSegment(
        nbits=nbits,
        Wpad=Wpad,
        periods=periods,
        patterns=patterns,
        m2=m2.astype(np.int32),
        r2=r2.astype(np.int32),
        K2=K2.astype(np.int32),
        rcp2=rcp2,
        act2=act2,
        corr_idx=corr_idx,
        corr_mask=corr_mask,
        pair_mask=_pair_mask(packing, lo),
    )


