"""Pallas `mark_multiples`: the fused single-pass TPU kernel (strategy B).

Where the XLA word kernel (jax_mark.py) makes one pass over the packed
words per scan step (HBM-bound once specs are few, VPU-bound otherwise),
this kernel sweeps the segment once: a (R, 128)-word tile lives in
registers/VMEM while EVERY marking spec, the self-mark corrections, the
validity mask, popcount, and the twin reduction are applied to it; the
packed words hit HBM exactly once on the way out. Grid execution on TPU is
sequential, which this kernel exploits twice: count/twin accumulators are
revisited SMEM blocks, and the cross-tile twin boundary carries the
previous tile's last word in SMEM scratch.

Spec groups (host-sorted by bit-stride m, sieve-correct for any segment
because residue-class marking plus seed self-mark correction is
start-free — see jax_mark.py's docstring):

  A (m < 32, static unroll): several marked bits per word — two-level
    exact f32-reciprocal mod to get the first hit t0, then a static
    16-layer OR of bits t0, t0+m, ... < 32.
  B (32 <= m <= 1024): one bit per word at most; two-level mod (a single
    f32 reciprocal is not exact for y/m up to 2^20 when m is small).
  C (1024 < m <= 4096): one bit per word; single-level mod (q error
    < 1/8, fixed by two selects).
  D (4096 < m < flat cutoff): at most one bit per ROW, so the mod runs
    once per (row, spec) instead of once per (word, spec) — 128 specs
    ride the lane dimension of one (R, 128) mod evaluation, and each
    spec's single hit is placed with a compare against the lane iota.
    Per-spec per-row cost drops from ~14 vector ops to ~4, and the spec
    table lives in VMEM behind a fori_loop, so compile time is
    independent of the spec count (the group that grows with sqrt(N)).
    Specs with zero crossings of the window are pruned at prepare time
    and the table compacted to live rows (see prepare_pallas).
  flat (m >= cutoff, see _flat_cutoff): so wide that even one D-block
    lane is a waste — the handful of (word, mask) crossings is enumerated
    on host (specs.flat_crossings) and applied by the XLA postlude as a
    duplicate-safe scatter-min, making their cost proportional to actual
    crossings. Tunable via SIEVE_PALLAS_FLAT_MIN.

All in-kernel control flow is static or fori_loop with static bounds +
act masks: no scatter, no gather, no data-dependent shapes (the flat
scatter lives in the XLA postlude, outside the kernel).

Fused reduction (the default path, SIEVE_PALLAS_FUSED=0 reverts): the
split kernel+postlude design pays two full HBM passes over the bitset per
segment — the kernel writes Wpad words, reduce_packed reads them all back
to apply flat clears, corrections, the validity mask, popcount, pair
counting, and boundary extraction. ``mark_pallas_fused`` folds all of that
into the marking kernel itself: each (R, 128) tile is patched in VMEM
(flat clears and corrections applied by per-tile crossing-list cursors, so
the cost stays proportional to actual crossings), then parked in a
double-buffered VMEM scratch — tile k's popcount/pair/boundary reduction
runs while tile k+1 is being marked (no data dependency between them, so
Mosaic can overlap the two) — and only a uint32[8] SMEM accumulator block
(count, pairs, first_word, last_spliced + carries) leaves the kernel. A
``need_bits`` flag additionally emits the patched+masked bitset for
enumeration/checkpoint consumers. The split path is kept verbatim as the
parity oracle (tests/test_fused_reduce.py proves bit-exactness).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sieve import env
from sieve.bitset import get_layout
from sieve.kernels.specs import _pair_mask, flat_crossings, tier1_specs

import os as _os


def _load_tuned() -> dict:
    """Hardware-tuned knob values written by tools/autotune_kernel.py.

    Looked up at import from SIEVE_TUNED_JSON or a ``tuned.json`` at the
    repo root; absent file (the normal state) means built-in defaults.
    Resolution order per knob: explicit env var > tuned.json > default,
    so a tuned file never overrides a deliberate env sweep."""
    import json

    path = env.env_str("SIEVE_TUNED_JSON")
    if path is None:
        path = _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.dirname(
                _os.path.abspath(__file__)))),
            "tuned.json",
        )
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return {k: v for k, v in data.items() if not k.startswith("_")}


_TUNED = _load_tuned()


def _knob(name: str, default: int) -> int:
    v = env.env_int(name, None)
    if v is None:
        v = int(_TUNED.get(name, default))
    return v


# Microbenchmarked on TPU v5e. Pre-group-D (n=1e9): R=64 -> 424ms,
# 128 -> 416ms, 256 -> 406ms, 512 -> 554ms. With group D (n=1e10 segment):
# 64 -> 914ms, 128 -> 901ms (best), 256 -> 931ms, 512 -> 1007ms.
R_ROWS = _knob("SIEVE_PALLAS_ROWS", 128)  # tile = (R, 128) words
TILE_WORDS = R_ROWS * 128
NA_PAD = 16                     # group-A slots (>= 11 primes below 32)
A_LAYERS = 16                   # max marked bits per word (m=2 -> 16)
B_MAX = 1024
# Group-D threshold: strides wider than one tile row (128 words * 32 bits)
# hit each row at most once. Env-overridable for microbenchmarking the C/D
# split point — only raising it is meaningful (prepare_pallas clamps to the
# 4096-bit row width, below which the one-hit-per-row invariant breaks);
# setting it huge routes everything through group C (the pre-D behavior).
D_MIN = _knob("SIEVE_PALLAS_DMIN", 4096)
D_LANES = 128                   # specs per D block (lane dimension)
# Flat-path cutoff: strides at least this wide leave the kernel entirely —
# their few crossings are enumerated on host (specs.flat_crossings) and
# applied as a scatter-min in the XLA postlude, so their cost is
# proportional to actual crossings instead of one D-block lane forever.
# Auto (the default) keeps strides with more than _FLAT_MAX_HITS crossings
# of the padded window in group D; the scatter only wins while the
# crossing list stays tiny. SIEVE_PALLAS_FLAT_MIN overrides the cutoff in
# bits (read at prepare time so tests can sweep it).
_FLAT_MAX_HITS = 8
_U32 = jnp.uint32


def _flat_cutoff(Wpad: int) -> int:
    v = _knob("SIEVE_PALLAS_FLAT_MIN", 0)
    if v <= 0:
        v = 32 * Wpad // _FLAT_MAX_HITS
    return max(v, max(D_MIN, 4096) + 1)


@dataclasses.dataclass(frozen=True)
class PallasSegment:
    nbits: int
    Wpad: int                   # padded word count, multiple of TILE_WORDS
    A: tuple[np.ndarray, ...]   # m, rK, M1, rcp1, rcp, act   each (1, NA_PAD)
    B: tuple[np.ndarray, ...]   # m, rK, M1, rcp1, rcp, act   each (1, SB)
    C: tuple[np.ndarray, ...]   # m, rK, rcp, act             each (1, SC)
    D: tuple[np.ndarray, ...]   # m, rK, rcp, act             each (ND, 128)
    corr_idx: np.ndarray        # (1, CC) int32 global word index (-1 pad)
    corr_mask: np.ndarray       # (1, CC) uint32
    flat_idx: np.ndarray        # (1, FC) int32 word index of flat clears (0 pad)
    flat_mask: np.ndarray       # (1, FC) uint32 bits to clear (0 pad = inert)
    pair_mask: int


def _group_arrays(m: np.ndarray, r: np.ndarray, Wpad: int, pad_to: int,
                  two_level: bool, pad_m: int = 3) -> tuple[np.ndarray, ...]:
    """Per-spec constants, padded with inert entries (act = 0)."""
    S = m.size
    P = max(pad_to, -(-S // pad_to) * pad_to)
    K = -(-32 * Wpad // np.maximum(m, 1))
    rK = r + K * m
    out_m = np.full(P, pad_m, np.int32)
    out_rK = np.zeros(P, np.int32)
    out_m[:S] = m
    out_rK[:S] = rK
    act = np.zeros(P, np.uint32)
    act[:S] = 0xFFFFFFFF
    rcp = (1.0 / out_m.astype(np.float64)).astype(np.float32)
    if two_level:
        M1 = (out_m.astype(np.int64) << 10).astype(np.int32)
        rcp1 = (1.0 / (out_m.astype(np.float64) * 1024.0)).astype(np.float32)
        arrs = (out_m, out_rK, M1, rcp1, rcp, act)
    else:
        arrs = (out_m, out_rK, rcp, act)
    return tuple(a.reshape(1, -1) for a in arrs)


def _group_d_arrays(m: np.ndarray, r: np.ndarray, Wpad: int) -> tuple[np.ndarray, ...]:
    """Group-D spec table, (ND, 128)-shaped for VMEM row loads.

    Specs stay sorted by m so a block's strides are similar — hit density
    per row is uniform within a block, which keeps the placement loop's
    work per block balanced across tiles."""
    arrs = _group_arrays(m, r, Wpad, D_LANES, two_level=False, pad_m=1 << 29)
    return tuple(a.reshape(-1, D_LANES) for a in arrs)


def prepare_pallas(
    packing: str, lo: int, hi: int, seeds: np.ndarray,
    wpad: int | None = None, pair_gap: int = 2,
) -> PallasSegment:
    """Host prep for one segment. ``wpad`` overrides the word padding with a
    larger common value (mesh path: every shard must share one shape; the
    rK offsets bake in the padding, so it must be fixed before grouping)."""
    layout = get_layout(packing)
    nbits = layout.nbits(lo, hi)
    W = -(-nbits // 32)
    Wpad = -(-(W + 1) // TILE_WORDS) * TILE_WORDS
    if wpad is not None:
        if wpad < Wpad or wpad % TILE_WORDS:
            raise ValueError(f"wpad {wpad} < segment's {Wpad} or unaligned")
        Wpad = wpad
    if 32 * Wpad >= 1 << 30:
        raise ValueError(f"segment too large for pallas kernel: {nbits} bits")
    # start-free residue-class specs for ALL seed primes (see module doc)
    m, r = tier1_specs(packing, lo, seeds, tier1_max=1 << 62)
    m = m.astype(np.int64)
    r = r.astype(np.int64)
    d_min = max(D_MIN, 4096)  # a D stride must exceed one 4096-bit tile row
    f_min = _flat_cutoff(Wpad)  # > d_min: widest strides skip the kernel
    ga = m < 32
    gb = (m >= 32) & (m <= B_MAX)
    gc = (m > B_MAX) & (m <= d_min)
    gd = (m > d_min) & (m < f_min)
    gf = m >= f_min
    # prune: a group-D spec hits bits r, r+m, ... so a first hit at or past
    # nbits means zero crossings of this window (only padding, masked by
    # the postlude). Dropping it here compacts the (ND, 128) table to live
    # rows, making the kernel's D sweep scale with crossings actually
    # present rather than with the seed-prime count.
    gd &= r < nbits
    if np.count_nonzero(ga) > NA_PAD:
        raise ValueError("group A overflow")
    A = _group_arrays(m[ga], r[ga], Wpad, NA_PAD, two_level=True)
    B = _group_arrays(m[gb], r[gb], Wpad, 128, two_level=True)
    C = _group_arrays(m[gc], r[gc], Wpad, 128, two_level=False)
    D = _group_d_arrays(m[gd], r[gd], Wpad)
    fi, fm = flat_crossings(m[gf], r[gf], nbits)

    from sieve.kernels.specs import _corrections

    ci, cm = _corrections(packing, lo, hi, seeds, pad_to=32)
    ci = ci.astype(np.int64)
    # _corrections returns bit-word indices for 32-bit words == our words
    ci_pad = np.full(ci.size, -1, np.int32)
    real = cm != 0
    ci_pad[real] = ci[real].astype(np.int32)
    return PallasSegment(
        nbits=nbits,
        Wpad=Wpad,
        A=A,
        B=B,
        C=C,
        D=D,
        corr_idx=ci_pad.reshape(1, -1),
        corr_mask=cm.reshape(1, -1),
        flat_idx=fi.reshape(1, -1),
        flat_mask=fm.reshape(1, -1),
        pair_mask=_pair_mask(packing, lo, pair_gap),
    )


class PallasChain:
    """Incremental ``prepare_pallas`` over a chain of segments sharing one
    padded width.

    Group membership (A/B/C/D/flat) depends only on the bit strides m, which
    are segment-independent, so the grouped tables are built once at
    construction; per segment only the residues advance — ``r' = (r - delta)
    mod m`` via specs.DeltaModCache, no per-seed division — and the cheap
    residue-dependent pieces are rebuilt: the rK column of each group, the
    zero-crossing pruning of the (ND, 128) group-D table, the host-enumerated
    flat crossings, self-mark corrections, and pair_mask. Output is identical
    to from-scratch ``prepare_pallas(packing, lo, hi, seeds, wpad)``
    (tests/test_prepare_stream.py), at a fraction of its host cost.

    ``phase_seconds`` accumulates per-phase host time (residue / group /
    flat / corrections) for tools/profile_prepare.py and the mesh metrics.
    """

    def __init__(self, packing: str, seeds: np.ndarray, wpad: int,
                 pair_gap: int = 2):
        from sieve.kernels.specs import DeltaModCache, _tier1_strides

        if wpad % TILE_WORDS:
            raise ValueError(f"wpad {wpad} not a multiple of {TILE_WORDS}")
        if 32 * wpad >= 1 << 30:
            raise ValueError(f"wpad {wpad} too large for pallas kernel")
        self.packing = packing
        self.seeds = seeds
        self.Wpad = wpad
        self.pair_gap = pair_gap
        self.layout = get_layout(packing)
        self.phase_seconds = {
            "residue": 0.0, "group": 0.0, "flat": 0.0, "corrections": 0.0,
        }
        self.segments_prepared = 0
        m = _tier1_strides(packing, seeds, 1 << 62)
        self._m = m
        d_min = max(D_MIN, 4096)
        f_min = _flat_cutoff(wpad)
        ga = m < 32
        gb = (m >= 32) & (m <= B_MAX)
        gc = (m > B_MAX) & (m <= d_min)
        self._gd = (m > d_min) & (m < f_min)
        self._gf = m >= f_min
        if np.count_nonzero(ga) > NA_PAD:
            raise ValueError("group A overflow")
        self._masks = (ga, gb, gc)
        z = np.zeros
        self._groups = tuple(
            {
                "arrs": _group_arrays(
                    m[g], z(int(np.count_nonzero(g)), np.int64),
                    wpad, pad, two_level=two,
                ),
                "Km": None,  # filled below: the segment-independent K*m term
                "S": int(np.count_nonzero(g)),
                "mask": g,
            }
            for g, pad, two in (
                (ga, NA_PAD, True), (gb, 128, True), (gc, 128, False),
            )
        )
        for g in self._groups:
            # rK of the zero-residue base IS K*m for the real entries
            g["Km"] = g["arrs"][1][0, : g["S"]].astype(np.int64)
        md = m[self._gd]
        self._d_m = md
        self._d_Km = -(-32 * wpad // np.maximum(md, 1)) * md
        self._d_rcp = (1.0 / md.astype(np.float64)).astype(np.float32)
        self._f_m = m[self._gf]
        self._dm = DeltaModCache(m)
        self._r: np.ndarray | None = None
        self._g0: int | None = None

    @property
    def SB(self) -> int:
        """Padded group-B width — identical for every segment of the chain."""
        return self._groups[1]["arrs"][0].shape[1]

    @property
    def SC(self) -> int:
        """Padded group-C width — identical for every segment of the chain."""
        return self._groups[2]["arrs"][0].shape[1]

    def _residues(self, lo: int) -> np.ndarray:
        g0 = self.layout.gidx(self.layout.first_candidate(lo))
        if self._r is None:
            m, r = tier1_specs(self.packing, lo, self.seeds, tier1_max=1 << 62)
            assert m.shape == self._m.shape
            self._r = r.astype(np.int64)
        else:
            self._r = self._dm.advance(self._r, g0 - self._g0)
        self._g0 = g0
        return self._r

    def _with_residue(self, g: dict, r_g: np.ndarray) -> tuple[np.ndarray, ...]:
        arrs = list(g["arrs"])
        rK = arrs[1].copy()
        if g["S"]:
            rK[0, : g["S"]] = g["Km"] + r_g
        arrs[1] = rK
        return tuple(arrs)

    def prepare(self, lo: int, hi: int) -> PallasSegment:
        import time

        layout = self.layout
        nbits = layout.nbits(lo, hi)
        W = -(-nbits // 32)
        Wseg = -(-(W + 1) // TILE_WORDS) * TILE_WORDS
        if self.Wpad < Wseg:
            raise ValueError(f"wpad {self.Wpad} < segment's {Wseg} or unaligned")
        t0 = time.perf_counter()
        r = self._residues(lo)
        t1 = time.perf_counter()
        A, B, C = (
            self._with_residue(g, r[g["mask"]]) for g in self._groups
        )
        r_d = r[self._gd]
        sel = r_d < nbits  # zero-crossing pruning (see prepare_pallas)
        S = int(np.count_nonzero(sel))
        P = max(D_LANES, -(-S // D_LANES) * D_LANES)
        out_m = np.full(P, 1 << 29, np.int32)
        out_rK = np.zeros(P, np.int32)
        rcp = np.full(P, np.float32(1.0 / (1 << 29)), np.float32)
        act = np.zeros(P, np.uint32)
        out_m[:S] = self._d_m[sel]
        out_rK[:S] = self._d_Km[sel] + r_d[sel]
        rcp[:S] = self._d_rcp[sel]
        act[:S] = 0xFFFFFFFF
        D = tuple(
            a.reshape(-1, D_LANES) for a in (out_m, out_rK, rcp, act)
        )
        t2 = time.perf_counter()
        fi, fm = flat_crossings(self._f_m, r[self._gf], nbits)
        t3 = time.perf_counter()

        from sieve.kernels.specs import _corrections

        ci, cm = _corrections(self.packing, lo, hi, self.seeds, pad_to=32)
        ci_pad = np.full(ci.size, -1, np.int32)
        real = cm != 0
        ci_pad[real] = ci[real].astype(np.int32)
        pair_mask = _pair_mask(self.packing, lo, self.pair_gap)
        t4 = time.perf_counter()
        ph = self.phase_seconds
        ph["residue"] += t1 - t0
        ph["group"] += t2 - t1
        ph["flat"] += t3 - t2
        ph["corrections"] += t4 - t3
        self.segments_prepared += 1
        return PallasSegment(
            nbits=nbits,
            Wpad=self.Wpad,
            A=A,
            B=B,
            C=C,
            D=D,
            corr_idx=ci_pad.reshape(1, -1),
            corr_mask=cm.reshape(1, -1),
            flat_idx=fi.reshape(1, -1),
            flat_mask=fm.reshape(1, -1),
            pair_mask=pair_mask,
        )


def spec_counts(ps: PallasSegment) -> dict:
    """Real (unpadded) per-tier spec counts of one prepared segment — for
    artifacts and logs (group D reports LIVE rows post-pruning; flat
    reports merged crossing words)."""
    return {
        "A": int((ps.A[5] != 0).sum()),
        "B": int((ps.B[5] != 0).sum()),
        "C": int((ps.C[3] != 0).sum()),
        "D": int((ps.D[3] != 0).sum()),
        "flat_words": int((ps.flat_mask != 0).sum()),
        "corr_words": int((ps.corr_mask != 0).sum()),
    }


def _pad_fills(two_level: bool, pad_m: int) -> tuple:
    """Inert pad entry per group-array position, derived from the same
    _group_arrays construction that builds real tables (act = 0 masks every
    hit; the other values only keep the arithmetic in range)."""
    z = np.zeros(0, np.int64)
    arrs = _group_arrays(z, z, 32, 1, two_level=two_level, pad_m=pad_m)
    return tuple(a[0, 0] for a in arrs)


_PAD_B = _pad_fills(two_level=True, pad_m=3)
_PAD_C = _pad_fills(two_level=False, pad_m=3)
_PAD_D = _pad_fills(two_level=False, pad_m=1 << 29)


def _pad_cols(arrs, fills, target: int):
    out = []
    for a, fill in zip(arrs, fills):
        pad = target - a.shape[1]
        if pad:
            ext = np.full((a.shape[0], pad), fill, a.dtype)
            a = np.concatenate([a, ext], axis=1)
        out.append(a)
    return tuple(out)


def pad_pallas(
    ps: PallasSegment, SB: int, SC: int, ND: int, CC: int, FC: int | None = None
) -> PallasSegment:
    """Pad a segment's group tables to common shapes (mesh path: all shards
    of a round share one compiled kernel, so spec counts must match across
    shards — but only to the ROUND's maxima: live group-D row counts vary
    per segment after pruning, and over-padding D re-adds the very sweep
    cost the pruner removed). Flat crossing lists pad with (0, 0) no-ops
    (inert under the postlude's scatter-min)."""
    D = ps.D
    pad_rows = ND - D[0].shape[0]
    if pad_rows > 0:
        D = tuple(
            np.concatenate(
                [a, np.full((pad_rows, D_LANES), fill, a.dtype)], axis=0
            )
            for a, fill in zip(D, _PAD_D)
        )
    ci, cm = _pad_cols((ps.corr_idx, ps.corr_mask), (-1, 0), CC)
    fi, fm = ps.flat_idx, ps.flat_mask
    if FC is not None and FC > fi.shape[1]:
        fi, fm = _pad_cols((fi, fm), (0, 0), FC)
    return dataclasses.replace(
        ps,
        B=_pad_cols(ps.B, _PAD_B, SB),
        C=_pad_cols(ps.C, _PAD_C, SC),
        D=D,
        corr_idx=ci,
        corr_mask=cm,
        flat_idx=fi,
        flat_mask=fm,
    )


def _mod_two_level(y, M1, rcp1, m, rcp):
    """Exact y mod m for 0 <= y < 2^30 via a 2^10-scaled first reduction."""
    q1 = jnp.floor(y.astype(jnp.float32) * rcp1).astype(jnp.int32)
    t1 = y - q1 * M1
    t1 = jnp.where(t1 < 0, t1 + M1, t1)
    t1 = jnp.where(t1 >= M1, t1 - M1, t1)
    q2 = jnp.floor(t1.astype(jnp.float32) * rcp).astype(jnp.int32)
    t0 = t1 - q2 * m
    t0 = jnp.where(t0 < 0, t0 + m, t0)
    t0 = jnp.where(t0 >= m, t0 - m, t0)
    return t0


def _mod_single(y, m, rcp):
    q = jnp.floor(y.astype(jnp.float32) * rcp).astype(jnp.int32)
    t = y - q * m
    t = jnp.where(t < 0, t + m, t)
    t = jnp.where(t >= m, t - m, t)
    return t


def _onebit(t, act):
    hit = jnp.where(
        t < 32, _U32(1) << (t.astype(_U32) & _U32(31)), _U32(0)
    )
    return hit & act


def _mark_tile(base, row, lane,
               Am, ArK, AM1, Arcp1, Arcp, Aact,
               Bm, BrK, BM1, Brcp1, Brcp, Bact,
               Cm, CrK, Crcp, Cact,
               Dm, DrK, Drcp, Dact,
               SB: int, SC: int, ND: int):
    """Marking body shared by the split and fused kernels: apply every
    A/B/C/D spec to the (R, 128)-word tile starting at word ``base`` and
    return the marked words (1 = still possibly prime)."""
    w32 = 32 * (base + row * 128 + lane)
    words = jnp.full((R_ROWS, 128), 0xFFFFFFFF, _U32)

    # --- group A: multi-bit small strides (static unroll) ------------
    for i in range(NA_PAD):
        m = Am[0, i]
        t0 = _mod_two_level(ArK[0, i] - w32, AM1[0, i], Arcp1[0, i],
                            m, Arcp[0, i])
        mask = jnp.zeros((R_ROWS, 128), _U32)
        for k in range(A_LAYERS):
            bit = t0 + k * m
            mask = mask | jnp.where(
                bit < 32, _U32(1) << (bit.astype(_U32) & _U32(31)), _U32(0)
            )
        words = words & ~(mask & Aact[0, i])

    # --- group B: two-level mod, one bit -----------------------------
    def bbody(i, ws):
        t0 = _mod_two_level(BrK[0, i] - w32, BM1[0, i], Brcp1[0, i],
                            Bm[0, i], Brcp[0, i])
        return ws & ~_onebit(t0, Bact[0, i])

    words = lax.fori_loop(0, SB, bbody, words)

    # --- group C: single-level mod, one bit --------------------------
    def cbody(i, ws):
        t0 = _mod_single(CrK[0, i] - w32, Cm[0, i], Crcp[0, i])
        return ws & ~_onebit(t0, Cact[0, i])

    words = lax.fori_loop(0, SC, cbody, words)

    # --- group D: one bit per tile ROW; 128 specs per mod pass -------
    if ND:
        # bit offset of each row's first flag (row r covers bits
        # [rowbit[r], rowbit[r] + 4096) of the padded segment)
        rowbit = 32 * (base + row * 128)  # (R, 128); lane-constant

        def dbody(i, ws):
            mD = Dm[pl.ds(i, 1), :]       # (1, 128): lane s = spec s
            rKD = DrK[pl.ds(i, 1), :]
            rcpD = Drcp[pl.ds(i, 1), :]
            actD = Dact[pl.ds(i, 1), :]
            # t[r, s] = (rK[s] - rowbit[r]) mod m[s]; hit in row r iff
            # t < 4096, at word t >> 5, bit t & 31
            y = rKD - rowbit[:, 0:1]      # (R, 128) via broadcast
            t0 = _mod_single(y, mD, rcpD)
            hw = t0 >> 5                  # word-in-row per (row, spec)
            hmask = jnp.where(
                t0 < 4096, _U32(1) << (t0.astype(_U32) & _U32(31)), _U32(0)
            ) & actD
            # Placement: the hit of the spec riding lane s belongs at
            # lane hw[r, s]. Rotating lanes right by k moves lane s to
            # lane s + k, so the spec's contribution rides rotation
            # k = (hw - s) mod 128. OR_k roll(sel_k, k) is evaluated
            # Horner-style: descending k, rotate the accumulator one
            # lane and OR in this k's selection — sel_k ends up rotated
            # exactly k times. Same select count as rotate-by-k, but
            # every rotation is the cheapest (distance-1) lane shuffle;
            # still no lane slicing, tiny live state, compile cost
            # independent of ND.
            dist = (hw - lane) & 127
            hit = jnp.where(dist == D_LANES - 1, hmask, _U32(0))
            for k in range(D_LANES - 2, -1, -1):
                hit = pltpu.roll(hit, 1, axis=1) | jnp.where(
                    dist == k, hmask, _U32(0)
                )
            return ws & ~hit

        words = lax.fori_loop(0, ND, dbody, words)

    return words


def _make_kernel(SB: int, SC: int, ND: int):
    """Pure marking kernel: specs in, marked words out. Corrections, the
    validity mask, counting, twins, and boundary words all happen in the
    XLA postlude (jax_mark.reduce_packed) — the split half of the fused /
    split pair (see _make_fused_kernel for why both exist)."""

    def kernel(Am, ArK, AM1, Arcp1, Arcp, Aact,
               Bm, BrK, BM1, Brcp1, Brcp, Bact,
               Cm, CrK, Crcp, Cact,
               Dm, DrK, Drcp, Dact,
               words_ref):
        t = pl.program_id(0)
        base = t * TILE_WORDS
        row = lax.broadcasted_iota(jnp.int32, (R_ROWS, 128), 0)
        lane = lax.broadcasted_iota(jnp.int32, (R_ROWS, 128), 1)
        words_ref[:, :] = _mark_tile(
            base, row, lane,
            Am, ArK, AM1, Arcp1, Arcp, Aact,
            Bm, BrK, BM1, Brcp1, Brcp, Bact,
            Cm, CrK, Crcp, Cact,
            Dm, DrK, Drcp, Dact,
            SB, SC, ND,
        )

    return kernel


@functools.lru_cache(maxsize=None)
def _build_call(Wpad: int, SB: int, SC: int, ND: int, interpret: bool):
    kernel = _make_kernel(SB, SC, ND)
    Wrows = Wpad // 128
    grid = Wpad // TILE_WORDS

    def smem(n):
        # per-spec scalars read with dynamic indices -> scalar memory
        # (Mosaic cannot scalar-load a dynamic lane from VMEM)
        return pl.BlockSpec((1, n), lambda t: (0, 0), memory_space=pltpu.SMEM)

    def vmem_rows(nrows):
        # group-D spec table: whole (ND, 128) array resident in VMEM, rows
        # loaded with a dynamic sublane index inside the fori_loop
        return pl.BlockSpec(
            (nrows, D_LANES), lambda t: (0, 0), memory_space=pltpu.VMEM
        )

    in_specs = (
        [smem(NA_PAD)] * 6
        + [smem(SB)] * 6
        + [smem(SC)] * 4
        + [vmem_rows(max(ND, 1))] * 4
    )
    call = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((R_ROWS, 128), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Wrows, 128), jnp.uint32),
        # group D's unrolled 128-rotation placement keeps more scheduler
        # temporaries live than the default 16M scoped-VMEM budget allows
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return call


def tile_offsets(idx: np.ndarray, mask: np.ndarray, Wpad: int) -> np.ndarray:
    """Per-tile cursors into a word-sorted (idx, mask) crossing list:
    entries [off[0, t], off[0, t+1]) are exactly those whose global word
    index falls inside tile t. The fused kernel's patch loops use these as
    fori_loop bounds, so per-tile patch cost stays proportional to the
    tile's actual crossings and the padding entries (appended past the
    real ones by flat_crossings/_corrections/pad_pallas) are never
    visited."""
    G = Wpad // TILE_WORDS
    flat = np.asarray(idx).reshape(-1)
    n_real = int(np.count_nonzero(np.asarray(mask).reshape(-1)))
    real = flat[:n_real].astype(np.int64)
    bounds = np.arange(G + 1, dtype=np.int64) * TILE_WORDS
    return np.searchsorted(real, bounds, side="left").astype(
        np.int32).reshape(1, -1)


def _make_fused_kernel(G: int, SB: int, SC: int, ND: int,
                       twin_kind: int, need_bits: bool):
    """Marking + full reduction in one pallas_call (the tentpole).

    Per grid step t: mark tile t (shared _mark_tile), patch it in VMEM
    (flat clears then corrections via the per-tile cursor loops, then the
    validity mask — same order as jax_mark.reduce_packed, the parity
    oracle), park it in the double-buffered VMEM scratch, and reduce tile
    t-1 out of the *other* buffer slot. The reduction of tile t-1 has no
    data dependency on tile t's marking, so Mosaic is free to overlap the
    two; the sequential grid makes the SMEM accumulator block a legal
    revisited output.

    Accumulator layout (uint32[1, 8] SMEM output):
      [0] count   [1] pairs      [2] first_word  [3] last_spliced
      [4] prev_last carry        [5] word at wl  [6] word at wl+1  [7] -

    Pair counting runs on the bitpacked lanes directly: the right-neighbor
    word arrives via two cheap rotations (distance-127 lane roll for the
    in-row neighbor, distance-(R-1) sublane roll for the lane-127 column),
    and the tile's very last word — whose neighbor lives in the NEXT tile —
    is masked out and deferred through the prev_last carry. The final
    tile's deferred pair is provably zero: Wpad >= W + 1 guarantees the
    last padded word dies under the validity mask.

    Known hardware limit: the correction/flat lists ride SMEM, so a
    segment whose merged correction list is huge (segment 0 at extreme N)
    may exceed SMEM on real chips — SIEVE_PALLAS_FUSED=0 falls back to the
    split kernel + XLA postlude, which has no such limit.
    """
    from sieve.kernels.jax_mark import PAIR_SHIFT, TWIN_NONE

    shift = PAIR_SHIFT.get(twin_kind, 0)

    def kernel(*refs):
        (Am, ArK, AM1, Arcp1, Arcp, Aact,
         Bm, BrK, BM1, Brcp1, Brcp, Bact,
         Cm, CrK, Crcp, Cact,
         Dm, DrK, Drcp, Dact,
         ci, cm, fi, fm, coff, foff, nb, pm) = refs[:28]
        acc = refs[28]
        if need_bits:
            words_out, buf = refs[29], refs[30]
        else:
            words_out, buf = None, refs[29]

        t = pl.program_id(0)
        base = t * TILE_WORDS
        row = lax.broadcasted_iota(jnp.int32, (R_ROWS, 128), 0)
        lane = lax.broadcasted_iota(jnp.int32, (R_ROWS, 128), 1)

        ws = _mark_tile(
            base, row, lane,
            Am, ArK, AM1, Arcp1, Arcp, Aact,
            Bm, BrK, BM1, Brcp1, Brcp, Bact,
            Cm, CrK, Crcp, Cact,
            Dm, DrK, Drcp, Dact,
            SB, SC, ND,
        )

        # --- in-tile patch: flat clears BEFORE corrections (a flat class
        # can cross its own seed's bit, which the correction re-sets),
        # then the validity mask — bit-for-bit the reduce_packed order.
        widx = base + row * 128 + lane

        def fbody(i, w):
            return w & ~jnp.where(widx == fi[0, i], fm[0, i], _U32(0))

        ws = lax.fori_loop(foff[0, t], foff[0, t + 1], fbody, ws)

        def cbody(i, w):
            return w | jnp.where(widx == ci[0, i], cm[0, i], _U32(0))

        ws = lax.fori_loop(coff[0, t], coff[0, t + 1], cbody, ws)

        nbits_s = nb[0, 0]
        bits_valid = jnp.clip(nbits_s - 32 * widx, 0, 32)
        part = (_U32(1) << jnp.minimum(bits_valid, 31).astype(_U32)) - _U32(1)
        ws = ws & jnp.where(bits_valid >= 32, _U32(0xFFFFFFFF), part)

        if need_bits:
            words_out[:, :] = ws

        # --- park tile t; static-index stores under slot-parity whens
        # (Mosaic cannot dynamically index the leading scratch dim)
        slot = lax.rem(t, 2)

        @pl.when(slot == 0)
        def _():
            buf[0] = ws

        @pl.when(slot == 1)
        def _():
            buf[1] = ws

        @pl.when(t == 0)
        def _():
            for j in range(8):
                acc[0, j] = _U32(0)

        pmask = pm[0, 0]
        zero = jnp.zeros((R_ROWS, 128), _U32)

        def reduce_tile(k, w):
            """Fold tile k's fully patched words into the accumulators.
            Scalar extraction is a masked full-tile sum (Mosaic cannot
            scalar-load a dynamic position from a vector value)."""
            kwidx = k * TILE_WORDS + row * 128 + lane
            acc[0, 0] = acc[0, 0] + jnp.sum(
                lax.population_count(w), dtype=_U32)
            fw = jnp.sum(
                jnp.where((row == 0) & (lane == 0), w, zero), dtype=_U32)
            lw = jnp.sum(
                jnp.where((row == R_ROWS - 1) & (lane == 127), w, zero),
                dtype=_U32)
            wl = (nbits_s - 32) // 32
            acc[0, 5] = acc[0, 5] + jnp.sum(
                jnp.where(kwidx == wl, w, zero), dtype=_U32)
            acc[0, 6] = acc[0, 6] + jnp.sum(
                jnp.where(kwidx == wl + 1, w, zero), dtype=_U32)
            if twin_kind != TWIN_NONE:
                low = _U32((1 << shift) - 1)
                nxt1 = pltpu.roll(w, 127, axis=1)   # w[r, l+1 mod 128]
                nxt = jnp.where(
                    lane == 127,
                    pltpu.roll(nxt1, R_ROWS - 1, axis=0),  # w[r+1, 0]
                    nxt1,
                )
                spl = (w >> _U32(shift)) | (nxt & low) << _U32(32 - shift)
                adj = w & spl & pmask
                # tile-last word's neighbor lives in the NEXT tile: defer
                adj = jnp.where(
                    (row == R_ROWS - 1) & (lane == 127), zero, adj)
                prev = acc[0, 4]
                spl_b = (prev >> _U32(shift)) | (fw & low) << _U32(32 - shift)
                acc[0, 1] = (
                    acc[0, 1]
                    + jnp.sum(lax.population_count(adj), dtype=_U32)
                    + lax.population_count(prev & spl_b & pmask)
                )

            @pl.when(k == 0)
            def _():
                acc[0, 2] = fw

            acc[0, 4] = lw

        @pl.when(t > 0)
        def _():
            prev_tile = jnp.where(slot == 0, buf[1], buf[0])
            reduce_tile(t - 1, prev_tile)

        @pl.when(t == G - 1)
        def _():
            reduce_tile(t, ws)
            # last-boundary splice, reduce_packed's formula verbatim
            off = nbits_s - 32
            sh = (off % 32).astype(_U32)
            spliced = (acc[0, 5] >> sh) | jnp.where(
                sh == 0, _U32(0), acc[0, 6] << (_U32(32) - sh)
            )
            acc[0, 3] = spliced

    return kernel


@functools.lru_cache(maxsize=None)
def _build_fused_call(Wpad: int, SB: int, SC: int, ND: int, CC: int,
                      FC: int, twin_kind: int, need_bits: bool,
                      interpret: bool):
    grid = Wpad // TILE_WORDS
    kernel = _make_fused_kernel(grid, SB, SC, ND, twin_kind, need_bits)
    Wrows = Wpad // 128

    def smem(n):
        return pl.BlockSpec((1, n), lambda t: (0, 0), memory_space=pltpu.SMEM)

    def vmem_rows(nrows):
        return pl.BlockSpec(
            (nrows, D_LANES), lambda t: (0, 0), memory_space=pltpu.VMEM
        )

    in_specs = (
        [smem(NA_PAD)] * 6
        + [smem(SB)] * 6
        + [smem(SC)] * 4
        + [vmem_rows(max(ND, 1))] * 4
        + [smem(CC)] * 2          # corr idx / mask
        + [smem(FC)] * 2          # flat idx / mask
        + [smem(grid + 1)] * 2    # corr / flat per-tile cursors
        + [smem(1)] * 2           # nbits, pair_mask
    )
    out_specs = [pl.BlockSpec((1, 8), lambda t: (0, 0),
                              memory_space=pltpu.SMEM)]
    out_shape = [jax.ShapeDtypeStruct((1, 8), jnp.uint32)]
    if need_bits:
        out_specs.append(pl.BlockSpec((R_ROWS, 128), lambda t: (t, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((Wrows, 128), jnp.uint32))
    call = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=tuple(out_specs) if need_bits else out_specs[0],
        out_shape=tuple(out_shape) if need_bits else out_shape[0],
        scratch_shapes=[pltpu.VMEM((2, R_ROWS, 128), jnp.uint32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return call


@functools.lru_cache(maxsize=None)
def _build_fused_jit(Wpad, SB, SC, ND, CC, FC, twin_kind, need_bits,
                     interpret):
    call = _build_fused_call(Wpad, SB, SC, ND, CC, FC, twin_kind,
                             need_bits, interpret)
    return jax.jit(lambda *a: call(*a))


def fused_args(ps: PallasSegment) -> tuple:
    """The fused call's argument tuple for one prepared segment (host-side
    numpy; shared by mark_pallas_fused, the mesh step, and the profilers)."""
    return (
        tuple(ps.A) + tuple(ps.B) + tuple(ps.C) + tuple(ps.D) + (
            ps.corr_idx, ps.corr_mask, ps.flat_idx, ps.flat_mask,
            tile_offsets(ps.corr_idx, ps.corr_mask, ps.Wpad),
            tile_offsets(ps.flat_idx, ps.flat_mask, ps.Wpad),
            np.full((1, 1), ps.nbits, np.int32),
            np.full((1, 1), ps.pair_mask, np.uint32),
        )
    )


def mark_pallas_fused(ps: PallasSegment, twin_kind: int, interpret: bool,
                      need_bits: bool = False):
    """Run the fused mark+reduce kernel; returns (count, pairs, first_word,
    last_word) — and additionally the patched, validity-masked word array
    (shape (Wpad//128, 128)) when ``need_bits``. Unlike the split path's
    raw kernel output, the need_bits words are FINAL: flat clears,
    corrections, and the beyond-nbits mask are already applied, so
    enumeration/checkpoint consumers can use them directly."""
    SB = ps.B[0].shape[1]
    SC = ps.C[0].shape[1]
    ND = ps.D[0].shape[0] if ps.D[3].any() else 0
    CC = ps.corr_idx.shape[1]
    FC = ps.flat_idx.shape[1]
    call = _build_fused_jit(ps.Wpad, SB, SC, ND, CC, FC, twin_kind,
                            need_bits, interpret)
    out = call(*fused_args(ps))
    if need_bits:
        acc, words = out
        acc = np.asarray(acc)
        res = tuple(int(v) for v in acc[0, :4])
        return res, np.asarray(words)
    acc = np.asarray(out)  # one uint32[1, 8] fetch
    return tuple(int(v) for v in acc[0, :4])


def pallas_fused_enabled() -> bool:
    """Fused in-kernel reduction is the default; SIEVE_PALLAS_FUSED=0
    selects the split kernel + XLA-postlude path (the parity oracle).
    Read per call so tests and dryruns can flip it."""
    v = env.env_str("SIEVE_PALLAS_FUSED")
    if v is None:
        v = str(_TUNED.get("SIEVE_PALLAS_FUSED", "1"))
    return v != "0"


def _postlude(words, nbits, pair_mask, ci, cm, twin_kind: int,
              fi=None, fm=None):
    """XLA tail on the kernel's words: flat clears + corrections +
    reductions."""
    from sieve.kernels.jax_mark import reduce_packed

    return reduce_packed(
        words.reshape(-1), nbits, twin_kind, pair_mask, ci, cm, fi, fm
    )


@functools.lru_cache(maxsize=None)
def _build_call_jit(Wpad, twin_kind, SB, SC, ND, FC, interpret):
    call = _build_call(Wpad, SB, SC, ND, interpret)

    def run(nbits, pmask, A_B_C_D_args, ci, cm, fi, fm):
        from sieve.kernels.jax_mark import pack4

        words = call(*A_B_C_D_args)
        return pack4(*_postlude(words, nbits, pmask, ci, cm, twin_kind,
                                fi, fm))

    return jax.jit(run, static_argnames=())


def mark_pallas_split(ps: PallasSegment, twin_kind: int, interpret: bool):
    """Run the marking kernel + XLA postlude; returns (count, twins,
    first_word, last_word). The packed words stay on device; only four
    scalars cross to the host. Kept verbatim as the fused path's parity
    oracle (and the fallback for SMEM-oversized correction lists)."""
    SB = ps.B[0].shape[1]
    SC = ps.C[0].shape[1]
    ND = ps.D[0].shape[0] if ps.D[3].any() else 0
    FC = ps.flat_idx.shape[1] if ps.flat_mask.any() else 0
    call = _build_call_jit(ps.Wpad, twin_kind, SB, SC, ND, FC, interpret)
    packed = np.asarray(call(
        np.int32(ps.nbits),
        np.uint32(ps.pair_mask),
        tuple(ps.A) + tuple(ps.B) + tuple(ps.C) + tuple(ps.D),
        ps.corr_idx[0],
        ps.corr_mask[0],
        ps.flat_idx[0, :FC],
        ps.flat_mask[0, :FC],
    ))  # one uint32[4] fetch: count, twins, first, last
    return int(packed[0]), int(packed[1]), int(packed[2]), int(packed[3])


def mark_pallas(ps: PallasSegment, twin_kind: int, interpret: bool):
    """Segment entry point: fused in-kernel reduction by default,
    SIEVE_PALLAS_FUSED=0 for the split kernel + postlude. Both return the
    same (count, pairs, first_word, last_word) quadruple."""
    if pallas_fused_enabled():
        return mark_pallas_fused(ps, twin_kind, interpret)
    return mark_pallas_split(ps, twin_kind, interpret)
