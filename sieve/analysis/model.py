"""Repo-specific concurrency model: thread roles, the canonical lock
order, and the blocking-call list for the event-loop rule (ISSUE 15).

This module is *data*, not machinery — :mod:`sieve.analysis.checks`
consumes a :class:`Model` and the default instance below describes the
sieve service plane. Fixture tests build their own small Models.

Canonical lock order
--------------------

``CANONICAL_LOCK_ORDER`` lists every lock in the package, outermost
first: a thread may only acquire a lock whose index is *greater* than
every lock it already holds. The order is derived from the acquisition
edges the analyzer observes (``tools/check_concurrency.py --dump``
prints them) and is cross-checked at runtime by
:mod:`sieve.analysis.lockdebug` under ``SIEVE_LOCK_DEBUG=1``. Adding a
lock means adding it here — an acquisition edge touching an unlisted
lock is a finding.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class Model:
    # allowed acquisition order, outermost first
    canonical_lock_order: tuple[str, ...] = ()
    # roles that run an event loop and must never block
    loop_roles: frozenset[str] = frozenset()
    # dotted external calls that block
    blocking_calls: frozenset[str] = frozenset()
    # resolved-target prefixes that block (module or Class. prefixes)
    blocking_prefixes: tuple[str, ...] = ()
    # bare attribute-call names that block regardless of receiver type
    blocking_attrs: frozenset[str] = frozenset({"wait"})
    # class names whose public methods seed the synthetic "app" role
    # (the application thread calling the public API)
    app_role_classes: frozenset[str] = frozenset()
    # extra (qualname, role) seeds
    extra_seeds: tuple[tuple[str, str], ...] = ()


# Locks outermost-first. Derived from the observed acquisition edges
# (``tools/check_concurrency.py --dump``); the runtime sanitizer
# asserts real executions agree. Within a tier the order is alphabetic
# convention — no edge exists yet — but once committed it is law: a new
# nesting that contradicts it is a finding, not a reason to reshuffle.
CANONICAL_LOCK_ORDER: tuple[str, ...] = (
    # -- cluster / client coordination (outermost: these call into
    #    everything below while held only in stop/teardown paths)
    "_Cluster.lock",
    "_Cluster.tele_lock",
    # -- fleet observer (ISSUE 19): guards trend/EWMA state only; by
    #    contract never held across a pool RPC or ring I/O, so it sits
    #    above the client tier without real edges into it
    "FleetObserver._lock",
    "ClientPool._lock",
    "ReplicaSet._lock",
    "_Replica.lock",
    # -- service plane outer tier: queue admission, refresh, dispatch
    "LedgerFollower._poll_lock",
    "SieveService._lane_cond",
    "SieveService._cold_lock",
    "SieveService._slo_lock",
    "SieveService._seq_lock",
    "SieveService._inflight_lock",
    "SieveService._conns_lock",
    "SieveService._stats_lock",
    # -- router tier
    "SieveRouter._totals_lock",
    "SieveRouter._down_lock",
    "SieveRouter._tele_lock",
    "SieveRouter._seq_lock",
    "SieveRouter._inflight_lock",
    "SieveRouter._conns_lock",
    "SieveRouter._stats_lock",
    # -- per-connection write path: tx (the wire) strictly outside
    #    lock (the queue) — _flush holds tx across queue inspections
    "_Conn.tx",
    "_Conn.lock",
    # -- cold backend: dispatch serialization, then breaker state
    "ColdBackend._lock",
    "ColdBackend._state_lock",
    # -- index tier
    "SieveIndex._stat_lock",
    "BitsetLRU._lock",
    # -- tiered segment store (ISSUE 17): entered from index demotion
    #    callbacks (fired AFTER BitsetLRU._lock is released) and from
    #    the store's own compactor thread; holds only leaf locks below
    #    (ChaosSchedule draw, metrics emits happen outside _lock)
    "TieredSegmentStore._lock",
    # -- capacity observatory sinks (ISSUE 19): the sampler's decision
    #    window/ring lock and its writer-queue condition are never
    #    nested with each other (keep() releases _lock before the
    #    enqueue; the writer thread releases the condition before
    #    touching the file); the snapshot ring holds only its own I/O
    "ExemplarSampler._lock",
    "ExemplarSampler._io_cond",
    "SnapshotRing._lock",
    # -- client wire-event logger init (ISSUE 16): taken during client
    #    construction (possibly under _Replica.lock) and released
    #    before the metrics leaf locks below are touched
    "client._wire_logger_lock",
    # -- leaf infrastructure (innermost: never call out while held)
    "ChaosSchedule._lock",
    # continuous profiler (ISSUE 20): guards the collapsed-stack table
    # only — held for one fold or one snapshot copy, never while
    # walking frames, drawing chaos, or emitting metrics
    "StackProfiler._lock",
    "FlightRecorder._lock",
    "MetricsHistory._lock",
    "MetricsRegistry._lock",
    "Counter._lock",
    "Gauge._lock",
    "Histogram._lock",
    "metrics._SINKS_LOCK",
    "MemorySink._lock",
    "StreamSink._lock",
    "PrepPipeline._cond",
    "Tracer._lock",
    "seed._cache_lock",
    # lockdebug's own pair-set mutex: the sanitizer records
    # while the recorded lock is already held, so it is the
    # global innermost lock by construction
    "_Recorder._mu",
)


#: Thread roles that run a selectors-based event loop: nothing
#: reachable from these may block (no waits, sleeps, ledger I/O, rpc
#: sends, or backend dispatch).
LOOP_ROLES = frozenset({"svc-wire", "router-accept"})

BLOCKING_CALLS = frozenset({
    "time.sleep",
    "socket.create_connection",
    "subprocess.run",
    "subprocess.check_call",
    "subprocess.check_output",
})

BLOCKING_PREFIXES = (
    # framed send/recv block on the socket (FrameDecoder/encode_msg are
    # pure CPU and deliberately not listed)
    "sieve.rpc:send_msg",
    "sieve.rpc:recv_msg",
    "sieve.rpc:_recv_exact",
    "sieve.checkpoint:",   # ledger I/O (fsync)
    # cold backend dispatch (ISSUE 18): listed per-method — describe()
    # and the _state_lock health probes are in-memory snapshots the wire
    # loop answers inline, so the class must NOT be blanket-blocking
    "sieve.service.server:ColdBackend.count_range",
    "sieve.service.server:ColdBackend.count_ranges",
    "sieve.service.server:ColdBackend._mesh_locked",  # device probe
    "sieve.service.server:ColdBackend._mesh_dispatch",  # SPMD launch
    "sieve.service.server:ColdBackend.close",
    "sieve.service.server:ColdBatcher.submit",  # waits on a flight
    # tiered segment store (ISSUE 17): appends/loads/compaction do file
    # I/O under a cross-process flock. Listed per-method on purpose —
    # stats()/health() are in-memory snapshots the wire loop answers
    # inline, so the whole module must NOT be blanket-blocking.
    "sieve.service.store:TieredSegmentStore.put_",
    "sieve.service.store:TieredSegmentStore.load_",
    "sieve.service.store:TieredSegmentStore.compact",
    "sieve.service.store:TieredSegmentStore.maybe_refresh",
    "sieve.service.store:TieredSegmentStore.import_ledger",
    "sieve.service.store:TieredSegmentStore.close",
)

APP_ROLE_CLASSES = frozenset({
    "SieveService",
    "SieveRouter",
    "ServiceClient",
    "ClientPool",
    "ReplicaSet",
    "ColdBackend",
    "TieredSegmentStore",
})


def default_model() -> Model:
    return Model(
        canonical_lock_order=CANONICAL_LOCK_ORDER,
        loop_roles=LOOP_ROLES,
        blocking_calls=BLOCKING_CALLS,
        blocking_prefixes=BLOCKING_PREFIXES,
        app_role_classes=APP_ROLE_CLASSES,
    )


#: Known constructor-like helpers: call target -> class fullid, so the
#: scanner can type ``tr = trace.get_tracer()`` receivers.
RETURN_TYPES = {
    "sieve.trace:get_tracer": "sieve.trace:Tracer",
    "sieve.metrics:registry": "sieve.metrics:MetricsRegistry",
}
