"""Concurrency checks over a scanned :class:`~sieve.analysis.core.Program`.

Four families of findings, each with a *stable* key (no line numbers)
so the committed baseline ratchets instead of churning:

* ``lock-order:A->B@func`` / ``lock-cycle:...`` / ``lock-self:...`` /
  ``lock-unlisted:...`` / ``lock-name:...`` — the acquisition graph
  against the canonical order.
* ``loop-blocking:role:func:op`` — blocking operation reachable from an
  event-loop role.
* ``guard:Class.attr@func`` — access to a ``# guard:``-annotated shared
  attribute without its lock held.
* ``unannotated:Class.attr`` — mutable attribute of a lock-owning class
  touched from >= 2 thread roles with no ``# guard:`` declaration.
"""

from __future__ import annotations

import dataclasses

from sieve.analysis.core import FunctionInfo, Program
from sieve.analysis.model import Model


@dataclasses.dataclass
class Finding:
    kind: str
    key: str  # stable baseline key
    msg: str
    where: str  # "module:func (path:line)"-ish display hint

    def __str__(self) -> str:
        return f"[{self.kind}] {self.key}: {self.msg} ({self.where})"


# --- thread roles --------------------------------------------------------


def assign_roles(prog: Program, model: Model) -> dict[str, set[str]]:
    """Map function qualname -> set of thread-role names that reach it.

    Seeds: every ``threading.Thread(...)`` spawn target (role = the
    thread's ``name=``), every ``Thread`` subclass ``run`` method
    (role = class name), the synthetic ``app`` role at public methods
    of the API classes, and any extra model seeds. Roles then flow
    along resolved call edges — but *not* through spawn sites: the
    spawned function runs on the new thread, not the spawner's.
    """
    roles: dict[str, set[str]] = {q: set() for q in prog.functions}
    work: list[str] = []

    def seed(qual: str | None, role: str) -> None:
        if qual is not None and qual in roles and role not in roles[qual]:
            roles[qual].add(role)
            work.append(qual)

    for fi in prog.functions.values():
        for sp in fi.spawns:
            seed(sp.target, sp.role)
    for ci in prog.classes.values():
        if ci.is_thread:
            seed(ci.methods.get("run"), ci.name)
        if ci.name in model.app_role_classes:
            for mname, qual in ci.methods.items():
                if not mname.startswith("_") or mname == "__init__":
                    seed(qual, "app")
    for qual, role in model.extra_seeds:
        seed(qual, role)

    while work:
        q = work.pop()
        fi = prog.functions[q]
        spawn_lines = {sp.line for sp in fi.spawns}
        for ce in fi.calls:
            if ce.target is None or ce.target not in roles:
                continue
            if ce.line in spawn_lines:
                continue  # the ctor call at a spawn site is not an edge
            for r in roles[q]:
                if r not in roles[ce.target]:
                    roles[ce.target].add(r)
                    work.append(ce.target)
    return roles


# --- lock graph ----------------------------------------------------------


def transitive_acquires(prog: Program) -> dict[str, set[str]]:
    """TA(f): every lock ``f`` may acquire, directly or via callees."""
    ta: dict[str, set[str]] = {
        q: {a.lock for a in fi.acquires}
        for q, fi in prog.functions.items()
    }
    changed = True
    while changed:
        changed = False
        for q, fi in prog.functions.items():
            cur = ta[q]
            for ce in fi.calls:
                if ce.target in ta:
                    extra = ta[ce.target] - cur
                    if extra:
                        cur |= extra
                        changed = True
    return ta


def lock_edges(prog: Program) -> dict[tuple[str, str], list[tuple[str, int]]]:
    """(held, acquired) -> [(func, line)] — direct ``with``-nesting plus
    interprocedural edges via calls made while holding a lock."""
    edges: dict[tuple[str, str], list[tuple[str, int]]] = {}

    def add(a: str, b: str, func: str, line: int) -> None:
        edges.setdefault((a, b), []).append((func, line))

    ta = transitive_acquires(prog)
    for q, fi in prog.functions.items():
        for ae in fi.acquires:
            for h in ae.held:
                add(h, ae.lock, q, ae.line)
        for ce in fi.calls:
            if ce.target not in ta or not ce.held:
                continue
            for h in ce.held:
                for l in ta[ce.target]:
                    add(h, l, q, ce.line)
    return edges


def _lock_kinds(prog: Program) -> dict[str, str]:
    kinds: dict[str, str] = {}
    for ci in prog.classes.values():
        for d in ci.locks.values():
            kinds[d.lock_id] = d.kind
    for m in prog.modules.values():
        for d in m.locks.values():
            kinds[d.lock_id] = d.kind
    return kinds


def _sccs(nodes: set[str], succ: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan SCCs, iterative; returns only components of size > 1."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        call = [(v0, iter(sorted(succ.get(v0, ()))))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on.add(v0)
        while call:
            v, it = call[-1]
            advanced = False
            for w in it:
                if w not in nodes:
                    continue
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    call.append((w, iter(sorted(succ.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            call.pop()
            if call:
                pv = call[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    return out


# --- the checks ----------------------------------------------------------


def check_lock_order(prog: Program, model: Model) -> list[Finding]:
    findings: list[Finding] = []
    order = {lock: i for i, lock in enumerate(model.canonical_lock_order)}
    kinds = _lock_kinds(prog)
    edges = lock_edges(prog)

    unlisted = {
        lock for lock in kinds if lock not in order
    } | {
        l for (a, b) in edges for l in (a, b) if l not in order
    }
    for lock in sorted(unlisted):
        findings.append(Finding(
            kind="lock-unlisted", key=f"lock-unlisted:{lock}",
            msg=f"lock {lock} missing from CANONICAL_LOCK_ORDER",
            where=lock))

    succ: dict[str, set[str]] = {}
    for (a, b), sites in sorted(edges.items()):
        func, line = sites[0]
        if a == b:
            if kinds.get(a) != "rlock":
                findings.append(Finding(
                    kind="lock-self", key=f"lock-self:{a}@{func}",
                    msg=f"re-acquisition of non-reentrant {a}",
                    where=f"{func}:{line}"))
            continue
        succ.setdefault(a, set()).add(b)
        if a in order and b in order and order[a] > order[b]:
            findings.append(Finding(
                kind="lock-order", key=f"lock-order:{a}->{b}@{func}",
                msg=(f"acquires {b} while holding {a}, against the "
                     f"canonical order"),
                where=f"{func}:{line}"))
    nodes = {l for (a, b) in edges for l in (a, b)}
    for comp in _sccs(nodes, succ):
        findings.append(Finding(
            kind="lock-cycle", key="lock-cycle:" + ">".join(comp),
            msg="cyclic lock acquisition (potential deadlock): "
                + " <-> ".join(comp),
            where=comp[0]))

    # named_lock literal must match the derived identity
    decls = [d for ci in prog.classes.values() for d in ci.locks.values()]
    decls += [d for m in prog.modules.values() for d in m.locks.values()]
    for d in decls:
        if d.given_name is not None and d.given_name != d.lock_id:
            findings.append(Finding(
                kind="lock-name", key=f"lock-name:{d.lock_id}",
                msg=(f"named_lock({d.given_name!r}) does not match the "
                     f"derived identity {d.lock_id!r}"),
                where=f"{d.lock_id}:{d.line}"))
    return findings


def check_loop_blocking(prog: Program, model: Model,
                        roles: dict[str, set[str]]) -> list[Finding]:
    findings: list[Finding] = []
    for q, fi in prog.functions.items():
        hit = roles.get(q, set()) & model.loop_roles
        if not hit:
            continue
        role = sorted(hit)[0]
        spawn_lines = {sp.line for sp in fi.spawns}
        seen: set[str] = set()
        for ce in fi.calls:
            if ce.line in spawn_lines:
                continue
            op = None
            if ce.target is not None:
                if ce.target in model.blocking_calls:
                    op = ce.target
                else:
                    for p in model.blocking_prefixes:
                        if ce.target.startswith(p):
                            op = ce.target
                            break
            if op is None and ce.attr in model.blocking_attrs:
                op = f".{ce.attr}"
            if op is None or op in seen:
                continue
            seen.add(op)
            findings.append(Finding(
                kind="loop-blocking",
                key=f"loop-blocking:{role}:{q}:{op}",
                msg=f"blocking op {op} reachable from loop role {role}",
                where=f"{q}:{ce.line}"))
    return findings


def _guard_exempt(fi: FunctionInfo, owner_fullid: str) -> bool:
    """Constructors of the owning class publish before sharing."""
    if fi.cls != owner_fullid:
        return False
    local = fi.qualname.rsplit(".", 1)[-1]
    return local in ("__init__", "__post_init__")


def check_guards(prog: Program, roles: dict[str, set[str]]) -> list[Finding]:
    findings: list[Finding] = []
    # gather declared guards: (owner_fullid, attr) -> (lock_id|None, decl)
    guards: dict[tuple[str, str], str | None] = {}
    for ci in prog.classes.values():
        for attr, g in ci.guards.items():
            if g.lock is None:
                guards[(ci.fullid, attr)] = None
            else:
                decl = ci.locks.get(g.lock)
                guards[(ci.fullid, attr)] = (
                    decl.lock_id if decl else f"{ci.name}.{g.lock}"
                )
    for m in prog.modules.values():
        for name, g in m.guards.items():
            if g.lock is None:
                guards[(f"{m.name}:", name)] = None
            else:
                decl = m.locks.get(g.lock)
                guards[(f"{m.name}:", name)] = (
                    decl.lock_id if decl else f"{m.base}.{g.lock}"
                )

    # accesses per guarded attr
    access_roles: dict[tuple[str, str], set[str]] = {}
    sites: dict[tuple[str, str], list[tuple[FunctionInfo, int, bool]]] = {}
    for q, fi in prog.functions.items():
        for ac in fi.accesses:
            key = (ac.owner, ac.attr)
            if key not in guards:
                continue
            lock_id = guards[key]
            if lock_id is None:
                continue  # guard: none(reason) — waived by annotation
            if _guard_exempt(fi, ac.owner):
                continue
            access_roles.setdefault(key, set()).update(
                roles.get(q, set()))
            if lock_id not in ac.held:
                sites.setdefault(key, []).append((fi, ac.line, ac.is_store))
    for key, bad in sorted(sites.items(),
                           key=lambda kv: (kv[0][0], kv[0][1])):
        if len(access_roles.get(key, set())) < 2:
            continue  # effectively single-threaded
        owner, attr = key
        disp = owner.split(":")[-1] or owner.split(":")[0].rsplit(".")[-1]
        flagged: set[str] = set()
        for fi, line, is_store in bad:
            if fi.qualname in flagged:
                continue
            flagged.add(fi.qualname)
            verb = "write to" if is_store else "read of"
            findings.append(Finding(
                kind="guard",
                key=f"guard:{disp}.{attr}@{fi.qualname}",
                msg=(f"{verb} {disp}.{attr} without "
                     f"{guards[key]} held"),
                where=f"{fi.qualname}:{line}"))
    return findings


def check_unannotated(prog: Program,
                      roles: dict[str, set[str]]) -> list[Finding]:
    """Mutable attrs of lock-owning classes touched from >= 2 roles but
    carrying no ``# guard:`` declaration."""
    findings: list[Finding] = []
    access_roles: dict[tuple[str, str], set[str]] = {}
    writers: dict[tuple[str, str], set[str]] = {}
    for q, fi in prog.functions.items():
        for ac in fi.accesses:
            key = (ac.owner, ac.attr)
            access_roles.setdefault(key, set()).update(roles.get(q, set()))
            if ac.is_store:
                writers.setdefault(key, set()).add(q)
    for ci in sorted(prog.classes.values(), key=lambda c: c.fullid):
        if not ci.locks and not ci.is_thread:
            continue
        # self-writes plus cross-object stores (obj.attr = ... from
        # another class, e.g. the follower poking the service)
        attrs = set(ci.attr_writes) | {
            a for (o, a) in writers if o == ci.fullid
        }
        for attr in sorted(attrs):
            if attr in ci.guards or attr in ci.locks \
                    or attr in ci.events or attr in ci.methods:
                continue
            init = ci.methods.get("__init__")
            wr = ci.attr_writes.get(attr, set()) \
                | writers.get((ci.fullid, attr), set())
            if wr <= ({init} if init else set()):
                continue  # only ever written during construction
            if len(access_roles.get((ci.fullid, attr), set())) < 2:
                continue
            findings.append(Finding(
                kind="unannotated",
                key=f"unannotated:{ci.name}.{attr}",
                msg=(f"{ci.name}.{attr} is written outside __init__ and "
                     f"reached from multiple thread roles but has no "
                     f"# guard: annotation"),
                where=ci.fullid))
    return findings


def analyze(prog: Program, model: Model) -> list[Finding]:
    roles = assign_roles(prog, model)
    findings = check_lock_order(prog, model)
    findings += check_loop_blocking(prog, model, roles)
    findings += check_guards(prog, roles)
    findings += check_unannotated(prog, roles)
    findings.sort(key=lambda f: (f.kind, f.key))
    return findings
