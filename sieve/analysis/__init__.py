"""Concurrency analysis for the threaded service plane (ISSUE 15).

Two halves that validate each other:

* **Static** (:mod:`sieve.analysis.core`, :mod:`~sieve.analysis.checks`,
  :mod:`~sieve.analysis.model`) — a stdlib-only (``ast``) pass over a
  source tree that builds a call graph, walks thread roles out from
  every ``threading.Thread`` creation site, extracts the lock-nesting
  graph from ``with``-statements, and checks it against the committed
  canonical lock order plus the ``# guard:`` shared-state annotations.
  Driven by ``tools/check_concurrency.py`` with a ratcheting baseline.
* **Dynamic** (:mod:`sieve.analysis.lockdebug`) — ``SIEVE_LOCK_DEBUG=1``
  swaps the named service-plane locks for recording wrappers, so the
  chaos/service smokes observe *real* acquisition orders and assert
  them consistent with the static canonical order. With the flag off
  the named constructors return plain ``threading`` primitives — the
  default path costs nothing.

This package is import-light on purpose: service modules import only
``lockdebug`` (stdlib ``threading`` + ``os``); the ast machinery loads
only inside the checker tools and tests.
"""
