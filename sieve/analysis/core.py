"""AST fact extraction for the concurrency analyzer (ISSUE 15).

Stdlib-only (``ast`` + source text; air-gap safe). :func:`scan` walks a
source tree and returns a :class:`Program` of per-function facts:

* resolved call sites (name-and-type based: ``self.x()`` through class
  methods, ``alias.f()`` through imports, ``obj.m()`` through attribute
  and parameter type inference),
* lock declarations (``threading.Lock/RLock/Condition`` attributes and
  the :mod:`sieve.analysis.lockdebug` named constructors, whose literal
  name must match the derived ``Class.attr`` identity),
* lock acquisitions from ``with`` statements, each recorded with the
  set of locks already held (lexically or via a ``# holds:`` contract
  comment on the enclosing ``def``),
* attribute accesses on lock-owning classes with the held set at the
  access site,
* thread-creation sites (``threading.Thread(target=..., name=...)`` and
  ``threading.Thread`` subclasses) that seed thread roles,
* blocking operations (``time.sleep``, ``.wait()``/``.join()``, queue
  gets, and the model-supplied blocking call list).

Annotation syntax (trailing comments, parsed from source text):

* ``self.attr = ...  # guard: _some_lock`` — shared attribute, must be
  touched under ``Class._some_lock`` wherever >= 2 thread roles reach.
* ``self.attr = ...  # guard: none(reason)`` — intentionally racy; the
  reason is required and shows up in ``--dump`` output.
* ``def f(...):  # holds: _some_lock`` — contract: every caller holds
  the named lock; the body is analyzed with it in the held set.

The scanner is deliberately approximate — unresolvable calls produce no
edge (under-approximation) and the committed baseline absorbs judged
false positives — but it is deterministic, so findings ratchet.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}
NAMED_CTORS = {
    "named_lock": "lock",
    "named_rlock": "rlock",
    "named_condition": "condition",
}

_GUARD_RE = re.compile(
    # the none(reason) close-paren may land on a continuation line;
    # the reason captured here is just the first line's worth
    r"#\s*guard:\s*(?:none\s*\((?P<reason>[^)]*)\)?|(?P<lock>[A-Za-z_]\w*))"
)
_HOLDS_RE = re.compile(r"#\s*holds:\s*(?P<locks>[\w.]+(?:\s*,\s*[\w.]+)*)")


@dataclasses.dataclass
class Guard:
    lock: str | None  # lock attr name; None means none(reason)
    reason: str
    line: int


@dataclasses.dataclass
class LockDecl:
    lock_id: str  # "Class.attr" or "modbase.name"
    kind: str  # lock | rlock | condition
    line: int
    given_name: str | None  # literal passed to a named_* ctor


@dataclasses.dataclass
class CallEvent:
    target: str | None  # "module:qual" | external dotted | None
    attr: str | None  # bare attribute name for unresolved method calls
    line: int
    held: tuple[str, ...]


@dataclasses.dataclass
class AcquireEvent:
    lock: str
    line: int
    held: tuple[str, ...]


@dataclasses.dataclass
class Access:
    owner: str  # class fullid "module:Class", or "module:" for globals
    attr: str
    is_store: bool
    line: int
    held: tuple[str, ...]


@dataclasses.dataclass
class BlockEvent:
    op: str
    line: int


@dataclasses.dataclass
class ThreadSpawn:
    role: str
    target: str | None
    line: int


@dataclasses.dataclass
class FunctionInfo:
    qualname: str  # "module:Class.method" | "module:func" | nested "a.b"
    module: str
    cls: str | None  # fullid of enclosing class
    line: int
    holds: tuple[str, ...] = ()
    calls: list[CallEvent] = dataclasses.field(default_factory=list)
    acquires: list[AcquireEvent] = dataclasses.field(default_factory=list)
    accesses: list[Access] = dataclasses.field(default_factory=list)
    blocking: list[BlockEvent] = dataclasses.field(default_factory=list)
    spawns: list[ThreadSpawn] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassInfo:
    fullid: str  # "module:Class"
    name: str
    module: str
    line: int
    bases: list[str] = dataclasses.field(default_factory=list)
    methods: dict[str, str] = dataclasses.field(default_factory=dict)
    locks: dict[str, LockDecl] = dataclasses.field(default_factory=dict)
    events: set[str] = dataclasses.field(default_factory=set)
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)
    guards: dict[str, Guard] = dataclasses.field(default_factory=dict)
    attr_writes: dict[str, set[str]] = dataclasses.field(
        default_factory=dict
    )  # attr -> funcs that store it
    is_thread: bool = False


@dataclasses.dataclass
class ModuleInfo:
    name: str  # dotted
    path: str
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    functions: dict[str, str] = dataclasses.field(default_factory=dict)
    locks: dict[str, LockDecl] = dataclasses.field(default_factory=dict)
    guards: dict[str, Guard] = dataclasses.field(default_factory=dict)
    imports: dict[str, str] = dataclasses.field(default_factory=dict)
    from_imports: dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def base(self) -> str:
        return self.name.rsplit(".", 1)[-1]


@dataclasses.dataclass
class Program:
    modules: dict[str, ModuleInfo]
    functions: dict[str, FunctionInfo]
    classes: dict[str, ClassInfo]  # by fullid

    def lock_ids(self) -> set[str]:
        out = set()
        for c in self.classes.values():
            out.update(d.lock_id for d in c.locks.values())
        for m in self.modules.values():
            out.update(d.lock_id for d in m.locks.values())
        return out


# --- discovery -----------------------------------------------------------


def _py_modules(root: str, pkg: str) -> list[tuple[str, str]]:
    """(dotted module name, path) under ``root`` (the package dir)."""
    out: list[tuple[str, str]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for f in sorted(filenames):
            if not f.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, f), root)
            parts = rel[:-3].split(os.sep)
            if parts[-1] == "__init__":
                parts = parts[:-1]
            out.append((".".join([pkg] + parts) if parts else pkg,
                        os.path.join(dirpath, f)))
    return out


def _ann_class_name(node: ast.AST | None) -> str | None:
    """Best-effort class name from an annotation expression: unwraps
    ``X | None``, ``Optional[X]``, quoted strings, and dotted names
    (keeping the final component)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _ann_class_name(node.left)
    if isinstance(node, ast.Subscript):
        base = _ann_class_name(node.value)
        if base in ("Optional", "Final"):
            return _ann_class_name(node.slice)
        return None  # dict[...]/list[...] element types stay untyped
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _joined_prefix(node: ast.JoinedStr) -> str:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            break
    return "".join(parts).rstrip("-_ .")


class _ModuleScanner:
    """Pass A: structure (classes, methods, locks, guards, imports)."""

    def __init__(self, name: str, path: str, src: str):
        self.info = ModuleInfo(name=name, path=path)
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)

    def _comment_guard(self, line: int) -> Guard | None:
        if 1 <= line <= len(self.lines):
            m = _GUARD_RE.search(self.lines[line - 1])
            if m:
                return Guard(lock=m.group("lock"),
                             reason=(m.group("reason") or "").strip(),
                             line=line)
        return None

    def _comment_holds(self, line: int) -> tuple[str, ...]:
        if 1 <= line <= len(self.lines):
            m = _HOLDS_RE.search(self.lines[line - 1])
            if m:
                return tuple(s.strip() for s in m.group("locks").split(","))
        return ()

    def scan(self) -> tuple[ModuleInfo, ast.Module]:
        mod = self.info
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    mod.from_imports[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_func(node, prefix="", cls=None)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._module_assign(node)
        return mod, self.tree

    # -- pieces -----------------------------------------------------------

    def _lock_decl(self, value: ast.AST, derived_id: str) -> LockDecl | None:
        if not isinstance(value, ast.Call):
            return None
        fn = value.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name in LOCK_CTORS:
            return LockDecl(derived_id, LOCK_CTORS[name], value.lineno, None)
        if name in NAMED_CTORS:
            given = None
            if value.args and isinstance(value.args[0], ast.Constant) \
                    and isinstance(value.args[0].value, str):
                given = value.args[0].value
            return LockDecl(derived_id, NAMED_CTORS[name], value.lineno,
                            given)
        return None

    def _module_assign(self, node: ast.Assign | ast.AnnAssign) -> None:
        mod = self.info
        targets = node.targets if isinstance(node, ast.Assign) else (
            [node.target] if node.target is not None else []
        )
        value = node.value
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            derived = f"{mod.base}.{t.id}"
            decl = self._lock_decl(value, derived) if value else None
            if decl is not None:
                mod.locks[t.id] = decl
                continue
            g = self._comment_guard(node.lineno)
            if g is not None:
                mod.guards[t.id] = g

    def _scan_class(self, node: ast.ClassDef) -> None:
        mod = self.info
        ci = ClassInfo(
            fullid=f"{mod.name}:{node.name}", name=node.name,
            module=mod.name, line=node.lineno,
        )
        for b in node.bases:
            if isinstance(b, ast.Attribute):
                ci.bases.append(b.attr)
            elif isinstance(b, ast.Name):
                ci.bases.append(b.id)
        ci.is_thread = "Thread" in ci.bases
        mod.classes[node.name] = ci
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[item.name] = f"{mod.name}:{node.name}.{item.name}"
                self._scan_func(item, prefix=f"{node.name}.", cls=ci)
            elif isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name):
                # dataclass-style field declaration
                ty = _ann_class_name(item.annotation)
                if ty:
                    ci.attr_types.setdefault(item.target.id, ty)
                g = self._comment_guard(item.lineno)
                if g is not None:
                    ci.guards[item.target.id] = g

    def _scan_func(self, node: ast.FunctionDef | ast.AsyncFunctionDef,
                   prefix: str, cls: ClassInfo | None) -> None:
        mod = self.info
        qual = f"{mod.name}:{prefix}{node.name}"
        fi = FunctionInfo(qualname=qual, module=mod.name,
                          cls=cls.fullid if cls else None, line=node.lineno)
        fi.holds = self._comment_holds(node.lineno)
        mod.functions[f"{prefix}{node.name}"] = qual
        self._funcs.append((fi, node, cls))
        # class structure harvested from method bodies: self.X = ...
        if cls is not None:
            ann = {
                a.arg: _ann_class_name(a.annotation)
                for a in (node.args.posonlyargs + node.args.args
                          + node.args.kwonlyargs)
            }
            for sub in ast.walk(node):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    attr = t.attr
                    cls.attr_writes.setdefault(attr, set()).add(qual)
                    decl = self._lock_decl(
                        sub.value, f"{cls.name}.{attr}"
                    ) if sub.value else None
                    if decl is not None:
                        cls.locks[attr] = decl
                        continue
                    if self._is_event_ctor(sub.value):
                        cls.events.add(attr)
                    g = self._comment_guard(sub.lineno)
                    if g is not None and attr not in cls.guards:
                        cls.guards[attr] = g
                    ty = self._value_class_name(sub.value, ann)
                    if isinstance(sub, ast.AnnAssign) and ty is None:
                        ty = _ann_class_name(sub.annotation)
                    if ty:
                        cls.attr_types.setdefault(attr, ty)
        # nested defs become their own functions
        for sub in node.body:
            self._collect_nested(sub, f"{prefix}{node.name}.", cls)

    def _collect_nested(self, node: ast.AST, prefix: str,
                        cls: ClassInfo | None) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._scan_func(node, prefix=prefix, cls=cls)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                continue
            self._collect_nested(child, prefix, cls)

    @staticmethod
    def _is_event_ctor(value: ast.AST | None) -> bool:
        return (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "Event")

    def _value_class_name(self, value: ast.AST | None,
                          param_ann: dict[str, str | None]) -> str | None:
        """Class name of ``self.x = <value>``: a constructor call, an
        annotated-parameter passthrough, or either branch of a
        conditional expression."""
        if value is None:
            return None
        if isinstance(value, ast.IfExp):
            return (self._value_class_name(value.body, param_ann)
                    or self._value_class_name(value.orelse, param_ann))
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                got = self._value_class_name(v, param_ann)
                if got:
                    return got
            return None
        if isinstance(value, ast.Name):
            return param_ann.get(value.id)
        if isinstance(value, ast.Call):
            fn = value.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if name and name[:1].isupper():
                return name
        return None

    _funcs: list  # set in scan_all


# --- program-level scan --------------------------------------------------


def scan(root: str, pkg: str | None = None,
         return_types: dict[str, str] | None = None) -> Program:
    """Scan the package directory ``root`` into a :class:`Program`."""
    pkg = pkg or os.path.basename(os.path.abspath(root))
    scanners: list[tuple[_ModuleScanner, ast.Module]] = []
    prog = Program(modules={}, functions={}, classes={})
    pending: list[tuple[ModuleInfo, ast.Module, _ModuleScanner]] = []
    for name, path in _py_modules(root, pkg):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        sc = _ModuleScanner(name, path, src)
        sc._funcs = []
        mod, tree = sc.scan()
        prog.modules[name] = mod
        for ci in mod.classes.values():
            prog.classes[ci.fullid] = ci
        pending.append((mod, tree, sc))
    # pass B needs every module's structure for cross-module typing
    res = _Resolver(prog, return_types or {})
    for mod, tree, sc in pending:
        for fi, node, cls in sc._funcs:
            prog.functions[fi.qualname] = fi
            _BehaviorWalker(res, mod, cls, fi).run(node)
    return prog


class _Resolver:
    """Name/type resolution shared by the behavior walkers."""

    def __init__(self, prog: Program, return_types: dict[str, str]):
        self.prog = prog
        self.return_types = return_types
        self.class_by_name: dict[str, list[ClassInfo]] = {}
        for ci in prog.classes.values():
            self.class_by_name.setdefault(ci.name, []).append(ci)

    def class_named(self, name: str | None,
                    prefer_module: str | None = None) -> ClassInfo | None:
        if not name:
            return None
        cands = self.class_by_name.get(name, [])
        if not cands:
            return None
        if prefer_module:
            for c in cands:
                if c.module == prefer_module:
                    return c
        return cands[0]

    def module_of_alias(self, mod: ModuleInfo, alias: str) -> str | None:
        return mod.imports.get(alias)

    def from_import(self, mod: ModuleInfo, name: str) -> str | None:
        return mod.from_imports.get(name)


class _BehaviorWalker:
    """Pass B: per-function facts — calls, acquisitions, accesses, and
    thread spawns, each recorded with the lexically-held lock set."""

    def __init__(self, res: _Resolver, mod: ModuleInfo,
                 cls: ClassInfo | None, fi: FunctionInfo):
        self.res = res
        self.mod = mod
        self.cls = cls
        self.fi = fi
        self.local_types: dict[str, ClassInfo] = {}
        self.held: list[str] = [self._lock_id_of_name(h) for h in fi.holds]
        self.held = [h for h in self.held if h]

    # -- identities -------------------------------------------------------

    def _lock_id_of_name(self, name: str) -> str | None:
        """Resolve a ``# holds:``/``# guard:`` name to a full lock id."""
        if "." in name:
            return name
        if self.cls is not None and name in self.cls.locks:
            return self.cls.locks[name].lock_id
        if name in self.mod.locks:
            return self.mod.locks[name].lock_id
        if self.cls is not None:
            return f"{self.cls.name}.{name}"
        return f"{self.mod.base}.{name}"

    def _type_of_expr(self, node: ast.AST) -> ClassInfo | None:
        if isinstance(node, ast.Name):
            if node.id == "self":
                return self.cls
            return self.local_types.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._type_of_expr(node.value)
            if base is not None:
                ty = base.attr_types.get(node.attr)
                return self.res.class_named(ty, prefer_module=base.module)
            return None
        if isinstance(node, ast.Call):
            tgt = self._resolve_call_target(node.func)
            if tgt is None:
                return None
            if tgt in self.res.return_types:
                return self.res.prog.classes.get(self.res.return_types[tgt])
            return self.res.prog.classes.get(tgt)
        return None

    def _lock_of_expr(self, node: ast.AST) -> str | None:
        """Lock id of a ``with`` context expression, if it is a declared
        lock attribute (``self._x``, ``obj._x`` for a typed obj, or a
        module-level lock name)."""
        if isinstance(node, ast.Name):
            decl = self.mod.locks.get(node.id)
            return decl.lock_id if decl else None
        if isinstance(node, ast.Attribute):
            owner = self._type_of_expr(node.value)
            if owner is not None:
                decl = owner.locks.get(node.attr)
                if decl is not None:
                    return decl.lock_id
        return None

    # -- call resolution --------------------------------------------------

    def _resolve_call_target(self, fn: ast.AST) -> str | None:
        prog = self.res.prog
        if isinstance(fn, ast.Name):
            name = fn.id
            # nested def in the current scope chain, innermost first
            local = self.fi.qualname.split(":", 1)[1]
            parts = local.split(".")
            for i in range(len(parts), 0, -1):
                cand = ".".join(parts[:i] + [name])
                if cand in self.mod.functions:
                    return self.mod.functions[cand]
            if name in self.mod.functions:
                return self.mod.functions[name]
            if name in self.mod.classes:
                ci = self.mod.classes[name]
                return ci.fullid
            dotted = self.mod.from_imports.get(name)
            if dotted:
                m, _, attr = dotted.rpartition(".")
                tgt = prog.modules.get(m)
                if tgt is not None:
                    if attr in tgt.functions:
                        return tgt.functions[attr]
                    if attr in tgt.classes:
                        return tgt.classes[attr].fullid
                return dotted
            return None
        if isinstance(fn, ast.Attribute):
            # typed receiver -> method
            owner = self._type_of_expr(fn.value)
            if owner is not None:
                if fn.attr in owner.methods:
                    return owner.methods[fn.attr]
                return None
            # module alias -> module function / class / external dotted
            if isinstance(fn.value, ast.Name):
                dotted_mod = self.mod.imports.get(fn.value.id)
                if dotted_mod:
                    tgt = prog.modules.get(dotted_mod)
                    if tgt is not None:
                        if fn.attr in tgt.functions:
                            return tgt.functions[fn.attr]
                        if fn.attr in tgt.classes:
                            return tgt.classes[fn.attr].fullid
                    return f"{dotted_mod}.{fn.attr}"
                # from-imported class used as namespace? rare; give up
            return None
        return None

    # -- walking ----------------------------------------------------------

    def run(self, node: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        ann = {
            a.arg: _ann_class_name(a.annotation)
            for a in (node.args.posonlyargs + node.args.args
                      + node.args.kwonlyargs)
        }
        for pname, ty in ann.items():
            ci = self.res.class_named(ty, prefer_module=self.mod.name)
            if ci is not None:
                self.local_types[pname] = ci
        self._prepass_types(node.body)
        for stmt in node.body:
            self._visit_stmt(stmt)

    def _prepass_types(self, body: list[ast.stmt]) -> None:
        """Straight-line local type inference: ``x = Cls(...)``,
        ``x = self.attr`` for a typed attr, ``x = mod.fn()`` with a
        known return type."""
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                    continue
                t = sub.targets[0]
                if not isinstance(t, ast.Name):
                    continue
                ci = self._type_of_expr(sub.value)
                if ci is not None:
                    self.local_types.setdefault(t.id, ci)

    def _visit_stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs are separate FunctionInfos
        if isinstance(node, ast.With):
            self._visit_with(node)
            return
        for expr in self._stmt_exprs(node):
            self._visit_expr(expr)
        for child in self._stmt_blocks(node):
            self._visit_stmt(child)

    @staticmethod
    def _stmt_exprs(node: ast.stmt):
        for field, value in ast.iter_fields(node):
            if isinstance(value, ast.expr):
                yield value
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        yield v

    @staticmethod
    def _stmt_blocks(node: ast.stmt):
        for field in ("body", "orelse", "finalbody"):
            for child in getattr(node, field, []) or []:
                if isinstance(child, ast.stmt):
                    yield child
        for h in getattr(node, "handlers", []) or []:
            for child in h.body:
                yield child

    def _visit_with(self, node: ast.With) -> None:
        entered: list[str] = []
        for item in node.items:
            lock_id = self._lock_of_expr(item.context_expr)
            if lock_id is not None:
                self.fi.acquires.append(AcquireEvent(
                    lock=lock_id, line=item.context_expr.lineno,
                    held=tuple(self.held)))
                self.held.append(lock_id)
                entered.append(lock_id)
            else:
                self._visit_expr(item.context_expr)
        for child in node.body:
            self._visit_stmt(child)
        for _ in entered:
            self.held.pop()

    def _visit_expr(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._record_call(sub)
            elif isinstance(sub, ast.Attribute):
                self._record_access(sub)
            elif isinstance(sub, ast.Name):
                self._record_global_access(sub)

    def _record_access(self, node: ast.Attribute) -> None:
        owner = self._type_of_expr(node.value)
        if owner is None:
            return
        is_store = isinstance(node.ctx, (ast.Store, ast.Del))
        self.fi.accesses.append(Access(
            owner=owner.fullid, attr=node.attr, is_store=is_store,
            line=node.lineno, held=tuple(self.held)))

    def _record_global_access(self, node: ast.Name) -> None:
        if node.id not in self.mod.guards:
            return
        is_store = isinstance(node.ctx, (ast.Store, ast.Del))
        self.fi.accesses.append(Access(
            owner=f"{self.mod.name}:", attr=node.id, is_store=is_store,
            line=node.lineno, held=tuple(self.held)))

    def _record_call(self, node: ast.Call) -> None:
        target = self._resolve_call_target(node.func)
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        # string-literal receivers (", ".join) are never blocking calls
        if attr is not None and isinstance(node.func.value, ast.Constant):
            attr = None
        self.fi.calls.append(CallEvent(
            target=target, attr=attr, line=node.lineno,
            held=tuple(self.held)))
        if target == "threading.Thread":
            self._record_spawn(node)
        elif target is not None and ":" not in target \
                and target.endswith(".Thread"):
            self._record_spawn(node)
        else:
            ci = self.res.prog.classes.get(target) if target else None
            if ci is not None and ci.is_thread:
                run_q = ci.methods.get("run")
                self.fi.spawns.append(ThreadSpawn(
                    role=ci.name, target=run_q, line=node.lineno))

    def _record_spawn(self, node: ast.Call) -> None:
        role = None
        target_q = None
        for kw in node.keywords:
            if kw.arg == "name":
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    role = kw.value.value
                elif isinstance(kw.value, ast.JoinedStr):
                    role = _joined_prefix(kw.value) or None
            elif kw.arg == "target":
                target_q = self._resolve_call_target(kw.value)
        if role is None:
            if target_q is not None:
                role = f"{self.mod.base}.{target_q.rsplit('.', 1)[-1]}"
            else:
                role = f"{self.mod.base}.anon-thread"
        self.fi.spawns.append(ThreadSpawn(
            role=role, target=target_q, line=node.lineno))
