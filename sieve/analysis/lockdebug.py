"""Runtime lock-order sanitizer (the dynamic half of ISSUE 15).

``named_lock/named_rlock/named_condition`` construct the service
plane's locks. The name is the lock's *identity* and must equal the id
the static pass derives from the declaration site (``Class.attr`` or
``modulebase.name`` — ``check_concurrency`` flags mismatches), so the
static acquisition graph and the orders observed here speak the same
vocabulary.

With ``SIEVE_LOCK_DEBUG`` unset (the default) the constructors return
plain :mod:`threading` primitives — the flag is read once, at
construction time, and the hot path costs nothing
(``bench.py:service_lock_debug_overhead_metric`` gates this). With
``SIEVE_LOCK_DEBUG=1`` they return recording wrappers that maintain a
per-thread stack of held names and fold every acquisition into a
global (held, acquired) pair set; :func:`check_static_consistency`
then asserts the observed orders agree with the committed
``CANONICAL_LOCK_ORDER`` — the chaos/service smokes run this before
declaring victory.
"""

from __future__ import annotations

import threading


def _enabled() -> bool:
    from sieve import env

    return env.env_flag("SIEVE_LOCK_DEBUG", False)


class _Recorder:
    """Global acquisition-order observations, keyed by lock name.

    Pair counts are deduplicated per thread: each (held, acquired)
    order folds into the global set once per observing thread, so the
    steady-state cost of a hot, already-seen nesting is a thread-local
    set lookup — never the global mutex. Counts therefore mean "how
    many threads observed this order", not "how many times"; the
    consistency check only needs the pair *set*."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._pairs: dict[tuple[str, str], int] = {}
        self._tls = threading.local()
        self._gen = 0  # bumped by reset() to invalidate per-thread dedup

    def _stack(self) -> list[str]:
        tls = self._tls
        try:
            return tls.stack
        except AttributeError:
            st = tls.stack = []
            return st

    def _fold(self, tls, st: list[str], name: str) -> None:
        """Record (held, name) for every held lock, deduped per thread."""
        seen = getattr(tls, "seen", None)
        if seen is None or tls.gen != self._gen:
            seen = tls.seen = set()
            tls.gen = self._gen
        for held in st:
            k = (held, name)
            if k not in seen:
                seen.add(k)
                with self._mu:
                    self._pairs[k] = self._pairs.get(k, 0) + 1

    def note_acquire(self, name: str) -> None:
        st = self._stack()
        if st:
            self._fold(self._tls, st, name)
        st.append(name)

    def note_release(self, name: str) -> None:
        st = self._stack()
        # LIFO is the overwhelmingly common case; non-LIFO releases
        # are legal for bare acquire()/release() — drop the innermost
        # matching entry
        if st and st[-1] == name:
            st.pop()
            return
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                return

    def holds(self, name: str) -> bool:
        return name in self._stack()

    def observed_pairs(self) -> dict[tuple[str, str], int]:
        with self._mu:
            return dict(self._pairs)

    def reset(self) -> None:
        with self._mu:
            self._pairs.clear()
            self._gen += 1


_RECORDER = _Recorder()


def recorder() -> _Recorder:
    return _RECORDER


class _DebugLock:
    """Recording wrapper with the full Lock surface the code uses.

    ``__enter__``/``__exit__`` inline the recording instead of routing
    through ``acquire``/``release`` — the wrapper's cost is gated at
    1.10x (``bench.py:service_lock_debug_overhead_metric``) and every
    spared Python call layer counts on sub-ms requests."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner) -> None:
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            _RECORDER.note_acquire(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        _RECORDER.note_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_DebugLock":
        self._inner.acquire()
        # note_acquire, inlined: the with-statement path is ~50
        # acquisitions per hot request and each spared call layer is
        # measurable against the 1.10x budget
        rec = _RECORDER
        tls = rec._tls
        try:
            st = tls.stack
        except AttributeError:
            st = tls.stack = []
        if st:
            rec._fold(tls, st, self.name)
        st.append(self.name)
        return self

    def __exit__(self, *exc) -> bool:
        self._inner.release()
        st = _RECORDER._tls.stack
        if st[-1] == self.name:
            st.pop()
        else:
            _RECORDER.note_release(self.name)
        return False


class _DebugRLock(_DebugLock):
    """Reentrant variant: only the outermost acquire/release records,
    so legal reentry never shows up as a (name, name) self-pair."""

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        reentry = _RECORDER.holds(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got and not reentry:
            _RECORDER.note_acquire(self.name)
        elif got:
            self._stack_depth()  # bump the reentry count
        return got

    def _stack_depth(self) -> None:
        depth = getattr(_RECORDER._tls, "rdepth", None)
        if depth is None:
            depth = _RECORDER._tls.rdepth = {}
        depth[self.name] = depth.get(self.name, 0) + 1

    def release(self) -> None:
        self._inner.release()
        depth = getattr(_RECORDER._tls, "rdepth", None) or {}
        if depth.get(self.name, 0) > 0:
            depth[self.name] -= 1
        else:
            _RECORDER.note_release(self.name)

    def __enter__(self) -> "_DebugRLock":
        self.acquire()  # reentry-aware, unlike the base fast path
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


class _DebugCondition:
    """Condition wrapper: ``wait`` releases and reacquires the
    underlying lock, and both transitions are recorded — the reacquire
    after a wake is a real acquisition against whatever else the
    thread still holds."""

    def __init__(self, name: str, inner: threading.Condition) -> None:
        self.name = name
        self._inner = inner

    def acquire(self, *args) -> bool:
        got = self._inner.acquire(*args)
        if got:
            _RECORDER.note_acquire(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        _RECORDER.note_release(self.name)

    def __enter__(self) -> "_DebugCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def wait(self, timeout: float | None = None) -> bool:
        _RECORDER.note_release(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            _RECORDER.note_acquire(self.name)

    def wait_for(self, predicate, timeout: float | None = None):
        _RECORDER.note_release(self.name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            _RECORDER.note_acquire(self.name)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


def named_lock(name: str):
    if not _enabled():
        return threading.Lock()
    return _DebugLock(name, threading.Lock())


def named_rlock(name: str):
    if not _enabled():
        return threading.RLock()
    return _DebugRLock(name, threading.RLock())


def named_condition(name: str):
    if not _enabled():
        return threading.Condition()
    return _DebugCondition(name, threading.Condition())


def observed_pairs() -> dict[tuple[str, str], int]:
    """(held, acquired) -> count, across every named lock so far."""
    return _RECORDER.observed_pairs()


def check_static_consistency(order: tuple[str, ...] | None = None,
                             ) -> list[str]:
    """Compare observed acquisition pairs against the canonical order.

    Returns problem strings (empty = consistent). Locks observed but
    absent from the order are problems too — the static pass should
    know every lock the runtime touches.
    """
    if order is None:
        from sieve.analysis.model import CANONICAL_LOCK_ORDER

        order = CANONICAL_LOCK_ORDER
    idx = {lock: i for i, lock in enumerate(order)}
    problems = []
    for (a, b), n in sorted(_RECORDER.observed_pairs().items()):
        if a == b:
            problems.append(f"self-nesting of {a} ({n}x)")
        elif a not in idx:
            problems.append(f"observed lock {a} not in canonical order")
        elif b not in idx:
            problems.append(f"observed lock {b} not in canonical order")
        elif idx[a] > idx[b]:
            problems.append(
                f"observed {a} -> {b} ({n}x) against the canonical order"
            )
    return problems
