"""Segment partitioning and assignment (coordinator control-plane math).

SURVEY.md section 2 ("Segment assignment"): contiguous ownership; the
segments tile [2, n+1) exactly with no overlap — properties enforced by
tests. On the TPU path the same plan maps 1:1 onto a ``Mesh`` sharding
(segment i <-> mesh position), per the north-star design.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Segment:
    seg_id: int
    lo: int
    hi: int  # half-open [lo, hi)
    owner: int = 0

    @property
    def span(self) -> int:
        return self.hi - self.lo


def plan_segments(
    n: int,
    n_segments: int,
    n_workers: int = 1,
    align: int = 2,
    lo_start: int = 2,
) -> list[Segment]:
    """Cut [lo_start, n+1) into <= n_segments contiguous segments.

    Interior boundaries are aligned down to a multiple of ``align`` (keeps
    odd/even pairing stable across packings); the first and last boundaries
    are exact. Degenerate (empty) segments are dropped, so fewer than
    n_segments may be returned for tiny ranges. Owners round-robin over
    n_workers.
    """
    if n < lo_start:
        raise ValueError(f"n={n} < lo_start={lo_start}")
    hi_total = n + 1
    span = hi_total - lo_start
    n_segments = max(1, min(n_segments, span))
    bounds = [lo_start]
    for i in range(1, n_segments):
        raw = lo_start + (span * i) // n_segments
        b = (raw // align) * align
        b = max(b, bounds[-1])  # keep monotone
        bounds.append(b)
    bounds.append(hi_total)
    segs: list[Segment] = []
    for i in range(len(bounds) - 1):
        lo, hi = bounds[i], bounds[i + 1]
        if hi <= lo:
            continue
        segs.append(Segment(seg_id=len(segs), lo=lo, hi=hi))
    # round-robin ownership
    segs = [
        dataclasses.replace(s, owner=s.seg_id % n_workers) for s in segs
    ]
    return segs


def validate_plan(segs: list[Segment], n: int, lo_start: int = 2) -> None:
    """Assert the plan tiles [lo_start, n+1) exactly with no overlap."""
    if not segs:
        raise ValueError("empty plan")
    if segs[0].lo != lo_start:
        raise ValueError(f"plan starts at {segs[0].lo}, expected {lo_start}")
    for a, b in zip(segs, segs[1:]):
        if a.hi != b.lo:
            raise ValueError(f"gap/overlap between segment {a.seg_id} and {b.seg_id}")
    if segs[-1].hi != n + 1:
        raise ValueError(f"plan ends at {segs[-1].hi}, expected {n + 1}")
