"""Twin-prime counting: in-segment adjacent-bit AND + cross-boundary fix-up.

SURVEY.md section 7.3 (odds layout): in-segment twins are
popcount(flags & flags >> 1); the cross-boundary case is "(last odd of seg i
is prime) AND (first odd of seg i+1 is prime) AND their values differ by 2".
This module implements the general-packing version of that merge-side fix-up
using only each segment's boundary bitwords — the same 32-bit words the TPU
path exchanges with ``lax.ppermute``.

A pair (v, v+2) straddles the boundary at hi exactly when v < hi <= v + 2,
i.e. v in {hi-2, hi-1}. The pair is attributed to the left segment.
"""

from __future__ import annotations

from sieve.bitset import WORD_BITS, Layout
from sieve.worker import SegmentResult

_SMALL_PRIMES = {2, 3, 5, 7, 11, 13}


def is_prime_from_boundary(layout: Layout, seg: SegmentResult, v: int) -> bool:
    """Primality of v using only seg's boundary words (v near lo or hi)."""
    if not (seg.lo <= v < seg.hi):
        raise ValueError(f"value {v} outside segment [{seg.lo}, {seg.hi})")
    if v in layout.extra_primes:
        return True
    if not layout.is_candidate(v):
        return False
    b = layout.bit_of(v, seg.lo)
    if b < 0 or b >= seg.nbits:
        return False
    if b < WORD_BITS:
        return bool((seg.first_word >> b) & 1)
    off = b - (seg.nbits - WORD_BITS)
    if off < 0:
        raise ValueError(
            f"value {v} (bit {b}) not within a boundary word of "
            f"segment [{seg.lo}, {seg.hi}) with nbits={seg.nbits}"
        )
    return bool((seg.last_word >> off) & 1)


def straddle_pairs(
    layout: Layout, left: SegmentResult, right: SegmentResult, n: int,
    gap: int = 2,
) -> int:
    """Prime pairs (v, v+gap) with v in `left`, v+gap in `right`
    (consecutive segments); gap is 2 (twins) or 4 (cousins)."""
    if left.hi != right.lo:
        raise ValueError("segments are not consecutive")
    hi = left.hi
    total = 0
    for v in range(hi - gap, hi):
        w = v + gap
        if v < left.lo or w < hi or w > n:
            continue
        if w >= right.hi:
            # pair would span beyond the right segment; only possible for
            # degenerate 1-value segments, which plan_segments never emits
            raise ValueError(f"segment [{right.lo},{right.hi}) too small for pair fix-up")
        if w in _SMALL_PRIMES:
            right_prime = True  # 3/5/7... are prime regardless of packing
        else:
            right_prime = is_prime_from_boundary(layout, right, w)
        if right_prime and is_prime_from_boundary(layout, left, v):
            total += 1
    return total


def straddle_twins(
    layout: Layout, left: SegmentResult, right: SegmentResult, n: int
) -> int:
    """Twin pairs (v, v+2) with v in `left`, v+2 in `right` (consecutive)."""
    return straddle_pairs(layout, left, right, n, 2)
