"""Composable fault-injection plane for the cpu-cluster backend.

Generalizes the one-shot ``--chaos-kill-worker k@s`` hook into a
*schedule* of directives (ISSUE 6 tentpole 4), e.g.::

    --chaos "kill:1@s4,stall:2@s7:3.0,drop_hb:any@s9,disconnect:0@s2"

Grammar — comma-separated items, each ``kind:worker@s<seg>[:param]``:

* ``kill:w@sK``           worker hard-exits (``os._exit``) on receiving
                          segment K — the section 5.3 crash injection.
* ``stall:w@sK:secs``     worker finishes segment K, then goes *silent*
                          for ``secs`` (default 1.0) before sending the
                          reply — a stalled-but-alive straggler whose
                          heartbeats have already stopped. Exercises the
                          adaptive silence deadline.
* ``drop_hb:w@sK``        worker suppresses its progress heartbeats for
                          segment K (clock alignment and deadline refresh
                          lose that sample stream).
* ``disconnect:w@sK:secs`` worker drops the TCP connection ``secs``
                          (default 0.05) after segment K's assignment is
                          in flight, then reconnects with backoff — a
                          mid-segment network blip.

Service-side kinds (ISSUE 7; consumed by sieve/service/server.py, where
"segment" means the server's request sequence number and ``worker`` the
handler thread drawing it — ``any`` is the deterministic choice):

* ``svc_stall:any@sK:secs``   the handler sleeps ``secs`` (default 1.0)
                              before answering request K — a stalled
                              server thread; the deadline machinery must
                              turn it into a typed ``deadline_exceeded``,
                              never a silent hang.
* ``svc_shed:any@sK``         request K is force-shed with a typed
                              ``overloaded`` reply regardless of queue
                              depth — admission-control injection.
* ``backend_down:any@sK:secs`` the cold-compute backend reports down for
                              ``secs`` (default 1.0) starting at request
                              K — hot-index queries must keep answering
                              while health degrades.

Replication kinds (ISSUE 8; the live-follow / failover / drain plane):

* ``svc_refresh_corrupt:any@sK`` the K-th ledger *refresh attempt* (not
                              request) is forced to fail — the follower
                              must skip the swap with a typed
                              ``service_refresh_failed`` event and keep
                              serving the previous snapshot.
* ``replica_down:any@sK:secs`` starting at request K the replica drops
                              every connection without a reply for
                              ``secs`` (default 1.0) — a dead replica
                              from the client's side; a ReplicaSet must
                              fail over, never return a wrong number.
* ``svc_drain:any@sK``        request K flips the server to draining
                              (as SIGTERM would): the request itself and
                              all later ones are shed as typed
                              ``draining`` while queued work completes.

Batched cold plane (ISSUE 9; drawn by the ColdBatcher on its own batch
counter, like the follower draws refresh attempts):

* ``svc_batch_partial:any@sK:i`` chunk ``i`` (0-based, in sorted chunk
                              order, default 0) of the K-th *batch
                              dispatch* fails before it reaches the
                              backend: its waiters get a typed
                              ``degraded`` reply while every surviving
                              chunk in the same batch still answers
                              exact — the batch path must degrade
                              per-chunk, never per-batch.

Priority lanes (ISSUE 10; drawn by the dispatcher on the request
sequence number like the other ``svc_*`` request kinds):

* ``svc_flood:any@sK:lane``   request K is refused admission as if the
                              named lane (``hot`` or ``cold``; default
                              ``cold``) were at capacity: a typed
                              ``overloaded`` reply carrying the lane, a
                              ``service_lane_shed`` event, and — for a
                              cold-lane shed — a ReplicaSet failover,
                              all without needing a real flood. The
                              only kind whose param is a lane name, not
                              seconds.

Router plane (ISSUE 11; drawn by sieve/service/router.py on its own
request sequence — here ``worker`` names a SHARD index, ``any`` every
shard):

* ``svc_shard_down:<shard>@sK:secs`` starting at router request K the
                              named shard (or every shard, for ``any``)
                              is treated as unreachable for ``secs``
                              (default 1.0): queries needing it get a
                              typed ``unavailable`` naming the shard,
                              queries answerable from other shards stay
                              exact — the whole-shard-outage drill
                              without killing real replicas.

Telemetry plane (ISSUE 12; drawn by the shard server on its request
sequence like the other ``svc_*`` request kinds):

* ``svc_trace_drop:any@sK``   request K's terminal reply carries no
                              piggybacked trace telemetry (the payload
                              is dropped as if lost in transit) while
                              the query result itself stays exact — the
                              router must degrade to uncorrelated spans
                              with a counted ``router_trace_gap`` event,
                              never an error.

Wire plane (ISSUE 14; drawn on the request sequence like the other
``svc_*`` request kinds):

* ``svc_slow_frame:any@sK:bytes``  from request K on, replies to THAT
                              connection are dribbled at ``bytes`` per
                              event-loop tick (default 1.0) — a slow
                              consumer on the write side. The event
                              loop must keep every other connection's
                              replies flowing at full speed (no
                              head-of-line blocking across sockets),
                              and the throttled client still gets an
                              exact answer, just slowly.

Segment store (ISSUE 17; drawn by the tiered segment store on its own
*append* counter, like the batcher draws batch dispatches):

* ``store_torn_write:any@sK``  the K-th store append is written torn:
                              same record length, garbled interior, so
                              the per-entry CRC fails while the file
                              framing survives. Readers must skip
                              exactly that entry with a counted
                              ``store_torn_entry`` event and
                              re-materialize the chunk — never a crash
                              or a wrong answer.

Mesh cold plane (ISSUE 18; drawn by the ColdBackend on its own
mesh-launch counter, like the batcher draws batch dispatches):

* ``svc_mesh_fail:any@sK``    the K-th mesh cold dispatch raises inside
                              the SPMD launch: the whole drain slice is
                              recomputed on the local loop worker, a
                              counted ``service_mesh_fallback`` event
                              fires, and every waiter still gets the
                              exact answer — the mesh must degrade to
                              the loop path, never to a wrong answer or
                              a crash.

Observer plane (ISSUE 19; drawn by the fleet observer on its own
*scrape* counter, like the batcher draws batch dispatches):

* ``svc_scrape_gap:any@sK``   the K-th observer scrape of a target
                              endpoint raises mid-poll: the observer
                              records a counted gap row (a
                              ``observer_scrape_gap`` event) and moves
                              on — it must never fabricate a sample for
                              the missed endpoint, and the anomaly
                              engine must not alarm on the gap itself
                              (gap-aware windows re-arm only after a
                              fresh real sample).

Continuous profiler (ISSUE 20; drawn inline by the ``profile`` wire op
on BOTH serving tiers — shard server and router — each on its own
profile-pull counter):

* ``svc_prof_gap:any@sK``     the K-th ``profile`` wire reply is
                              dropped (the puller sees a timeout, never
                              a malformed frame) and the sampler pauses
                              one beat. ``tools/fleet_profile.py`` must
                              ride the gap: a partial merge still
                              lands, exit 1 names the missing process,
                              nothing crashes, and the next pull heals.

Flight recorder (ISSUE 13):

* ``svc_crash:any@sK``        request K's worker thread raises uncaught
                              (:class:`ChaosCrash` deliberately escapes
                              the handler's catch-all nets): the worker
                              dies, ``threading.excepthook`` fires the
                              recorder's crash trigger (writing a debug
                              bundle under ``--debug-dir``), and the
                              replica's surviving workers keep
                              answering. The crashed request itself
                              never gets a reply — from the client's
                              side it is a dead-worker timeout.

``worker`` is an integer id, or ``any``/``*`` for whichever worker draws
the segment (the pull model makes a specific id probabilistic, ``any``
deterministic). Directives are transported to the worker inside the
``assign`` message, so tests and tools/chaos_smoke.py compose multi-fault
scenarios purely from config. Each plane ignores the other plane's kinds
(a cluster worker skips ``svc_*``; the service skips ``kill``/``stall``/
...), so one ``--chaos`` string can drive a composed scenario end to end.

Directives are consumed when taken (one-shot): a reassigned segment's
replacement owner runs fault-free, which is what makes every composed
scenario terminate deterministically.
"""

from __future__ import annotations

import dataclasses
import threading

from sieve.analysis.lockdebug import named_lock

ANY_WORKER = -1  # "any@sK": whichever worker draws segment K
KINDS = (
    "kill",
    "stall",
    "drop_hb",
    "disconnect",
    "svc_stall",
    "svc_shed",
    "backend_down",
    "svc_refresh_corrupt",
    "replica_down",
    "svc_drain",
    "svc_batch_partial",
    "svc_flood",
    "svc_shard_down",
    "svc_trace_drop",
    "svc_crash",
    "svc_slow_frame",
    "store_torn_write",
    "svc_mesh_fail",
    "svc_scrape_gap",
    "svc_prof_gap",
)
# kinds handled by the query service (sieve/service/); the cluster plane
# ignores these and vice versa. Request-scoped kinds key on the request
# sequence number; svc_refresh_corrupt keys on the refresh attempt
# number and is drawn by the LedgerFollower, not the dispatcher;
# svc_batch_partial keys on the batch-dispatch number and is drawn by
# the ColdBatcher; store_torn_write keys on the store's append counter
# and is drawn by the TieredSegmentStore; svc_mesh_fail keys on the
# mesh-launch counter and is drawn by the ColdBackend.
SERVICE_KINDS = (
    "svc_stall",
    "svc_shed",
    "backend_down",
    "svc_refresh_corrupt",
    "replica_down",
    "svc_drain",
    "svc_batch_partial",
    "svc_flood",
    "svc_trace_drop",
    "svc_crash",
    "svc_slow_frame",
    "store_torn_write",
    "svc_mesh_fail",
)
SERVICE_REQUEST_KINDS = (
    "svc_stall",
    "svc_shed",
    "backend_down",
    "replica_down",
    "svc_drain",
    "svc_flood",
    "svc_trace_drop",
    "svc_crash",
    "svc_slow_frame",
)
# drawn by the router tier (ISSUE 11) on ITS request sequence; the
# directive's worker field names a shard index there, so shard servers
# never consume these even when one --chaos string drives both tiers
ROUTER_REQUEST_KINDS = ("svc_shard_down",)
# drawn by the fleet observer (ISSUE 19) on its own scrape counter; the
# worker field names the target's index in the observer's target list,
# so neither serving tier ever consumes these
OBSERVER_KINDS = ("svc_scrape_gap",)
# drawn inline by the ``profile`` wire op (ISSUE 20) on BOTH serving
# tiers, each on its own profile-pull counter — the only kind two
# planes consume, and each plane's counter keeps the draws disjoint
PROFILE_KINDS = ("svc_prof_gap",)
# kinds whose param is a LANE NAME ("hot"/"cold"), not seconds
LANE_PARAM_KINDS = ("svc_flood",)
_LANES = ("hot", "cold")
# default param (seconds, or a lane name) for kinds that take one;
# None = no param
DEFAULT_PARAM: dict[str, float | str | None] = {
    "kill": None,
    "stall": 1.0,
    "drop_hb": None,
    "disconnect": 0.05,
    "svc_stall": 1.0,
    "svc_shed": None,
    "backend_down": 1.0,
    "svc_refresh_corrupt": None,
    "replica_down": 1.0,
    "svc_drain": None,
    # param = 0-based index of the chunk to fail, in sorted batch order
    "svc_batch_partial": 0.0,
    # param = the lane to refuse admission on
    "svc_flood": "cold",
    # param = seconds the shard stays unreachable to the router
    "svc_shard_down": 1.0,
    "svc_trace_drop": None,
    "svc_crash": None,
    # param = reply bytes written per event-loop tick on that connection
    "svc_slow_frame": 1.0,
    "store_torn_write": None,
    "svc_mesh_fail": None,
    "svc_scrape_gap": None,
    "svc_prof_gap": None,
}


class ChaosCrash(RuntimeError):
    """Raised by the ``svc_crash`` directive. Deliberately re-raised
    past the service handler's catch-all nets so the worker thread
    genuinely dies and the flight recorder's ``threading.excepthook``
    crash trigger fires (ISSUE 13)."""


@dataclasses.dataclass(frozen=True)
class ChaosDirective:
    kind: str
    worker: int  # ANY_WORKER matches every worker
    seg_id: int
    param: float | str | None = None

    def matches(self, worker_id: int, seg_id: int) -> bool:
        return self.seg_id == seg_id and self.worker in (ANY_WORKER, worker_id)

    def to_wire(self) -> dict:
        """The per-assignment payload shipped to the worker. ``worker``
        rides along for planes where it is an *address* rather than a
        match key — the router reads it as a shard index (ANY_WORKER =
        every shard); cluster workers ignore it."""
        return {"kind": self.kind, "param": self.param, "worker": self.worker}


def parse_chaos(spec: str) -> list[ChaosDirective]:
    """Parse a chaos schedule string; raises ValueError with the offending
    item on bad grammar so config construction fails early."""
    out: list[ChaosDirective] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        parts = item.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"chaos item {item!r}: expected kind:worker@s<seg>[:param]"
            )
        kind, target = parts[0], parts[1]
        if kind not in KINDS:
            raise ValueError(
                f"chaos item {item!r}: unknown kind {kind!r} "
                f"(one of {', '.join(KINDS)})"
            )
        if "@" not in target:
            raise ValueError(
                f"chaos item {item!r}: target must be worker@s<seg>"
            )
        who, seg = target.split("@", 1)
        if who in ("any", "*"):
            worker = ANY_WORKER
        else:
            try:
                worker = int(who)
            except ValueError:
                raise ValueError(
                    f"chaos item {item!r}: worker must be an integer id, "
                    f"'any', or '*', got {who!r}"
                ) from None
            if worker < 0:
                raise ValueError(
                    f"chaos item {item!r}: worker id must be >= 0"
                )
        if not seg.startswith("s") or not seg[1:].isdigit():
            raise ValueError(
                f"chaos item {item!r}: segment must be written s<id>, "
                f"got {seg!r}"
            )
        seg_id = int(seg[1:])
        if len(parts) == 3:
            if DEFAULT_PARAM[kind] is None:
                raise ValueError(f"chaos item {item!r}: {kind} takes no param")
            if kind in LANE_PARAM_KINDS:
                param = parts[2]
                if param not in _LANES:
                    raise ValueError(
                        f"chaos item {item!r}: param must be a lane "
                        f"({' or '.join(_LANES)}), got {param!r}"
                    )
            else:
                try:
                    param = float(parts[2])
                except ValueError:
                    raise ValueError(
                        f"chaos item {item!r}: param must be a number "
                        "(seconds)"
                    ) from None
                if param < 0:
                    raise ValueError(
                        f"chaos item {item!r}: param must be >= 0"
                    )
        else:
            param = DEFAULT_PARAM[kind]
        out.append(ChaosDirective(kind, worker, seg_id, param))
    return out


class ChaosSchedule:
    """Coordinator-side one-shot schedule.

    ``take(worker, seg)`` atomically removes and returns every directive
    matching the assignment, as wire dicts — so a segment requeued after
    an injected fault finds a fault-free replacement owner.
    """

    def __init__(self, directives: list[ChaosDirective]):
        self._lock = named_lock("ChaosSchedule._lock")
        self._pending = list(directives)  # guard: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)

    def take(self, worker_id: int, seg_id: int) -> list[dict]:
        return self.take_kinds(worker_id, seg_id, None)

    def take_kinds(
        self, worker_id: int, seg_id: int, kinds: tuple[str, ...] | None
    ) -> list[dict]:
        """Like :meth:`take`, but only consume directives whose kind is in
        ``kinds`` (None = all). The query service's dispatcher and its
        ledger follower number their "segments" independently (request
        sequence vs refresh attempt), so each must only draw — and
        consume — its own kinds."""
        with self._lock:
            hit = [
                d for d in self._pending
                if d.matches(worker_id, seg_id)
                and (kinds is None or d.kind in kinds)
            ]
            if hit:
                taken = set(map(id, hit))
                self._pending = [
                    d for d in self._pending if id(d) not in taken
                ]
        return [d.to_wire() for d in hit]

    def extend(self, directives: list[ChaosDirective]) -> None:
        """Inject more directives at runtime (service chaos endpoint)."""
        with self._lock:
            self._pending.extend(directives)
