"""Validated ``SIEVE_*`` environment-knob readers (ISSUE 15).

Every ``SIEVE_*`` knob read inside ``sieve/`` goes through one of these
helpers: a malformed value raises ``ValueError`` *naming the variable*
at startup instead of an anonymous ``int()`` traceback deep inside a
worker thread, and the read site is statically greppable.
``tools/check_env_vars.py`` enforces both properties — any
``os.environ`` read of a ``SIEVE_*`` name outside this module fails the
gate, as does any knob left undocumented in README.md.
"""

from __future__ import annotations

import os


def env_int(name: str, default: int | None) -> int | None:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"env {name}={raw!r}: expected an integer"
        ) from None


def env_float(name: str, default: float | None) -> float | None:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"env {name}={raw!r}: expected a number"
        ) from None


def env_str(name: str, default: str | None = None) -> str | None:
    """Tracked read of a free-form knob (paths, backend names, modes)."""
    return os.environ.get(name, default)


def env_flag(name: str, default: bool = False) -> bool:
    """Boolean knob: unset -> default; ``""`` and ``"0"`` -> False;
    anything else -> True (so ``SIEVE_X=1`` and ``SIEVE_X=yes`` agree)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw not in ("", "0")


def env_items() -> list[tuple[str, str]]:
    """Every currently-set ``SIEVE_*`` variable (prefix scans like the
    per-op SLO table read the environment through this, keeping the
    no-raw-reads rule greppable)."""
    return [(k, v) for k, v in os.environ.items() if k.startswith("SIEVE_")]
