"""Seed-prime sieve: primes <= sqrt(N), computed once on the host.

SURVEY.md section 0: the reference computes seed primes on the host and
ships them to every worker. For the north-star N=1e12 the seed set is
pi(1e6) = 78,498 primes (~628 KB as int64) — trivially replicated, so a
simple numpy sieve is the right tool; no need for segmentation here.
"""

from __future__ import annotations

import math

import numpy as np


def seed_primes(limit: int) -> np.ndarray:
    """All primes p <= limit, ascending, as int64.

    Plain (non-segmented) Sieve of Eratosthenes; O(limit) memory as bool.
    """
    if limit < 2:
        return np.zeros(0, dtype=np.int64)
    flags = np.ones(limit + 1, dtype=bool)
    flags[:2] = False
    for p in range(2, math.isqrt(limit) + 1):
        if flags[p]:
            flags[p * p :: p] = False
    return np.nonzero(flags)[0].astype(np.int64)


def pi_reference(n: int) -> int:
    """pi(n) by direct whole-range sieve — test oracle for small n only."""
    return int(seed_primes(n).size)


def twin_reference(n: int) -> int:
    """Count of twin pairs (p, p+2), p+2 <= n — test oracle for small n."""
    primes = seed_primes(n)
    if primes.size < 2:
        return 0
    return int(np.count_nonzero(np.diff(primes) == 2))
