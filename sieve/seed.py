"""Seed-prime sieve: primes <= sqrt(N), computed once on the host.

SURVEY.md section 0: the reference computes seed primes on the host and
ships them to every worker. For the north-star N=1e12 the seed set is
pi(1e6) = 78,498 primes (~628 KB as int64) — trivially replicated, so a
simple numpy sieve is the right tool; no need for segmentation here.

``seed_primes`` memoizes its last few results (ISSUE 7): the query
service and ``primes_in_range`` call it per request/slice with a handful
of distinct limits, and recomputing a 1e7 sieve per query would dominate
hot-path latency. Cached arrays are returned read-only so one caller
cannot corrupt another's view; callers that need to mutate must copy.
"""

from __future__ import annotations

import collections
import math
import threading

from sieve.analysis.lockdebug import named_lock

import numpy as np

_CACHE_SIZE = 8  # distinct limits kept (largest seed set ~628 KB at 1e7)
_cache: "collections.OrderedDict[int, np.ndarray]" = collections.OrderedDict()
_cache_lock = named_lock("seed._cache_lock")


def seed_primes(limit: int) -> np.ndarray:
    """All primes p <= limit, ascending, as int64 (read-only array).

    Plain (non-segmented) Sieve of Eratosthenes; O(limit) memory as bool.
    Memoized on ``limit`` (small LRU); results are bit-exact vs uncached.
    """
    limit = int(limit)
    with _cache_lock:
        hit = _cache.get(limit)
        if hit is not None:
            _cache.move_to_end(limit)
            return hit
    primes = _seed_primes_uncached(limit)
    primes.setflags(write=False)
    with _cache_lock:
        _cache[limit] = primes
        _cache.move_to_end(limit)
        while len(_cache) > _CACHE_SIZE:
            _cache.popitem(last=False)
    return primes


def seed_cache_clear() -> None:
    """Drop all memoized seed sets (tests, memory-pressure hooks)."""
    with _cache_lock:
        _cache.clear()


def _seed_primes_uncached(limit: int) -> np.ndarray:
    if limit < 2:
        return np.zeros(0, dtype=np.int64)
    flags = np.ones(limit + 1, dtype=bool)
    flags[:2] = False
    for p in range(2, math.isqrt(limit) + 1):
        if flags[p]:
            flags[p * p :: p] = False
    return np.nonzero(flags)[0].astype(np.int64)


def pi_reference(n: int) -> int:
    """pi(n) by direct whole-range sieve — test oracle for small n only."""
    return int(seed_primes(n).size)


def twin_reference(n: int) -> int:
    """Count of twin pairs (p, p+2), p+2 <= n — test oracle for small n."""
    primes = seed_primes(n)
    if primes.size < 2:
        return 0
    return int(np.count_nonzero(np.diff(primes) == 2))
