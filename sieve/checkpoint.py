"""Checkpoint / resume ledger (SURVEY.md section 5.4).

A JSON ledger ``{config_hash, completed: {seg_id: SegmentResult}}`` written
atomically after each completed segment (CPU path) or round (TPU path).
``--resume`` replays the merge over ledger + remaining segments; a
config-hash mismatch refuses to resume (the math would differ).
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path
from typing import TYPE_CHECKING

from sieve.worker import SegmentResult

if TYPE_CHECKING:
    from sieve.config import SieveConfig

LEDGER_NAME = "sieve_ledger.json"


class LedgerMismatch(RuntimeError):
    pass


class Ledger:
    def __init__(self, path: Path, config_hash: str, entries: dict[int, dict]):
        self.path = path
        self.config_hash = config_hash
        self._entries = entries

    @classmethod
    def open(cls, config: "SieveConfig") -> "Ledger":
        assert config.checkpoint_dir is not None
        path = Path(config.checkpoint_dir) / LEDGER_NAME
        chash = config.config_hash()
        entries: dict[int, dict] = {}
        if path.exists():
            data = json.loads(path.read_text())
            if data.get("config_hash") != chash:
                raise LedgerMismatch(
                    f"ledger at {path} was written for config_hash="
                    f"{data.get('config_hash')}, current run is {chash}; "
                    "refusing to mix results (delete the ledger or match the config)"
                )
            entries = {int(k): v for k, v in data.get("completed", {}).items()}
        else:
            path.parent.mkdir(parents=True, exist_ok=True)
        return cls(path, chash, entries)

    def completed(self) -> dict[int, SegmentResult]:
        return {k: SegmentResult.from_dict(v) for k, v in self._entries.items()}

    def record(self, res: SegmentResult) -> None:
        """Idempotent: the ledger keys on segment id, so a segment processed
        twice (e.g. after worker-failure reassignment) is counted once."""
        self._entries[res.seg_id] = res.to_dict()
        self._flush()

    def _flush(self) -> None:
        payload = {
            "config_hash": self.config_hash,
            "completed": {str(k): v for k, v in self._entries.items()},
        }
        fd, tmp = tempfile.mkstemp(dir=self.path.parent, prefix=".ledger.")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)  # atomic on POSIX
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
