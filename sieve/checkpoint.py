"""Checkpoint / resume ledger (SURVEY.md section 5.4; ISSUE 6 tentpole 3).

Format (version 2)::

    {"version": 2, "config_hash": h, "checksum": c,
     "completed": {seg_id: SegmentResult}}

``checksum`` is a truncated sha256 over the canonical
``{config_hash, completed}`` payload, verified on every open — so bit rot
is *detected* instead of silently merged. Version-1 files (no
``version``/``checksum``) written by older builds still load.

Durability: every flush writes a temp file, fsyncs it, atomically renames
it over the ledger, and fsyncs the directory (``SIEVE_LEDGER_FSYNC=0``
opts out) — a host crash can't leave a torn checkpoint, only the previous
complete one.

Corruption handling on open:

* unparseable / truncated file — the damaged file is quarantined to
  ``<ledger>.quarantined`` and salvaged entry-by-entry: any complete
  ``SegmentResult`` object whose fields pass :meth:`SegmentResult.is_sane`
  is recovered, provided the embedded ``config_hash`` still matches the
  current run. A clean checksummed ledger is rewritten immediately and
  ``Ledger.salvaged``/``Ledger.quarantined`` let the caller emit a
  ``ledger_salvaged`` event. If nothing is salvageable, :class:`LedgerCorrupt`
  names the quarantined file and spells out the ``--resume`` implications.
* parseable but checksum-mismatched — silent corruption with no way to
  tell *which* entry is bad: quarantined, never salvaged,
  :class:`LedgerCorrupt` raised.

``--resume`` replays the merge over ledger + remaining segments; a
config-hash mismatch refuses to resume (the math would differ).

Readers (the query service, ISSUE 7) use :meth:`Ledger.open_readonly`: a
snapshot open that verifies the checksum but never quarantines, salvages,
or flushes — a concurrent reader must not race the coordinator's
atomic-replace or steal its corrupt-file recovery. A read-only ledger
raises on :meth:`Ledger.record`.

The ledger is also the cold-compute write-back target (ISSUE 9): a
query server started with ``--persist-cold`` is the designated *writer*
for its checkpoint dir and records each batch of cold chunk results via
:meth:`Ledger.record_many` — one atomic fsync'd flush per batch, entries
keyed ``COLD_SEG_BASE + lo`` so a chunk recomputed (or re-clipped) is
overwritten, never double-counted. Replicas inherit the work through the
same live-follow path as coordinator writes.

Live-following readers (ISSUE 8) poll :func:`ledger_fingerprint` (mtime
+ size, no read) and re-open when it moves; :attr:`Ledger.checksum`
identifies the loaded content so an atomic rewrite of identical bytes is
a no-op swap. Two windows a reader must survive without crashing: the
file vanishing between ``stat`` and ``read`` (the writing coordinator's
quarantine ``os.replace``) reads as an *empty snapshot*, same as a
ledger that never existed; a checksum-less version-1 file loads with
:attr:`Ledger.unverified` set so the caller can emit a
``ledger_unverified`` warning instead of trusting it silently.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import tempfile
from pathlib import Path
from typing import TYPE_CHECKING

from sieve import env
from sieve.worker import SegmentResult

if TYPE_CHECKING:
    from sieve.config import SieveConfig

LEDGER_NAME = "sieve_ledger.json"
LEDGER_VERSION = 2

# Cold write-back entries (ISSUE 9) key on COLD_SEG_BASE + lo: far above
# any sieving run's seg_id space, deterministic per chunk (idempotent
# re-record), and unique because chunks at distinct lo never collide.
COLD_SEG_BASE = 1 << 40

# completed-dict entries: '"<seg_id>": {flat object}' — SegmentResult
# serializations are flat, so a non-greedy brace match per entry is exact
_ENTRY_RE = re.compile(r'"(\d+)"\s*:\s*(\{[^{}]*\})')
_HASH_RE = re.compile(r'"config_hash"\s*:\s*"([0-9a-f]+)"')


class LedgerMismatch(RuntimeError):
    pass


class LedgerCorrupt(LedgerMismatch):
    """The ledger file failed parse or checksum; the damaged file has been
    quarantined (path in the message) and nothing could be salvaged."""


def _payload_checksum(config_hash: str, completed: dict[str, dict]) -> str:
    blob = json.dumps(
        {"config_hash": config_hash, "completed": completed}, sort_keys=True
    ).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _fsync_enabled() -> bool:
    return env.env_str("SIEVE_LEDGER_FSYNC", "1") != "0"


def ledger_fingerprint(path: Path | str) -> tuple[int, int] | None:
    """Cheap change detector for live-following readers: (mtime_ns, size),
    or None when the file is absent. One stat, no read — pollers compare
    fingerprints and only re-open (and checksum) when it moves."""
    try:
        st = os.stat(path)
    except FileNotFoundError:
        return None
    return (st.st_mtime_ns, st.st_size)


def _salvage_entries(text: str) -> dict[int, dict]:
    """Recover complete, sane SegmentResult entries from corrupt ledger
    bytes (truncation keeps every fully-written entry intact)."""
    out: dict[int, dict] = {}
    for m in _ENTRY_RE.finditer(text):
        try:
            res = SegmentResult.from_dict(json.loads(m.group(2)))
        except (ValueError, KeyError, TypeError):
            continue
        if res.is_sane():
            out[int(m.group(1))] = res.to_dict()
    return out


class Ledger:
    def __init__(self, path: Path, config_hash: str, entries: dict[int, dict]):
        self.path = path
        self.config_hash = config_hash
        self._entries = entries
        # salvage provenance (set by open() when a corrupt file was
        # recovered) — callers emit the ledger_salvaged metrics event
        self.salvaged = 0
        self.quarantined: str | None = None
        self.read_only = False
        # read-only provenance: the loaded payload's content checksum
        # (computed for v1 files, which carry none — unverified is then
        # True so callers can emit a ledger_unverified warning)
        self.checksum: str | None = None
        self.unverified = False

    @classmethod
    def open(cls, config: "SieveConfig") -> "Ledger":
        assert config.checkpoint_dir is not None
        path = Path(config.checkpoint_dir) / LEDGER_NAME
        chash = config.config_hash()
        entries: dict[int, dict] = {}
        salvaged = 0
        quarantined: Path | None = None
        if path.exists():
            text = path.read_text()
            data, corrupt = cls._parse(text)
            if data is not None:
                if data.get("config_hash") != chash:
                    raise LedgerMismatch(
                        f"ledger at {path} was written for config_hash="
                        f"{data.get('config_hash')}, current run is {chash}; "
                        "refusing to mix results (delete the ledger or match "
                        "the config)"
                    )
                if int(data.get("version", 1)) > LEDGER_VERSION:
                    raise LedgerMismatch(
                        f"ledger at {path} has version {data.get('version')} "
                        f"(this build writes {LEDGER_VERSION}); refusing to "
                        "rewrite a newer format"
                    )
                entries = {
                    int(k): v for k, v in data.get("completed", {}).items()
                }
            else:
                quarantined, entries = cls._quarantine_and_salvage(
                    path, text, chash, corrupt
                )
                salvaged = len(entries)
        else:
            path.parent.mkdir(parents=True, exist_ok=True)
        ledger = cls(path, chash, entries)
        if salvaged:
            ledger.salvaged = salvaged
            ledger.quarantined = str(quarantined)
            ledger._flush()  # rewrite a clean, checksummed ledger now
        return ledger

    @classmethod
    def open_readonly(cls, config: "SieveConfig") -> "Ledger":
        """Snapshot open for readers: verify, never mutate.

        Unlike :meth:`open`, a corrupt file is NOT quarantined or salvaged
        (that is the writing coordinator's recovery to perform — a reader
        racing it could steal the atomic-replace) and nothing is ever
        flushed back. A missing ledger is an empty snapshot, not an error:
        the service starts cold and fills from backends. The same goes
        for a file that vanishes *between* the existence check and the
        read — that is the coordinator's quarantine ``os.replace`` window,
        not a reader bug — so the TOCTOU race reads as empty, never as an
        escaped ``FileNotFoundError``.
        """
        assert config.checkpoint_dir is not None
        path = Path(config.checkpoint_dir) / LEDGER_NAME
        chash = config.config_hash()
        entries: dict[int, dict] = {}
        unverified = False
        checksum: str | None = None
        try:
            text = path.read_text() if path.exists() else None
        except FileNotFoundError:
            text = None  # quarantined out from under us mid-open
        if text is not None:
            data, corrupt = cls._parse(text)
            if data is None:
                raise LedgerCorrupt(
                    f"ledger at {path} is corrupt ({corrupt}); refusing "
                    "read-only open. Run the owning coordinator (which "
                    "quarantines and salvages) or restore a known-good "
                    "ledger."
                )
            if data.get("config_hash") != chash:
                raise LedgerMismatch(
                    f"ledger at {path} was written for config_hash="
                    f"{data.get('config_hash')}, reader expects {chash}; "
                    "the segment counts would describe a different sieve"
                )
            entries = {int(k): v for k, v in data.get("completed", {}).items()}
            unverified = "checksum" not in data
            checksum = data.get("checksum") or _payload_checksum(
                chash, data.get("completed") or {}
            )
        ledger = cls(path, chash, entries)
        ledger.read_only = True
        ledger.unverified = unverified
        ledger.checksum = checksum
        return ledger

    @staticmethod
    def _parse(text: str) -> tuple[dict | None, str]:
        """(payload, "") when intact; (None, reason) when corrupt.

        reason "truncated" = unparseable bytes (salvageable per entry);
        reason "checksum" = parseable but failing its own checksum
        (silent corruption — not salvageable)."""
        try:
            data = json.loads(text)
        except ValueError:
            return None, "truncated"
        if not isinstance(data, dict) or "config_hash" not in data:
            return None, "truncated"
        want = data.get("checksum")
        if want is not None and want != _payload_checksum(
            data.get("config_hash"), data.get("completed") or {}
        ):
            return None, "checksum"
        return data, ""

    @classmethod
    def _quarantine_and_salvage(
        cls, path: Path, text: str, chash: str, reason: str
    ) -> tuple[Path, dict[int, dict]]:
        qpath = path.with_name(path.name + ".quarantined")
        os.replace(path, qpath)
        entries: dict[int, dict] = {}
        m = _HASH_RE.search(text)
        if reason == "truncated" and m and m.group(1) == chash:
            entries = _salvage_entries(text)
        if entries:
            return qpath, entries
        detail = (
            "its checksum does not match its payload (silent corruption; "
            "per-entry salvage is unsafe)"
            if reason == "checksum"
            else "it is truncated or unparseable and no complete entry "
            "matching this run's config could be salvaged"
            if m is None or m.group(1) == chash
            else f"its recovered config_hash {m.group(1)} does not match "
            f"this run's {chash}"
        )
        raise LedgerCorrupt(
            f"ledger at {path} is corrupt: {detail}. The damaged file was "
            f"quarantined to {qpath}; --resume has no completed segments to "
            f"restore from it. Rerun without --resume to recompute from "
            f"scratch, or restore a known-good ledger to {path} "
            f"(delete {qpath} once investigated)."
        )

    def recorded_hi(self, seg_id: int) -> int:
        """``hi`` of the entry currently recorded under ``seg_id`` (0 if
        none) — lets the cold write-back (ISSUE 9) skip a clipped
        recompute of a chunk that is already persisted to a larger hi,
        so ledger coverage never shrinks under racing queries."""
        e = self._entries.get(seg_id)
        return int(e.get("hi", 0)) if e else 0

    def completed(self) -> dict[int, SegmentResult]:
        return {k: SegmentResult.from_dict(v) for k, v in self._entries.items()}

    def store_tier0_entries(self) -> list[tuple[int, int, int]]:
        """Tier import seam for the segment store (ISSUE 17): every
        completed segment as sorted ``(lo, hi, count)``. The elected
        writer seeds the store's tier 0 from these at open — count
        facts exist for the whole covered range before anything was
        ever materialized, and the store's export
        (``TieredSegmentStore.export_counts``) round-trips them."""
        out: list[tuple[int, int, int]] = []
        for e in self._entries.values():
            try:
                out.append((int(e["lo"]), int(e["hi"]), int(e["count"])))
            except (KeyError, TypeError, ValueError):
                continue
        return sorted(out)

    def record(self, res: SegmentResult) -> None:
        """Idempotent: the ledger keys on segment id, so a segment processed
        twice (e.g. after worker-failure reassignment) is counted once."""
        self.record_many([res])

    def record_many(self, results: list[SegmentResult]) -> None:
        """Record a batch of results with ONE atomic fsync'd flush — the
        cold-compute write-back path (ISSUE 9) persists every chunk of a
        batch dispatch in a single temp-file + rename, so a crash leaves
        either the whole batch or none of it (same idempotent seg_id
        keying as :meth:`record`)."""
        if self.read_only:
            raise LedgerMismatch(
                f"ledger at {self.path} was opened read-only; record() is "
                "reserved for the owning coordinator"
            )
        if not results:
            return
        for res in results:
            self._entries[res.seg_id] = res.to_dict()
        self._flush()

    def _flush(self) -> None:
        assert not self.read_only, "read-only ledger must never flush"
        completed = {str(k): v for k, v in self._entries.items()}
        payload = {
            "version": LEDGER_VERSION,
            "config_hash": self.config_hash,
            "checksum": _payload_checksum(self.config_hash, completed),
            "completed": completed,
        }
        fd, tmp = tempfile.mkstemp(dir=self.path.parent, prefix=".ledger.")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
                if _fsync_enabled():
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, self.path)  # atomic on POSIX
            if _fsync_enabled():
                # fsync the directory so the rename itself is durable: a
                # crash after this point replays the NEW ledger, before it
                # the previous complete one — never a torn file
                dfd = os.open(self.path.parent, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
