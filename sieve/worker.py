"""The ``SieveWorker`` plugin boundary — THE backend-selection seam.

SURVEY.md section 2: every execution backend (cpu-numpy, cpu-native,
cpu-cluster, jax, tpu-pallas) implements the identical
``process_segment(lo, hi, seed_primes) -> SegmentResult`` signature and is
parity-tested pairwise. The TPU backend plugs in through this same boundary,
"alongside the CPU-cluster path" (BASELINE.json north_star).

This module is also the worker-side telemetry seam for the cluster
transport: a worker process records its spans (``worker.recv`` /
``worker.segment`` / ``worker.reply`` plus the backend's own
``segment.*`` spans) and registry counters locally, and
:func:`telemetry_payload` drains them into a bounded payload that rides
the terminal ``done``/``error`` RPC reply back to the coordinator, which
rebases and merges them into one cluster timeline (sieve/cluster.py).
"""

from __future__ import annotations

import abc
import dataclasses
import os
from typing import TYPE_CHECKING

import numpy as np

from sieve import env, trace
from sieve.metrics import registry

if TYPE_CHECKING:
    from sieve.config import SieveConfig

# Worker-side event ring: at most this many trace events are held (and
# therefore shipped per reply); overflow drops the oldest event and is
# counted, so truncation is visible (never silent) on the coordinator.
TELEMETRY_RING_EVENTS = 4096


def telemetry_ring_size() -> int:
    """Ring capacity: ``SIEVE_TELEMETRY_RING`` env override, 0 disables."""
    return env.env_int("SIEVE_TELEMETRY_RING", TELEMETRY_RING_EVENTS)


def telemetry_start() -> bool:
    """Begin bounded span capture for telemetry shipping (worker role).

    Returns False (capture untouched) when shipping is disabled via
    ``SIEVE_TELEMETRY_RING=0``."""
    limit = telemetry_ring_size()
    if limit <= 0:
        return False
    tr = trace.get_tracer()
    tr.set_event_limit(limit)
    tr.enable()
    return True


def telemetry_payload(worker_id: int) -> dict:
    """Drain the not-yet-shipped trace events + a registry snapshot.

    Timestamps are on the *worker's* trace epoch; the coordinator rebases
    them using its NTP-style per-worker clock-offset estimate. ``dropped``
    is the cumulative ring-eviction count for this worker."""
    events, dropped = trace.drain_events()
    return {
        "worker_id": worker_id,
        "events": events,
        "dropped": dropped,
        "registry": registry().snapshot(),
    }


@dataclasses.dataclass
class SegmentResult:
    """Per-segment output merged by the coordinator.

    ``count`` includes the layout's extra primes (2 / 2,3,5) when they fall
    in [lo, hi). ``twin_count`` counts pairs (v, v+2) with both members in
    [lo, hi); pairs straddling a segment boundary are reconstructed at merge
    time from the boundary bitwords (sieve/twins.py).
    """

    seg_id: int
    lo: int
    hi: int
    count: int
    twin_count: int
    first_word: int  # first min(32, nbits) flag bits; bit k = flag[k]
    last_word: int   # bit k = flag[nbits-32+k] (== first_word when nbits <= 32)
    nbits: int
    elapsed_s: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SegmentResult":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls)})

    def is_sane(self) -> bool:
        """Structural validity, used by the ledger's corrupt-file salvage
        path: an entry that parses but violates these bounds is damage,
        not a result (sieve/checkpoint.py)."""
        ints = (
            self.seg_id, self.lo, self.hi, self.count, self.twin_count,
            self.first_word, self.last_word, self.nbits,
        )
        if not all(isinstance(v, int) for v in ints):
            return False
        return (
            self.seg_id >= 0
            and 2 <= self.lo < self.hi
            and self.nbits > 0
            and 0 <= self.count <= self.hi - self.lo
            and 0 <= self.twin_count <= self.hi - self.lo
            and self.first_word >= 0
            and self.last_word >= 0
            and isinstance(self.elapsed_s, (int, float))
            and self.elapsed_s >= 0
        )


class SieveWorker(abc.ABC):
    """A backend that sieves one segment at a time.

    Contract: given [lo, hi) and the host-computed seed primes (all primes
    <= isqrt(n), including 2/3/5 — the backend filters per packing), return
    the SegmentResult for the configured packing. Must be deterministic and
    idempotent: re-processing a segment yields an identical result (this is
    what makes failure-reassignment safe, SURVEY.md section 5.3).
    """

    name: str = ""

    def __init__(self, config: "SieveConfig"):
        self.config = config
        # host-prepare phase totals (seconds), populated by backends that
        # prepare incrementally (see sieve/kernels/specs.py chains); the
        # coordinator surfaces them in SieveResult.host_phases
        self.phase_seconds: dict[str, float] = {}

    @abc.abstractmethod
    def process_segment(
        self, lo: int, hi: int, seed_primes: np.ndarray, seg_id: int = 0
    ) -> SegmentResult:
        ...

    def process_segments(
        self,
        segments: list[tuple[int, int]],
        seed_primes: np.ndarray,
        seg_ids: list[int] | None = None,
    ) -> list[SegmentResult]:
        """Batched seam (ISSUE 9): sieve a list of [lo, hi) segments in
        one call. The default loops :meth:`process_segment` — bit-exact
        by construction — while device backends override it to stack the
        segments into a single dispatch (one launch for the whole list
        instead of N round trips). ``seed_primes`` must cover the
        largest ``hi`` (a superset is safe: every backend stops marking
        at ``p*p >= hi`` per segment). Results come back in input order,
        carrying ``seg_ids[i]`` (default ``i``).
        """
        if seg_ids is None:
            seg_ids = list(range(len(segments)))
        if len(seg_ids) != len(segments):
            raise ValueError(
                f"process_segments: {len(segments)} segments but "
                f"{len(seg_ids)} seg_ids"
            )
        return [
            self.process_segment(lo, hi, seed_primes, seg_id=sid)
            for (lo, hi), sid in zip(segments, seg_ids)
        ]

    def close(self) -> None:
        """Release backend resources (sockets, device buffers)."""
