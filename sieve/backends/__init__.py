"""Backend registry: maps ``--backend`` names to SieveWorker implementations.

SURVEY.md section 7.5: ``--backend`` selects among {cpu-numpy, cpu-native,
cpu-cluster, jax, tpu-pallas} through the one SieveWorker boundary.
Imports are lazy so CPU-only environments never import jax and vice versa.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable

if TYPE_CHECKING:
    from sieve.config import SieveConfig
    from sieve.worker import SieveWorker


def _cpu_numpy(config: "SieveConfig") -> "SieveWorker":
    from sieve.backends.cpu_numpy import CpuNumpyWorker

    return CpuNumpyWorker(config)


def _cpu_native(config: "SieveConfig") -> "SieveWorker":
    try:
        from sieve.backends.cpu_native import CpuNativeWorker
    except ImportError as e:
        raise RuntimeError(
            f"cpu-native backend unavailable ({e}); build it with "
            f"`make -C csrc` or use --backend cpu-numpy"
        ) from e

    return CpuNativeWorker(config)


def _jax(config: "SieveConfig") -> "SieveWorker":
    from sieve.backends.jax_backend import JaxWorker

    return JaxWorker(config)


def _tpu_pallas(config: "SieveConfig") -> "SieveWorker":
    try:
        from sieve.backends.tpu_pallas import PallasWorker
    except ImportError as e:
        raise RuntimeError(
            f"tpu-pallas backend unavailable ({e}); use --backend jax"
        ) from e

    return PallasWorker(config)


WORKER_FACTORIES: dict[str, Callable[["SieveConfig"], "SieveWorker"]] = {
    "cpu-numpy": _cpu_numpy,
    "cpu-native": _cpu_native,
    "jax": _jax,
    "tpu-pallas": _tpu_pallas,
}


def make_worker(config: "SieveConfig") -> "SieveWorker":
    try:
        factory = WORKER_FACTORIES[config.backend]
    except KeyError:
        raise ValueError(
            f"backend {config.backend!r} has no in-process worker "
            f"(cpu-cluster runs through sieve.cluster)"
        ) from None
    return factory(config)
