"""Reference CPU backend: readable numpy segmented marking.

This is the rebuild's readable reference `mark_multiples` (SURVEY.md
section 2, "CPU marking kernel (Python)") — the recipe every other backend
is parity-tested against. Slow is fine; correct is mandatory.
"""

from __future__ import annotations

import numpy as np

from sieve import trace
from sieve.bitset import boundary_words, get_layout
from sieve.worker import SegmentResult, SieveWorker


def sieve_segment_flags(
    layout_name: str, lo: int, hi: int, seed_primes: np.ndarray
) -> np.ndarray:
    """Boolean candidate flags for [lo, hi) after marking all composites."""
    layout = get_layout(layout_name)
    nbits = layout.nbits(lo, hi)
    flags = np.ones(nbits, dtype=bool)
    if nbits == 0:
        return flags
    wheel = set(layout.wheel_primes)
    for p in seed_primes.tolist():
        if p in wheel:
            continue
        if p * p >= hi:
            break  # seeds ascend; no later prime can mark in [lo, hi)
        layout.mark_numpy(flags, lo, hi, p)
    return flags


class CpuNumpyWorker(SieveWorker):
    name = "cpu-numpy"

    def process_segment(
        self, lo: int, hi: int, seed_primes: np.ndarray, seg_id: int = 0
    ) -> SegmentResult:
        with trace.span(
            "segment.mark", backend=self.name, seg=seg_id
        ) as sp:
            layout = get_layout(self.config.packing)
            flags = sieve_segment_flags(
                self.config.packing, lo, hi, seed_primes
            )
            count = int(np.count_nonzero(flags)) + layout.extras_in(lo, hi)
            gap = getattr(self.config, "pair_gap", 2) or 2
            twin_count = (
                layout.pairs_internal(flags, lo, hi, gap)
                if self.config.twins
                else 0
            )
            first_word, last_word = boundary_words(flags)
        return SegmentResult(
            seg_id=seg_id,
            lo=lo,
            hi=hi,
            count=count,
            twin_count=twin_count,
            first_word=first_word,
            last_word=last_word,
            nbits=int(flags.size),
            elapsed_s=sp.elapsed,
        )
