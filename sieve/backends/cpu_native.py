"""cpu-native backend: the C++ mark_multiples hot loop via ctypes.

The reference keeps its hot loop native (SURVEY.md section 0, "On
implementation language"); this backend is the rebuild's equivalent. Python
still computes marking specs (control plane); the strided bit-clear,
popcount, and twin reduction run in csrc/mark_multiples.cc over a packed
uint64 buffer. Auto-builds the shared library on first use (g++ is baked
into the image; pybind11 is not, hence ctypes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import time
from pathlib import Path

import numpy as np

from sieve import env, trace
from sieve.bitset import get_layout
from sieve.worker import SegmentResult, SieveWorker

_CSRC = Path(__file__).resolve().parent.parent.parent / "csrc"
_LIB: ctypes.CDLL | None = None


def _build_and_load() -> ctypes.CDLL:
    global _LIB
    if _LIB is not None:
        return _LIB
    name = "libmark_asan.so" if env.env_str("SIEVE_NATIVE_ASAN") else "libmark.so"
    so = _CSRC / "build" / name
    src = _CSRC / "mark_multiples.cc"
    if not so.exists() or so.stat().st_mtime < src.stat().st_mtime:
        import fcntl

        # serialize concurrent auto-builds (cluster workers start together;
        # two parallel `make`s writing the same .so would let a worker
        # dlopen a half-written library)
        so.parent.mkdir(parents=True, exist_ok=True)
        with open(so.parent / ".build_lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if not so.exists() or so.stat().st_mtime < src.stat().st_mtime:
                target = "asan" if name.endswith("asan.so") else "all"
                subprocess.run(
                    ["make", "-C", str(_CSRC), target],
                    check=True,
                    capture_output=True,
                    text=True,
                )
    lib = ctypes.CDLL(str(so))
    lib.sieve_init.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.mark_multiples.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.popcount_words.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.popcount_words.restype = ctypes.c_int64
    lib.twin_count.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int, ctypes.c_uint64,
    ]
    lib.twin_count.restype = ctypes.c_int64
    _LIB = lib
    return lib


def _pair_mask64(packing: str, lo: int, gap: int = 2) -> int:
    """64-bit pairability mask: the 32-bit rule's period (8) divides
    32, so the wide mask is just the 32-bit helper doubled."""
    from sieve.kernels.specs import _pair_mask

    m32 = _pair_mask(packing, lo, gap)
    return m32 | (m32 << 32)


def _boundary_words_u64(words: np.ndarray, nbits: int) -> tuple[int, int]:
    """(first_word, last_word) in SegmentResult's uint32 semantics."""
    first = int(words[0]) & 0xFFFFFFFF
    if nbits <= 32:
        return first, first
    off = nbits - 32
    w, sh = divmod(off, 64)
    val = int(words[w]) >> sh
    if sh > 32 and w + 1 < words.size:
        val |= int(words[w + 1]) << (64 - sh)
    return first, val & 0xFFFFFFFF


class CpuNativeWorker(SieveWorker):
    name = "cpu-native"

    def __init__(self, config):
        super().__init__(config)
        self._lib = _build_and_load()

    def process_segment(
        self, lo: int, hi: int, seed_primes: np.ndarray, seg_id: int = 0
    ) -> SegmentResult:
        from sieve.kernels.specs import marking_specs

        t0 = time.perf_counter()
        packing = self.config.packing
        layout = get_layout(packing)
        with trace.span("segment.prepare", backend=self.name, seg=seg_id):
            specs = marking_specs(packing, lo, hi, seed_primes)
        nbits = specs.nbits
        nwords = max(1, -(-nbits // 64))
        words = np.empty(nwords, dtype=np.uint64)
        m = specs.m.astype(np.int64)
        s = specs.s.astype(np.int64)

        lib = self._lib
        words_p = words.ctypes.data_as(ctypes.c_void_p)
        with trace.span("segment.mark", backend=self.name, seg=seg_id):
            lib.sieve_init(words_p, nwords, nbits)
            lib.mark_multiples(
                words_p,
                nbits,
                m.ctypes.data_as(ctypes.c_void_p),
                s.ctypes.data_as(ctypes.c_void_p),
                len(m),
            )
        count = int(lib.popcount_words(words_p, nwords)) + layout.extras_in(lo, hi)
        twin = 0
        if self.config.twins and nbits:
            gap = getattr(self.config, "pair_gap", 2) or 2
            if packing == "plain":
                shift = gap
            elif packing == "odds":
                shift = gap // 2
            else:
                shift = 1
            twin = int(
                lib.twin_count(
                    words_p, nwords, shift, _pair_mask64(packing, lo, gap)
                )
            )
            twin += layout.extra_pairs(lo, hi, gap)
        first_word, last_word = _boundary_words_u64(words, nbits)
        return SegmentResult(
            seg_id=seg_id,
            lo=lo,
            hi=hi,
            count=count,
            twin_count=twin,
            first_word=first_word,
            last_word=last_word,
            nbits=nbits,
            elapsed_s=time.perf_counter() - t0,
        )
