"""JAX single-device backend (SURVEY.md milestones M2+M3, strategy A).

Runs the tiered scatter-free word kernel (sieve/kernels/jax_mark.py) on the
default device — TPU when present, CPU in CI. Segments smaller than 64
candidate bits fall back to the numpy reference (boundary-word semantics
for sub-word segments are a host-side concern, not worth a device kernel).

Shapes are bucketed (words to WORD_BUCKET, tier-2 spec count to a power of
two) so the jit cache stays small across segments (SURVEY.md 7.4 "avoiding
recompilation across rounds — bounds as traced scalars, shapes static").
"""

from __future__ import annotations

import contextlib
import os
import time

import numpy as np

from sieve import env, trace
from sieve.backends.cpu_numpy import CpuNumpyWorker
from sieve.bitset import get_layout
from sieve.kernels.jax_mark import (
    COUSIN_ADJ,
    COUSIN_PLAIN,
    COUSIN_W30,
    SPEC_BLOCK,
    TIER1_MAX,
    TWIN_ADJ,
    TWIN_NONE,
    TWIN_PLAIN,
    TWIN_W30,
    WORD_BUCKET,
    mark_words,
    mark_words_batch,
    next_pow2,
)
from sieve.kernels.specs import TieredChain, prepare_tiered
from sieve.worker import SegmentResult, SieveWorker

TWIN_KIND = {"plain": TWIN_PLAIN, "odds": TWIN_ADJ, "wheel30": TWIN_W30}
COUSIN_KIND = {"plain": COUSIN_PLAIN, "odds": COUSIN_ADJ, "wheel30": COUSIN_W30}

MIN_DEVICE_BITS = 64


def pair_kind(config) -> int:
    """Device pair-reduction kind for a config (--count-kind plug point):
    TWIN_NONE when no pairs are counted, else the (packing, gap)-specific
    splice kind the kernels run."""
    gap = getattr(config, "pair_gap", 2 if config.twins else 0)
    if gap == 0:
        return TWIN_NONE
    return (TWIN_KIND if gap == 2 else COUSIN_KIND)[config.packing]


def prepare_segment(packing: str, lo: int, hi: int, seeds: np.ndarray):
    """Host prep with bucketed shapes; returns a TieredSegment."""
    ts = prepare_tiered(
        packing, lo, hi, seeds,
        tier1_max=TIER1_MAX, spec_block=SPEC_BLOCK, word_bucket=WORD_BUCKET,
    )
    # bucket the tier-2 spec count to a power of two for jit-cache economy
    return ts.with_spec_count(max(SPEC_BLOCK, next_pow2(ts.m2.size)))


class JaxWorker(SieveWorker):
    name = "jax"

    def __init__(self, config):
        super().__init__(config)
        import jax  # deferred so CPU-only paths never need it

        self._jax = jax
        # SIEVE_JAX_PLATFORM pins the device platform (tests use "cpu" so CI
        # never depends on — or occupies — the real TPU).
        platform = env.env_str("SIEVE_JAX_PLATFORM")
        self._device = jax.devices(platform)[0] if platform else None
        self._cpu_fallback = CpuNumpyWorker(config)
        self._chain: TieredChain | None = None
        self._chain_seeds: np.ndarray | None = None

    def _placement(self):
        if self._device is None:
            return contextlib.nullcontext()
        return self._jax.default_device(self._device)

    def _prepare(self, packing: str, lo: int, hi: int, seeds: np.ndarray):
        """Incremental per-worker prepare: segments of one run arrive in
        order, so residues advance O(1) per seed instead of re-deriving
        from scratch (exact for arbitrary jumps; see specs.TieredChain)."""
        if self._chain is None or self._chain_seeds is not seeds:
            self._chain = TieredChain(
                packing, seeds,
                tier1_max=TIER1_MAX, spec_block=SPEC_BLOCK,
                word_bucket=WORD_BUCKET,
                pair_gap=getattr(self.config, "pair_gap", 2) or 2,
            )
            self._chain_seeds = seeds
            self.phase_seconds = self._chain.phase_seconds
        ts = self._chain.prepare(lo, hi)
        return ts.with_spec_count(max(SPEC_BLOCK, next_pow2(ts.m2.size)))

    def process_segment(
        self, lo: int, hi: int, seed_primes: np.ndarray, seg_id: int = 0
    ) -> SegmentResult:
        t0 = time.perf_counter()
        packing = self.config.packing
        layout = get_layout(packing)
        nbits = layout.nbits(lo, hi)
        if nbits < MIN_DEVICE_BITS:
            return self._cpu_fallback.process_segment(lo, hi, seed_primes, seg_id)

        with trace.span("segment.prepare", backend=self.name, seg=seg_id):
            ts = self._prepare(packing, lo, hi, seed_primes)
        twin_kind = pair_kind(self.config)
        with trace.span("segment.device", backend=self.name, seg=seg_id), \
                self._placement():
            packed = np.asarray(mark_words(
                ts.Wpad,
                twin_kind,
                ts.periods,
                np.int32(nbits),
                ts.patterns,
                ts.m2, ts.r2, ts.K2, ts.rcp2, ts.act2,
                ts.corr_idx, ts.corr_mask,
                np.uint32(ts.pair_mask),
            ))  # one uint32[4] fetch: count, twins, first32, last32
        count, twins, first32, last32 = (int(v) for v in packed)
        count += layout.extras_in(lo, hi)
        twin_count = (
            twins + layout.extra_pairs(
                lo, hi, getattr(self.config, "pair_gap", 2) or 2)
            if self.config.twins
            else 0
        )
        return SegmentResult(
            seg_id=seg_id,
            lo=lo,
            hi=hi,
            count=count,
            twin_count=twin_count,
            first_word=int(first32),
            last_word=int(last32),
            nbits=nbits,
            elapsed_s=time.perf_counter() - t0,
        )

    def process_segments(
        self,
        segments: list[tuple[int, int]],
        seed_primes: np.ndarray,
        seg_ids: list[int] | None = None,
    ) -> list[SegmentResult]:
        """Batched dispatch (ISSUE 9): prepare every segment on the host,
        group by bucketed kernel shape, stack each group's spec arrays
        along a leading batch axis and run ONE vmapped device launch per
        group (`mark_words_batch`). Segments of equal span — the cold
        plane's fixed grid — land in one group, so a drained queue of N
        chunks costs a single dispatch. Bit-exact vs the sequential path
        by the shared `mark_words_impl`; sub-word segments fall back to
        the numpy reference exactly as `process_segment` does."""
        if seg_ids is None:
            seg_ids = list(range(len(segments)))
        if len(seg_ids) != len(segments):
            raise ValueError(
                f"process_segments: {len(segments)} segments but "
                f"{len(seg_ids)} seg_ids"
            )
        packing = self.config.packing
        layout = get_layout(packing)
        out: list[SegmentResult | None] = [None] * len(segments)
        # (Wpad, periods, S2, C_padded) -> [(pos, ts, t_start)]
        groups: dict[tuple, list[tuple[int, object, float]]] = {}
        for pos, (lo, hi) in enumerate(segments):
            t0 = time.perf_counter()
            if layout.nbits(lo, hi) < MIN_DEVICE_BITS:
                out[pos] = self._cpu_fallback.process_segment(
                    lo, hi, seed_primes, seg_ids[pos]
                )
                continue
            with trace.span(
                "segment.prepare", backend=self.name, seg=seg_ids[pos]
            ):
                ts = self._prepare(packing, lo, hi, seed_primes)
            # corrections are padded per group to a pow2 bucket; key on
            # the bucket so the jit cache stays bounded across batches
            c_pad = max(1, next_pow2(ts.corr_idx.size))
            key = (ts.Wpad, ts.periods, ts.m2.size, c_pad)
            groups.setdefault(key, []).append((pos, ts, t0))
        twin_kind = pair_kind(self.config)
        gap = getattr(self.config, "pair_gap", 2) or 2
        for (Wpad, periods, _s2, c_pad), members in groups.items():
            with trace.span(
                "segment.device", backend=self.name, batch=len(members)
            ), self._placement():
                packed = np.asarray(mark_words_batch(
                    Wpad,
                    twin_kind,
                    periods,
                    np.array([m[1].nbits for m in members], np.int32),
                    tuple(
                        np.stack([m[1].patterns[i] for m in members])
                        for i in range(len(periods))
                    ),
                    *(
                        np.stack([getattr(m[1], f) for m in members])
                        for f in ("m2", "r2", "K2", "rcp2", "act2")
                    ),
                    np.stack([
                        _pad_to(m[1].corr_idx, c_pad, 0) for m in members
                    ]),
                    np.stack([
                        _pad_to(m[1].corr_mask, c_pad, 0) for m in members
                    ]),
                    np.array(
                        [m[1].pair_mask for m in members], np.uint32
                    ),
                ))  # uint32[B, 4]: count, pairs, first32, last32
            for (pos, ts, t0), row in zip(members, packed):
                lo, hi = segments[pos]
                count, twins, first32, last32 = (int(v) for v in row)
                count += layout.extras_in(lo, hi)
                twin_count = (
                    twins + layout.extra_pairs(lo, hi, gap)
                    if self.config.twins
                    else 0
                )
                out[pos] = SegmentResult(
                    seg_id=seg_ids[pos],
                    lo=lo,
                    hi=hi,
                    count=count,
                    twin_count=twin_count,
                    first_word=int(first32),
                    last_word=int(last32),
                    nbits=ts.nbits,
                    elapsed_s=time.perf_counter() - t0,
                )
        return out


def _pad_to(a: np.ndarray, n: int, fill) -> np.ndarray:
    """Right-pad ``a`` to length ``n`` with ``fill`` — correction pads use
    (idx=0, mask=0): the scatter-max `cur | 0` at word 0 is a no-op, so a
    padded batch stays bit-exact."""
    if a.size == n:
        return a
    return np.concatenate([a, np.full(n - a.size, fill, a.dtype)])
