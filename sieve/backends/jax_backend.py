"""JAX single-device backend (SURVEY.md milestones M2+M3, strategy A).

Runs the tiered scatter-free word kernel (sieve/kernels/jax_mark.py) on the
default device — TPU when present, CPU in CI. Segments smaller than 64
candidate bits fall back to the numpy reference (boundary-word semantics
for sub-word segments are a host-side concern, not worth a device kernel).

Shapes are bucketed (words to WORD_BUCKET, tier-2 spec count to a power of
two) so the jit cache stays small across segments (SURVEY.md 7.4 "avoiding
recompilation across rounds — bounds as traced scalars, shapes static").
"""

from __future__ import annotations

import contextlib
import os
import time

import numpy as np

from sieve import trace
from sieve.backends.cpu_numpy import CpuNumpyWorker
from sieve.bitset import get_layout
from sieve.kernels.jax_mark import (
    COUSIN_ADJ,
    COUSIN_PLAIN,
    COUSIN_W30,
    SPEC_BLOCK,
    TIER1_MAX,
    TWIN_ADJ,
    TWIN_NONE,
    TWIN_PLAIN,
    TWIN_W30,
    WORD_BUCKET,
    mark_words,
    next_pow2,
)
from sieve.kernels.specs import TieredChain, prepare_tiered
from sieve.worker import SegmentResult, SieveWorker

TWIN_KIND = {"plain": TWIN_PLAIN, "odds": TWIN_ADJ, "wheel30": TWIN_W30}
COUSIN_KIND = {"plain": COUSIN_PLAIN, "odds": COUSIN_ADJ, "wheel30": COUSIN_W30}

MIN_DEVICE_BITS = 64


def pair_kind(config) -> int:
    """Device pair-reduction kind for a config (--count-kind plug point):
    TWIN_NONE when no pairs are counted, else the (packing, gap)-specific
    splice kind the kernels run."""
    gap = getattr(config, "pair_gap", 2 if config.twins else 0)
    if gap == 0:
        return TWIN_NONE
    return (TWIN_KIND if gap == 2 else COUSIN_KIND)[config.packing]


def prepare_segment(packing: str, lo: int, hi: int, seeds: np.ndarray):
    """Host prep with bucketed shapes; returns a TieredSegment."""
    ts = prepare_tiered(
        packing, lo, hi, seeds,
        tier1_max=TIER1_MAX, spec_block=SPEC_BLOCK, word_bucket=WORD_BUCKET,
    )
    # bucket the tier-2 spec count to a power of two for jit-cache economy
    return ts.with_spec_count(max(SPEC_BLOCK, next_pow2(ts.m2.size)))


class JaxWorker(SieveWorker):
    name = "jax"

    def __init__(self, config):
        super().__init__(config)
        import jax  # deferred so CPU-only paths never need it

        self._jax = jax
        # SIEVE_JAX_PLATFORM pins the device platform (tests use "cpu" so CI
        # never depends on — or occupies — the real TPU).
        platform = os.environ.get("SIEVE_JAX_PLATFORM")
        self._device = jax.devices(platform)[0] if platform else None
        self._cpu_fallback = CpuNumpyWorker(config)
        self._chain: TieredChain | None = None
        self._chain_seeds: np.ndarray | None = None

    def _placement(self):
        if self._device is None:
            return contextlib.nullcontext()
        return self._jax.default_device(self._device)

    def _prepare(self, packing: str, lo: int, hi: int, seeds: np.ndarray):
        """Incremental per-worker prepare: segments of one run arrive in
        order, so residues advance O(1) per seed instead of re-deriving
        from scratch (exact for arbitrary jumps; see specs.TieredChain)."""
        if self._chain is None or self._chain_seeds is not seeds:
            self._chain = TieredChain(
                packing, seeds,
                tier1_max=TIER1_MAX, spec_block=SPEC_BLOCK,
                word_bucket=WORD_BUCKET,
                pair_gap=getattr(self.config, "pair_gap", 2) or 2,
            )
            self._chain_seeds = seeds
            self.phase_seconds = self._chain.phase_seconds
        ts = self._chain.prepare(lo, hi)
        return ts.with_spec_count(max(SPEC_BLOCK, next_pow2(ts.m2.size)))

    def process_segment(
        self, lo: int, hi: int, seed_primes: np.ndarray, seg_id: int = 0
    ) -> SegmentResult:
        t0 = time.perf_counter()
        packing = self.config.packing
        layout = get_layout(packing)
        nbits = layout.nbits(lo, hi)
        if nbits < MIN_DEVICE_BITS:
            return self._cpu_fallback.process_segment(lo, hi, seed_primes, seg_id)

        with trace.span("segment.prepare", backend=self.name, seg=seg_id):
            ts = self._prepare(packing, lo, hi, seed_primes)
        twin_kind = pair_kind(self.config)
        with trace.span("segment.device", backend=self.name, seg=seg_id), \
                self._placement():
            packed = np.asarray(mark_words(
                ts.Wpad,
                twin_kind,
                ts.periods,
                np.int32(nbits),
                ts.patterns,
                ts.m2, ts.r2, ts.K2, ts.rcp2, ts.act2,
                ts.corr_idx, ts.corr_mask,
                np.uint32(ts.pair_mask),
            ))  # one uint32[4] fetch: count, twins, first32, last32
        count, twins, first32, last32 = (int(v) for v in packed)
        count += layout.extras_in(lo, hi)
        twin_count = (
            twins + layout.extra_pairs(
                lo, hi, getattr(self.config, "pair_gap", 2) or 2)
            if self.config.twins
            else 0
        )
        return SegmentResult(
            seg_id=seg_id,
            lo=lo,
            hi=hi,
            count=count,
            twin_count=twin_count,
            first_word=int(first32),
            last_word=int(last32),
            nbits=nbits,
            elapsed_s=time.perf_counter() - t0,
        )
