"""Mesh-backed cold compute plane (ISSUE 18): one drain, every chip.

``MeshWorker`` implements the ``SieveWorker.process_segments`` seam by
padding a drained chunk list onto the device mesh and issuing ONE
``shard_map``/``jit`` SPMD launch over the word kernel per drain slice —
a cold burst over K chunks costs one multi-device round instead of K
sequential markings. It reuses JaxWorker's host prepare (TieredChain)
and shape-bucketed grouping verbatim, so results are bit-exact against
the loop path by construction; the only new moving part is the batch
padding onto the mesh:

- the batch's leading dim is padded to ``ndev * next_pow2(ceil(B/ndev))``
  so every device holds the same number of rows AND the per-device row
  count buckets to a power of two (jit-cache economy across drains);
- pad rows duplicate the group's first member — they compute a real
  (discarded) result, so padding cannot perturb the live rows.

Construction raises when the mesh cannot be built (fewer devices than
requested); the service's ColdBackend catches that and falls back to the
loop worker — typed-degraded, never a wrong answer (sieve/service).
"""

from __future__ import annotations

import time

import numpy as np

from sieve import env, trace
from sieve.backends.jax_backend import (
    MIN_DEVICE_BITS,
    JaxWorker,
    _pad_to,
    pair_kind,
)
from sieve.bitset import get_layout
from sieve.kernels.jax_mark import next_pow2
from sieve.worker import SegmentResult


def mesh_device_count() -> int:
    """Devices the cold mesh should span: ``SIEVE_MESH_COLD_DEVICES``
    override, else every device on the pinned platform."""
    want = env.env_int("SIEVE_MESH_COLD_DEVICES", 0)
    if want > 0:
        return want
    import jax

    platform = env.env_str("SIEVE_JAX_PLATFORM")
    try:
        return max(1, len(jax.devices(platform) if platform else jax.devices()))
    except RuntimeError:
        return 1


class MeshWorker(JaxWorker):
    """SPMD cold-plane worker: ``process_segments`` shards the drained
    chunk batch over the device mesh (one launch per shape group)."""

    name = "mesh"

    def __init__(self, config, n_devices: int | None = None):
        super().__init__(config)
        from sieve.parallel.mesh import _register_mesh, build_mesh

        ndev = int(n_devices) if n_devices else mesh_device_count()
        self.mesh = build_mesh(ndev)  # raises when the host is too small
        self._mesh_key = _register_mesh(self.mesh)
        self.devices = ndev
        # capacity class for the coordinator hello handshake: a mesh host
        # marks ndev chunks per round, so it can drain ndev-sized batches
        self.capacity = ndev
        self.launches = 0  # guard: caller (ColdBackend._lock / 1 test thread)

    def process_segments(
        self,
        segments: list[tuple[int, int]],
        seed_primes: np.ndarray,
        seg_ids: list[int] | None = None,
    ) -> list[SegmentResult]:
        """One SPMD launch per shape group: same host prepare + grouping
        as JaxWorker.process_segments, but each group's batch is padded
        onto the mesh and dispatched through the sharded cold step.
        Equal-span chunks — the cold plane's fixed grid — land in one
        group, so a drain slice costs a single multi-device round."""
        from sieve.parallel.mesh import _make_cold_step

        if seg_ids is None:
            seg_ids = list(range(len(segments)))
        if len(seg_ids) != len(segments):
            raise ValueError(
                f"process_segments: {len(segments)} segments but "
                f"{len(seg_ids)} seg_ids"
            )
        packing = self.config.packing
        layout = get_layout(packing)
        out: list[SegmentResult | None] = [None] * len(segments)
        # (Wpad, periods, S2, C_padded) -> [(pos, ts, t_start)] — the same
        # bucket key as JaxWorker, so the two paths group identically
        groups: dict[tuple, list[tuple[int, object, float]]] = {}
        for pos, (lo, hi) in enumerate(segments):
            t0 = time.perf_counter()
            if layout.nbits(lo, hi) < MIN_DEVICE_BITS:
                # sub-word slivers: numpy reference, as process_segment does
                out[pos] = self._cpu_fallback.process_segment(
                    lo, hi, seed_primes, seg_ids[pos]
                )
                continue
            with trace.span(
                "segment.prepare", backend=self.name, seg=seg_ids[pos]
            ):
                ts = self._prepare(packing, lo, hi, seed_primes)
            c_pad = max(1, next_pow2(ts.corr_idx.size))
            key = (ts.Wpad, ts.periods, ts.m2.size, c_pad)
            groups.setdefault(key, []).append((pos, ts, t0))
        twin_kind = pair_kind(self.config)
        gap = getattr(self.config, "pair_gap", 2) or 2
        ndev = self.devices
        for (Wpad, periods, _s2, c_pad), members in groups.items():
            b = len(members)
            # pad the batch so every device gets an equal, pow2-bucketed
            # row count; pad rows recompute member 0 and are discarded
            b_pad = ndev * next_pow2(-(-b // ndev))
            rows = [m[1] for m in members] + [members[0][1]] * (b_pad - b)
            step = _make_cold_step(
                self._mesh_key, Wpad, twin_kind, periods, ndev
            )
            with trace.span(
                "segment.device", backend=self.name, batch=b,
                padded=b_pad, devices=ndev,
            ):
                packed = np.asarray(step(
                    np.array([ts.nbits for ts in rows], np.int32),
                    tuple(
                        np.stack([ts.patterns[i] for ts in rows])
                        for i in range(len(periods))
                    ),
                    *(
                        np.stack([getattr(ts, f) for ts in rows])
                        for f in ("m2", "r2", "K2", "rcp2", "act2")
                    ),
                    np.stack([
                        _pad_to(ts.corr_idx, c_pad, 0) for ts in rows
                    ]),
                    np.stack([
                        _pad_to(ts.corr_mask, c_pad, 0) for ts in rows
                    ]),
                    np.array([ts.pair_mask for ts in rows], np.uint32),
                ))  # uint32[b_pad, 4]: count, pairs, first32, last32
            self.launches += 1
            for (pos, ts, t0), row in zip(members, packed[:b]):
                lo, hi = segments[pos]
                count, twins, first32, last32 = (int(v) for v in row)
                count += layout.extras_in(lo, hi)
                twin_count = (
                    twins + layout.extra_pairs(lo, hi, gap)
                    if self.config.twins
                    else 0
                )
                out[pos] = SegmentResult(
                    seg_id=seg_ids[pos],
                    lo=lo,
                    hi=hi,
                    count=count,
                    twin_count=twin_count,
                    first_word=int(first32),
                    last_word=int(last32),
                    nbits=ts.nbits,
                    elapsed_s=time.perf_counter() - t0,
                )
        return out
