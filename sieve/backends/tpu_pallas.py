"""tpu-pallas backend: the fused single-pass Pallas kernel (SURVEY.md M3).

Same SieveWorker contract and host-side result assembly as the jax
backend; only the device path differs (sieve/kernels/pallas_mark.py). On
non-TPU platforms (CI) the kernel runs in Pallas interpret mode, so the
exact same kernel logic is parity-tested against cpu-numpy without TPU
hardware.

Wide strides are handled crossing-proportionally at prepare time:
group-D specs with zero crossings of the segment are pruned (the (ND,128)
table compacts to live rows), and strides at or above the
SIEVE_PALLAS_FLAT_MIN cutoff skip the kernel entirely — their few
(word, mask) crossings are host-enumerated and applied by the XLA
postlude scatter. Both mechanisms preserve exact parity (see
tests/test_wide_stride.py); tune the cutoff on real hardware with
tools/profile_kernel.py.
"""

from __future__ import annotations

import contextlib
import os
import time

import numpy as np

from sieve import env, trace
from sieve.backends.cpu_numpy import CpuNumpyWorker
from sieve.backends.jax_backend import MIN_DEVICE_BITS, pair_kind
from sieve.bitset import get_layout
from sieve.kernels.pallas_mark import (
    TILE_WORDS,
    PallasChain,
    mark_pallas,
    pallas_fused_enabled,
)
from sieve.worker import SegmentResult, SieveWorker


class PallasWorker(SieveWorker):
    name = "tpu-pallas"

    def __init__(self, config):
        super().__init__(config)
        import jax

        self._jax = jax
        platform = env.env_str("SIEVE_JAX_PLATFORM")
        self._device = jax.devices(platform)[0] if platform else jax.devices()[0]
        self._interpret = self._device.platform == "cpu"
        self._cpu_fallback = CpuNumpyWorker(config)
        self._chains: dict[int, PallasChain] = {}  # keyed by padded width
        self._chain_seeds: np.ndarray | None = None
        # device mark+reduce time by reduction mode ("postlude_fused" /
        # "postlude_split"); surfaced through SieveResult.host_phases
        self.reduce_seconds: dict[str, float] = {}

    def _placement(self):
        if self._device is None:
            return contextlib.nullcontext()
        return self._jax.default_device(self._device)

    def _prepare(self, packing: str, lo: int, hi: int, seeds: np.ndarray):
        """Incremental per-worker prepare (see specs.SpecChain): one chain
        per padded width — a run's equal-sized segments share one chain, so
        residues advance O(1) per seed instead of being re-derived."""
        if self._chain_seeds is not seeds:
            self._chains.clear()
            self._chain_seeds = seeds
        layout = get_layout(packing)
        W = -(-layout.nbits(lo, hi) // 32)
        wpad = -(-(W + 1) // TILE_WORDS) * TILE_WORDS
        chain = self._chains.get(wpad)
        if chain is None:
            chain = self._chains[wpad] = PallasChain(
                packing, seeds, wpad,
                pair_gap=getattr(self.config, "pair_gap", 2) or 2,
            )
        ps = chain.prepare(lo, hi)
        agg: dict[str, float] = {}
        for c in self._chains.values():
            for k, v in c.phase_seconds.items():
                agg[k] = agg.get(k, 0.0) + v
        self.phase_seconds = agg
        return ps

    def process_segment(
        self, lo: int, hi: int, seed_primes: np.ndarray, seg_id: int = 0
    ) -> SegmentResult:
        t0 = time.perf_counter()
        packing = self.config.packing
        layout = get_layout(packing)
        nbits = layout.nbits(lo, hi)
        if nbits < MIN_DEVICE_BITS:
            return self._cpu_fallback.process_segment(lo, hi, seed_primes, seg_id)

        with trace.span("segment.prepare", backend=self.name, seg=seg_id):
            ps = self._prepare(packing, lo, hi, seed_primes)
        twin_kind = pair_kind(self.config)
        self.reduction_mode = (
            "fused" if pallas_fused_enabled() else "split"
        )
        key = "postlude_" + self.reduction_mode
        with trace.span(
            "segment.device", backend=self.name, seg=seg_id, mode=key
        ) as sp, self._placement():
            count, twins, first_word, last_word = mark_pallas(
                ps, twin_kind, self._interpret
            )
        self.reduce_seconds[key] = (
            self.reduce_seconds.get(key, 0.0) + sp.elapsed
        )
        count += layout.extras_in(lo, hi)
        twin_count = (
            twins + layout.extra_pairs(
                lo, hi, getattr(self.config, "pair_gap", 2) or 2)
            if self.config.twins else 0
        )
        return SegmentResult(
            seg_id=seg_id,
            lo=lo,
            hi=hi,
            count=count,
            twin_count=twin_count,
            first_word=first_word,
            last_word=last_word,
            nbits=nbits,
            elapsed_s=time.perf_counter() - t0,
        )
