"""Prime enumeration: materialize the primes in a subrange.

The reference counts AND enumerates primes over [2, N] (SURVEY.md section 0
[D]); counting is the scalable product, enumeration is the inspection tool.
Emission is host/IO-bound by nature, so it runs the readable numpy marking
(sieve/backends/cpu_numpy.py) over the requested window in segment-sized
slices — any packing, any window inside [2, n+1), modest memory.
"""

from __future__ import annotations

import bisect
import math
from typing import Callable, Iterator, Sequence

import numpy as np

from sieve.backends.cpu_numpy import sieve_segment_flags
from sieve.bitset import get_layout
from sieve.seed import seed_primes

# Enumerating more than this per call is almost certainly a mistake (the
# output alone would be GBs); counting is the scalable interface.
MAX_SPAN = 10**9
# Window position cap: the seed sieve needs isqrt(hi) memory (a 10**14
# ceiling keeps it at ~10 MB). Windows beyond that need a segmented seed
# sieve — out of scope for an inspection tool.
MAX_HI = 10**14
_SLICE = 1 << 24  # values per internal slice


def primes_in_range(
    packing: str,
    lo: int,
    hi: int,
    *,
    bounds: Sequence[int] | None = None,
    flags_fn: Callable[[int, int], "np.ndarray | None"] | None = None,
) -> Iterator[np.ndarray]:
    """Yield ascending int64 arrays of the primes in [lo, hi).

    Streams one array per internal slice so callers can print without
    holding the whole result. Bounds are validated eagerly (before the
    first yield), so callers can start writing output once this returns.

    The query service (sieve/service/) plugs in here: ``bounds`` is an
    ascending sequence of segment boundaries the internal slices must not
    straddle (so a cached whole-segment bitset can be bit-sliced per
    slice), and ``flags_fn(slo, shi)`` may return the candidate-flag
    array for a slice — returning ``None`` falls back to the local
    numpy marking for that slice.
    """
    lo = max(lo, 2)
    if hi > lo + MAX_SPAN:
        raise ValueError(
            f"enumeration span {hi - lo} exceeds {MAX_SPAN}; "
            "narrow the window (counting scales, enumeration is for windows)"
        )
    if hi > MAX_HI:
        raise ValueError(
            f"enumeration window ends at {hi} > {MAX_HI}: the seed sieve "
            "for that offset would need isqrt(hi) memory"
        )
    return _primes_in_range_gen(packing, lo, hi, bounds, flags_fn)


def _slices(
    lo: int, hi: int, bounds: Sequence[int] | None
) -> Iterator[tuple[int, int]]:
    """Cut [lo, hi) at every interior bound, then sub-chunk by _SLICE."""
    cuts = [lo]
    if bounds:
        i = bisect.bisect_right(bounds, lo)
        while i < len(bounds) and bounds[i] < hi:
            cuts.append(int(bounds[i]))
            i += 1
    cuts.append(hi)
    for clo, chi in zip(cuts, cuts[1:]):
        for slo in range(clo, chi, _SLICE):
            yield slo, min(slo + _SLICE, chi)


def _primes_in_range_gen(
    packing: str,
    lo: int,
    hi: int,
    bounds: Sequence[int] | None = None,
    flags_fn: Callable[[int, int], "np.ndarray | None"] | None = None,
) -> Iterator[np.ndarray]:
    if hi <= lo:
        return
    layout = get_layout(packing)
    seeds = None
    for slo, shi in _slices(lo, hi, bounds):
        flags = flags_fn(slo, shi) if flags_fn is not None else None
        if flags is None:
            if seeds is None:
                seeds = seed_primes(math.isqrt(hi - 1))
            flags = sieve_segment_flags(packing, slo, shi, seeds)
        vals = layout.values_np(slo, np.nonzero(flags)[0])
        extras = np.array(
            [p for p in layout.extra_primes if slo <= p < shi], dtype=np.int64
        )
        if extras.size:
            vals = np.concatenate([extras, vals])
        yield vals
