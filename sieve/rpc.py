"""Length-prefixed RPC framing shared by the cluster and service planes.

One wire format for every TCP endpoint in the repo (SURVEY.md section
3.2): an 8-byte big-endian length prefix followed by a JSON object. The
cpu-cluster transport (sieve/cluster.py) ships seed primes, segment
assignments, and telemetry over it; the query service
(sieve/service/server.py) answers ``pi``/``count``/``nth_prime``/
``primes`` requests over the very same framing, so a worker host and a
query client speak to the coordinator with the same four functions.

``recv_msg`` returns ``None`` on a cleanly closed peer (EOF mid-header
or mid-body), letting callers distinguish an orderly close from a
protocol error; socket timeouts propagate as ``socket.timeout`` so both
planes can bound every read (a dead peer must never park a thread in
``recv`` forever — ISSUE 6/7).
"""

from __future__ import annotations

import json
import socket
import struct


def send_msg(sock: socket.socket, msg: dict) -> None:
    blob = json.dumps(msg).encode()
    sock.sendall(struct.pack(">Q", len(blob)) + blob)


def recv_msg(sock: socket.socket) -> dict | None:
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (length,) = struct.unpack(">Q", header)
    blob = _recv_exact(sock, length)
    if blob is None:
        return None
    return json.loads(blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def parse_addr(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)
