"""Length-prefixed RPC framing shared by the cluster and service planes.

One wire format for every TCP endpoint in the repo (SURVEY.md section
3.2): an 8-byte big-endian length prefix followed by a JSON object. The
cpu-cluster transport (sieve/cluster.py) ships seed primes, segment
assignments, and telemetry over it; the query service
(sieve/service/server.py) answers ``pi``/``count``/``nth_prime``/
``primes`` requests over the very same framing, so a worker host and a
query client speak to the coordinator with the same four functions.

``recv_msg`` returns ``None`` on a cleanly closed peer (EOF mid-header
or mid-body), letting callers distinguish an orderly close from a
protocol error; socket timeouts propagate as ``socket.timeout`` so both
planes can bound every read (a dead peer must never park a thread in
``recv`` forever — ISSUE 6/7).

Pipelined framing (ISSUE 14): the frame format is self-delimiting, so
nothing in it ties one request to one reply — requests carry ``id``,
replies echo it, and any number may be in flight per connection.
``encode_msg`` produces one wire frame for queue-based senders, and
:class:`FrameDecoder` turns an arbitrary byte stream (non-blocking
reads of any size, including mid-frame) back into messages — the
service's selector event loop reads through it, while blocking callers
keep using ``recv_msg`` unchanged.
"""

from __future__ import annotations

import json
import socket
import struct

# Upper bound on a single frame accepted by the incremental decoder: a
# peer that sends a garbage length prefix must be cut off, not allowed
# to make the event loop buffer gigabytes waiting for a body that never
# comes. Generous — a max_primes=200_000 reply is ~2 MB.
MAX_FRAME = 256 << 20


def encode_msg(msg: dict) -> bytes:
    """One complete wire frame (length prefix + JSON body)."""
    blob = json.dumps(msg).encode()
    return struct.pack(">Q", len(blob)) + blob


def send_msg(sock: socket.socket, msg: dict) -> None:
    sock.sendall(encode_msg(msg))


class FrameDecoder:
    """Incremental frame decoder for non-blocking readers.

    Feed it whatever ``recv`` returned — single bytes, half a header,
    ten frames at once — and it yields every complete message, keeping
    the undecoded tail buffered. Raises ``ValueError`` on an oversized
    length prefix or a non-JSON body, which callers treat exactly like
    a framing error from ``recv_msg``: close the connection.
    """

    __slots__ = ("_buf", "_max_frame")

    def __init__(self, max_frame: int = MAX_FRAME):
        self._buf = bytearray()
        self._max_frame = max_frame

    def feed(self, data: bytes) -> list[dict]:
        self._buf += data
        out: list[dict] = []
        while True:
            if len(self._buf) < 8:
                return out
            (length,) = struct.unpack(">Q", bytes(self._buf[:8]))
            if length > self._max_frame:
                raise ValueError(
                    f"frame of {length} bytes exceeds MAX_FRAME "
                    f"({self._max_frame})"
                )
            if len(self._buf) < 8 + length:
                return out
            blob = bytes(self._buf[8:8 + length])
            del self._buf[:8 + length]
            out.append(json.loads(blob))

    def buffered(self) -> int:
        """Bytes waiting for the rest of their frame (slowloris gauge)."""
        return len(self._buf)


def recv_msg(sock: socket.socket) -> dict | None:
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (length,) = struct.unpack(">Q", header)
    blob = _recv_exact(sock, length)
    if blob is None:
        return None
    return json.loads(blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def parse_addr(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)
