"""Length-prefixed RPC framing shared by the cluster and service planes.

One wire format for every TCP endpoint in the repo (SURVEY.md section
3.2): an 8-byte big-endian length prefix followed by a JSON object. The
cpu-cluster transport (sieve/cluster.py) ships seed primes, segment
assignments, and telemetry over it; the query service
(sieve/service/server.py) answers ``pi``/``count``/``nth_prime``/
``primes`` requests over the very same framing, so a worker host and a
query client speak to the coordinator with the same four functions.

``recv_msg`` returns ``None`` on a cleanly closed peer (EOF mid-header
or mid-body), letting callers distinguish an orderly close from a
protocol error; socket timeouts propagate as ``socket.timeout`` so both
planes can bound every read (a dead peer must never park a thread in
``recv`` forever — ISSUE 6/7).

Pipelined framing (ISSUE 14): the frame format is self-delimiting, so
nothing in it ties one request to one reply — requests carry ``id``,
replies echo it, and any number may be in flight per connection.
``encode_msg`` produces one wire frame for queue-based senders, and
:class:`FrameDecoder` turns an arbitrary byte stream (non-blocking
reads of any size, including mid-frame) back into messages — the
service's selector event loop reads through it, while blocking callers
keep using ``recv_msg`` unchanged.

Binary wire v2 (ISSUE 16): the 8-byte length prefix is unchanged, but
the body may now be a **columnar binary frame** instead of JSON. The
first body byte discriminates: a JSON object always opens with ``{``
(0x7b), a v2 frame opens with the version byte 0x02, followed by a
little-endian ``uint32`` header length, a JSON *header* object (the
ordinary message fields plus a ``_cols`` manifest), and the raw
little-endian column payloads concatenated in manifest order. Decoding
a column is one ``np.frombuffer`` view over the frame — no per-element
parse. Both encodings interleave freely on one connection; which one a
*sender* uses is decided by the ``hello`` handshake (``SUPPORTED_WIRE``
capability sets, highest mutual version wins, v1 JSON is the floor so
an old peer keeps working). Structural violations — truncated header,
column overrunning the frame, unknown dtype — raise ``ValueError``
exactly like a non-JSON v1 body: close the connection.
"""

from __future__ import annotations

import json
import socket
import struct

import numpy as np

# Upper bound on a single frame accepted by the incremental decoder: a
# peer that sends a garbage length prefix must be cut off, not allowed
# to make the event loop buffer gigabytes waiting for a body that never
# comes. Generous — a max_primes=200_000 reply is ~2 MB. Applies to v1
# and v2 bodies alike: the prefix is checked before either is parsed.
MAX_FRAME = 256 << 20

#: wire protocol versions this build can speak. v1 = JSON bodies only;
#: v2 adds columnar binary frames. ``hello`` negotiation intersects the
#: two peers' sets and picks the max; absent a hello, everything is v1.
WIRE_V1 = 1
WIRE_V2 = 2
SUPPORTED_WIRE = (WIRE_V1, WIRE_V2)

#: first body byte of a v2 frame. JSON objects open with ``{`` (0x7b),
#: so one byte discriminates the encodings with no framing change.
V2_MAGIC = 0x02

#: dtypes a v2 column may carry -> itemsize. A closed whitelist: the
#: decoder must never eval an attacker-supplied dtype string.
_V2_DTYPES = {"<u1": 1, "<u4": 4, "<i8": 8, "<f8": 8}

#: batch member opcodes for the ``b_op`` request column
OP_PI = 0
OP_IS_PRIME = 1
OP_COUNT = 2
OP_NAMES = ("pi", "is_prime", "count")

_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


def encode_msg(msg: dict) -> bytes:
    """One complete wire frame (length prefix + JSON body)."""
    blob = json.dumps(msg).encode()
    return struct.pack(">Q", len(blob)) + blob


def _canon_dtype(arr: np.ndarray) -> str:
    """Little-endian dtype string for the manifest (``|u1`` -> ``<u1``)."""
    s = arr.dtype.str
    if s[0] == "|":
        s = "<" + s[1:]
    elif s[0] == ">":
        raise ValueError(f"big-endian column dtype {s!r} not encodable")
    if s not in _V2_DTYPES:
        raise ValueError(f"dtype {s!r} not in the v2 wire whitelist")
    return s


def encode_msg_v2(msg: dict, cols: dict[str, np.ndarray] | None) -> bytes:
    """One v2 wire frame: header JSON + packed little-endian columns.

    ``msg`` is the ordinary message dict (no numpy values); ``cols``
    maps column name -> 1-D array. The header gains a ``_cols``
    manifest of ``[name, dtype, count]`` triples; payloads follow in
    manifest order so the decoder can slice them back out with
    ``np.frombuffer`` views. ``cols=None`` falls back to plain JSON.
    """
    if not cols:
        return encode_msg(msg)
    entries = []
    payloads = []
    nbytes = 0
    for name, arr in cols.items():
        a = np.ascontiguousarray(arr)
        ds = _canon_dtype(a)
        entries.append([name, ds, int(a.size)])
        payloads.append(a.data)
        nbytes += a.size * _V2_DTYPES[ds]
    header = dict(msg)
    header["_cols"] = entries
    hblob = json.dumps(header).encode()
    length = 5 + len(hblob) + nbytes
    return b"".join(
        [struct.pack(">Q", length), bytes((V2_MAGIC,)),
         struct.pack("<I", len(hblob)), hblob, *payloads]
    )


def decode_body(blob: bytes) -> dict:
    """Decode one frame body — v1 JSON or v2 columnar — to a message.

    For v2, each manifest column lands in the message dict as a
    read-only ``np.frombuffer`` view over ``blob`` (zero copy); the
    ``_cols`` manifest stays in the dict so consumers can tell a
    columnar message from plain JSON. Malformed structure raises
    ``ValueError``, same as a non-JSON v1 body.
    """
    if blob[:1] != b"\x02":
        return json.loads(blob)
    if len(blob) < 5:
        raise ValueError("v2 frame truncated before header length")
    (hlen,) = struct.unpack_from("<I", blob, 1)
    end = 5 + hlen
    if end > len(blob):
        raise ValueError(
            f"v2 header of {hlen} bytes overruns the {len(blob)}-byte frame"
        )
    msg = json.loads(blob[5:end])
    if not isinstance(msg, dict):
        raise ValueError("v2 header is not a JSON object")
    manifest = msg.get("_cols", [])
    if not isinstance(manifest, list):
        raise ValueError("v2 _cols manifest is not a list")
    off = end
    for ent in manifest:
        if (not isinstance(ent, list) or len(ent) != 3
                or not isinstance(ent[0], str)):
            raise ValueError(f"malformed v2 column entry {ent!r}")
        name, ds, count = ent
        isize = _V2_DTYPES.get(ds)
        if isize is None:
            raise ValueError(f"v2 column {name!r} has unknown dtype {ds!r}")
        if not isinstance(count, int) or isinstance(count, bool) or count < 0:
            raise ValueError(f"v2 column {name!r} has bad count {count!r}")
        size = count * isize
        if off + size > len(blob):
            raise ValueError(
                f"v2 column {name!r} ({size} bytes at {off}) overruns "
                f"the {len(blob)}-byte frame"
            )
        msg[name] = np.frombuffer(blob, dtype=ds, count=count, offset=off)
        off += size
    if off != len(blob):
        raise ValueError(
            f"v2 frame has {len(blob) - off} trailing bytes past its columns"
        )
    return msg


def send_msg(sock: socket.socket, msg: dict) -> None:
    sock.sendall(encode_msg(msg))


class FrameDecoder:
    """Incremental frame decoder for non-blocking readers.

    Feed it whatever ``recv`` returned — single bytes, half a header,
    ten frames at once — and it yields every complete message, keeping
    the undecoded tail buffered. Raises ``ValueError`` on an oversized
    length prefix or a non-JSON body, which callers treat exactly like
    a framing error from ``recv_msg``: close the connection.
    """

    __slots__ = ("_buf", "_max_frame")

    def __init__(self, max_frame: int = MAX_FRAME):
        self._buf = bytearray()
        self._max_frame = max_frame

    def feed(self, data: bytes) -> list[dict]:
        self._buf += data
        out: list[dict] = []
        while True:
            if len(self._buf) < 8:
                return out
            (length,) = struct.unpack(">Q", bytes(self._buf[:8]))
            if length > self._max_frame:
                raise ValueError(
                    f"frame of {length} bytes exceeds MAX_FRAME "
                    f"({self._max_frame})"
                )
            if len(self._buf) < 8 + length:
                return out
            blob = bytes(self._buf[8:8 + length])
            del self._buf[:8 + length]
            out.append(decode_body(blob))

    def buffered(self) -> int:
        """Bytes waiting for the rest of their frame (slowloris gauge)."""
        return len(self._buf)


def recv_msg(sock: socket.socket) -> dict | None:
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (length,) = struct.unpack(">Q", header)
    blob = _recv_exact(sock, length)
    if blob is None:
        return None
    return decode_body(blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def parse_addr(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


# --- columnar batch encoding (ISSUE 16) --------------------------------------
#
# A batch request becomes three parallel columns instead of a list of
# member dicts: ``b_op`` (uint8 opcode), ``b_a`` (int64: x, or lo for
# count) and ``b_b`` (int64: hi for count, 0 otherwise). The reply is
# ``r_ok`` (uint8) + ``r_val`` (int64) columns plus a *sparse* JSON
# ``errors`` map {member index -> typed outcome dict} in the header, so
# the all-ok hot path never serializes a single per-member dict.

_PI_KEYS = frozenset(("op", "x"))
_COUNT_KEYS = frozenset(("op", "lo", "hi", "kind"))


def _wire_int(v) -> bool:
    return type(v) is int and _I64_MIN <= v <= _I64_MAX


def batch_items_to_cols(items) -> tuple[dict[str, np.ndarray], list] | None:
    """``(cols, member ops)`` for a columnar-eligible batch, else None.

    Eligible means every member is a well-formed ``pi``/``is_prime``/
    ``count`` dict with int64-range arguments; anything else (unknown
    ops, malformed members, huge ints) returns None and the caller
    ships the batch as v1 JSON, where the server's existing per-member
    validation produces the typed outcome.
    """
    if not items:
        return None
    # plain lists + one bulk np.array at the end: per-element ndarray
    # stores cost ~4x this loop on the 1024-member hot path
    b_op: list[int] = []
    b_a: list[int] = []
    b_b: list[int] = []
    ops: list = []
    for m in items:
        if type(m) is not dict:
            return None
        op = m.get("op")
        if op == "pi" or op == "is_prime":
            x = m.get("x")
            # key-set discipline without issuperset: op and x checked
            # out, so len(m) == 2 means keys are exactly {op, x}
            if (type(x) is not int or x > _I64_MAX or x < _I64_MIN
                    or len(m) != 2):
                return None
            b_op.append(OP_PI if op == "pi" else OP_IS_PRIME)
            b_a.append(x)
            b_b.append(0)
        elif op == "count":
            lo, hi = m.get("lo"), m.get("hi")
            if (type(lo) is not int or lo > _I64_MAX or lo < _I64_MIN
                    or type(hi) is not int or hi > _I64_MAX
                    or hi < _I64_MIN
                    or m.get("kind", "primes") != "primes"
                    or len(m) != (4 if "kind" in m else 3)):
                return None
            b_op.append(OP_COUNT)
            b_a.append(lo)
            b_b.append(hi)
        else:
            return None
        ops.append(op)
    return {"b_op": np.array(b_op, dtype=np.uint8),
            "b_a": np.array(b_a, dtype=np.int64),
            "b_b": np.array(b_b, dtype=np.int64)}, ops


def batch_cols_to_items(b_op, b_a, b_b) -> list[dict]:
    """Rebuild v1 member dicts from request columns (the fallback path)."""
    items: list[dict] = []
    for o, x, y in zip(b_op.tolist(), b_a.tolist(), b_b.tolist()):
        if o == OP_PI:
            items.append({"op": "pi", "x": x})
        elif o == OP_IS_PRIME:
            items.append({"op": "is_prime", "x": x})
        elif o == OP_COUNT:
            items.append({"op": "count", "lo": x, "hi": y})
        else:
            # unknown opcode -> an op name no handler knows, so the
            # member gets the ordinary typed bad_request outcome
            items.append({"op": f"opcode_{o}"})
    return items


class BatchOutcomes:
    """Columnar batch result: ok flags + int64 values + sparse errors.

    The server's vectorized fast path builds one directly; the fallback
    and router paths convert a list of outcome dicts via
    :meth:`from_items`. ``wire()`` yields the v2 header fields and
    columns; ``to_items()`` rebuilds the v1 outcome list for JSON
    connections. ``ops`` is per-member op names (or a ``b_op`` opcode
    array), needed only to rebuild dicts — the wire never carries it,
    the client remembers what it asked.
    """

    __slots__ = ("ok", "val", "errors", "ops")

    def __init__(self, ok, val, errors, ops):
        self.ok = ok
        self.val = val
        self.errors = errors
        self.ops = ops

    @classmethod
    def from_items(cls, outcomes: list[dict]) -> "BatchOutcomes":
        n = len(outcomes)
        ok = np.zeros(n, dtype=np.uint8)
        val = np.zeros(n, dtype=np.int64)
        errors: dict[str, dict] = {}
        ops: list = []
        for i, o in enumerate(outcomes):
            ops.append(o.get("op"))
            if o.get("ok"):
                ok[i] = 1
                val[i] = int(o.get("value") or 0)
            else:
                errors[str(i)] = o
        return cls(ok, val, errors, ops)

    def _op_names(self) -> list:
        if isinstance(self.ops, np.ndarray):
            return [OP_NAMES[c] if c < len(OP_NAMES) else f"opcode_{c}"
                    for c in self.ops.tolist()]
        return list(self.ops)

    def wire(self) -> tuple[dict, dict[str, np.ndarray]]:
        """(extra header fields, columns) for a v2 reply."""
        extra = {"vkind": "batch"}
        if self.errors:
            extra["errors"] = self.errors
        return extra, {"r_ok": self.ok, "r_val": self.val}

    def to_items(self) -> list[dict]:
        names = self._op_names()
        out: list[dict] = []
        for i, (okf, v) in enumerate(zip(self.ok.tolist(), self.val.tolist())):
            err = self.errors.get(str(i))
            if err is not None:
                out.append(err)
                continue
            op = names[i]
            out.append({"ok": True, "op": op,
                        "value": bool(v) if op == "is_prime" else v})
        return out


def batch_reply_value(reply: dict, ops: list | None) -> list[dict]:
    """Rebuild the v1 outcome list from a v2 batch reply, in place.

    Pops the reply's column keys; ``ops`` is the member op list the
    client recorded at send time (the wire does not repeat it).
    """
    ok = reply.pop("r_ok")
    val = reply.pop("r_val")
    errors = reply.pop("errors", None) or {}
    if ops is None:
        ops = ["?"] * ok.size
    if not errors:
        # all-ok hot path: no per-index error lookups
        return [{"ok": True, "op": op,
                 "value": bool(v) if op == "is_prime" else v}
                for op, v in zip(ops, val.tolist())]
    out: list[dict] = []
    for i, (op, v) in enumerate(zip(ops, val.tolist())):
        err = errors.get(str(i))
        if err is not None:
            out.append(err)
        else:
            out.append({"ok": True, "op": op,
                        "value": bool(v) if op == "is_prime" else v})
    return out


# --- binary primes replies (ISSUE 16) ----------------------------------------
#
# A hot ``primes`` window is dense: shipping it as the wheel layout's
# raw bitset words (one ``p_words`` uint32 column) beats both JSON and
# an int64 value column by ~30x. The header carries the layout name and
# the effective window so the client can reconstruct values locally;
# sparse windows (few primes over a wide range) flip to an int64
# ``p_vals`` column when that is smaller.


def primes_to_cols(vals: np.ndarray, packing: str,
                   lo: int, hi: int) -> tuple[dict, dict[str, np.ndarray]]:
    """(extra header fields, columns) for a v2 ``primes`` reply."""
    from sieve.bitset import get_layout, pack_words

    lo = max(int(lo), 2)
    hi = int(hi)
    layout = get_layout(packing)
    nbits = layout.nbits(lo, hi) if hi > lo else 0
    words_bytes = 4 * ((nbits + 31) // 32)
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    if nbits and words_bytes < 8 * vals.size:
        cand = vals
        if layout.extra_primes:
            cand = vals[vals >= layout.first_candidate(2)]
        flags = np.zeros(nbits, dtype=bool)
        if cand.size:
            base_g = layout.gidx(layout.first_candidate(lo))
            flags[layout.gidx_np(cand) - base_g] = True
        return ({"vkind": "primes", "prepr": "bitset", "packing": packing,
                 "plo": lo, "phi": hi, "pnbits": nbits},
                {"p_words": pack_words(flags)})
    return ({"vkind": "primes", "prepr": "values"}, {"p_vals": vals})


def primes_reply_value(reply: dict, as_array: bool = False):
    """Rebuild the v1 ``primes`` value from a v2 reply, in place.

    Returns a plain int list by default; ``as_array=True`` keeps the
    decoded int64 array (the router's shard legs pass it through to
    their own reply encode without ever touching Python ints).
    """
    from sieve.bitset import get_layout, unpack_words

    if reply.pop("prepr", None) == "bitset":
        words = reply.pop("p_words")
        layout = get_layout(reply.pop("packing"))
        lo = reply.pop("plo")
        hi = reply.pop("phi")
        nbits = reply.pop("pnbits")
        flags = unpack_words(np.ascontiguousarray(words, dtype=np.uint32),
                             nbits)
        vals = layout.values_np(lo, np.nonzero(flags)[0])
        extras = [p for p in layout.extra_primes if lo <= p < hi]
        if as_array:
            if extras:
                vals = np.concatenate(
                    (np.asarray(extras, dtype=np.int64),
                     vals.astype(np.int64, copy=False))
                )
            return vals.astype(np.int64, copy=False)
        return extras + vals.tolist()
    vals = reply.pop("p_vals")
    return vals if as_array else vals.tolist()
