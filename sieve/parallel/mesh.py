"""TPU mesh path: segment assignment == mesh sharding (SURVEY.md section 3.3).

One XLA dispatch runs a whole round: each device owns one contiguous
bit-packed segment of [2, n+1), per-segment marking specs ride in sharded
over the 'seg' axis, counts merge with ``lax.psum`` and boundary flag
words are exchanged with ``lax.ppermute`` over ICI. The host then builds
ordinary SegmentResults and reuses the *identical* ``merge_results`` the
CPU coordinator uses — the north-star's "merge step unchanged at the API
surface" (BASELINE.json).

Two per-shard kernels plug into the same collectives: the XLA word kernel
(``--backend jax``) and the fused Pallas kernel (``--backend tpu-pallas``,
interpret mode on CPU meshes so CI covers it without TPU hardware).

Rounds (``--rounds k``) split the run into k sequential dispatches of one
segment per device each: the failure-recovery / beyond-HBM streaming
granularity of SURVEY.md sections 5.3 and 5.7. The word-kernel path
shares one compiled step across all rounds; the pallas path compiles one
step per distinct ROUND shape bucket (live group-D spec rows and flat
crossing-list lengths vary per round once zero-crossing specs are pruned
— padding every round to the global maximum would re-add the pruned
sweep cost), with shard shapes still static within a round.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from sieve import env, trace
from sieve.backends.jax_backend import pair_kind
from sieve.bitset import get_layout
from sieve.checkpoint import Ledger
from sieve.config import SieveConfig
from sieve.coordinator import SieveResult, merge_results
from sieve.kernels.jax_mark import (
    SPEC_BLOCK,
    TIER1_MAX,
    WORD_BUCKET,
    mark_words_impl,
    next_pow2,
    pack4,
)
from sieve.kernels.specs import TieredChain
from sieve.metrics import MetricsLogger
from sieve.parallel.pipeline import PrepPipeline
from sieve.seed import seed_primes
from sieve.segments import plan_segments, validate_plan
from sieve.worker import SegmentResult

MIN_SHARD_BITS = 64
# group-D row-count bucket for the per-round pallas step cache: padding
# within a bucket costs at most ND_BUCKET-1 inert (but swept) spec blocks,
# while bounding the number of distinct compiles across rounds
ND_BUCKET = 8


class MeshCrossCheckError(RuntimeError):
    """The ICI-collective totals (psum / ppermute straddle) disagree with
    the host-side merge semantics — data corruption or a collective bug.
    A real exception (not an assert) so the check survives ``python -O``."""


def _shard_map():
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map  # older jax

    return shard_map


def build_mesh(n_devices: int):
    """Mesh over the 'seg' axis. Honors SIEVE_JAX_PLATFORM; falls back to
    the (virtual) CPU devices when the default platform is too small, so
    multi-chip logic is exercisable on a single-chip host (SURVEY 4.2)."""
    import jax

    platform = env.env_str("SIEVE_JAX_PLATFORM")
    devices = jax.devices(platform) if platform else jax.devices()
    if len(devices) < n_devices:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            devices = cpu
        else:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"(cpu fallback has {len(cpu)}; set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices})"
            )
    from jax.sharding import Mesh

    return Mesh(np.asarray(devices[:n_devices]), ("seg",))


_MESHES: dict = {}


def _register_mesh(mesh) -> tuple:
    key = tuple(d.id for d in mesh.devices.flat)
    _MESHES[key] = mesh
    return key


def _collective_merge(count, twins, first32, last32, gap_ok, ndev: int):
    """ICI/DCN collectives shared by both mesh steps (the TPU 'transport'
    layer): psum count merge; left-neighbor ppermute of the first flag bit
    for the on-device odds straddle count (the host merge recomputes this
    exactly for every packing; the psum'd value cross-checks the
    collective path). Per-segment vectors come back all_gathered, i.e.
    REPLICATED on every device — so on multi-host meshes every process can
    read every segment's result without host-side exchange."""
    import jax.numpy as jnp
    from jax import lax

    total = lax.psum(count, "seg")
    first_bit = (first32 & jnp.uint32(1)).astype(jnp.int32)
    recv = lax.ppermute(
        first_bit, "seg", perm=[(i, i - 1) for i in range(1, ndev)]
    )
    last_bit = (last32 >> jnp.uint32(31)).astype(jnp.int32)
    straddle = last_bit * recv * gap_ok[0]
    total_twins = lax.psum(twins + straddle, "seg")
    gather = lambda x: lax.all_gather(x, "seg")
    # ONE packed uint32[2 + 4*ndev] result: [total, total_twins, counts...,
    # twins..., first32..., last32...]. A single replicated output means a
    # single device->host fetch per round — over a tunneled device each
    # separate fetch costs a full round trip (~70 ms measured on axon).
    return jnp.concatenate([
        jnp.stack([total, total_twins]).astype(jnp.uint32),
        gather(count).astype(jnp.uint32).reshape(-1),
        gather(twins).astype(jnp.uint32).reshape(-1),
        gather(first32).reshape(-1),
        gather(last32).reshape(-1),
    ])


def _globalize(mesh, tree):
    """Host numpy inputs -> global jax.Arrays sharded over 'seg'.

    On a multi-host mesh (DCN: ``jax.distributed.initialize``), jit cannot
    transfer plain host arrays — every process holds the same full-size
    numpy args (host prep is cheap and deterministic), and each contributes
    only its addressable shards here. Single-host runs skip this."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    def conv(a):
        a = np.asarray(a)
        spec = P(*(("seg",) + (None,) * (a.ndim - 1)))
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            a.shape, sh, lambda idx, _a=a: _a[idx]
        )

    return jax.tree.map(conv, tree)


@functools.lru_cache(maxsize=None)
def _make_step(mesh_key, Wpad: int, twin_kind: int, periods: tuple, ndev: int):
    """Jitted one-round step over a fixed mesh; cached per shape bucket."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    mesh = _MESHES[mesh_key]
    smap = _shard_map()

    def shard_fn(nbits, patterns, m2, r2, K2, rcp2, act2,
                 ci, cm, pmask, gap_ok):
        count, twins, first32, last32 = mark_words_impl(
            Wpad, twin_kind, periods, nbits[0],
            tuple(p[0] for p in patterns),
            m2[0], r2[0], K2[0], rcp2[0], act2[0],
            ci[0], cm[0], pmask[0],
        )
        return _collective_merge(count, twins, first32, last32, gap_ok, ndev)

    n_pat = len(periods)
    in_specs = (
        P("seg"),                    # nbits
        (P("seg"),) * n_pat,         # patterns
        P("seg"), P("seg"), P("seg"), P("seg"), P("seg"),  # tier-2
        P("seg"), P("seg"),          # corrections
        P("seg"), P("seg"),          # pair_mask, gap_ok
    )
    out_specs = P()  # one packed replicated vector (see _collective_merge)
    return _jit_sharded(smap, shard_fn, mesh, in_specs, out_specs)


@functools.lru_cache(maxsize=None)
def _make_cold_step(mesh_key, Wpad: int, twin_kind: int, periods: tuple,
                    ndev: int):
    """Jitted SPMD step for the service cold plane (ISSUE 18): a batch of
    B independent drained chunks (B a multiple of ndev) is sharded over
    the 'seg' axis, each device vmaps the word kernel over its B/ndev
    rows, and the packed uint32[B, 4] result rides back row-sharded — no
    collectives, because cold chunks are independent queries, not one
    contiguous range. One launch per drain slice replaces K sequential
    markings; cached per (mesh, Wpad, periods, batch-shape) bucket via
    the arrays' leading dim."""
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = _MESHES[mesh_key]
    smap = _shard_map()

    def one(nbits, patterns, m2, r2, K2, rcp2, act2, ci, cm, pmask):
        return pack4(*mark_words_impl(
            Wpad, twin_kind, periods, nbits, patterns,
            m2, r2, K2, rcp2, act2, ci, cm, pmask,
        ))

    def shard_fn(nbits, patterns, m2, r2, K2, rcp2, act2, ci, cm, pmask):
        # per-device sub-batch [B/ndev, ...] -> uint32[B/ndev, 4]
        return jax.vmap(one)(
            nbits, patterns, m2, r2, K2, rcp2, act2, ci, cm, pmask
        )

    n_pat = len(periods)
    in_specs = (
        P("seg"),                    # nbits
        (P("seg"),) * n_pat,         # patterns
        P("seg"), P("seg"), P("seg"), P("seg"), P("seg"),  # tier-2
        P("seg"), P("seg"),          # corrections
        P("seg"),                    # pair_mask
    )
    out_specs = P("seg")  # uint32[B, 4], rows in batch order
    return _jit_sharded(smap, shard_fn, mesh, in_specs, out_specs)


def _jit_sharded(smap, shard_fn, mesh, in_specs, out_specs):
    import jax

    try:
        sharded = smap(
            shard_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:  # older jax spells the replication check differently
        sharded = smap(
            shard_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
    return jax.jit(sharded)


@functools.lru_cache(maxsize=None)
def _make_pallas_step(mesh_key, Wpad: int, twin_kind: int, SB: int, SC: int,
                      ND: int, CC: int, FC: int, ndev: int, interpret: bool):
    """Jitted one-round step running the fused Pallas kernel per shard —
    the north-star composition (SURVEY.md section 3.3): pallas_call inside
    shard_map, counts merged with lax.psum and boundary bits exchanged
    with lax.ppermute over ICI. On CPU meshes the kernel runs in interpret
    mode, so the multi-chip path is CI-testable without TPU hardware.
    Cached per (ND, FC, ...) round shape bucket — see run_mesh."""
    from jax.sharding import PartitionSpec as P

    from sieve.kernels.pallas_mark import _build_call, _postlude

    mesh = _MESHES[mesh_key]
    smap = _shard_map()
    call = _build_call(Wpad, SB, SC, ND, interpret)

    def shard_fn(nbits, pmask, *rest):
        groups = tuple(a[0] for a in rest[:20])   # A(6) + B(6) + C(4) + D(4)
        ci, cm = rest[20][0, 0], rest[21][0, 0]
        fi, fm = rest[22][0, 0], rest[23][0, 0]
        gap_ok = rest[24]
        words = call(*groups)
        count, twins, first32, last32 = _postlude(
            words, nbits[0, 0, 0], pmask[0, 0, 0], ci, cm, twin_kind, fi, fm
        )
        return _collective_merge(count, twins, first32, last32, gap_ok, ndev)

    in_specs = (P("seg"),) * 27
    out_specs = P()  # one packed replicated vector (see _collective_merge)
    return _jit_sharded(smap, shard_fn, mesh, in_specs, out_specs)


@functools.lru_cache(maxsize=None)
def _make_pallas_fused_step(mesh_key, Wpad: int, twin_kind: int, SB: int,
                            SC: int, ND: int, CC: int, FC: int, ndev: int,
                            interpret: bool):
    """Jitted one-round step running the FUSED Pallas kernel per shard: the
    in-kernel reduction leaves only the (1, 8) SMEM accumulator per shard,
    which feeds the psum/ppermute collectives directly — no full-width
    bitset ever crosses back through HBM to an XLA postlude. Arg order
    mirrors fused_args() with gap_ok appended."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from sieve.kernels.pallas_mark import _build_fused_call

    mesh = _MESHES[mesh_key]
    smap = _shard_map()
    call = _build_fused_call(Wpad, SB, SC, ND, CC, FC, twin_kind,
                             need_bits=False, interpret=interpret)

    def shard_fn(*rest):
        args = tuple(a[0] for a in rest[:28])  # groups(20) + lists(6) + nb/pm
        gap_ok = rest[28]
        acc = call(*args)
        count = acc[0, 0].astype(jnp.int32)
        twins = acc[0, 1].astype(jnp.int32)
        return _collective_merge(count, twins, acc[0, 2], acc[0, 3],
                                 gap_ok, ndev)

    in_specs = (P("seg"),) * 29
    out_specs = P()  # one packed replicated vector (see _collective_merge)
    return _jit_sharded(smap, shard_fn, mesh, in_specs, out_specs)


def _broadcast_done(done: dict) -> dict:
    """Replicate process 0's completed-segment map to every process
    (multi-host resume safety — see call site)."""
    import json as _json

    import numpy as np_
    from jax.experimental import multihost_utils as mhu

    blob = _json.dumps(
        {str(k): v.to_dict() for k, v in done.items()}
    ).encode()
    n = int(mhu.broadcast_one_to_all(np_.int64(len(blob))))
    buf = np_.zeros(n, np_.uint8)
    k = min(len(blob), n)  # non-source content is ignored, only shape matters
    buf[:k] = np_.frombuffer(blob, np_.uint8)[:k]
    buf = np_.asarray(mhu.broadcast_one_to_all(buf))
    data = _json.loads(bytes(buf).decode())
    return {int(k): SegmentResult.from_dict(v) for k, v in data.items()}


def run_mesh(config: SieveConfig, mesh=None) -> SieveResult:
    """Run the sieve sharded over a device mesh, one segment per device per
    round. Falls back to the local coordinator for ranges too small to
    shard meaningfully."""
    cfg = config
    metrics = MetricsLogger(cfg)
    t0 = trace.now_s()
    # host_phases is span-derived: snapshot the process-wide tracer so
    # this run's phase totals are the delta (pipeline producer threads
    # start emitting prep.round spans as soon as the pipeline exists)
    tsnap = trace.snapshot()
    ndev = cfg.workers
    if mesh is None:
        mesh = build_mesh(ndev)
    else:
        ndev = int(np.prod(mesh.devices.shape))
    mesh_key = _register_mesh(mesh)

    n_segs = ndev * max(1, cfg.rounds)
    if cfg.n_segments is not None and cfg.n_segments != n_segs:
        raise ValueError(
            f"mesh path segments by workers*rounds = {n_segs}; "
            f"--segments {cfg.n_segments} conflicts (drop it or match)"
        )
    if cfg.segment_values is not None:
        raise ValueError(
            "mesh path segments by workers*rounds; --segment-size is not "
            "honored here — use --rounds to control per-dispatch size"
        )
    segs = plan_segments(cfg.n, n_segs)
    layout = get_layout(cfg.packing)
    use_pallas = cfg.backend == "tpu-pallas"
    if len(segs) != n_segs or any(
        layout.nbits(s.lo, s.hi) < MIN_SHARD_BITS for s in segs
    ):
        from sieve.coordinator import run_local

        small_backend = cfg.backend if use_pallas else "jax"
        small = SieveConfig(
            **{**cfg.to_dict(), "backend": small_backend, "workers": 1}
        )
        return run_local(small)
    validate_plan(segs, cfg.n)
    # the ledger must describe the segmentation actually used, so a resume
    # with different workers/rounds (or the CPU coordinator's default plan)
    # is refused by the config-hash guard rather than mis-merged
    cfg = SieveConfig(**{**cfg.to_dict(), "n_segments": n_segs})

    with trace.span("run.seed", backend=cfg.backend):
        seeds = seed_primes(cfg.seed_limit)
    twin_kind = pair_kind(cfg)
    pgap = getattr(cfg, "pair_gap", 2) or 2
    # Shared shapes are derived from the segment plan and the chain's
    # segment-independent structure — no upfront prepare of any segment.
    # Corrections-word bound: one word per seed prime in range at most.
    seg_lo = np.array([s.lo for s in segs], np.int64)
    seg_hi = np.array([s.hi for s in segs], np.int64)
    seed_cnt = np.searchsorted(seeds, seg_hi) - np.searchsorted(seeds, seg_lo)
    CC = int(max(32, -(-int(seed_cnt.max()) // 32) * 32))
    if use_pallas:
        # Wpad/SB/SC/CC are shared across ALL shards and rounds (Wpad is
        # baked into every spec's rK offset, so it must be fixed before
        # grouping; B/C membership depends only on the strides, so every
        # segment gets the same padded widths). ND and FC are padded per
        # ROUND instead: live group-D rows (post-pruning) and flat
        # crossing lists shrink as rounds move to windows the wide
        # strides barely cross, and padding them to the global max would
        # re-add exactly the sweep cost the pruner removed. The per-round
        # step is lru_cached by its (ND, FC) bucket.
        from sieve.kernels.pallas_mark import (
            TILE_WORDS,
            PallasChain,
            pad_pallas,
            pallas_fused_enabled,
            tile_offsets,
        )

        Wmax = max(-(-layout.nbits(s.lo, s.hi) // 32) for s in segs)
        Wpad = -(-(Wmax + 1) // TILE_WORDS) * TILE_WORDS
        template = PallasChain(cfg.packing, seeds, Wpad, pair_gap=pgap)
        SB = template.SB
        SC = template.SC
        interpret = mesh.devices.flat[0].platform == "cpu"
        # reduction mode is fixed once per run (not per round) so every
        # round of a run compiles and cross-checks the same path
        fused = pallas_fused_enabled()
        step = None  # built per round (shape-bucketed) in the loop below

        def _make_chain():
            return PallasChain(cfg.packing, seeds, Wpad, pair_gap=pgap)
    else:
        Wseg = [-(-layout.nbits(s.lo, s.hi) // 32) for s in segs]
        Wpad = max(
            -(-(W + 1) // WORD_BUCKET) * WORD_BUCKET for W in Wseg
        )
        template = TieredChain(cfg.packing, seeds, TIER1_MAX, SPEC_BLOCK,
                               WORD_BUCKET, pair_gap=pgap)
        periods = template.periods
        # every segment's live tier-2 set is a subset of the chain's
        # tier-2 specs; padding to the (pow2-bucketed) full count is inert
        S2 = next_pow2(
            max(SPEC_BLOCK, -(-template.n_tier2 // SPEC_BLOCK) * SPEC_BLOCK)
        )
        C = CC
        step = _make_step(mesh_key, Wpad, twin_kind, periods, ndev)

        def _make_chain():
            return TieredChain(cfg.packing, seeds, TIER1_MAX, SPEC_BLOCK,
                               WORD_BUCKET, pair_gap=pgap)

    def _pad1(a, n, fill=0):
        if a.size == n:
            return a
        return np.concatenate([a, np.full(n - a.size, fill, a.dtype)])

    import jax

    multihost = jax.process_count() > 1
    if multihost and step is not None:
        raw_step = step
        step = lambda *args: raw_step(*_globalize(mesh, args))

    ledger = Ledger.open(cfg) if cfg.checkpoint_dir else None
    # multi-host: every process computes identical results; only process 0
    # writes the ledger to avoid write races
    record_ledger = ledger is not None and jax.process_index() == 0
    done: dict[int, SegmentResult] = {}
    if ledger is not None and cfg.resume:
        done = ledger.completed()
        metrics.event("resume", restored=len(done))
    if multihost and ledger is not None:
        # Every process must agree on which rounds to skip, or a process
        # whose local ledger differs (non-shared checkpoint dir) would sit
        # out a collective and deadlock the rest. Process 0's view wins.
        done = _broadcast_done(done)

    # Async round window: dispatch round k while round k-1 still runs on
    # device, fetching each round's ONE packed result vector at most
    # `window` rounds late. Overlaps host prep/stacking and device->host
    # round trips (tunnel RTT ~70 ms) with device compute; checkpoint
    # granularity worsens by at most `window` rounds on failure.
    window = max(0, env.env_int("SIEVE_ROUND_WINDOW", 2))
    pending: list = []

    def _drain_one():
        batch, nbits_b, out, rt0 = pending.pop(0)
        with trace.span("round.drain", round=batch[0].seg_id // ndev):
            vals = np.asarray(out).astype(np.int64)  # single uint32 fetch
        total = int(vals[0])
        total_twins = int(vals[1])
        counts = vals[2 : 2 + ndev]
        twins_v = vals[2 + ndev : 2 + 2 * ndev]
        fw = vals[2 + 2 * ndev : 2 + 3 * ndev]
        lw = vals[2 + 3 * ndev : 2 + 4 * ndev]
        # dispatch-to-fetch time; with a nonzero window this includes
        # overlapped rounds, so it bounds rather than equals device time
        elapsed_round = trace.now_s() - rt0
        for i, s in enumerate(batch):
            res = SegmentResult(
                seg_id=s.seg_id,
                lo=s.lo,
                hi=s.hi,
                count=int(counts[i]) + layout.extras_in(s.lo, s.hi),
                twin_count=(
                    int(twins_v[i]) + layout.extra_pairs(s.lo, s.hi, pgap)
                    if cfg.twins
                    else 0
                ),
                first_word=int(fw[i]),
                last_word=int(lw[i]),
                nbits=int(nbits_b[i]),
                elapsed_s=elapsed_round / ndev,
            )
            done[s.seg_id] = res
            if record_ledger:
                ledger.record(res)
            metrics.segment(res)
        # cross-check: the ICI-collective totals agree with the host-side
        # merge semantics (psum for counts; psum + ppermute straddle for
        # the odds twin path — the transport this path exists to exercise)
        if total != int(counts.sum()):
            raise MeshCrossCheckError(
                f"psum/count mismatch: collective total {total} != "
                f"host sum {int(counts.sum())}"
            )
        if cfg.twins and cfg.packing == "odds" and pgap == 2:
            from sieve.twins import straddle_twins

            batch_res = [done[s.seg_id] for s in batch]
            expect = int(twins_v.sum()) + sum(
                straddle_twins(layout, a, b, cfg.n)
                for a, b in zip(batch_res, batch_res[1:])
            )
            if total_twins != expect:
                raise MeshCrossCheckError(
                    f"ppermute twin path diverged: {total_twins} != {expect}"
                )

    # Streaming prepare (the tentpole): only rounds NOT already restored
    # from the ledger enter the pipeline — a resume prepares nothing for
    # completed rounds — and at most window+1 rounds of preps are ever
    # resident while background threads prepare round k+window during
    # round k's device compute.
    todo = [
        rnd
        for rnd in range(max(1, cfg.rounds))
        if not all(
            s.seg_id in done for s in segs[rnd * ndev : (rnd + 1) * ndev]
        )
    ]
    pipeline = PrepPipeline(
        todo,
        _make_chain,
        lambda chain, rnd: [
            chain.prepare(s.lo, s.hi)
            for s in segs[rnd * ndev : (rnd + 1) * ndev]
        ],
        window,
    )
    try:
        for rnd in todo:
            batch = segs[rnd * ndev : (rnd + 1) * ndev]
            rt0 = trace.now_s()
            # nothing dispatched and undrained -> the device sits idle for
            # exactly the host time until the next dispatch below
            device_starved = not pending
            preps = pipeline.take(rnd)
            t_prep = trace.now_s()
            trace.add_span("round.prep_wait", rt0, t_prep - rt0, round=rnd)
            nbits_v = np.array([p.nbits for p in preps], np.int32)
            # gap_ok[d] = 1 iff (last candidate of seg d, first of seg d+1)
            # is a potential twin pair (values differ by 2) — odds
            # on-device straddle. Cousins (gap 4) resolve their straddles
            # host-side in merge_results; the device straddle stays off.
            gap_ok = np.zeros(ndev, np.int32)
            if cfg.packing == "odds" and cfg.twins and pgap == 2:
                for i in range(len(batch) - 1):
                    lv = layout.last_candidate(batch[i].hi)
                    fv = layout.first_candidate(batch[i + 1].lo)
                    if fv - lv == 2 and fv <= cfg.n:
                        gap_ok[i] = 1
            if use_pallas:
                # round-max shared shapes (bucketed -> bounded recompiles)
                nd = max(
                    (p.D[0].shape[0] if p.D[3].any() else 0) for p in preps
                )
                ND_r = -(-nd // ND_BUCKET) * ND_BUCKET
                FC_r = max(p.flat_idx.shape[1] for p in preps)
                preps = [
                    pad_pallas(p, SB, SC, max(ND_r, 1), CC, FC_r)
                    for p in preps
                ]
                if fused:
                    rstep = _make_pallas_fused_step(
                        mesh_key, Wpad, twin_kind, SB, SC, max(ND_r, 1), CC,
                        FC_r, ndev, interpret,
                    )
                else:
                    rstep = _make_pallas_step(
                        mesh_key, Wpad, twin_kind, SB, SC, ND_r, CC, FC_r,
                        ndev, interpret,
                    )
                if multihost:
                    rstep = (lambda *a, _r=rstep: _r(*_globalize(mesh, a)))
                groups = [
                    np.stack([p.A[i] for p in preps]) for i in range(6)
                ] + [
                    np.stack([p.B[i] for p in preps]) for i in range(6)
                ] + [
                    np.stack([p.C[i] for p in preps]) for i in range(4)
                ] + [
                    np.stack([p.D[i] for p in preps]) for i in range(4)
                ]
                if fused:
                    # fused_args() order per shard, stacked over 'seg':
                    # tile cursors are derived from the PADDED lists (pad
                    # entries carry zero masks, so searchsorted over the
                    # real prefix is unaffected)
                    args = (
                        *groups,
                        np.stack([p.corr_idx for p in preps]),
                        np.stack([p.corr_mask for p in preps]),
                        np.stack([p.flat_idx for p in preps]),
                        np.stack([p.flat_mask for p in preps]),
                        np.stack([
                            tile_offsets(p.corr_idx, p.corr_mask, Wpad)
                            for p in preps
                        ]),
                        np.stack([
                            tile_offsets(p.flat_idx, p.flat_mask, Wpad)
                            for p in preps
                        ]),
                        nbits_v.astype(np.int32).reshape(-1, 1, 1),
                        np.array(
                            [p.pair_mask for p in preps], np.uint32
                        ).reshape(-1, 1, 1),
                        gap_ok,
                    )
                else:
                    args = (
                        nbits_v.reshape(-1, 1, 1),
                        np.array(
                            [p.pair_mask for p in preps], np.uint32
                        ).reshape(-1, 1, 1),
                        *groups,
                        np.stack([p.corr_idx for p in preps]),
                        np.stack([p.corr_mask for p in preps]),
                        np.stack([p.flat_idx for p in preps]),
                        np.stack([p.flat_mask for p in preps]),
                        gap_ok,
                    )
                dispatch_step = rstep
            else:
                patterns = tuple(
                    np.stack([p.patterns[i] for p in preps])
                    for i in range(len(periods))
                )
                m2 = np.stack([_pad1(p.m2, S2, 1 << 20) for p in preps])
                r2 = np.stack([_pad1(p.r2, S2) for p in preps])
                K2 = np.stack([_pad1(p.K2, S2, 1) for p in preps])
                rcp2 = np.stack(
                    [_pad1(p.rcp2, S2, np.float32(2.0 ** -20)) for p in preps]
                )
                act2 = np.stack([_pad1(p.act2, S2) for p in preps])
                ci = np.stack([_pad1(p.corr_idx, C) for p in preps])
                cm = np.stack([_pad1(p.corr_mask, C) for p in preps])
                pmask = np.array([p.pair_mask for p in preps], np.uint32)
                args = (
                    nbits_v, patterns, m2, r2, K2, rcp2, act2, ci, cm,
                    pmask, gap_ok,
                )
                dispatch_step = step
            t_stack = trace.now_s()
            trace.add_span("round.stack", t_prep, t_stack - t_prep, round=rnd)
            if device_starved:
                # prep-wait + stacking with an empty device queue is true
                # device idle; the dispatch call itself (which includes
                # trace/compile on first use of a shape bucket) is not
                # counted — compile cost is amortized and not a
                # prepare-pipeline property
                trace.add_span(
                    "round.device_idle", rt0, t_stack - rt0, round=rnd
                )
            with trace.span("round.dispatch", round=rnd):
                out = dispatch_step(*args)
            pending.append((batch, nbits_v, out, rt0))
            while len(pending) > window:
                _drain_one()

        while pending:
            _drain_one()
    finally:
        pipeline.close()

    results = [done[s.seg_id] for s in segs]
    with trace.span("run.merge"):
        pi, twin_pairs = merge_results(cfg, results)
    elapsed = trace.now_s() - t0

    chain_phases: dict[str, float] = {}
    for st in pipeline.states:
        for k, v in getattr(st, "phase_seconds", {}).items():
            chain_phases[k] = chain_phases.get(k, 0.0) + v
    # Every phase total below is the sum of this run's spans (delta vs
    # the snapshot taken at entry) — the same numbers a --trace file
    # shows, by construction. Keys are unchanged from the hand-rolled
    # bookkeeping this replaces (BASELINE.md "host-prepare" section);
    # dispatch_s/drain_s are new.
    agg = trace.since(tsnap)

    def _tot(name: str) -> float:
        return agg.get(name, (0.0, 0))[0]

    prep_s = _tot("prep.round")
    device_idle_s = _tot("round.device_idle")
    values_prepared = sum(
        s.hi - s.lo for rnd in todo for s in segs[rnd * ndev : (rnd + 1) * ndev]
    )
    idle_frac = device_idle_s / elapsed if elapsed > 0 else 0.0
    host_phases = {
        "prep_s": round(prep_s, 6),
        "prep_wait_s": round(_tot("round.prep_wait"), 6),
        "stack_s": round(_tot("round.stack"), 6),
        "dispatch_s": round(_tot("round.dispatch"), 6),
        "drain_s": round(_tot("round.drain"), 6),
        "device_idle_s": round(device_idle_s, 6),
        "device_idle_frac": round(idle_frac, 6),
        "overlap_efficiency": round(1.0 - idle_frac, 6),
        "rounds_prepared": pipeline.stats["rounds_prepared"],
        "peak_resident_rounds": pipeline.stats["peak_resident"],
        "prep_values_per_sec": (
            round(values_prepared / prep_s, 1) if prep_s > 0 else None
        ),
        **{f"prep_{k}_s": round(v, 6) for k, v in chain_phases.items()},
    }
    if use_pallas:
        host_phases["reduction_mode"] = "fused" if fused else "split"
    metrics.event("host_prepare", **host_phases)

    result = SieveResult(
        n=cfg.n,
        pi=pi,
        twin_pairs=twin_pairs,
        backend=cfg.backend,
        packing=cfg.packing,
        n_segments=len(segs),
        elapsed_s=elapsed,
        values_per_sec=(cfg.n - 1) / elapsed if elapsed > 0 else float("inf"),
        segments=results,
        host_phases=host_phases,
    )
    metrics.run_summary(result)
    return result
