"""Multi-device execution: segment ownership as a jax.sharding.Mesh axis."""
