"""Streaming host-prepare pipeline: spec prep off the critical path.

``run_mesh`` used to build every round's prep upfront — thousands of
from-scratch ``prepare_pallas`` calls of pure pre-compute latency at
10^12 scale, all resident at once. This module replaces that with a
bounded producer/consumer: a small thread pool prepares round k+window
while round k computes on device, holding at most ``window + 1`` rounds
of preps resident (bounded host RSS regardless of round count).

Each worker thread owns its own incremental chain state (specs.SpecChain
/ TieredChain / pallas_mark.PallasChain), created via ``make_state`` on
first use; the chains' residue advancement is exact for arbitrary round
jumps, so per-thread round interleaving preserves bit-exact parity with
from-scratch preparation. Rounds are claimed strictly in order and only
after a residency slot is available; the consumer also consumes in
order, so the smallest outstanding round is always actively being
prepared — no deadlock at any (threads, window) combination.

The prep work is numpy, which releases the GIL for the heavy vector ops,
so a couple of threads suffice to hide prep behind device compute.
Thread count is ``SIEVE_PREP_THREADS`` (default: min(capacity, 2)).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Sequence

from sieve import env, trace
from sieve.analysis.lockdebug import named_condition


class PrepPipeline:
    """Prepare ``rounds`` in order on background threads, bounded residency.

    ``prep_round(state, rnd)`` builds one round's preps using the
    thread-local ``state`` (an incremental chain bundle from
    ``make_state()``). ``take(rnd)`` must be called in the same order as
    ``rounds``; it blocks until that round is ready and releases its
    residency slot. Worker exceptions re-raise in ``take``.
    """

    def __init__(
        self,
        rounds: Sequence[int],
        make_state: Callable[[], Any],
        prep_round: Callable[[Any, int], Any],
        window: int,
        threads: int | None = None,
    ):
        self.rounds = list(rounds)
        self._make_state = make_state
        self._prep = prep_round
        self.capacity = max(1, window + 1)
        if threads is None:
            threads = env.env_int("SIEVE_PREP_THREADS", 0) or min(
                self.capacity, 2
            )
        nthreads = max(1, min(threads, self.capacity, max(1, len(self.rounds))))
        self._cond = named_condition("PrepPipeline._cond")
        self._next = 0          # index into rounds of the next unclaimed round
        self._consumed = 0      # rounds handed back through take()
        self._ready: dict[int, Any] = {}
        self._error: BaseException | None = None
        self._closed = False
        self.states: list[Any] = []  # per-thread chains, for metric harvest
        self.stats = {
            "rounds_prepared": 0,
            "prep_seconds": 0.0,     # summed across threads (cpu-seconds)
            "peak_resident": 0,      # max rounds resident (ready + in-flight)
        }
        self._threads = [
            threading.Thread(target=self._run, daemon=True)
            for _ in range(nthreads if self.rounds else 0)
        ]
        for t in self._threads:
            t.start()

    def _run(self) -> None:
        state = self._make_state()
        with self._cond:
            self.states.append(state)
        while True:
            with self._cond:
                while (
                    not self._closed
                    and self._error is None
                    and self._next < len(self.rounds)
                    and self._next - self._consumed >= self.capacity
                ):
                    self._cond.wait()
                if (
                    self._closed
                    or self._error is not None
                    or self._next >= len(self.rounds)
                ):
                    return
                i = self._next
                self._next += 1
                resident = self._next - self._consumed
                if resident > self.stats["peak_resident"]:
                    self.stats["peak_resident"] = resident
                rnd = self.rounds[i]
            try:
                # producer-thread span: lands on its own track in a
                # --trace file, making prep/device overlap visible
                with trace.span("prep.round", round=rnd) as sp:
                    prep = self._prep(state, rnd)
            except BaseException as e:  # propagate to the consumer
                with self._cond:
                    self._error = e
                    self._cond.notify_all()
                return
            with self._cond:
                self._ready[rnd] = prep
                self.stats["rounds_prepared"] += 1
                self.stats["prep_seconds"] += sp.elapsed
                self._cond.notify_all()

    def take(self, rnd: int) -> Any:
        """Blocking fetch of round ``rnd``'s preps (call in rounds order)."""
        with self._cond:
            while rnd not in self._ready and self._error is None:
                self._cond.wait()
            if self._error is not None:
                raise self._error
            prep = self._ready.pop(rnd)
            self._consumed += 1
            self._cond.notify_all()
        return prep

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        for t in self._threads:
            t.join()
