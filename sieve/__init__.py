"""tpu-sieve: a TPU-native distributed segmented Sieve of Eratosthenes.

A ground-up rebuild of the capabilities of `dpbriggs/Distributed-Sieve-e`
(reference mount at /root/reference was empty this round; built against the
driver-anchored spec in SURVEY.md — see SURVEY.md "STATUS" for provenance).

Architecture (SURVEY.md section 1b):
  - coordinator computes seed primes (<= sqrt(N)) on host, partitions [2, N]
    into contiguous bit-packed segments, merges per-segment results;
  - a pluggable ``SieveWorker`` boundary selected by ``--backend`` runs the
    hot segmented composite-marking loop: cpu-numpy / cpu-native (C++) /
    cpu-cluster (sockets) on CPUs, jax / tpu-pallas on TPU;
  - on TPU, segment ownership is a ``jax.sharding.Mesh`` axis: seed primes
    replicate over ICI, counts merge with ``lax.psum``, twin boundary words
    exchange with ``lax.ppermute``.
"""

__version__ = "0.1.0"

import os as _os

# Persistent XLA compile cache for every entry point (CLI, bench, tests):
# first TPU compile of a shape bucket is tens of seconds, repeats are
# subsecond. Lives under the user cache dir (never inside the install
# tree). Opt out by setting JAX_COMPILATION_CACHE_DIR=''.
_os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    _os.path.join(
        _os.environ.get("XDG_CACHE_HOME", _os.path.expanduser("~/.cache")),
        "tpu-sieve", "jax-cache",
    ),
)
_os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

from sieve.config import SieveConfig
from sieve.worker import SegmentResult, SieveWorker
from sieve.coordinator import Coordinator, SieveResult

__all__ = [
    "SieveConfig",
    "SieveWorker",
    "SegmentResult",
    "Coordinator",
    "SieveResult",
    "__version__",
]
