"""tpu-sieve: a TPU-native distributed segmented Sieve of Eratosthenes.

A ground-up rebuild of the capabilities of `dpbriggs/Distributed-Sieve-e`
(reference mount at /root/reference was empty this round; built against the
driver-anchored spec in SURVEY.md — see SURVEY.md "STATUS" for provenance).

Architecture (SURVEY.md section 1b):
  - coordinator computes seed primes (<= sqrt(N)) on host, partitions [2, N]
    into contiguous bit-packed segments, merges per-segment results;
  - a pluggable ``SieveWorker`` boundary selected by ``--backend`` runs the
    hot segmented composite-marking loop: cpu-numpy / cpu-native (C++) /
    cpu-cluster (sockets) on CPUs, jax / tpu-pallas on TPU;
  - on TPU, segment ownership is a ``jax.sharding.Mesh`` axis: seed primes
    replicate over ICI, counts merge with ``lax.psum``, twin boundary words
    exchange with ``lax.ppermute``.
"""

__version__ = "0.1.0"

from sieve.config import SieveConfig
from sieve.worker import SegmentResult, SieveWorker
from sieve.coordinator import Coordinator, SieveResult

__all__ = [
    "SieveConfig",
    "SieveWorker",
    "SegmentResult",
    "Coordinator",
    "SieveResult",
    "__version__",
]
