"""Fleet trend aggregation with anomaly-triggered advisories (ISSUE 19).

tools/fleet_top.py shows the fleet *now*; nothing watches it over time.
This module adds the always-on capacity observatory: a
:class:`FleetObserver` daemon (``python -m sieve observe``) scrapes the
router and every advertised shard replica on a cadence through one
:class:`~sieve.service.client.ClientPool`, derives per-endpoint trend
signals from consecutive samples (hot/cold qps, shed and error rates,
lane depth, SLO burn, store hit ratio, covered_hi growth, mesh fanout),
and persists a compact downsampled snapshot per scrape into an on-disk
:class:`SnapshotRing` under ``--observe-dir`` so trends survive the
process and feed ``tools/fleet_top.py --observe-dir`` sparklines.

The ring file follows the PR 17 store discipline: append-only CRC'd
records (magic + length + crc32 header per JSON payload), a torn tail
is silently trimmed at open and skipped by readers, and the size cap is
enforced by compaction — newest records rewritten through a tempfile +
``os.replace`` + directory fsync, never an in-place truncate.

On top of the samples runs an EWMA + robust z-score anomaly engine.
Per (endpoint, signal) the observer tracks an exponentially-weighted
mean and mean absolute deviation; a sample alarms only when the
endpoint is *armed* (``warmup`` consecutive real samples — a scrape gap
resets the streak, so the sample right after a gap can never alarm) and
the excursion clears BOTH an absolute floor (``min_delta``) and the
robust z threshold. A breach is edge-triggered with a global cooldown:
one ``fleet_anomaly`` event with its evidence row, plus a fleet-wide
flight-recorder pull (every endpoint's inline ``debug`` op, merged into
``anomaly_<scrape>.json`` — the PR 13 bundle, fired by trend data
instead of a crash). The same windows drive ``scaling_advice``
(add_replica on sustained shed, split on a shard holding most of the
fleet's hot qps, merge on a near-idle shard), also edge-triggered.

Scrape faults are first-class: the ``svc_scrape_gap`` chaos kind (drawn
on the observer's own scrape counter, worker = target index) and any
genuinely unreachable endpoint produce a counted gap row and an
``observer_scrape_gap`` event — never a fabricated sample, and never an
alarm caused by the gap itself.

Locking: ``FleetObserver._lock`` guards counters and trend state and is
NEVER held across a pool RPC or ring I/O; ``SnapshotRing._lock``
serializes file appends/compactions and is a leaf.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import struct
import tempfile
import threading
import time
import types
import zlib
from typing import Any

from sieve import trace
from sieve.analysis.lockdebug import named_lock
from sieve.chaos import OBSERVER_KINDS, ChaosSchedule
from sieve.debug import FLEET_BUNDLE_VERSION
from sieve.metrics import MetricsLogger
from sieve.service.client import ClientPool

RING_FILE = "fleet_ring.bin"

# per-record framing: magic, payload length, crc32(payload); payload is
# UTF-8 JSON. Mirrors the PR 17 store header discipline at snapshot
# granularity.
_REC_HEADER = struct.Struct("<III")
_REC_MAGIC = 0x53524E47  # "SRNG"

# signals the anomaly engine watches (the rest are recorded for trends
# and sparklines but never alarm — a store hit ratio drifting is
# capacity planning, not an incident)
ANOMALY_SIGNALS = ("hot_qps", "shed_rate", "err_rate", "lane_depth",
                   "slo_burn")


# --- settings ----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ObserverSettings:
    """Knobs for the observer daemon (env: ``SIEVE_OBSERVE_*``)."""

    scrape_s: float = 1.0        # SIEVE_OBSERVE_SCRAPE_S
    timeout_s: float = 5.0       # SIEVE_OBSERVE_TIMEOUT_S (per-endpoint RPC)
    ring_bytes: int = 4 << 20    # SIEVE_OBSERVE_RING_BYTES (snapshot ring cap)
    alpha: float = 0.3           # SIEVE_OBSERVE_ALPHA (EWMA smoothing)
    z_threshold: float = 6.0     # SIEVE_OBSERVE_Z (robust z-score gate)
    min_delta: float = 2.0       # SIEVE_OBSERVE_MIN_DELTA (absolute floor)
    warmup: int = 8              # SIEVE_OBSERVE_WARMUP (consecutive samples
    #                              before an endpoint may alarm)
    cooldown_s: float = 30.0     # SIEVE_OBSERVE_COOLDOWN_S (edge-trigger
    #                              re-arm delay, anomalies and advice)
    observe_dir: str | None = None  # ring + anomaly bundles land here
    debug_pull: bool = True      # pull fleet debug bundles on anomaly
    quiet: bool = False

    def validate(self) -> "ObserverSettings":
        for name in ("scrape_s", "timeout_s"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or not math.isfinite(v) \
                    or v <= 0:
                raise ValueError(f"{name} must be a positive number, got {v!r}")
        if not isinstance(self.cooldown_s, (int, float)) or not \
                math.isfinite(self.cooldown_s) or self.cooldown_s < 0:
            raise ValueError(
                f"cooldown_s must be non-negative, got {self.cooldown_s!r}")
        if not isinstance(self.ring_bytes, int) or isinstance(
                self.ring_bytes, bool) or self.ring_bytes <= 0:
            raise ValueError(
                f"ring_bytes must be a positive int, got {self.ring_bytes!r}")
        if not isinstance(self.warmup, int) or isinstance(
                self.warmup, bool) or self.warmup < 0:
            raise ValueError(
                f"warmup must be a non-negative int, got {self.warmup!r}")
        if not isinstance(self.alpha, (int, float)) or not (
                0 < self.alpha <= 1):
            raise ValueError(f"alpha must be in (0, 1], got {self.alpha!r}")
        for name in ("z_threshold", "min_delta"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or not math.isfinite(v) \
                    or v < 0:
                raise ValueError(
                    f"{name} must be a non-negative number, got {v!r}")
        if self.observe_dir is not None and not isinstance(
                self.observe_dir, str):
            raise ValueError("observe_dir must be a string path or None")
        return self

    @classmethod
    def from_env(cls, **overrides: Any) -> "ObserverSettings":
        from sieve import env

        s = cls(
            scrape_s=env.env_float("SIEVE_OBSERVE_SCRAPE_S", cls.scrape_s),
            timeout_s=env.env_float("SIEVE_OBSERVE_TIMEOUT_S", cls.timeout_s),
            ring_bytes=env.env_int("SIEVE_OBSERVE_RING_BYTES",
                                   cls.ring_bytes),
            alpha=env.env_float("SIEVE_OBSERVE_ALPHA", cls.alpha),
            z_threshold=env.env_float("SIEVE_OBSERVE_Z", cls.z_threshold),
            min_delta=env.env_float("SIEVE_OBSERVE_MIN_DELTA", cls.min_delta),
            warmup=env.env_int("SIEVE_OBSERVE_WARMUP", cls.warmup),
            cooldown_s=env.env_float("SIEVE_OBSERVE_COOLDOWN_S",
                                     cls.cooldown_s),
        )
        return dataclasses.replace(s, **overrides) if overrides else s


# --- on-disk snapshot ring ---------------------------------------------------


class SnapshotRing:
    """Append-only CRC'd record file with a compaction-enforced size cap.

    Writer-side object (the observer daemon). Readers in other
    processes (``tools/fleet_top.py --observe-dir``, tests) use the
    module-level :func:`read_ring`, which tolerates a racing appender by
    construction: a record is either completely present with a valid
    CRC or it is the torn tail, and the scan stops there.
    """

    def __init__(self, path: str, cap_bytes: int = 4 << 20) -> None:
        self.path = path
        self._cap = max(1, int(cap_bytes))
        self._lock = named_lock("SnapshotRing._lock")
        self.torn = 0       # guard: _lock — bytes trimmed at open, torn tails
        self.compactions = 0  # guard: _lock
        self.appended = 0   # guard: _lock
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with self._lock:
            self._trim_torn_tail_locked()

    def _trim_torn_tail_locked(self) -> None:
        """Drop a partially-written final record left by a crash: scan
        to the last structurally complete record and truncate there."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        good = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(_REC_HEADER.size)
                if len(hdr) < _REC_HEADER.size:
                    break
                magic, ln, crc = _REC_HEADER.unpack(hdr)
                if magic != _REC_MAGIC:
                    break
                payload = f.read(ln)
                if len(payload) < ln or zlib.crc32(payload) != crc:
                    break
                good = f.tell()
        if good < size:
            self.torn += 1
            with open(self.path, "r+b") as f:
                f.truncate(good)

    def append(self, record: dict) -> None:
        payload = json.dumps(record, separators=(",", ":")).encode()
        frame = _REC_HEADER.pack(_REC_MAGIC, len(payload),
                                 zlib.crc32(payload)) + payload
        with self._lock:
            with open(self.path, "ab") as f:
                f.write(frame)
                f.flush()
            self.appended += 1
            try:
                if os.path.getsize(self.path) > self._cap:
                    self._compact_locked()
            except OSError:
                pass

    def _compact_locked(self) -> None:
        """Rewrite the newest records into half the cap (so compaction
        amortizes instead of thrashing at the boundary), then swap the
        new generation in atomically: tempfile + ``os.replace`` +
        directory fsync — a reader either sees the old file or the new
        one, never a half-written middle."""
        recs = read_ring(self.path)
        budget = self._cap // 2
        kept: list[bytes] = []
        total = 0
        for rec in reversed(recs):
            payload = json.dumps(rec, separators=(",", ":")).encode()
            frame = _REC_HEADER.pack(_REC_MAGIC, len(payload),
                                     zlib.crc32(payload)) + payload
            if total + len(frame) > budget and kept:
                break
            kept.append(frame)
            total += len(frame)
        kept.reverse()
        d = os.path.dirname(self.path) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".ring-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(b"".join(kept))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        self.compactions += 1

    def records(self, n: int | None = None) -> list[dict]:
        with self._lock:
            recs = read_ring(self.path)
        return recs[-n:] if n is not None and n >= 0 else recs

    def stats(self) -> dict:
        with self._lock:
            return {"appended": self.appended, "torn": self.torn,
                    "compactions": self.compactions}


def read_ring(path: str) -> list[dict]:
    """Every structurally complete, CRC-valid record of a ring file,
    oldest first. Stops silently at the first torn/invalid frame — a
    racing appender's half-written tail is tomorrow's valid record, not
    an error."""
    out: list[dict] = []
    try:
        f = open(path, "rb")
    except OSError:
        return out
    with f:
        while True:
            hdr = f.read(_REC_HEADER.size)
            if len(hdr) < _REC_HEADER.size:
                break
            magic, ln, crc = _REC_HEADER.unpack(hdr)
            if magic != _REC_MAGIC:
                break
            payload = f.read(ln)
            if len(payload) < ln or zlib.crc32(payload) != crc:
                break
            try:
                rec = json.loads(payload)
            except (json.JSONDecodeError, UnicodeDecodeError):
                break
            if isinstance(rec, dict):
                out.append(rec)
    return out


# --- signal derivation -------------------------------------------------------


def _counter(stats: dict | None, *keys: str) -> int:
    return sum(int(stats.get(k) or 0) for k in keys) if stats else 0


def _worst_burn(stats: dict | None) -> float:
    slo = (stats or {}).get("slo") or {}
    burns = [v.get("burn") for v in slo.values()
             if isinstance(v, dict) and v.get("burn") is not None]
    return float(max(burns)) if burns else 0.0


def derive_signals(role: str, health: dict | None, stats: dict | None,
                   prev: dict | None, dt: float | None) -> dict[str, float]:
    """Per-endpoint trend signals from two consecutive samples.

    Counter-valued signals (qps, shed/err rates, covered_hi growth) are
    deltas over ``dt`` and come out 0.0 on the first sample — a trend
    needs two points; the observer never fabricates one. Instantaneous
    signals (lane depth, SLO burn, store hit ratio, mesh fanout) read
    straight off the current sample."""
    rate_dt = dt if dt is not None and dt > 0 else None

    def rate(*keys: str) -> float:
        if prev is None or rate_dt is None:
            return 0.0
        return max(0, _counter(stats, *keys) - _counter(prev, *keys)) / rate_dt

    sig: dict[str, float] = {}
    if role == "router":
        sig["hot_qps"] = rate("requests")
        sig["cold_qps"] = 0.0
        sig["shed_rate"] = rate("shed_relayed")
        sig["err_rate"] = rate("deadline_exceeded", "internal_errors",
                               "shard_errors", "unavailable_replies")
        sig["lane_depth"] = 0.0
    else:
        sig["hot_qps"] = rate("hot_admitted")
        sig["cold_qps"] = rate("cold_admitted")
        sig["shed_rate"] = rate("shed", "lane_shed_hot", "lane_shed_cold")
        sig["err_rate"] = rate("deadline_exceeded", "internal_errors",
                               "degraded_replies")
        sig["lane_depth"] = float((stats or {}).get("queue_depth") or 0)
    sig["slo_burn"] = _worst_burn(stats)
    st = (stats or {}).get("store") or {}
    hits = int(st.get("hits") or 0)
    misses = int(st.get("misses") or 0)
    sig["store_hit"] = hits / (hits + misses) if hits + misses else 0.0
    covered = float((health or {}).get("covered_hi") or 0)
    prev_covered = float((prev or {}).get("_covered_hi") or covered)
    sig["covered_rate"] = (
        max(0.0, covered - prev_covered) / rate_dt
        if prev is not None and rate_dt else 0.0
    )
    sig["mesh_fanout"] = float((stats or {}).get("mesh_fanout") or 0)
    return sig


# --- the observer ------------------------------------------------------------


class FleetObserver:
    """Scrape → derive → detect → advise loop. See the module docstring."""

    def __init__(
        self,
        router_addr: str,
        settings: ObserverSettings | None = None,
        chaos: ChaosSchedule | None = None,
    ) -> None:
        self.settings = (settings or ObserverSettings.from_env()).validate()
        self.router_addr = router_addr
        # MetricsLogger only reads .quiet off its config (router shim)
        self.metrics = MetricsLogger(
            types.SimpleNamespace(quiet=self.settings.quiet)
        )
        self.chaos = chaos if chaos is not None else ChaosSchedule([])
        self.pool = ClientPool(timeout_s=self.settings.timeout_s)
        self.ring: SnapshotRing | None = None
        if self.settings.observe_dir:
            self.ring = SnapshotRing(
                os.path.join(self.settings.observe_dir, RING_FILE),
                cap_bytes=self.settings.ring_bytes,
            )
        self._lock = named_lock("FleetObserver._lock")
        self._scrapes = 0        # guard: _lock — global scrape counter (the
        #                          svc_scrape_gap chaos segment key)
        self._gap_count = 0      # guard: _lock
        self._anomaly_count = 0  # guard: _lock
        self._advice_count = 0   # guard: _lock
        self._prev: dict[str, dict] = {}   # guard: _lock — addr -> last
        #                          sample {"ts","stats","_covered_hi"}
        self._good: dict[str, int] = {}    # guard: _lock — addr ->
        #                          consecutive real samples (gap resets)
        self._ewma: dict[tuple, dict] = {}  # guard: _lock —
        #                          (addr, signal) -> {"mean","dev","n"}
        self._anomaly_ts = -math.inf       # guard: _lock — last fire
        self._advice_ts: dict[tuple, float] = {}  # guard: _lock —
        #                          (advice, shard) -> last fire
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    # --- target discovery ------------------------------------------------

    def _discover(self) -> list[dict]:
        """Router first, then every advertised shard replica. A failed
        router poll still yields the router target (as a gap row);
        replicas are whatever the last reachable health advertised."""
        targets = [{"role": "router", "addr": self.router_addr,
                    "shard": None}]
        try:
            health = self.pool.get(self.router_addr).health()
        except Exception:  # noqa: BLE001 — discovery gap, scrape records it
            self.pool.invalidate(self.router_addr)
            return targets
        for ent in health.get("shards", []) or []:
            for addr in ent.get("addrs", []) or []:
                targets.append({"role": "shard", "addr": addr,
                                "shard": ent.get("shard")})
        return targets

    # --- one scrape ------------------------------------------------------

    def scrape_once(self) -> dict:
        """One full scrape cycle, synchronous (tests call it directly).

        Returns the snapshot row that was appended to the ring."""
        with self._lock:
            self._scrapes += 1
            k = self._scrapes
        targets = self._discover()
        now = time.time()

        # 1) poll every target — RPCs happen with NO observer lock held.
        #    A chaos draw or transport failure is a gap row, never a
        #    fabricated sample.
        rows: list[dict] = []
        for ti, tgt in enumerate(targets):
            addr = tgt["addr"]
            drawn = self.chaos.take_kinds(ti, k, OBSERVER_KINDS)
            if drawn:
                rows.append({**tgt, "gap": drawn[0]["kind"]})
                continue
            try:
                cli = self.pool.get(addr)
                rows.append({**tgt, "gap": None, "health": cli.health(),
                             "stats": cli.stats()})
            except Exception as e:  # noqa: BLE001 — dead endpoint = gap row
                self.pool.invalidate(addr)
                rows.append({**tgt, "gap": type(e).__name__})

        # 2) fold into trend state under the lock (pure computation)
        snapshot_targets: list[dict] = []
        anomalies: list[dict] = []
        gap_events: list[dict] = []
        with self._lock:
            for row in rows:
                addr = row["addr"]
                if row["gap"] is not None:
                    self._gap_count += 1
                    # the gap disarms the endpoint: the next REAL sample
                    # re-seeds the delta baseline and can never alarm
                    self._good[addr] = 0
                    self._prev.pop(addr, None)
                    gap_events.append({"addr": addr, "scrape": k,
                                       "gap": row["gap"]})
                    snapshot_targets.append({
                        "addr": addr, "role": row["role"],
                        "shard": row["shard"], "gap": row["gap"],
                    })
                    continue
                prev = self._prev.get(addr)
                dt = (now - prev["ts"]) if prev else None
                sig = derive_signals(row["role"], row["health"],
                                     row["stats"],
                                     prev["stats"] if prev else None, dt)
                good = self._good.get(addr, 0)
                self._good[addr] = good + 1
                armed = good >= max(2, self.settings.warmup)
                for name in ANOMALY_SIGNALS:
                    x = sig[name]
                    state = self._ewma.setdefault(
                        (addr, name), {"mean": x, "dev": 0.0, "n": 0})
                    if armed and state["n"] >= 2:
                        delta = abs(x - state["mean"])
                        z = delta / max(state["dev"], 1e-9)
                        if (delta > self.settings.min_delta
                                and z > self.settings.z_threshold):
                            anomalies.append({
                                "addr": addr, "signal": name,
                                "value": round(x, 4),
                                "mean": round(state["mean"], 4),
                                "dev": round(state["dev"], 4),
                                "z": round(min(z, 1e6), 2), "scrape": k,
                            })
                    a = self.settings.alpha
                    state["mean"] += a * (x - state["mean"])
                    state["dev"] = ((1 - a) * state["dev"]
                                    + a * abs(x - state["mean"]))
                    state["n"] += 1
                stats = dict(row["stats"] or {})
                stats["_covered_hi"] = (row["health"] or {}).get(
                    "covered_hi") or 0
                self._prev[addr] = {"ts": now, "stats": stats}
                snapshot_targets.append({
                    "addr": addr, "role": row["role"],
                    "shard": row["shard"], "gap": None,
                    "signals": {s: round(v, 4) for s, v in sig.items()},
                })
            advice = self._advise_locked(snapshot_targets, k, now)
            fire = None
            if anomalies and now - self._anomaly_ts >= \
                    self.settings.cooldown_s:
                # edge-trigger: one bundle per breach episode, the
                # first breaching row is the evidence
                self._anomaly_ts = now
                self._anomaly_count += 1
                fire = anomalies[0]
            self._advice_count += len(advice)

        # 3) side effects with the lock released: events, the fleet
        #    debug pull, the ring append
        for g in gap_events:
            self.metrics.event("observer_scrape_gap", quietable=True, **g)
        bundle_path = None
        if fire is not None:
            bundle_path = self._pull_fleet_bundle(targets, k)
            self.metrics.event("fleet_anomaly", bundle=bundle_path, **fire)
        for adv in advice:
            self.metrics.event("scaling_advice", **adv)
        snap = {"ts": round(now, 3), "scrape": k,
                "targets": snapshot_targets, "anomalies": anomalies,
                "advice": advice}
        if self.ring is not None:
            self.ring.append(snap)
        return snap

    # --- advisories ------------------------------------------------------

    def _advise_locked(self, targets: list[dict], k: int,
                       now: float) -> list[dict]:
        """Split/merge/add-replica advisories from the EWMA windows.
        Caller holds ``_lock``. Edge-triggered per (advice, shard)."""
        per_shard: dict[int, dict] = {}
        for t in targets:
            if t["role"] != "shard" or t.get("gap") is not None:
                continue
            si = t["shard"]
            if not isinstance(si, int):
                continue
            agg = per_shard.setdefault(
                si, {"qps": 0.0, "shed": 0.0, "armed": True})
            mean = self._ewma.get((t["addr"], "hot_qps"),
                                  {"mean": 0.0, "n": 0})
            shed = self._ewma.get((t["addr"], "shed_rate"),
                                  {"mean": 0.0, "n": 0})
            agg["qps"] += max(0.0, mean["mean"])
            agg["shed"] += max(0.0, shed["mean"])
            if min(mean.get("n", 0), shed.get("n", 0)) < max(
                    2, self.settings.warmup):
                agg["armed"] = False
        fleet_qps = sum(a["qps"] for a in per_shard.values())
        out: list[dict] = []

        def fire(advice: str, si: int, agg: dict, share: float) -> None:
            key = (advice, si)
            if now - self._advice_ts.get(key, -math.inf) < \
                    self.settings.cooldown_s:
                return
            self._advice_ts[key] = now
            out.append({"advice": advice, "shard": si,
                        "qps": round(agg["qps"], 3),
                        "shed_rate": round(agg["shed"], 3),
                        "share": round(share, 4), "scrape": k})

        for si, agg in sorted(per_shard.items()):
            if not agg["armed"]:
                continue
            share = agg["qps"] / fleet_qps if fleet_qps > 0 else 0.0
            if agg["shed"] > 0.5:
                fire("add_replica", si, agg, share)
            elif share > 0.6 and len(per_shard) > 1 and fleet_qps > 1.0:
                fire("split", si, agg, share)
            elif share < 0.05 and len(per_shard) > 1 and fleet_qps > 1.0:
                fire("merge", si, agg, share)
        return out

    # --- anomaly bundle --------------------------------------------------

    def _pull_fleet_bundle(self, targets: list[dict],
                           k: int) -> str | None:
        """Fleet-wide flight-recorder pull (every endpoint's inline
        ``debug`` op) plus its continuous-profiler snapshot (ISSUE 20
        ``profile`` op), written as ``anomaly_<scrape>.json`` under the
        observe dir. A partial pull still lands — each unreachable
        endpoint carries its named error, and a profile gap (svc_prof_gap
        chaos) never takes the debug half down with it."""
        if not self.settings.debug_pull or not self.settings.observe_dir:
            return None
        procs: list[dict] = []
        for tgt in targets:
            addr = tgt["addr"]
            try:
                row = {"addr": addr, "role": tgt["role"],
                       "shard": tgt["shard"],
                       "bundle": self.pool.get(addr).debug(),
                       "error": None, "profile": None,
                       "profile_error": None}
                try:
                    row["profile"] = self.pool.get(addr).profile()
                except Exception as pe:  # noqa: BLE001 — gap != down
                    self.pool.invalidate(addr)
                    row["profile_error"] = f"{type(pe).__name__}: {pe}"
                prof = row["profile"]
                self.metrics.event(
                    "profile_pulled", quietable=True, role="observer",
                    samples=(prof or {}).get("samples"),
                    stacks=len((prof or {}).get("stacks") or ()),
                    gap=row["profile_error"] is not None,
                )
                procs.append(row)
            except Exception as e:  # noqa: BLE001 — partial bundle is fine
                self.pool.invalidate(addr)
                procs.append({"addr": addr, "role": tgt["role"],
                              "shard": tgt["shard"], "bundle": None,
                              "error": f"{type(e).__name__}: {e}",
                              "profile": None, "profile_error": None})
        doc = {"bundle": FLEET_BUNDLE_VERSION, "ts": time.time(),
               "trigger": "fleet_anomaly", "scrape": k,
               "processes": procs}
        path = os.path.join(self.settings.observe_dir,
                            f"anomaly_{k}.json")
        try:
            os.makedirs(self.settings.observe_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
        except OSError:
            return None
        return path

    # --- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="sieve-observer", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            t0 = trace.now_s()
            try:
                self.scrape_once()
            except Exception as e:  # noqa: BLE001 — observer must survive
                self.metrics.event("observer_error", quietable=True,
                                   error=f"{type(e).__name__}: {e}")
            elapsed = trace.now_s() - t0
            self._stop_evt.wait(max(0.0, self.settings.scrape_s - elapsed))

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
        self.pool.close()

    def stats(self) -> dict:
        with self._lock:
            out = {"scrapes": self._scrapes, "gaps": self._gap_count,
                   "anomalies": self._anomaly_count,
                   "advice": self._advice_count,
                   "endpoints": len(self._good)}
        if self.ring is not None:
            out["ring"] = self.ring.stats()
        return out

    def __enter__(self) -> "FleetObserver":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


__all__ = [
    "ANOMALY_SIGNALS",
    "RING_FILE",
    "FleetObserver",
    "ObserverSettings",
    "SnapshotRing",
    "derive_signals",
    "read_ring",
]
