"""Sieve-as-a-service: the robust query plane (ISSUE 7 tentpole).

The compute plane (coordinator/mesh/cluster) fills a checkpoint ledger;
this package promotes that ledger into a queryable store and serves
``pi`` / ``count`` / ``nth_prime`` / ``primes`` over the shared RPC
framing (sieve/rpc.py), failure-first:

* :mod:`sieve.service.index` — read-only segment-boundary index with
  O(log segments) prefix counts and an LRU of materialized bitsets.
* :mod:`sieve.service.server` — :class:`SieveService`: bounded admission
  queue with typed load-shedding, per-request deadlines with partial
  answers, single-flight coalescing of cold ranges, and a circuit
  breaker that keeps hot-index queries alive while the cold backend is
  down (degraded health, never a wrong number).
* :mod:`sieve.service.client` — :class:`ServiceClient`, the blocking
  client used by the CLI, tests, and tools/service_smoke.py, and
  :class:`ReplicaSet`, the failover client over N replicas (ISSUE 8).

Replication (ISSUE 8): each replica live-follows the shared ledger via
:class:`~sieve.service.server.LedgerFollower` (atomic snapshot swaps,
monotonic ``covered_hi``), drains gracefully on SIGTERM/``shutdown``
(typed ``draining`` sheds, zero dropped in-flight answers), and clients
spread across replicas with :class:`ReplicaSet` — so a rolling restart
of the query plane is invisible except as failovers.

Batched cold plane (ISSUE 9): the admission queue doubles as the
batching point — a :class:`~sieve.service.server.ColdBatcher` drains
every distinct cold chunk registered by queued requests into ONE
backend dispatch (`SieveWorker.process_segments`; a single vmapped
device launch on jax), and ``--persist-cold`` writes the results back
into the ledger so ``covered_hi`` grows under read traffic and
restarts/replicas answer yesterday's cold ranges from the index.

Priority lanes (ISSUE 10): admission splits into two bounded lanes —
**hot** (fully answerable from the index + caches) and **cold** (may
need a backend dispatch) — with a worker reserved for hot whenever
``workers > 1``, cold-lane aging so cold is delayed but never starved,
brownout (under hot backlog the cold lane sheds first), and demotion
(a hot query that discovers a cold chunk mid-execution hands off to
the cold lane). Typed ``overloaded`` sheds carry the lane; the
``svc_flood`` chaos kind injects them deterministically.

Range-sharded fabric (ISSUE 11): :mod:`sieve.service.shards` partitions
[2, N] into contiguous :class:`Shard` ranges (a validated
:class:`ShardMap`), each backed by its own ledger and replica set, and
:mod:`sieve.service.router` fronts them with :class:`SieveRouter` —
the same wire protocol on both sides, so clients need zero changes.
Point queries range-route to one shard; ``pi``/``count`` scatter-gather
as cached full-shard totals plus boundary-shard queries; twin/cousin
counts are spliced across shard edges; deadline budgets, lane-aware
sheds, and per-shard failover compose through the fabric. Shard servers
run with ``--range-lo`` and refuse global-prefix ops — composition is
the router's job. ``python -m sieve route`` is the CLI front door; the
``svc_shard_down`` chaos kind drills whole-shard outages.

Flight recorder (ISSUE 13): every server and router runs a
:class:`~sieve.debug.FlightRecorder` — a black box continuously
holding the span-ring tail, the last structured events, the bounded
:class:`~sieve.metrics.MetricsHistory` trend window, and a redacted
config. Edge triggers (SLO burn, circuit-breaker open,
``router_shard_down``, crash) freeze it into a timestamped bundle
under ``--debug-dir``, one per trigger kind per cooldown; the
``debug`` wire op snapshots the same state inline, and
``tools/fleet_debug.py`` merges router + every replica into one fleet
bundle that ``tools/trace_report.py --bundle`` renders. The
``svc_crash`` chaos kind kills a worker thread for real to drill the
crash path.

Multiplexed wire plane (ISSUE 14): the listener is a single-threaded
``selectors`` event loop — non-blocking reads stream through an
incremental frame decoder, any number of pipelined requests ride one
connection (replies correlate by id, in COMPLETION order), and each
connection owns a bounded write queue with inline ops (health / stats
/ metrics / debug) front-inserted ahead of queued query replies. The
``batch`` wire op answers M prefix/interval/is_prime members with one
vectorized ``np.searchsorted`` row over the index prefix (cold members
walk the ColdBatcher individually, each with a typed per-member
outcome); the router scatter-gathers a client batch as at most ONE
downstream batch RPC per shard. :class:`ServiceClient` grows
``submit``/``drain``/``query_batch``, :class:`ReplicaSet` grows
``query_many`` (mid-pipeline failover retries only the unanswered
suffix) and ``query_batch``, and :class:`ClientPool` gives the fleet
tools one reused pipelined connection per endpoint. The
``svc_slow_frame`` chaos kind dribbles one connection's replies
byte-by-byte to prove no cross-connection head-of-line blocking.

Multi-process serving over a tiered segment store (ISSUE 17): Python
threads share one GIL, so ``python -m sieve serve --procs N`` escapes
it — N full server processes SO_REUSEPORT-bind ONE port (the kernel
load-balances connections), each running its own event loop and worker
pool. What makes that cheap is :class:`TieredSegmentStore`
(sieve/service/store.py): an mmap'd, append-only, per-record-CRC'd
store under the checkpoint dir holding three tiers per chunk — counts
only (0), boundary words (1), and full wheel-210-compressed bitsets
(2, 48 residues per 210 values ≈ 0.229 bits/value). ``BitsetLRU``
evictions DEMOTE into tier 2 instead of vanishing, so hot chunks
survive both eviction and restart, shared across all N processes
through the page cache instead of N private copies. Process 0 is the
designated writer (persist-cold ledger appends, background
compaction + atomic generation swaps); the rest follow generations
read-only on the ledger-follower cadence. The ``store_torn_write``
chaos kind garbles a record mid-append: CRC readers skip it, count a
``store_torn_entry`` event, and re-materialize — never a crash, never
a wrong answer.

Capacity observatory (ISSUE 19): tracing becomes always-on tail
sampling — every server and router keeps a cheap exemplar span ring
and an :class:`~sieve.service.exemplar.ExemplarSampler` decides at
request *completion* which span trees to keep (100% of typed-error /
shed / demoted requests, latency outliers past the sampler's own
rolling p95 × slack, and a deterministic 1-in-N healthy baseline),
persisting them to a rolling ``exemplars.jsonl`` under ``--debug-dir``;
the ``exemplars`` wire op serves the in-memory ring inline, and the
router pulls the downstream exemplars of a kept route so one file
explains the whole path. On top, :mod:`sieve.service.observe` runs the
fleet trend plane: ``python -m sieve observe`` scrapes router + every
advertised replica through one :class:`ClientPool`, persists a CRC'd
:class:`~sieve.service.observe.SnapshotRing` of downsampled fleet
snapshots, and an EWMA + robust z-score engine emits edge-triggered
``fleet_anomaly`` events (each firing a fleet-wide flight-recorder
pull) and ``scaling_advice`` rows. The ``svc_scrape_gap`` chaos kind
drills a failed scrape: a counted gap, never a fabricated sample,
never a false alarm.
"""

from sieve.service.client import (
    CallTimeout,
    ClientPool,
    ReplicaSet,
    ServiceClient,
    ServiceError,
)
from sieve.service.exemplar import ExemplarSampler, load_exemplars
from sieve.service.index import QueryCtx, SieveIndex
from sieve.service.observe import (
    FleetObserver,
    ObserverSettings,
    SnapshotRing,
    read_ring,
)
from sieve.service.router import RouterSettings, ShardUnavailable, SieveRouter
from sieve.service.server import (
    BadRequest,
    ColdBatcher,
    DeadlineExceeded,
    Degraded,
    Draining,
    LedgerFollower,
    Overloaded,
    ServiceSettings,
    SieveService,
)
from sieve.service.shards import Shard, ShardMap
from sieve.service.store import StoreSettings, TieredSegmentStore

__all__ = [
    "BadRequest",
    "CallTimeout",
    "ClientPool",
    "ColdBatcher",
    "DeadlineExceeded",
    "Degraded",
    "Draining",
    "ExemplarSampler",
    "FleetObserver",
    "LedgerFollower",
    "ObserverSettings",
    "Overloaded",
    "QueryCtx",
    "ReplicaSet",
    "RouterSettings",
    "ServiceClient",
    "ServiceError",
    "ServiceSettings",
    "Shard",
    "ShardMap",
    "ShardUnavailable",
    "SieveIndex",
    "SieveRouter",
    "SieveService",
    "SnapshotRing",
    "StoreSettings",
    "TieredSegmentStore",
    "load_exemplars",
    "read_ring",
]
